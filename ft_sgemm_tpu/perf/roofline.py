"""Roofline model: device peak specs + per-stage utilization summaries.

The source paper's entire argument is a GFLOPS table — "ABFT is free" is
a claim about distance from the hardware ceiling (arXiv:2305.01024) — and
TPU linear-algebra studies characterize kernels the same way: achieved
FLOP/s as a fraction of peak MXU throughput and achieved bytes/s as a
fraction of peak HBM bandwidth (arXiv:2112.09017). This module turns one
measured ``(cost estimate, seconds)`` pair into that characterization:

- arithmetic intensity (FLOPs per HBM byte) against the device's ridge
  point, yielding a compute-bound / memory-bound verdict;
- %-of-peak-compute and %-of-peak-bandwidth;
- the ABFT overhead decomposition — what fraction of the stage's FLOPs
  are checksum encode and detect/correct work rather than the GEMM
  itself (:func:`ft_sgemm_tpu.ops.common.gemm_cost_breakdown`).

Everything here is pure host-side Python over plain numbers — no jax
import, so the bench SUPERVISOR (which must never import jax; see
``bench.py``) and offline artifact tooling can use it freely.

Spec provenance: per-chip figures from Google's public Cloud TPU system
documentation (bf16 peak FLOP/s and HBM bandwidth per chip). f32 peak is
DERIVED as bf16/6: XLA's highest-precision f32 dot decomposes each
operand into bf16 limbs and runs a 6-pass MXU schedule, and the repo's
measured v5e ratio agrees (RESULTS.md: f32 xla_dot ~32 TF vs bf16
~190 TF ≈ 1/6). The CPU entry is an order-of-magnitude placeholder
(``estimated=True``) so %-of-peak on a dev box reads as a rough shape,
never a calibrated claim.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

# f32 MXU throughput = bf16 / F32_DERATE (6-pass bf16-limb decomposition
# of highest-precision f32 dots; matches measured v5e f32/bf16 ~ 1/6).
F32_DERATE = 6.0


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak throughput of one device class.

    ``peak_flops`` maps dtype name -> FLOP/s; ``hbm_bytes_per_s`` is the
    per-chip HBM bandwidth. ``estimated`` marks entries whose numbers are
    placeholders rather than published spec (the CPU fallback) — renderers
    annotate their percentages with ``~``.
    """

    name: str
    peak_flops: Mapping[str, float]
    hbm_bytes_per_s: float
    source: str
    estimated: bool = False

    def peak_for(self, dtype: str) -> Optional[float]:
        """The peak for one stage dtype — the roofline summary picks its
        ceiling by the dtype the stage actually ran (an int8 stage judged
        against the f32 peak would read as a >100%-of-peak fiction).
        Accepts the configs dtype aliases (``fp8_e4m3`` etc.); unknown
        dtypes return None (the row renders with null percentages)."""
        name = str(dtype)
        try:
            from ft_sgemm_tpu.configs import canonical_in_dtype

            name = canonical_in_dtype(name)
        except Exception:  # noqa: BLE001 — foreign dtype: raw lookup
            pass
        return self.peak_flops.get(name)

    def ridge_point(self, dtype: str) -> Optional[float]:
        """FLOPs/byte above which this device is compute-bound."""
        peak = self.peak_for(dtype)
        if peak is None or self.hbm_bytes_per_s <= 0:
            return None
        return peak / self.hbm_bytes_per_s


def _tpu(name: str, bf16_tflops: float, hbm_gbps: float,
         source: str, int8_tflops: Optional[float] = None,
         fp8_tflops: Optional[float] = None) -> DeviceSpec:
    bf16 = bf16_tflops * 1e12
    peaks = {"bfloat16": bf16, "float32": bf16 / F32_DERATE}
    # Low-precision serving dtypes (ISSUE 7): int8 from the published
    # per-chip TOPS figure where one exists; parts with no published int8
    # acceleration run int8 operands at the bf16 MXU rate (same systolic
    # passes, narrower operands), so bf16 is the honest ceiling there.
    peaks["int8"] = (int8_tflops * 1e12 if int8_tflops is not None
                     else bf16)
    # fp8 (e4m3): native only on Trillium-class parts (2x bf16); earlier
    # generations consume fp8 via upcast at the bf16 rate.
    peaks["float8_e4m3fn"] = (fp8_tflops * 1e12 if fp8_tflops is not None
                              else bf16)
    return DeviceSpec(
        name=name,
        peak_flops=peaks,
        hbm_bytes_per_s=hbm_gbps * 1e9,
        source=source,
    )


# Per-chip peaks (Cloud TPU system architecture docs; bandwidth in GB/s).
# int8/fp8 provenance per entry: v5e publishes 394 int8 TOPS (2x bf16),
# v5p 918 int8 TOPS, v6e (Trillium) 1836 int8 TOPS and fp8 at the same
# doubled rate; v4 publishes no separate int8 figure (its MXU runs int8
# at the bf16 rate). Where no native figure exists the bf16 ceiling is
# used — documented in _tpu, marked only via `source` (the row itself
# stays exact: that IS the achievable rate).
DEVICE_SPECS = (
    _tpu("TPU v4", 275.0, 1228.0, "cloud.google.com/tpu v4: 275 TFLOPS "
         "bf16, 1228 GB/s HBM2 per chip; no published int8/fp8 "
         "acceleration (bf16 rate applies)"),
    _tpu("TPU v5e", 197.0, 819.0, "cloud.google.com/tpu v5e: 197 TFLOPS "
         "bf16 / 394 TOPS int8, 819 GB/s HBM2 per chip; fp8 via upcast "
         "at bf16 rate", int8_tflops=394.0),
    _tpu("TPU v5p", 459.0, 2765.0, "cloud.google.com/tpu v5p: 459 TFLOPS "
         "bf16 / 918 TOPS int8, 2765 GB/s HBM2e per chip; fp8 via "
         "upcast at bf16 rate", int8_tflops=918.0),
    _tpu("TPU v6e", 918.0, 1640.0, "cloud.google.com/tpu v6e (Trillium): "
         "918 TFLOPS bf16 / 1836 TOPS int8 / 1836 TFLOPS fp8, 1640 GB/s "
         "HBM per chip", int8_tflops=1836.0, fp8_tflops=1836.0),
    DeviceSpec(
        name="cpu",
        peak_flops={"float32": 1e11, "bfloat16": 1e11, "int8": 1e11,
                    "float8_e4m3fn": 1e11},
        hbm_bytes_per_s=5e10,
        source="order-of-magnitude placeholder for a dev-box CPU "
               "(~100 GFLOP/s, ~50 GB/s); utilization numbers on CPU are "
               "shape, not spec",
        estimated=True,
    ),
)

# device_kind normalization: jax reports e.g. "TPU v4", "TPU v5 lite"
# (v5e), "TPU v5p", "TPU v6 lite" / "TPU v6e" (Trillium). Ordered: the
# first matching alias wins, so "v5p" is tested before the bare "v5".
_ALIASES = (
    ("v6", "TPU v6e"),
    ("trillium", "TPU v6e"),
    ("v5p", "TPU v5p"),
    ("v5 lite", "TPU v5e"),
    ("v5e", "TPU v5e"),
    ("v5", "TPU v5e"),  # bare "v5 litepod" style strings: the lite class
    ("v4", "TPU v4"),
)


def find_spec(device_kind: Optional[str]) -> DeviceSpec:
    """The :class:`DeviceSpec` for a jax ``device_kind`` string.

    Unknown / absent kinds fall back to the estimated CPU entry — a
    roofline row is always renderable, and ``estimated`` keeps the
    fallback honest.
    """
    kind = (device_kind or "").lower()
    by_name = {s.name: s for s in DEVICE_SPECS}
    if "tpu" in kind or kind.startswith("v"):
        for needle, name in _ALIASES:
            if needle in kind:
                return by_name[name]
    return by_name["cpu"]


def abft_fractions(breakdown: Mapping[str, int]) -> dict:
    """The ABFT overhead decomposition of one
    :func:`~ft_sgemm_tpu.ops.common.gemm_cost_breakdown` dict: encode,
    detect/correct, and total overhead FLOPs as fractions of the stage's
    total FLOPs (0.0 for a plain kernel)."""
    total = (breakdown["flops_base"] + breakdown["flops_encode"]
             + breakdown["flops_check"])
    if total <= 0:
        return {"encode_fraction": 0.0, "check_fraction": 0.0,
                "abft_fraction": 0.0}
    enc = breakdown["flops_encode"] / total
    chk = breakdown["flops_check"] / total
    return {"encode_fraction": enc, "check_fraction": chk,
            "abft_fraction": enc + chk}


def roofline_summary(*, flops: float, bytes_accessed: float,
                     seconds: Optional[float],
                     device_kind: Optional[str] = None,
                     spec: Optional[DeviceSpec] = None,
                     dtype: str = "float32",
                     breakdown: Optional[Mapping[str, int]] = None,
                     name: Optional[str] = None) -> dict:
    """One roofline row: measured seconds against the device ceilings.

    ``flops``/``bytes_accessed`` come from the kernel's cost estimate
    (:func:`~ft_sgemm_tpu.ops.common.gemm_cost_estimate` — the same
    numbers Mosaic's scheduler sees); ``breakdown`` optionally adds the
    ABFT-overhead fractions. ``seconds`` may be None/non-positive (a
    skipped or failed stage): the row still renders with null rates so
    downstream comparison reports ``incomparable`` instead of crashing.
    """
    spec = find_spec(device_kind) if spec is None else spec
    peak = spec.peak_for(dtype)
    ridge = spec.ridge_point(dtype)
    ai = (flops / bytes_accessed) if bytes_accessed else None
    row = {
        "name": name,
        "dtype": str(dtype),
        "flops": int(flops),
        "bytes": int(bytes_accessed),
        "arithmetic_intensity": ai,
        "device": spec.name,
        "spec_estimated": spec.estimated,
        "peak_gflops": None if peak is None else peak / 1e9,
        "peak_gbps": spec.hbm_bytes_per_s / 1e9,
        "ridge_point": ridge,
        "seconds": None,
        "gflops": None,
        "pct_peak_compute": None,
        "pct_peak_bandwidth": None,
        "bound": None,
    }
    if ai is not None and ridge is not None:
        # The model's verdict from the costs alone: which ceiling this
        # stage runs under, independent of how well it ran.
        row["bound"] = "compute" if ai >= ridge else "memory"
    if seconds is not None and seconds > 0:
        row["seconds"] = float(seconds)
        row["gflops"] = flops / 1e9 / seconds
        if peak:
            row["pct_peak_compute"] = (flops / seconds) / peak
        if spec.hbm_bytes_per_s:
            row["pct_peak_bandwidth"] = (
                (bytes_accessed / seconds) / spec.hbm_bytes_per_s)
    if breakdown is not None:
        row.update(abft_fractions(breakdown))
    return row


__all__ = ["DEVICE_SPECS", "DeviceSpec", "F32_DERATE", "abft_fractions",
           "find_spec", "roofline_summary"]
