"""Persistent XLA compile-cache observability and control.

``bench.py`` has configured ``jax_compilation_cache_dir`` since round 3 —
silently: a fixed repo-local path, every setup failure swallowed
anonymously, and no record of whether a run ever HIT the cache. Tunnel
windows are ~20 minutes and the 4096 compiles are the prime suspect for
every deadline-killed round, so the cache is promoted here to a
first-class, observable module:

- **One env-overridable location** (``FT_SGEMM_COMPILE_CACHE``), keyed
  alongside the tuner cache under ``~/.cache/ft_sgemm_tpu/`` by default —
  XLA keys entries by module content + compile options, so sharing one
  directory across code versions is safe by construction (unlike the
  bench's value records, which stay code-version keyed). ``0``/``off``
  disables (the hermetic test/CI pin, mirroring ``FT_SGEMM_TUNER_CACHE``'s
  conftest pattern).
- **Counted, not guessed**: a ``jax.monitoring`` event listener counts
  the runtime's own ``/jax/compilation_cache/`` hit/miss/request events,
  and a directory snapshot at :func:`enable` time yields files/bytes
  written since. :func:`stats` is what bench artifacts and RunReport
  manifests embed; :func:`record` mirrors it into the telemetry registry
  as ``compile_cache.*`` when enabled.
- **Named failure, never a crash**: :func:`enable` returns a status dict
  whose ``reason`` says exactly why caching is off (env pin, unwritable
  dir, jax too old) instead of swallowing the exception — the
  ``compile_cache_enabled`` / ``compile_cache_reason`` artifact context
  fields come straight from it.

jax is imported lazily inside :func:`enable`; importing this module (or
:mod:`ft_sgemm_tpu.perf`) stays jax-free.
"""

from __future__ import annotations

import os
import stat as _stat
import threading
from typing import Optional

ENV_COMPILE_CACHE = "FT_SGEMM_COMPILE_CACHE"
_OFF_VALUES = ("0", "off", "false", "no")

# The runtime's own cache telemetry (jax._src.compiler): one event per
# compile request that consulted the cache, one per hit, one per miss.
_EVENT_PREFIX = "/jax/compilation_cache/"
_EVENT_MAP = {
    "/jax/compilation_cache/cache_hits": "hits",
    "/jax/compilation_cache/cache_misses": "misses",
    "/jax/compilation_cache/compile_requests_use_cache": "requests",
}

_LOCK = threading.Lock()
# Serializes the whole enable()/disable() sequence (config updates +
# jax's internal cache-latch reset + directory snapshot): under the
# serving layer's concurrent dispatch two racing enables could otherwise
# interleave `jax.config.update` with `reset_cache()` and leave the
# process latched against a half-configured directory. Reentrant so a
# future enable-from-enable refactor cannot deadlock; _LOCK stays the
# cheap guard for the counters the event listener bumps per compile.
_ENABLE_LOCK = threading.RLock()
_STATE = {
    "enabled": False,
    "path": None,
    "reason": "enable() never called",
    "listener_installed": False,
    "events": {"hits": 0, "misses": 0, "requests": 0},
    "baseline": None,  # {"files", "bytes"} dir snapshot at enable time
}


def default_cache_dir() -> str:
    """The default cache directory — alongside the tuner cache."""
    return os.path.join(os.path.expanduser("~"), ".cache", "ft_sgemm_tpu",
                        "jaxcache")


def resolve_dir(default: Optional[str] = None):
    """``(path_or_None, reason_or_None)`` for the active cache location.

    Resolution: ``FT_SGEMM_COMPILE_CACHE`` wins (a path points there; an
    off-value disables with a named reason), then the caller's
    ``default``, then :func:`default_cache_dir`. Pure — no filesystem or
    jax touched."""
    env = os.environ.get(ENV_COMPILE_CACHE)
    if env:
        if env.lower() in _OFF_VALUES:
            return None, f"disabled by {ENV_COMPILE_CACHE}={env}"
        return env, None
    return (default or default_cache_dir()), None


def _on_event(event: str, **kwargs) -> None:
    key = _EVENT_MAP.get(event)
    if key is None:
        return
    with _LOCK:
        _STATE["events"][key] += 1


def _install_listener() -> None:
    """Register the jax.monitoring event listener once per process."""
    with _LOCK:
        if _STATE["listener_installed"]:
            return
        _STATE["listener_installed"] = True
    try:
        from jax import monitoring
    except ImportError:  # older layout
        from jax._src import monitoring  # type: ignore
    monitoring.register_event_listener(_on_event)


def _snapshot(path: str) -> Optional[dict]:
    """``{"files", "bytes"}`` of the regular files under ``path``."""
    files = 0
    size = 0
    try:
        for name in os.listdir(path):
            try:
                st = os.stat(os.path.join(path, name))
            except OSError:
                continue
            if _stat.S_ISREG(st.st_mode):
                files += 1
                size += st.st_size
    except OSError:
        return None
    return {"files": files, "bytes": size}


def enable(default: Optional[str] = None, *,
           min_compile_time_secs: float = 0.0) -> dict:
    """Point jax's persistent compilation cache at the resolved dir.

    Returns :func:`status` (``{"enabled", "path", "reason"}``) and never
    raises: an env pin, an unwritable directory, or a jax without the
    config knob all land as ``enabled: False`` with a NAMED reason. Hit
    and miss counters reset here, and the directory is snapshotted so
    :func:`stats` can report bytes written by this run.

    ``min_compile_time_secs`` defaults to 0: disk is cheap, tunnel
    windows are not — every executable is worth banking (the bench's old
    block used 0.5 s, which skips exactly the small-kernel compiles a
    warm CI run needs to prove hits on).

    Thread-safe: the config-update + latch-reset + snapshot sequence
    runs under one lock, so concurrent enables (the serving layer's
    dispatch threads, a bench worker's setup racing a prewarm) serialize
    instead of interleaving jax's process-global cache state.
    """
    with _ENABLE_LOCK:
        return _enable_locked(default,
                              min_compile_time_secs=min_compile_time_secs)


def _enable_locked(default: Optional[str], *,
                   min_compile_time_secs: float) -> dict:
    path, reason = resolve_dir(default)
    if path is None:
        with _LOCK:
            _STATE.update(enabled=False, path=None, reason=reason)
        return status()
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        # Probe writability up front: jax swallows cache write errors per
        # entry, which would report a "working" cache that banks nothing.
        probe = os.path.join(path, ".writable")
        with open(probe, "w"):
            pass
        os.unlink(probe)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)
        except Exception:  # noqa: BLE001 — knob absent on some versions
            pass
        try:
            # jax latches a per-process used/unused decision at the FIRST
            # compile (compilation_cache._cache_checked): any compile
            # before this enable() — a suite's earlier tests, a library
            # warmup — pins the cache off for good. Reset to pristine so
            # the next compile re-evaluates against the dir just
            # configured (disk content is untouched; only in-memory
            # latches drop).
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — best effort, internal API
            pass
        _install_listener()
        with _LOCK:
            _STATE.update(enabled=True, path=path, reason=None,
                          baseline=_snapshot(path))
            _STATE["events"] = {"hits": 0, "misses": 0, "requests": 0}
    except Exception as e:  # noqa: BLE001 — named failure, never a crash
        with _LOCK:
            _STATE.update(enabled=False, path=path,
                          reason=f"{type(e).__name__}: {e}")
    return status()


def disable() -> dict:
    """Turn the persistent cache back off (tests; the config is process
    global, so a suite that enabled it must restore the default)."""
    with _ENABLE_LOCK:
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
            # Drop the initialized cache object + used-latch too: without
            # this, compiles after disable() keep writing to the old dir.
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001
            pass
        with _LOCK:
            _STATE.update(enabled=False, reason="disabled by disable()")
        return status()


def status() -> dict:
    """The enable-state triple bench artifacts record:
    ``{"enabled", "path", "reason"}``."""
    with _LOCK:
        return {"enabled": _STATE["enabled"], "path": _STATE["path"],
                "reason": _STATE["reason"]}


def stats() -> dict:
    """Everything a run knows about its compile-cache traffic.

    ``{"enabled", "path", "reason", "hits", "misses", "requests",
    "files_written", "bytes_written"}`` — hits/misses/requests from the
    runtime's own events since :func:`enable`; files/bytes from the
    directory-snapshot diff (clamped at 0: a concurrent prune must not
    produce negative writes). Never raises."""
    with _LOCK:
        out = {"enabled": _STATE["enabled"], "path": _STATE["path"],
               "reason": _STATE["reason"]}
        out.update(_STATE["events"])
        baseline = _STATE["baseline"]
        path = _STATE["path"]
    now = _snapshot(path) if (path and baseline is not None) else None
    if now is not None and baseline is not None:
        out["files_written"] = max(0, now["files"] - baseline["files"])
        out["bytes_written"] = max(0, now["bytes"] - baseline["bytes"])
    else:
        out["files_written"] = None
        out["bytes_written"] = None
    return out


def record(registry=None) -> None:
    """Mirror :func:`stats` into the telemetry registry as
    ``compile_cache.*`` gauges (explicit registry, or the active one when
    telemetry is enabled; otherwise a no-op)."""
    try:
        if registry is None:
            from ft_sgemm_tpu import telemetry

            if not telemetry.enabled():
                return
            registry = telemetry.get_registry()
        s = stats()
        registry.gauge("compile_cache.enabled").set(
            1.0 if s["enabled"] else 0.0)
        for key in ("hits", "misses", "requests", "files_written",
                    "bytes_written"):
            if isinstance(s.get(key), (int, float)):
                registry.gauge(f"compile_cache.{key}").set(float(s[key]))
    except Exception:  # noqa: BLE001 — observability never kills a run
        pass


def _reset_for_tests() -> None:
    """Zero the module state (the listener stays installed — jax has no
    unregister API; its counts simply restart from the next enable)."""
    with _LOCK:
        _STATE.update(enabled=False, path=None,
                      reason="enable() never called", baseline=None)
        _STATE["events"] = {"hits": 0, "misses": 0, "requests": 0}


__all__ = ["ENV_COMPILE_CACHE", "default_cache_dir", "disable", "enable",
           "record", "resolve_dir", "stats", "status"]
