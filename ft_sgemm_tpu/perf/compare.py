"""Noise-aware A/B comparison of two bench artifacts — the CI perf gate.

Two runs of the same benchmark never produce identical numbers; the
question a gate must answer is whether B is *meaningfully* slower than A.
This module extracts every comparable measurement from a pair of bench
artifacts (the one-line JSON ``bench.py`` emits — headline value, context
GFLOPS rows, smoke per-encode seconds, and the embedded RunReport's
per-stage roofline rows), compares each under a relative-delta tolerance,
and returns structured verdicts:

- ``improvement`` / ``regression`` — the delta exceeds the tolerance in
  the stage's goodness direction (GFLOPS up is good, seconds down is
  good);
- ``within_noise`` — the delta is inside the tolerance band;
- ``incomparable`` — the stage is missing or null on either side. Never
  an exception: a half-dead artifact (the exact thing a regression gate
  exists to catch early) still produces a readable report, and
  incomparability alone never fails the build (a MISSING baseline is a
  setup problem, not a perf regression — the gate's exit code only
  reflects measured regressions).

Exit-code contract (:func:`exit_code`): 0 = no regression (identical,
within-noise, improved, or merely incomparable), 1 = at least one
regression verdict, 2 = an artifact could not be read at all.

Pure stdlib — usable from any process, no jax.
"""

from __future__ import annotations

import json
from typing import Optional

DEFAULT_TOLERANCE = 0.10

VERDICT_IMPROVEMENT = "improvement"
VERDICT_WITHIN_NOISE = "within_noise"
VERDICT_REGRESSION = "regression"
VERDICT_INCOMPARABLE = "incomparable"
VERDICTS = (VERDICT_IMPROVEMENT, VERDICT_WITHIN_NOISE,
            VERDICT_REGRESSION, VERDICT_INCOMPARABLE)


def load_artifact(path: str) -> dict:
    """Read one bench artifact: the LAST parseable JSON-object line of the
    file (bench prints exactly one; logs may precede it), or the whole
    file as JSON. A driver wrapper document (``{"parsed": {...}}``) is
    unwrapped. Raises ``ValueError``/``OSError`` on an unreadable file —
    the CLI maps those to exit code 2."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        for line in reversed(text.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: no JSON object found")
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    return doc


def _stage(value, higher_is_better: bool) -> Optional[dict]:
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return None
    return {"value": float(value), "higher_is_better": higher_is_better}


def extract_stages(artifact: dict) -> dict:
    """Every comparable measurement of one artifact, keyed by stage name.

    Each entry is ``{"value": float, "higher_is_better": bool}``; null /
    missing / non-numeric measurements simply don't appear (the compare
    step reports them ``incomparable``)."""
    stages = {}
    ctx = artifact.get("context") or {}

    metric = artifact.get("metric") or "value"
    s = _stage(artifact.get("value"), higher_is_better=True)
    if s and metric != "bench_smoke":
        # The smoke headline is a 0/1 ok flag, not a measurement.
        stages[metric] = s

    for key, v in ctx.items():
        if key.endswith("_gflops"):
            s = _stage(v, higher_is_better=True)
            if s:
                stages[key] = s
    tuned = ctx.get("abft_tuned")
    if isinstance(tuned, dict):
        s = _stage(tuned.get("gflops"), higher_is_better=True)
        if s:
            stages["abft_tuned_gflops"] = s

    modes = ctx.get("encode_modes")
    if isinstance(modes, dict):
        for enc, rec in modes.items():
            if isinstance(rec, dict):
                s = _stage(rec.get("seconds"), higher_is_better=False)
                if s:
                    stages[f"smoke_encode[{enc}].seconds"] = s

    rr = ctx.get("run_report")
    if isinstance(rr, dict):
        for row in rr.get("stages") or []:
            if not isinstance(row, dict) or not row.get("name"):
                continue
            s = _stage(row.get("seconds"), higher_is_better=False)
            if s:
                stages[f"stage[{row['name']}].seconds"] = s
    return stages


def compare(a: dict, b: dict, *,
            tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Compare artifact ``b`` (candidate) against ``a`` (baseline).

    Returns ``{"tolerance", "stages": [...], "counts": {verdict: n},
    "regressions": [names]}``; each stage row carries both values, the
    relative delta in the GOODNESS direction (positive = better), and
    the verdict."""
    sa, sb = extract_stages(a), extract_stages(b)
    rows = []
    counts = {v: 0 for v in VERDICTS}
    for name in sorted(set(sa) | set(sb)):
        ra, rb = sa.get(name), sb.get(name)
        row = {"stage": name,
               "baseline": ra["value"] if ra else None,
               "candidate": rb["value"] if rb else None,
               "delta": None}
        if ra is None or rb is None or ra["value"] == 0:
            row["verdict"] = VERDICT_INCOMPARABLE
            row["reason"] = ("missing in candidate" if rb is None
                            else "missing in baseline" if ra is None
                            else "zero baseline")
        else:
            d = (rb["value"] - ra["value"]) / abs(ra["value"])
            if not ra["higher_is_better"]:
                d = -d
            row["delta"] = d
            row["verdict"] = (VERDICT_WITHIN_NOISE if abs(d) <= tolerance
                              else VERDICT_IMPROVEMENT if d > 0
                              else VERDICT_REGRESSION)
        counts[row["verdict"]] += 1
        rows.append(row)
    return {"tolerance": tolerance, "stages": rows, "counts": counts,
            "regressions": [r["stage"] for r in rows
                            if r["verdict"] == VERDICT_REGRESSION]}


def exit_code(result: dict) -> int:
    """0 = no regression verdicts; 1 = at least one."""
    return 1 if result["counts"][VERDICT_REGRESSION] else 0


def format_comparison(result: dict) -> str:
    """Human rendering of one :func:`compare` result."""
    lines = [f"bench-compare (tolerance ±{100 * result['tolerance']:.0f}% "
             "relative)"]
    width = max((len(r["stage"]) for r in result["stages"]), default=5)
    for r in result["stages"]:
        def num(v):
            return "—" if v is None else f"{v:.6g}"

        delta = ("" if r["delta"] is None
                 else f"  {100 * r['delta']:+.1f}%")
        reason = f"  ({r['reason']})" if r.get("reason") else ""
        lines.append(f"  {r['stage']:<{width}}  {num(r['baseline']):>12} "
                     f"-> {num(r['candidate']):>12}  "
                     f"{r['verdict']}{delta}{reason}")
    c = result["counts"]
    lines.append("verdicts: " + "  ".join(
        f"{k}={c[k]}" for k in VERDICTS if c[k]))
    if not result["stages"]:
        lines.append("no comparable stages found in either artifact")
    return "\n".join(lines)


__all__ = ["DEFAULT_TOLERANCE", "VERDICTS", "VERDICT_IMPROVEMENT",
           "VERDICT_INCOMPARABLE", "VERDICT_REGRESSION",
           "VERDICT_WITHIN_NOISE", "compare", "exit_code",
           "extract_stages", "format_comparison", "load_artifact"]
