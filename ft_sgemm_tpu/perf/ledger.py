"""Longitudinal run ledger: every bench/serve artifact as one durable row.

Every observability surface so far is scoped to a single run — the
roofline report, the wall attribution, the live monitor all answer "what
happened in THIS process". But the repo's actual perf story is
longitudinal: ``BENCH_r01``–``r05`` are null/partial artifacts whose
failure modes (backend init crash, four deadline kills at the 4096
stage) only make sense as a *sequence*, and the CI gate still compares
against one static committed baseline instead of the run history. This
module is the cross-run memory: an append-only, schema-versioned JSONL
ledger where each line is one run's distilled facts —

- identity: ``run_id``, git rev, the platform triple
  (requested / used / device_kind) — the ledger key;
- the headline (metric, value, unit) and every comparable measurement
  ``perf/compare.py`` knows how to extract (stage seconds, GFLOPS rows,
  smoke encode timings) so pairwise verdicts extend to N-run trends;
- wall-phase fractions, fault counters, the SLO/device-health snapshot,
  tuner/compile-cache hit rates;
- partial/kill metadata (``context.partial``, ``killed_at_stage``) and
  NAMED degradation reasons for everything that could not be extracted.

Null and partial artifacts ingest cleanly — they are the norm, not the
exception (r01 crashed before measuring anything; r02–r05 were
supervisor-killed mid-stage) — :func:`ingest` never raises. A run that
measured nothing still lands as a row whose ``degradations`` list says
*why*, because "five consecutive null runs, all killed at the same
stage" is exactly the longitudinal fact the ledger exists to surface.

HARD CONSTRAINT — timeline.py discipline: stdlib only, no
package-relative imports. ``bench.py``'s jax-free supervisor loads this
file directly via ``importlib.util.spec_from_file_location`` to append
the artifact it just emitted (``FT_SGEMM_LEDGER=``), so importing the
``ft_sgemm_tpu`` package root (which pulls jax) is forbidden here. The
measurement extractor therefore MIRRORS ``perf/compare.py``'s
``extract_stages`` instead of importing it; ``tests/test_ledger.py``
pins the two equal on a real artifact so they cannot drift.

Entry schema (one JSON object per ledger line), version 1::

    {"schema": 1, "run_id": str, "source": str|null, "kind": str,
     "git_rev": str|null,
     "platform": {"requested": str|null, "used": str|null,
                  "device_kind": str|null},
     "metric": str|null, "unit": str|null, "value": float|null,
     "measurements": {name: {"value": float, "higher_is_better": bool}},
     "wall": {"wall_seconds": float, "fractions": {...}}|null,
     "fault_counters": {...}|null, "slo": {...}|null,
     "tuner_cache": {...}|null, "compile_cache": {...}|null,
     "partial": bool, "killed_at_stage": str|null,
     "completed_stages": [...]|null,
     "degradations": [str, ...]}

Reading migrates older lines forward (schema 0 = the pre-ledger ad-hoc
layout some tooling banked: ``run``/``rev`` keys, flat string platform)
and tags them ``migrated_from_schema_0`` instead of refusing them.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

SCHEMA_VERSION = 1

KINDS = ("bench", "smoke", "serve", "multichip", "baseline", "chaos",
         "unknown")

# Measurement keys whose value is seconds (lower is better) vs
# throughput (higher is better) — the goodness convention compare.py
# established and trend.py inherits.


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _measurement(value, higher_is_better: bool) -> Optional[dict]:
    v = _num(value)
    if v is None:
        return None
    return {"value": v, "higher_is_better": higher_is_better}


def extract_measurements(artifact: dict) -> dict:
    """Every comparable measurement of one bench artifact, keyed by the
    SAME stage names ``perf/compare.py::extract_stages`` produces (the
    equality is test-pinned — see module docstring for why this is a
    mirror, not an import)."""
    stages: dict = {}
    if not isinstance(artifact, dict):
        return stages
    ctx = artifact.get("context") or {}
    if not isinstance(ctx, dict):
        ctx = {}

    metric = artifact.get("metric") or "value"
    s = _measurement(artifact.get("value"), higher_is_better=True)
    if s and metric != "bench_smoke":
        # The smoke headline is a 0/1 ok flag, not a measurement.
        stages[metric] = s

    for key, v in ctx.items():
        if isinstance(key, str) and key.endswith("_gflops"):
            s = _measurement(v, higher_is_better=True)
            if s:
                stages[key] = s
    tuned = ctx.get("abft_tuned")
    if isinstance(tuned, dict):
        s = _measurement(tuned.get("gflops"), higher_is_better=True)
        if s:
            stages["abft_tuned_gflops"] = s

    modes = ctx.get("encode_modes")
    if isinstance(modes, dict):
        for enc, rec in modes.items():
            if isinstance(rec, dict):
                s = _measurement(rec.get("seconds"), higher_is_better=False)
                if s:
                    stages[f"smoke_encode[{enc}].seconds"] = s

    rr = ctx.get("run_report")
    if isinstance(rr, dict):
        for row in rr.get("stages") or []:
            if not isinstance(row, dict) or not row.get("name"):
                continue
            s = _measurement(row.get("seconds"), higher_is_better=False)
            if s:
                stages[f"stage[{row['name']}].seconds"] = s
    return stages


def _infer_kind(doc: dict, ctx: dict, source: Optional[str]) -> str:
    if "n_devices" in doc and "metric" not in doc:
        return "multichip"
    metric = doc.get("metric")
    # The chaos campaign's coverage artifact (ISSUE 19): identified by
    # its metric or the context.chaos matrix.
    if metric == "chaos_coverage" or isinstance(ctx.get("chaos"), dict):
        return "chaos"
    # serve before smoke: a `--serve --smoke` artifact carries both
    # context flags, and the serve identity is the meaningful one.
    # Both serve workloads land here (gemm requests/s, block tokens/s).
    if metric in ("serve_goodput_rps", "serve_block_goodput_tps") \
            or ctx.get("serve") or ctx.get("workload") == "block":
        return "serve"
    if metric == "bench_smoke" or ctx.get("smoke"):
        return "smoke"
    name = os.path.basename(source or "").upper()
    if name.startswith("BASELINE"):
        return "baseline"
    if isinstance(metric, str) and ("gflops" in metric.lower()
                                    or "abft" in metric.lower()):
        return "bench"
    if isinstance(metric, str) and "value" in doc:
        return "bench"
    return "unknown"


def _slo_snapshot(ctx: dict) -> Optional[dict]:
    slo = ctx.get("slo")
    if not isinstance(slo, dict):
        return None
    keep = ("status", "budget_remaining", "burn_rate", "goodput_ratio",
            "observed_p99_seconds", "device_health_min")
    return {k: slo.get(k) for k in keep if k in slo}


def _cache_snapshot(d, keys=("enabled", "hits", "misses",
                             "requests")) -> Optional[dict]:
    if not isinstance(d, dict):
        return None
    return {k: d.get(k) for k in keys if k in d}


def _platform(ctx: dict, manifest: dict) -> dict:
    return {
        "requested": (ctx.get("platform_requested")
                      or manifest.get("platform_requested")),
        "used": (ctx.get("platform_used") or manifest.get("platform_used")
                 or ctx.get("backend") or manifest.get("backend")),
        "device_kind": (ctx.get("device_kind")
                        or manifest.get("device_kind")),
    }


def platform_key(entry: dict) -> str:
    """The platform half of the ledger key, as one comparable string."""
    p = entry.get("platform") or {}
    return "/".join(str(p.get(k) or "?")
                    for k in ("requested", "used", "device_kind"))


def entry_key(entry: dict) -> tuple:
    """The full ledger key: (run_id, git rev, platform triple)."""
    return (entry.get("run_id"), entry.get("git_rev"),
            platform_key(entry))


def ingest(doc, *, run_id: str, source: Optional[str] = None) -> dict:
    """One parsed document -> one schema-1 ledger entry. NEVER raises:
    hostile inputs (null artifacts, driver wrappers whose ``parsed`` is
    null, north-star docs with no value, non-dicts) all produce a row
    whose ``degradations`` list names what was missing — the r01–r05
    class is the expected diet, not an error path."""
    try:
        return _ingest_inner(doc, run_id=run_id, source=source)
    except Exception as e:  # noqa: BLE001 — ingestion never raises
        return _entry_base(run_id, source,
                           degradations=[f"ingest_error:{type(e).__name__}"])


def _entry_base(run_id, source, *, degradations=None) -> dict:
    return {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "source": os.path.basename(source) if source else None,
        "kind": "unknown",
        "git_rev": None,
        "platform": {"requested": None, "used": None, "device_kind": None},
        "metric": None, "unit": None, "value": None,
        "measurements": {},
        "wall": None, "fault_counters": None, "slo": None,
        "tuner_cache": None, "compile_cache": None,
        "partial": False, "killed_at_stage": None,
        "completed_stages": None,
        "degradations": list(degradations or []),
    }


def _ingest_inner(doc, *, run_id, source) -> dict:
    entry = _entry_base(run_id, source)
    deg = entry["degradations"]
    if not isinstance(doc, dict):
        deg.append("not_a_dict")
        return entry

    # Driver wrapper ({"n", "cmd", "rc", "tail", "parsed"}): the banked
    # BENCH_r* shape. A null "parsed" means the run died before emitting
    # its artifact line — record the rc and whatever the tail names.
    # A wrapper carrying "n_devices" is a MULTICHIP artifact even when
    # its parsed payload is an ordinary serve/bench line (PR 14: the
    # multi-device pool bench IS the multichip probe, with real
    # measurements instead of an ok flag).
    multichip_wrapper = False
    if "parsed" in doc and ("rc" in doc or "cmd" in doc):
        wrapper, doc = doc, doc.get("parsed")
        multichip_wrapper = "n_devices" in wrapper
        rc = wrapper.get("rc")
        if rc not in (0, None):
            deg.append(f"worker_rc:{rc}")
        if not isinstance(doc, dict):
            deg.append("no_artifact_parsed")
            tail = wrapper.get("tail") or ""
            last = [ln for ln in str(tail).splitlines() if ln.strip()]
            if last:
                deg.append(f"tail:{last[-1].strip()[:120]}")
            name = os.path.basename(source or "").upper()
            if name.startswith("BENCH"):
                entry["kind"] = "bench"
            elif name.startswith("MULTICHIP"):
                entry["kind"] = "multichip"
            return entry

    ctx = doc.get("context")
    if not isinstance(ctx, dict):
        ctx = {}
        if "context" in doc or "metric" in doc:
            deg.append("no_context")
    rr = ctx.get("run_report")
    rr = rr if isinstance(rr, dict) else {}
    manifest = rr.get("manifest")
    manifest = manifest if isinstance(manifest, dict) else {}

    entry["kind"] = _infer_kind(doc, ctx, source)
    if multichip_wrapper:
        entry["kind"] = "multichip"
    entry["git_rev"] = manifest.get("git_rev")
    entry["platform"] = _platform(ctx, manifest)
    entry["metric"] = doc.get("metric") if isinstance(
        doc.get("metric"), str) else None
    entry["unit"] = doc.get("unit") if isinstance(
        doc.get("unit"), str) else None
    entry["value"] = _num(doc.get("value"))
    entry["measurements"] = extract_measurements(doc)
    # Static-analysis health rides the manifest (bench.py runs the
    # contract checker per process): ledger rows carry lint.findings /
    # lint.seconds as ordinary lower-is-better measurements so `cli
    # trend` watches checker runtime and finding count longitudinally.
    # NOT part of extract_measurements — that function mirrors
    # compare.extract_stages exactly (test-pinned), and lint facts are
    # not an A/B-comparable stage.
    lint = manifest.get("lint")
    if isinstance(lint, dict):
        entry["lint"] = {"findings": lint.get("findings"),
                         "seconds": lint.get("seconds")}
        for key in ("findings", "seconds"):
            s = _measurement(lint.get(key), higher_is_better=False)
            if s:
                entry["measurements"][f"lint.{key}"] = s
    # Transformer-block serving measurements (serve_block.*): the block
    # workload's goodput plane — tokens-correct/sec, latency, and the
    # KV-cache verify hit rate — so `cli trend` gates them
    # longitudinally. Like lint.*, added OUTSIDE extract_measurements:
    # that function mirrors compare.extract_stages exactly (test-pinned)
    # and block-serving facts are not an A/B-comparable GEMM stage.
    if ctx.get("workload") == "block":
        for key, hib in (("goodput_tps", True), ("throughput_tps", True),
                         ("tokens_correct", True),
                         ("p50_latency_seconds", False),
                         ("p99_latency_seconds", False)):
            s = _measurement(ctx.get(key), higher_is_better=hib)
            if s:
                entry["measurements"][f"serve_block.{key}"] = s
        kv = ctx.get("kv")
        if isinstance(kv, dict):
            s = _measurement(kv.get("verify_hit_rate"),
                             higher_is_better=True)
            if s:
                entry["measurements"]["serve_block.kv_verify_hit_rate"] \
                    = s
    elif ctx.get("serve"):
        # GEMM serve workload (ISSUE 13): steady-state p50/p99 and
        # throughput land as serve.* measurements so a tuner win on the
        # serve path is judged by `cli trend --gate` against its own
        # rolling history, not a one-off A/B. Same lint.*/serve_block.*
        # pattern: OUTSIDE extract_measurements (the compare mirror pin
        # stands; goodput_rps itself already flows through it as the
        # artifact headline).
        for key, hib in (("throughput_rps", True),
                         ("p50_latency_seconds", False),
                         ("p99_latency_seconds", False)):
            s = _measurement(ctx.get(key), higher_is_better=hib)
            if s:
                entry["measurements"][f"serve.{key}"] = s
        # Pool stage (PR 14): goodput scaling vs the single-device
        # control is the headline multi-device fact — higher is better,
        # gated longitudinally like every serve.* series.
        scaling = ctx.get("scaling")
        if isinstance(scaling, dict):
            for key in ("throughput_ratio", "goodput_ratio"):
                s = _measurement(scaling.get(key), higher_is_better=True)
                if s:
                    entry["measurements"][f"serve_pool.{key}"] = s
    # Elastic recovery (PR 15): the eviction fire drill's facts land as
    # recovery.* measurements so `cli trend --gate` judges recovery
    # health longitudinally — MTTR and the panel-recompute flops ratio
    # must not creep up, the goodput recovery ratio must not creep
    # down. Same lint.*/serve_block.* pattern: OUTSIDE
    # extract_measurements (the compare.extract_stages mirror pin
    # stands; a drill is not an A/B-comparable GEMM stage). Tier-of-
    # detection counts ride the entry body (not the trend plane — they
    # are categorical facts, not a monotone health series).
    rec = ctx.get("recovery")
    if isinstance(rec, dict):
        for key, hib in (("mttr_seconds", False),
                         ("evictions", False),
                         ("panel_recompute_flops_ratio", False),
                         ("goodput_recovery_ratio", True)):
            s = _measurement(rec.get(key), higher_is_better=hib)
            if s:
                entry["measurements"][f"recovery.{key}"] = s
        keep = ("evicted_device", "reason", "migrated_batches",
                "tier_checks", "tier_detections", "ladder",
                "incorrect_responses")
        entry["recovery"] = {k: rec.get(k) for k in keep if k in rec}
    # Fleet runtime (PR 16): the 2-proc smoke's acceptance facts land
    # as fleet.* measurements — same recovery.* stance. The trend plane
    # carries the monotone health series (goodput recovery, MTTR,
    # global-tier detection count, incorrect responses must stay 0);
    # categorical facts (which host, the localization) ride the entry
    # body.
    fleet = ctx.get("fleet")
    if isinstance(fleet, dict):
        for key, hib in (("goodput_recovery_ratio", True),
                         ("mttr_seconds", False),
                         ("global_tier_detections", True),
                         ("incorrect_responses", False),
                         ("goodput_post_rps", True)):
            s = _measurement(fleet.get(key), higher_is_better=hib)
            if s:
                entry["measurements"][f"fleet.{key}"] = s
        keep = ("processes", "vdevs_per_process", "evicted_host",
                "eviction_action", "localized", "merged_hosts",
                "global_tier", "staged_equals_flat", "host_blames",
                "reshard")
        entry["fleet"] = {k: fleet.get(k) for k in keep if k in fleet}
    # Request cost economics (ISSUE 20): the flops-accounted cost view
    # rides the ledger as economics.* measurements — NOT a new artifact:
    # the useful-flops fraction is a longitudinal health series exactly
    # like recovery MTTR, and a second history file would fork the
    # trend plane (DESIGN.md §21). The trend plane gates the useful
    # fraction and per-device correct-token throughput up, the overhead
    # fraction down; the full cause breakdown and rollups ride the
    # entry body.
    econ = ctx.get("economics")
    if not isinstance(econ, dict) and isinstance(fleet, dict):
        econ = fleet.get("economics")
    if isinstance(econ, dict):
        for key, hib in (("useful_flops_fraction", True),
                         ("tokens_correct_per_second_per_device", True),
                         ("overhead_flops_fraction", False)):
            s = _measurement(econ.get(key), higher_is_better=hib)
            if s:
                entry["measurements"][f"economics.{key}"] = s
        keep = ("requests", "requests_ok", "flops_total",
                "flops_productive", "overhead_fractions", "tokens",
                "tokens_correct", "devices", "wall_seconds")
        entry["economics"] = {k: econ.get(k) for k in keep if k in econ}
    # Chaos campaign (ISSUE 19): the per-model coverage rollups land as
    # chaos.<model>.* measurements so `cli trend --gate` fails a fault
    # model whose detection/correction rate or goodput retention
    # regresses (or whose detection latency / MTTR / false-positive
    # rate creeps up). Same lint.*/recovery.* stance: OUTSIDE
    # extract_measurements (the compare.extract_stages mirror pin
    # stands; a coverage matrix is not an A/B-comparable GEMM stage).
    # Categorical facts — tier-of-detection and the policy picks — ride
    # the entry body.
    chaos = ctx.get("chaos")
    if isinstance(chaos, dict) and isinstance(chaos.get("models"), dict):
        keep_chaos = {}
        for name, model_entry in chaos["models"].items():
            if not isinstance(model_entry, dict):
                continue
            rollup = model_entry.get("rollup")
            if not isinstance(rollup, dict):
                continue
            for key, hib in (
                    ("detection_rate", True),
                    ("correction_rate", True),
                    ("goodput_retention", True),
                    ("p95_detection_latency_seconds", False),
                    ("mttr_seconds", False),
                    ("false_positive_rate", False),
                    ("incorrect_results", False)):
                s = _measurement(rollup.get(key), higher_is_better=hib)
                if s:
                    entry["measurements"][f"chaos.{name}.{key}"] = s
            keep_chaos[name] = {
                "tier_of_detection": rollup.get("tier_of_detection"),
                "policy": model_entry.get("policy"),
                "mtbf_seconds": model_entry.get("mtbf_seconds"),
            }
        if keep_chaos:
            entry["chaos"] = keep_chaos

    if entry["kind"] == "multichip" and not entry["measurements"] \
            and entry["value"] is None:
        # The historical flag-only probe ({"n_devices", "ok"}): the ok
        # flag is the whole signal. A multichip artifact that DID
        # measure (the PR-14 pool bench wrapper) keeps its real
        # metric/value/measurements and skips this degradation.
        entry["metric"] = entry["metric"] or "multichip_ok"
        ok = doc.get("ok")
        entry["value"] = 1.0 if ok else (0.0 if ok is not None else None)
        deg.append("no_measurements:multichip_ok_flag_only")
    elif entry["kind"] == "multichip":
        entry["metric"] = entry["metric"] or "multichip_ok"
    elif entry["value"] is None and "value" in doc:
        # The BENCH_r02–r05 class: the artifact line landed but the
        # headline never did. Name the reason the artifact itself gives.
        reasons = ctx.get("errors") if isinstance(ctx.get("errors"),
                                                  dict) else {}
        named = "; ".join(f"{k}={str(v).splitlines()[0][:80]}"
                          for k, v in sorted(reasons.items())) if reasons \
            else "unstated"
        deg.append(f"null_value:{named}")
    elif "value" not in doc:
        deg.append("no_value")
    if not entry["measurements"] and entry["kind"] not in ("multichip",):
        deg.append("no_measurements")

    wall = rr.get("wall")
    if isinstance(wall, dict):
        entry["wall"] = {"wall_seconds": wall.get("wall_seconds"),
                         "fractions": wall.get("fractions")}
    fc = (ctx.get("fault_counters") or manifest.get("fault_counters"))
    if isinstance(fc, dict):
        entry["fault_counters"] = dict(fc)
    entry["slo"] = _slo_snapshot(ctx)
    entry["tuner_cache"] = _cache_snapshot(
        manifest.get("tuner_cache"), keys=("hits", "misses"))
    entry["compile_cache"] = _cache_snapshot(
        ctx.get("compile_cache") or manifest.get("compile_cache"))

    entry["partial"] = bool(ctx.get("partial"))
    if isinstance(ctx.get("killed_at_stage"), str):
        entry["killed_at_stage"] = ctx["killed_at_stage"]
    if isinstance(ctx.get("completed_stages"), list):
        entry["completed_stages"] = [str(s)
                                     for s in ctx["completed_stages"]]
    if entry["partial"]:
        deg.append("partial:" + (entry["killed_at_stage"]
                                 or "killed_at_unknown_stage"))
    return entry


def load_document(path: str):
    """Parse one artifact file: whole-file JSON, or the LAST parseable
    JSON-object line (bench prints one line; logs may precede it) —
    ``perf/compare.py::load_artifact`` semantics WITHOUT unwrapping the
    driver document (the wrapper's rc/tail are ingestion facts here).
    Returns None when no JSON object is found (named in the entry)."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in reversed(text.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
    return doc if isinstance(doc, dict) else None


def ingest_file(path: str, *, run_id: Optional[str] = None) -> dict:
    """One artifact file -> one ledger entry; ``run_id`` defaults to the
    filename stem (``BENCH_r03.json`` -> ``BENCH_r03``). Never raises —
    an unreadable file becomes a row naming the read failure."""
    if run_id is None:
        run_id = os.path.splitext(os.path.basename(path))[0]
    try:
        doc = load_document(path)
    except OSError as e:
        return _entry_base(run_id, path,
                           degradations=[f"unreadable:{type(e).__name__}"])
    if doc is None:
        return _entry_base(run_id, path, degradations=["no_json_object"])
    return ingest(doc, run_id=run_id, source=path)


# ---------------------------------------------------------------------------
# Ledger file I/O + schema migration
# ---------------------------------------------------------------------------


def migrate(d: dict) -> dict:
    """One raw ledger line -> a current-schema entry.

    Schema 0 (the pre-ledger ad-hoc layout: ``run``/``rev`` keys, flat
    string ``platform``, no ``schema`` field) migrates forward and is
    tagged; a line already at the current version passes through; a
    NEWER version is kept (append-only files outlive readers) but tagged
    so trend consumers can choose to skip it."""
    schema = d.get("schema")
    if schema == SCHEMA_VERSION:
        return d
    if isinstance(schema, int) and schema > SCHEMA_VERSION:
        d = dict(d)
        d.setdefault("degradations", []).append(
            f"schema_newer_than_reader:{schema}")
        return d
    # Schema 0 / missing: map the old spellings onto the current layout.
    entry = _entry_base(d.get("run") or d.get("run_id"), d.get("source"))
    entry["git_rev"] = d.get("rev") or d.get("git_rev")
    plat = d.get("platform")
    if isinstance(plat, str):
        entry["platform"] = {"requested": None, "used": plat,
                             "device_kind": None}
    elif isinstance(plat, dict):
        entry["platform"].update({k: plat.get(k) for k in entry["platform"]})
    for key in ("kind", "metric", "unit", "partial", "killed_at_stage"):
        if key in d:
            entry[key] = d[key]
    entry["value"] = _num(d.get("value"))
    if isinstance(d.get("measurements"), dict):
        entry["measurements"] = d["measurements"]
    entry["degradations"] = list(d.get("degradations") or [])
    entry["degradations"].append("migrated_from_schema_0")
    return entry


def read_ledger(path: str) -> List[dict]:
    """Parse a ledger JSONL file into current-schema entries, in append
    order (each gains a ``seq`` index). Torn/foreign lines are skipped —
    the file is append-only across crashes, so a torn tail is expected."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(d, dict):
                continue
            if not any(k in d for k in ("run_id", "run", "schema")):
                continue
            entry = migrate(d)
            entry["seq"] = len(out)
            out.append(entry)
    return out


def append(path: str, entry: dict) -> None:
    """Append one entry to the ledger, fsync'd (timeline.py durability
    stance: whatever kills the process next, this row survived)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    rec = {k: v for k, v in entry.items() if k != "seq"}
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except (OSError, ValueError):
            pass


def latest_per_key(entries) -> dict:
    """Collapse duplicate ledger keys, later append wins (re-ingesting
    the same run supersedes silently — append-only storage, last-writer
    semantics on read). Returns {entry_key: entry} preserving each
    winner's ``seq``."""
    out: dict = {}
    for e in entries:
        out[entry_key(e)] = e
    return out


def dedup_entries(entries) -> List[dict]:
    """The read-side view trend analysis consumes: duplicates collapsed
    (last wins), original append order preserved."""
    winners = latest_per_key(entries)
    keep = {id(e) for e in winners.values()}
    return [e for e in entries if id(e) in keep]


# ---------------------------------------------------------------------------
# History rendering (the `cli history` table)
# ---------------------------------------------------------------------------


def format_history(entries, *, limit: Optional[int] = None) -> str:
    """Human rendering: one line per run — id, kind, platform, value,
    and the partial/kill/degradation annotations that make the r01–r05
    sequence readable at a glance."""
    entries = dedup_entries(entries)
    if limit:
        entries = entries[-limit:]
    lines = [f"run ledger: {len(entries)} runs"]
    if not entries:
        return lines[0] + " (empty)"
    wid = max(len(str(e.get("run_id") or "?")) for e in entries)
    wid = max(wid, 6)
    for e in entries:
        val = e.get("value")
        unit = e.get("unit") or ""
        if isinstance(val, (int, float)):
            shown = f"{val:12.1f} {unit}".rstrip()
        else:
            shown = f"{'null':>12s}"
        note = ""
        if e.get("partial"):
            note = "  PARTIAL" + (f"@{e['killed_at_stage']}"
                                  if e.get("killed_at_stage") else "")
        deg = [d for d in (e.get("degradations") or [])
               if not d.startswith("partial:")]
        if deg:
            note += f"  [{'; '.join(deg[:2])}]"
        p = e.get("platform") or {}
        plat = p.get("device_kind") or p.get("used") or "?"
        rev = (e.get("git_rev") or "?")[:12]
        lines.append(
            f"  {str(e.get('run_id') or '?'):<{wid}}  "
            f"{e.get('kind') or '?':<9s} {plat:<8s} {rev:<12s} "
            f"{e.get('metric') or '-':<34s} {shown}"
            f"  ({len(e.get('measurements') or {})} measurements){note}")
    return "\n".join(lines)


__all__ = ["KINDS", "SCHEMA_VERSION", "append", "dedup_entries",
           "entry_key", "extract_measurements", "format_history",
           "ingest", "ingest_file", "latest_per_key", "load_document",
           "migrate", "platform_key", "read_ledger"]
