"""Performance-observability subsystem: roofline run-reports,
compiled-HLO cost introspection, and noise-aware bench comparison.

PR 1 made *faults* observable (counters, events, spans); this package
makes *performance* observable — every bench run self-reports how close
each stage ran to the hardware roofline, what the compiler built, and
whether a candidate artifact regressed against a baseline:

- :mod:`.roofline` — device peak specs (TPU v4/v5e/v5p/v6e + CPU
  fallback) and per-stage utilization summaries with the ABFT-overhead
  decomposition. Pure Python, no jax.
- :mod:`.hlo` — lower/compile a jitted callable once and record
  ``cost_analysis()`` / ``memory_analysis()`` / HLO op counts (guarded
  per backend) into the telemetry registry as ``compile.*`` / ``hlo.*``.
- :mod:`.report` — the :class:`~ft_sgemm_tpu.perf.report.RunReport`
  manifest a bench artifact embeds (device, versions, git rev, tuner
  cache hits, fault counters, roofline rows), JSON + markdown.
- :mod:`.compare` — A/B artifact comparison under a relative-delta
  tolerance: improvement / within-noise / regression / incomparable
  verdicts and the CI exit-code contract. Pure Python, no jax.
- :mod:`.wallclock` — per-run wall-clock attribution: timeline spans
  rolled up into {import, backend_init, compile, tune, transfer,
  execute, other} fractions (``cli timeline --phases``, the RunReport
  "Wall attribution" section, ``wall.*`` registry series). Pure Python.
- :mod:`.compile_cache` — the persistent XLA compile cache as a
  first-class observable: ``FT_SGEMM_COMPILE_CACHE`` location control,
  hit/miss/bytes-written counting via ``jax.monitoring`` events, and
  the named-reason enable status bench artifacts record.
- :mod:`.ledger` — the longitudinal run ledger: append-only,
  schema-versioned JSONL where every bench/serve artifact (null and
  partial ones included, with named degradation reasons) lands as one
  row keyed by (run_id, git rev, platform triple). Pure stdlib,
  path-loadable by the jax-free bench supervisor.
- :mod:`.trend` — N-run trend verdicts over the ledger: a rolling-
  window noise model per (measurement, platform) series extends
  :mod:`.compare`'s pairwise verdicts to improvement / flat /
  regression / insufficient-data with the same exit-code contract,
  plus fault-rate and SLO-burn drift detection. Pure stdlib.

Importing this package never imports jax (the bench supervisor's
constraint); modules that need it import lazily inside functions.

CLI: ``python -m ft_sgemm_tpu.cli report ARTIFACT.json`` and
``python -m ft_sgemm_tpu.cli bench-compare A.json B.json``.
"""

from __future__ import annotations

from ft_sgemm_tpu.perf import (
    compare,
    compile_cache,
    hlo,
    ledger,
    report,
    roofline,
    trend,
    wallclock,
)
from ft_sgemm_tpu.perf.compare import (
    DEFAULT_TOLERANCE,
    VERDICTS,
    exit_code,
    extract_stages,
    format_comparison,
    load_artifact,
)
from ft_sgemm_tpu.perf.report import (
    RunReport,
    build_manifest,
    from_artifact,
    stage_row,
)
from ft_sgemm_tpu.perf.roofline import (
    DEVICE_SPECS,
    DeviceSpec,
    abft_fractions,
    find_spec,
    roofline_summary,
)
from ft_sgemm_tpu.perf.wallclock import attribute_wall

__all__ = [
    "DEFAULT_TOLERANCE",
    "DEVICE_SPECS",
    "DeviceSpec",
    "RunReport",
    "VERDICTS",
    "abft_fractions",
    "attribute_wall",
    "build_manifest",
    "compare",
    "compile_cache",
    "exit_code",
    "extract_stages",
    "find_spec",
    "format_comparison",
    "from_artifact",
    "hlo",
    "ledger",
    "load_artifact",
    "report",
    "roofline",
    "roofline_summary",
    "stage_row",
    "trend",
    "wallclock",
]
