"""Request cost economics: flops-accounted useful-vs-overhead ledger.

ROADMAP item 2's headline metric is tokens-correct-per-second-per-device
under injected faults, and the PR-15 recompute ladder already prices
every recovery rung in flops (``resilience/recompute.py::recover_local``
returns ``recomputed_flops`` / ``full_retry_flops``) — but no plane
attributes a REQUEST's total cost to its causes. This module is that
plane: every served request rolls into one :class:`CostRecord` —
productive GEMM/attention flops from the same component cost model the
roofline uses (``ops/common.gemm_cost_breakdown``), plus the overhead
flops each fault-tolerance mechanism spent on its behalf — and a
:class:`CostLedger` aggregates the records per device/host/bucket into
the three numbers the arXiv 2507.16676 end-to-end stance asks for:

- **useful-flops fraction** — productive / (productive + overhead);
- **overhead breakdown by cause** — each cause's flops divided by the
  SAME grand total, so ``useful + sum(overhead fractions) == 1``
  exactly and the breakdown can never sum past 1 by construction;
- **tokens-correct-per-second-per-device** — correct output tokens over
  the observed wall window, normalized by distinct devices touched.

The closed overhead-cause axis is :data:`OVERHEAD_CAUSES` (mirrored by
``contracts.OVERHEAD_CAUSES`` and ``events.AXIS_LABELS
["overhead_cause"]`` — the BLOCK_PHASES import-free mirror discipline,
cross-checked by the lint axis-drift pass):

  encode        ABFT checksum-encode flops (the always-on premium)
  check         detect/correct epilogue flops (always-on premium)
  retry         full re-execution flops of bounded retry attempts
  recompute     recovery-ladder rung flops (recover_local's accounting)
  kv_reverify   stored-state re-verification + page-restore flops

Callers compute the component flops with the tools they already have
(``gemm_cost_breakdown`` for GEMM requests, :func:`attention_cost` for
block requests, a ``RecoveryOutcome`` for ladder runs) and hand the
numbers in; the ledger itself never prices anything — one cost model,
one accounting plane, no second opinion.

Economics rides the RUN LEDGER (``perf/ledger.py`` ``economics.*``
measurements, trend-gated like GFLOPS), not a new artifact: the
useful-flops fraction is a longitudinal health series exactly like
recovery MTTR, and inventing a second history file would fork the
trend plane (DESIGN.md §21).

HARD CONSTRAINT — timeline.py discipline: stdlib only, no
package-relative imports. The jax-free supervisor side (bench.py,
``cli economics``, scripts) loads this file directly via
``importlib.util.spec_from_file_location``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

# Runtime spelling of contracts.OVERHEAD_CAUSES (the lint axis-drift
# pass cross-checks both against events.AXIS_LABELS["overhead_cause"]).
OVERHEAD_CAUSES = ("encode", "check", "retry", "recompute", "kv_reverify")


def _f(v) -> float:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else 0.0


@dataclasses.dataclass
class CostRecord:
    """One request's flops accounting: what was useful, what each
    fault-tolerance mechanism spent on its behalf, and whether the
    tokens it produced were correct."""

    flops_productive: float = 0.0
    overhead: Dict[str, float] = dataclasses.field(default_factory=dict)
    tokens: int = 0
    tokens_correct: int = 0
    seconds: Optional[float] = None
    device: Optional[str] = None
    host: Optional[object] = None
    bucket: Optional[str] = None
    trace_id: Optional[str] = None
    request_id: Optional[object] = None
    ok: bool = True

    def __post_init__(self):
        unknown = [c for c in self.overhead if c not in OVERHEAD_CAUSES]
        if unknown:
            raise ValueError(
                f"unknown overhead cause(s) {unknown!r}; the closed axis"
                f" is {OVERHEAD_CAUSES}")
        self.flops_productive = _f(self.flops_productive)
        self.overhead = {c: _f(v) for c, v in self.overhead.items()}

    @property
    def flops_overhead(self) -> float:
        return sum(self.overhead.values())

    @property
    def flops_total(self) -> float:
        return self.flops_productive + self.flops_overhead


def gemm_request_cost(parts: dict, *, retries: int = 0,
                      recompute_flops: float = 0.0) -> Tuple[float, dict]:
    """(productive, overhead-by-cause) of one GEMM request from a
    ``gemm_cost_breakdown`` dict. The plain GEMM (``flops_base``) is the
    productive work; encode/check are the always-on ABFT premium; each
    bounded retry re-executes the WHOLE pass (base + premium — the
    transient-SDC model re-runs everything); ladder recompute flops are
    whatever ``recover_local`` priced."""
    base = _f(parts.get("flops_base"))
    encode = _f(parts.get("flops_encode"))
    check = _f(parts.get("flops_check"))
    overhead = {"encode": encode, "check": check}
    if retries:
        overhead["retry"] = int(retries) * (base + encode + check)
    if recompute_flops:
        overhead["recompute"] = _f(recompute_flops)
    return base, overhead


def attention_cost(lq: int, lk: int, d: int, dv: int) -> dict:
    """Component flops of one checked attention block call, in the
    ``gemm_cost_breakdown`` key vocabulary. Productive work is the two
    dense products (``Q@K^T`` then ``P@V``: ``2*lq*lk*(d+dv)``); the
    ABFT premium is the operand checksum-row encode (one reduction over
    each of K, V, and Q: ``2*(lk*(d+dv) + lq*d)``) and the per-query
    residual check over scores and output (``2*lq*(lk+dv)``). Pinned
    here (and in tests/test_economics.py) as THE accounting the block
    engine reports — the attention mirror of the GEMM cost model."""
    lq, lk, d, dv = int(lq), int(lk), int(d), int(dv)
    return {
        "flops_base": 2 * lq * lk * (d + dv),
        "flops_encode": 2 * (lk * (d + dv) + lq * d),
        "flops_check": 2 * lq * (lk + dv),
    }


def kv_reverify_flops(*, restores: int = 0, reread_rows: int = 0,
                      page_size: int = 0, d: int = 0,
                      dv: int = 0) -> float:
    """Flops of the stored-state ladder: each page restore reseals one
    page's checksum rows (``2*page_size*(d+dv)``), and every re-read
    pass re-reduces the whole cached stream (``2*reread_rows*(d+dv)``).
    """
    width = int(d) + int(dv)
    return float(2 * int(restores) * int(page_size) * width
                 + 2 * int(reread_rows) * width)


def recovery_overhead(outcome) -> float:
    """The ``recompute`` overhead flops of one ladder run — exactly
    ``RecoveryOutcome.recomputed_flops`` (attribute or dict key), the
    pinned accounting of ``resilience/recompute.py::recover_local``."""
    if isinstance(outcome, dict):
        return _f(outcome.get("recomputed_flops"))
    return _f(getattr(outcome, "recomputed_flops", 0.0))


class CostLedger:
    """Thread-safe roll-up of :class:`CostRecord`\\ s into the
    per-device/host/bucket economics view. ``add`` never raises past
    record validation; ``snapshot`` is pure derivation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records = 0
        self._productive = 0.0
        self._overhead = {c: 0.0 for c in OVERHEAD_CAUSES}
        self._tokens = 0
        self._tokens_correct = 0
        self._seconds = 0.0
        self._requests_ok = 0
        self._t0: Optional[float] = None
        self._t1: Optional[float] = None
        self._per: Dict[str, Dict[object, dict]] = {
            "device": {}, "host": {}, "bucket": {}}

    def add(self, record: Optional[CostRecord] = None, **fields) -> CostRecord:
        """Roll one request in (pass a record, or the CostRecord fields
        directly). Returns the record for chaining."""
        rec = record if record is not None else CostRecord(**fields)
        now = time.monotonic()
        with self._lock:
            self._records += 1
            self._productive += rec.flops_productive
            for cause, v in rec.overhead.items():
                self._overhead[cause] += v
            self._tokens += int(rec.tokens)
            self._tokens_correct += int(rec.tokens_correct)
            if rec.seconds is not None:
                self._seconds += _f(rec.seconds)
            if rec.ok:
                self._requests_ok += 1
            self._t0 = now if self._t0 is None else self._t0
            self._t1 = now
            for axis, key in (("device", rec.device), ("host", rec.host),
                              ("bucket", rec.bucket)):
                if key is None:
                    continue
                row = self._per[axis].setdefault(
                    key, {"requests": 0, "flops_productive": 0.0,
                          "flops_overhead": 0.0, "tokens_correct": 0})
                row["requests"] += 1
                row["flops_productive"] += rec.flops_productive
                row["flops_overhead"] += rec.flops_overhead
                row["tokens_correct"] += int(rec.tokens_correct)
        return rec

    def merge_reply(self, economics: dict, **fields) -> Optional[CostRecord]:
        """Roll in a wire-shaped economics dict (the fleet reply block:
        ``{"flops_productive", "overhead": {...}, "tokens",
        "tokens_correct", "seconds"}``). Hostile shapes are dropped —
        a remote rank's missing accounting must not kill dispatch."""
        if not isinstance(economics, dict):
            return None
        try:
            overhead = economics.get("overhead")
            return self.add(
                flops_productive=_f(economics.get("flops_productive")),
                overhead={c: _f(v) for c, v in overhead.items()
                          if c in OVERHEAD_CAUSES}
                if isinstance(overhead, dict) else {},
                tokens=int(_f(economics.get("tokens"))),
                tokens_correct=int(_f(economics.get("tokens_correct"))),
                seconds=economics.get("seconds")
                if isinstance(economics.get("seconds"), (int, float))
                else None,
                **fields)
        except (TypeError, ValueError):
            return None

    def snapshot(self, *, wall_seconds: Optional[float] = None,
                 devices: Optional[int] = None) -> dict:
        """The aggregated economics view. Every fraction divides by the
        SAME grand total (productive + all overhead), so
        ``useful_flops_fraction + sum(overhead_fractions.values())``
        is exactly 1.0 when any flops were recorded — the breakdown
        sums to <= 1 by construction, never by luck."""
        with self._lock:
            productive = self._productive
            overhead = dict(self._overhead)
            records = self._records
            tokens = self._tokens
            tokens_correct = self._tokens_correct
            seconds = self._seconds
            requests_ok = self._requests_ok
            wall = (self._t1 - self._t0
                    if self._t0 is not None and self._t1 is not None
                    else None)
            per = {axis: {k: dict(v) for k, v in rows.items()}
                   for axis, rows in self._per.items()}
        if wall_seconds is not None:
            wall = float(wall_seconds)
        total = productive + sum(overhead.values())
        n_dev = (int(devices) if devices is not None
                 else max(len(per["device"]), 1))
        tcpspd = None
        if wall is not None and wall > 0:
            tcpspd = round(tokens_correct / wall / max(n_dev, 1), 3)
        snap = {
            "requests": records,
            "requests_ok": requests_ok,
            "flops_productive": productive,
            "flops_overhead": overhead,
            "flops_total": total,
            "useful_flops_fraction": (round(productive / total, 6)
                                      if total > 0 else None),
            "overhead_fractions": {
                c: (round(v / total, 6) if total > 0 else None)
                for c, v in overhead.items()},
            "overhead_flops_fraction": (
                round(sum(overhead.values()) / total, 6)
                if total > 0 else None),
            "tokens": tokens,
            "tokens_correct": tokens_correct,
            "busy_seconds": round(seconds, 6),
            "wall_seconds": (round(wall, 6) if wall is not None else None),
            "devices": n_dev if per["device"] or devices is not None
            else None,
            "tokens_correct_per_second_per_device": tcpspd,
            "per_device": per["device"],
            "per_host": per["host"],
            "per_bucket": per["bucket"],
        }
        return snap

    def publish(self, registry, *, wall_seconds: Optional[float] = None,
                devices: Optional[int] = None) -> dict:
        """Set the live ``economics_*`` gauges on a telemetry registry
        (duck-typed: anything with ``.gauge(name, **labels).set(v)``) —
        the ``cli top`` feed. Returns the snapshot it published."""
        snap = self.snapshot(wall_seconds=wall_seconds, devices=devices)
        try:
            if snap["useful_flops_fraction"] is not None:
                registry.gauge("economics_useful_flops_fraction").set(
                    snap["useful_flops_fraction"])
            registry.gauge("economics_flops_total").set(
                snap["flops_total"])
            registry.gauge("economics_requests").set(snap["requests"])
            registry.gauge("economics_tokens_correct").set(
                snap["tokens_correct"])
            if snap["tokens_correct_per_second_per_device"] is not None:
                registry.gauge(
                    "economics_tokens_correct_per_second_per_device"
                ).set(snap["tokens_correct_per_second_per_device"])
            for cause, frac in snap["overhead_fractions"].items():
                if frac is not None:
                    registry.gauge("economics_overhead_flops_fraction",
                                   overhead_cause=cause).set(frac)
        except Exception:  # noqa: BLE001 — observability never raises
            pass
        return snap


__all__ = ["OVERHEAD_CAUSES", "CostLedger", "CostRecord",
           "attention_cost", "gemm_request_cost", "kv_reverify_flops",
           "recovery_overhead"]
