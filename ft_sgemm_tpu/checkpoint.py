"""Checkpoint / resume for fault-tolerant training (orbax-backed).

The reference has no checkpoint or resume of any kind (SURVEY.md §5:
"Checkpoint / resume: none" — it is a single-kernel study). A training
framework built around ABFT needs one, and the two subsystems compose:
ABFT guarantees a *step* is either clean or reported
(``FtSgemmResult.uncorrectable``), and the checkpointer must only ever
persist states that passed that gate — otherwise a corrupted-but-detected
step could be laundered into a "known-good" checkpoint and every later
resume would inherit the corruption silently, defeating the never-silent
contract end to end.

So the core API couples the two:

    ckpt = FtCheckpointer(directory, max_to_keep=3)
    for step in range(...):
        state, uncorrectable = train_step(state)
        ckpt.save(step, state, uncorrectable=uncorrectable)  # gate inside
    step, state = ckpt.restore_latest(state)                 # resume

``save`` refuses (returning ``False``, or raising with ``strict=True``)
when the step reports a violated correction assumption — the caller
re-runs the step from live state or restores the last clean checkpoint;
``restore_latest`` is the recovery path. Works with sharded arrays: orbax
saves/restores ``jax.sharding``-annotated pytrees across a Mesh without
gathering to one host.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np


class UncleanStateError(RuntimeError):
    """Refused to checkpoint a state with reported uncorrectable faults."""


def total_count(counts: Any, match: Optional[str] = None) -> int:
    """Sum a count report's leaves — scalar, array, or pytree (the
    ``ft_counts`` collection, a backward sink's ``[det, unc]``, …).

    ``match`` restricts the sum to leaves whose tree path contains the
    substring (e.g. ``"uncorrectable"`` over a full ``ft_counts`` tree);
    None sums everything. Host-side only (concrete values, not tracers).
    """
    if match is None:
        leaves = jax.tree.leaves(counts)
    else:
        with_path = jax.tree_util.tree_leaves_with_path(counts)
        # EVERY leaf must be reachable through at least one NAMED key
        # (dict key / attribute): a leaf with only positional keys (bare
        # array, plain list/tuple, keypath-less registered node) can
        # never match a name, and silently dropping it from the sum
        # would read a faulted report as clean — the exact silent-zero
        # the never-silent contract forbids. (A named tree simply
        # missing the key still sums to 0: absence of a count category
        # is a real answer.)
        def _named(path):
            return any(isinstance(k, (jax.tree_util.DictKey,
                                      jax.tree_util.GetAttrKey))
                       for k in path)

        if not all(_named(p) for p, _ in with_path):
            raise ValueError(
                "total_count(match=...) needs every leaf under a NAMED "
                "key (dict/dataclass); bare arrays and plain lists/"
                "tuples have no key names to filter — pass match=None "
                "to sum them")
        leaves = [v for p, v in with_path if match in str(p)]
    return int(sum(int(np.sum(np.asarray(leaf))) for leaf in leaves))


def gate_total(report: Any) -> int:
    """Sum an UNCORRECTABLE report for the clean-state gates.

    The gates must see only uncorrectable counts: corrected
    ``detections`` (and ``softmax_flags``) are the ABFT success case,
    and summing them would block every save / burn every retry under
    normal operation. Passing an unfiltered report tree is therefore an
    ERROR, not a silent starvation: any leaf whose path names another
    count category is rejected with instructions to filter first.
    """
    offending = sorted({
        str(key) for path, _ in jax.tree_util.tree_leaves_with_path(report)
        for key in path
        if any(name in str(key) for name in ("detections", "softmax_flags"))
    })
    if offending:
        raise ValueError(
            "the clean-state gate takes UNCORRECTABLE counts only, but the "
            f"report contains {offending} leaves — corrected detections "
            "are benign and would block every step. Filter first: "
            "total_count(counts, 'uncorrectable') plus the bwd sink "
            "gradient's [1] element.")
    return total_count(report)


# Deprecated alias: the gate predates its public promotion and other
# modules imported the underscore name; new code should use gate_total.
_gate_total = gate_total


class FtCheckpointer:
    """Orbax ``CheckpointManager`` with the ABFT clean-state gate.

    Parameters
    ----------
    directory:
        Checkpoint root (created if missing; must be absolute or
        relative to cwd — orbax requires a concrete path).
    max_to_keep:
        Retention; oldest checkpoints beyond this are deleted.
    strict:
        When True, :meth:`save` raises :class:`UncleanStateError` on a
        nonzero uncorrectable count instead of returning ``False``.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 strict: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._strict = strict
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(str(directory)),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    # -- saving ----------------------------------------------------------

    def save(self, step: int, state: Any, *,
             uncorrectable: Any = 0, force: bool = False) -> bool:
        """Persist ``state`` at ``step`` iff the step verified clean.

        ``uncorrectable`` is the step's UNCORRECTABLE total — a scalar,
        array, or pytree whose leaves all count uncorrectable intervals
        (e.g. ``total_count(counts, "uncorrectable") + int(bwd[1])``);
        any nonzero leaf sum blocks the save. Do NOT pass a full report
        tree: corrected ``detections`` are the ABFT success case, and a
        tree containing them is rejected loudly rather than blocking
        every save. ``force=True`` bypasses the gate (for states
        verified by other means). Returns True iff a checkpoint was
        written.

        ``state`` must be a pytree CONTAINER (dict/list/dataclass —
        orbax's StandardSave rejects a bare array or scalar).
        """
        if not force:  # force bypasses the gate AND its report validation
            unc = gate_total(uncorrectable)
            if unc:
                if self._strict:
                    raise UncleanStateError(
                        f"step {step}: {unc} uncorrectable fault "
                        "interval(s) reported — refusing to checkpoint "
                        "unverified state; re-run the step or "
                        "restore_latest()")
                return False
        # orbax itself may skip the save (e.g. should_save is False when
        # latest_step >= step after restoring an older step): forward its
        # verdict so "True" really means "written".
        return bool(self._mgr.save(
            step, args=self._ocp.args.StandardSave(state)))

    def wait(self) -> None:
        """Block until any async save has committed to disk."""
        self._mgr.wait_until_finished()

    # -- restoring -------------------------------------------------------

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, target: Any) -> Any:
        """Restore ``step``; ``target`` is a matching pytree of arrays (or
        ShapeDtypeStructs with shardings) supplying structure/placement."""
        ref = jax.tree.map(_as_abstract, target)
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(ref))

    def restore_latest(self, target: Any) -> Tuple[Optional[int], Any]:
        """(step, state) of the newest clean checkpoint, or (None, target)
        when none exists — callers start fresh without a special case."""
        step = self.latest_step
        if step is None:
            return None, target
        return step, self.restore(step, target)

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()



def _as_abstract(x):
    """Structure/placement reference for restore: keep ShapeDtypeStructs,
    map concrete arrays to their shape/dtype/sharding."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    a = np.asarray(x) if not isinstance(x, jax.Array) else x
    sharding = getattr(x, "sharding", None)
    return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding)
