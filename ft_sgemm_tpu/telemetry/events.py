"""Structured fault events and the JSON-lines sink.

One :class:`FaultEvent` per GEMM / attention call or training-step
transition that has something to report: what was detected, where (op,
layer, tile coordinates), against what threshold, and what happened to it
(outcome). Events serialize to JSON lines — an append-only, crash-tolerant
format any log pipeline can ingest, and the raw input the adaptive-
threshold work (V-ABFT, arXiv:2602.08043) needs: per-call residual
magnitudes and fault statistics, which ``analysis.calibrate_threshold``
currently has to re-measure from scratch.

Everything here is host-side Python over already-concrete values; nothing
imports jax, so writing events can never perturb a traced computation.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import IO, Iterable, Iterator, Optional

# Event outcomes, the lifecycle a fault can take through the stack:
#   clean          no fault this call (logged only when log_clean is set)
#   corrected      in-kernel ABFT correction succeeded (detections > 0,
#                  uncorrectable == 0)
#   uncorrectable  residual-after-correct re-check still flags: output
#                  unverified, caller must re-run
#   retry / restore / raise / exhausted
#                  training-loop recovery ladder stages
#                  (train.resilient_step); "exhausted" is the non-raising
#                  terminal — every recovery option spent, the last clean
#                  state returned to the caller
#   alert          an observability threshold crossed (SLO burn rate,
#                  device-health drift — telemetry/monitor.py); carries
#                  the crossing's facts in ``extra``, counts toward no
#                  call totals (like the recovery-ladder stream)
#   evicted        a device was removed from live placement (serve pool
#                  eviction / training-mesh reshard —
#                  resilience/elastic.py); carries the device label,
#                  reason, and migration facts in ``extra``; counts
#                  toward no call totals
OUTCOMES = ("clean", "corrected", "uncorrectable", "retry", "restore",
            "raise", "exhausted", "alert", "evicted")

# Kernel-axis label values an event (or the registry series rebuilt from
# one, :func:`registry_from_events`) may carry: ``strategy`` rides the
# event field of that name; ``encode`` / ``threshold_mode`` ride
# ``extra``. Deliberately a MIRROR of the configs declarations
# (``configs.STRATEGIES`` / ``ENCODE_MODES`` / ``THRESHOLD_MODES``)
# rather than an import: this module stays jax-free and import-light,
# and the lint axis-drift pass cross-checks the two spellings statically
# — drift between what kernels can run and what telemetry can label is
# a CI finding, not a silent unlabeled series.
AXIS_LABELS = {
    "strategy": ("rowcol", "global", "weighted", "fused"),
    "encode": ("vpu", "mxu"),
    "threshold_mode": ("static", "auto", "adaptive"),
    # Transformer-block serving phase (rides ``extra["block_phase"]`` on
    # serve_block events) — mirrors contracts.BLOCK_PHASES, the same
    # import-free mirror discipline as the kernel axes above (the lint
    # axis-drift pass cross-checks the two spellings).
    "block_phase": ("prefill", "decode"),
    # Searched kernel-variant axes (PR 13) — mirror configs.GRID_ORDERS /
    # DIM_SEMANTICS / EPILOGUE_ACTIVATIONS / EPILOGUE_QUANTIZE and
    # contracts.VARIANT_AXES (lint-cross-checked). The composite epilogue
    # SPELLING ("bias+relu+qint8") rides event ``extra["epilogue"]``; the
    # closed per-axis value sets are what label schemas may enumerate.
    "grid_order": ("mn", "nm"),
    "dim_semantics": ("parallel", "arbitrary"),
    "epilogue_activation": ("none", "relu", "gelu"),
    "epilogue_quantize": ("none", "int8", "float8_e4m3fn"),
    # Ring hop schedule (PR 14) — mirrors configs.RING_OVERLAP_MODES and
    # contracts.VARIANT_AXES["ring_overlap"]; rides mesh-GEMM event
    # ``extra["ring_overlap"]``.
    "ring_overlap": ("serial", "overlap"),
    # Serve device-pool placement policy — mirrors
    # contracts.POOL_PLACEMENTS (serve/pool.py::PLACEMENTS is the
    # runtime spelling); rides pool placement timeline points and
    # serve_gemm event extras when the pool executes the request.
    "pool_placement": ("health", "round_robin"),
    # Data-plane checksum tier-of-detection (PR 15) — mirrors
    # contracts.RECOVERY_TIERS (resilience/tiers.py::TIERS is the
    # runtime spelling); rides ``extra["recovery_tier"]`` on tiered
    # detection events, ordered cheapest-communication first.
    "recovery_tier": ("device", "host", "global"),
    # Recovery-ladder rung chosen by a panel recompute (PR 15) —
    # mirrors contracts.LADDER_RUNGS (resilience/recompute.py::
    # LADDER_RUNGS is the runtime spelling); rides
    # ``extra["ladder_rung"]`` on recovery events, cheapest-flops
    # first.
    "ladder_rung": ("element_correct", "panel_recompute",
                    "shard_restore", "full_retry"),
    # Fleet host-slot interconnect tier (PR 16) — mirrors
    # contracts.HOST_TIERS (fleet/dispatch.py::HOST_TIERS is the runtime
    # spelling); rides ``extra["host_tier"]`` on fleet dispatch events:
    # "local" = the coordinator's own process, "dcn" = a remote rank.
    "host_tier": ("local", "dcn"),
    # Cross-host fleet dispatcher placement policy (PR 16) — mirrors
    # contracts.FLEET_PLACEMENTS (fleet/dispatch.py::FLEET_PLACEMENTS is
    # the runtime spelling); rides fleet timeline points and dispatch
    # event extras.
    "fleet_placement": ("dcn_cost", "round_robin"),
    # Per-hop latency decomposition of a fleet-dispatched request —
    # mirrors contracts.FLEET_HOPS (fleet/dispatch.py::FLEET_HOPS is
    # the runtime spelling; the lint axis-drift pass cross-checks all
    # three). Each hop names one fleet_hop_<hop>_seconds histogram
    # family and rides ``extra["hop"]`` on fleet trace events, ordered
    # along the request's path.
    "hop": ("queue_wait", "rtt", "remote_queue", "remote_execute",
            "retry"),
    # Cost-plane overhead cause — mirrors contracts.OVERHEAD_CAUSES
    # (perf/economics.py::OVERHEAD_CAUSES is the runtime spelling; the
    # lint axis-drift pass cross-checks all three). Labels the
    # economics_overhead_flops_fraction gauge and the ledger
    # overhead-fraction keys: every non-productive flop is attributed
    # to exactly one of these spellings.
    "overhead_cause": ("encode", "check", "retry", "recompute",
                       "kv_reverify"),
    # Chaos-campaign fault model (PR 19) — mirrors
    # contracts.FAULT_MODELS (chaos/models.py::FAULT_MODELS is the
    # runtime spelling; the lint axis-drift pass cross-checks all
    # three). Rides ``extra["fault_model"]`` on campaign events and
    # labels the ``fault_detection_latency_seconds`` histogram.
    "fault_model": ("bit_flip", "stuck_device", "multi_device_burst",
                    "residual_drift", "kv_rot", "throughput_sag"),
}


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One structured record in the fault-event stream.

    ``detected``/``corrected``/``uncorrectable`` carry the call's summed
    counters (for correcting strategies corrected == detected; for the
    detect-only ``global`` strategy corrected == 0). ``tiles`` lists the
    ``[i, j]`` output-tile coordinates whose per-tile counter was nonzero
    — the per-layer/per-tile attribution the attention-ABFT literature
    (arXiv:2507.16676) shows matters in transformer stacks. ``residual``
    is the call's max |checksum residual| when the emitter measured one
    (see ``telemetry.record_gemm(measure_residual=...)``); None when not
    measured. ``threshold`` is None when the call ran a traced/auto
    threshold whose concrete value never materialized on host.

    Distributed attribution (DESIGN.md §8): ``host`` is the recording
    process's ``jax.process_index()``; ``devices`` lists the per-device
    entries of a mesh-sharded call whose local counter was nonzero —
    ``{"host", "device", "id", "coords", "axes", "detected",
    "uncorrectable"}`` with ``coords`` the shard's mesh coordinates along
    ``axes`` — the "which chip produced this SDC" answer the fleet view
    (``cli attribute``) ranks on. ``ts`` is the wall-clock emission time
    (merging per-host JSONL shards orders on it).
    """

    outcome: str
    op: str
    detected: int = 0
    corrected: int = 0
    uncorrectable: int = 0
    step: Optional[int] = None
    strategy: Optional[str] = None
    layer: Optional[str] = None
    device: Optional[str] = None
    threshold: Optional[float] = None
    residual: Optional[float] = None
    tiles: Optional[list] = None
    extra: Optional[dict] = None
    host: Optional[int] = None
    devices: Optional[list] = None
    ts: Optional[float] = None

    def __post_init__(self):
        if self.outcome not in OUTCOMES:
            raise ValueError(
                f"FaultEvent.outcome={self.outcome!r} not in {OUTCOMES}")

    def to_json(self) -> str:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None}
        return json.dumps(d, sort_keys=True)

    @staticmethod
    def from_dict(d: dict) -> "FaultEvent":
        known = {f.name for f in dataclasses.fields(FaultEvent)}
        kw = {k: v for k, v in d.items() if k in known}
        return FaultEvent(**kw)


class JsonlSink:
    """Append-only JSON-lines event sink, thread-safe.

    One event per line, flushed per write (a crash loses at most the line
    in flight — the same durability stance as bench.py's stage records).
    Accepts a path (opened lazily, parent dirs created) or an open
    text-mode file object (not closed on :meth:`close` unless owned).
    """

    def __init__(self, path_or_file):
        self._lock = threading.Lock()
        if hasattr(path_or_file, "write"):
            self._fh: Optional[IO] = path_or_file
            self._path = getattr(path_or_file, "name", None)
            self._owns = False
        else:
            self._fh = None
            self._path = os.fspath(path_or_file)
            self._owns = True

    @property
    def path(self) -> Optional[str]:
        return self._path

    def write(self, event: FaultEvent) -> None:
        with self._lock:
            if self._fh is None:
                if self._path is None:
                    return  # closed file-object sink: nothing to reopen
                parent = os.path.dirname(os.path.abspath(self._path))
                os.makedirs(parent, exist_ok=True)
                self._fh = open(self._path, "a", encoding="utf-8")
            self._fh.write(event.to_json() + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and self._owns:
                self._fh.close()
            self._fh = None


def parse_event_line(line: str) -> Optional[FaultEvent]:
    """One JSONL line -> :class:`FaultEvent`, or None for blank, torn,
    or foreign lines (the skip rules :func:`read_events` applies — shared
    here so the CLI's follow mode tails a growing shard with identical
    semantics)."""
    line = line.strip()
    if not line:
        return None
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        return None
    if not isinstance(d, dict) or "outcome" not in d:
        return None
    try:
        return FaultEvent.from_dict(d)
    except (TypeError, ValueError):
        return None


def read_events(path) -> Iterator[FaultEvent]:
    """Iterate the events of a JSONL log; torn/foreign lines are skipped
    (the log is append-only across crashes, so a torn tail is expected)."""
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            ev = parse_event_line(line)
            if ev is not None:
                yield ev


def summarize_events(events: Iterable[FaultEvent]) -> dict:
    """Aggregate an event stream into the summary the CLI prints.

    Returns totals (events, detected, corrected, uncorrectable), per-op
    and per-layer breakdowns, per-outcome counts, and a decade histogram
    of observed residual magnitudes — the raw material
    ``analysis.calibrate_threshold`` needs (clean-call residuals bound the
    noise floor; fault residuals sit above the threshold).
    """
    from ft_sgemm_tpu.telemetry.registry import DEFAULT_BUCKETS, Histogram

    totals = {"events": 0, "detected": 0, "corrected": 0,
              "uncorrectable": 0}
    per_op: dict = {}
    per_layer: dict = {}
    outcomes: dict = {}
    hist = Histogram("residual", (), DEFAULT_BUCKETS)
    call_outcomes = ("clean", "corrected", "uncorrectable")
    for ev in events:
        totals["events"] += 1
        outcomes[ev.outcome] = outcomes.get(ev.outcome, 0) + 1
        if ev.outcome not in call_outcomes:
            # Recovery-ladder events (retry/restore/raise) echo the
            # uncorrectable count of a call that already recorded its own
            # event: summing them too would double-count the counters.
            continue
        totals["detected"] += ev.detected
        totals["corrected"] += ev.corrected
        totals["uncorrectable"] += ev.uncorrectable
        for key, table in ((ev.op, per_op), (ev.layer, per_layer)):
            if key is None:
                continue
            row = table.setdefault(
                key, {"events": 0, "detected": 0, "corrected": 0,
                      "uncorrectable": 0})
            row["events"] += 1
            row["detected"] += ev.detected
            row["corrected"] += ev.corrected
            row["uncorrectable"] += ev.uncorrectable
        if ev.residual is not None:
            hist.observe(ev.residual)
    return {"totals": totals, "outcomes": outcomes, "per_op": per_op,
            "per_layer": per_layer, "residuals": hist.value}


def registry_from_events(events: Iterable[FaultEvent]):
    """Rebuild a :class:`~ft_sgemm_tpu.telemetry.registry.MetricsRegistry`
    from a fault-event log — the bridge from the JSONL stream to any
    registry exporter (``cli telemetry LOG --format=prom``). The series
    mirror what live recording would have produced: ``ft_calls`` /
    ``ft_detections`` / ``ft_corrected`` / ``ft_uncorrectable`` counters
    labeled by op/strategy/layer, ``ft_step_events`` per outcome, the
    ``ft_residual`` histogram, and — for serving-layer events whose
    ``extra`` carries a ``latency_seconds`` observation — the
    ``serve_latency_seconds`` histogram the engine records live, so one
    request log exports the same p50/p99-bearing series the in-process
    registry held (no parallel stats path). Chaos campaign events whose
    ``extra`` carries ``detection_latency_seconds`` (labeled by
    ``fault_model``) rebuild the ``fault_detection_latency_seconds``
    histogram under the same discipline."""
    from ft_sgemm_tpu.telemetry.registry import (
        LATENCY_BUCKETS, MetricsRegistry)

    reg = MetricsRegistry()
    call_outcomes = ("clean", "corrected", "uncorrectable")
    for ev in events:
        # Chaos detection latencies ride ``extra["detection_latency_
        # seconds"]`` on campaign events (outcome ``alert``, but any
        # carrier counts) — rebuilt BEFORE the outcome branch because
        # the carrier is usually not a call report. Same single-stats-
        # path discipline as serve_latency_seconds below: the live
        # campaign observes the identical value into its registry, so
        # one event log exports the same histogram.
        det_lat = (ev.extra.get("detection_latency_seconds")
                   if isinstance(ev.extra, dict) else None)
        if isinstance(det_lat, (int, float)):
            model = ev.extra.get("fault_model")
            labels = {"fault_model": model} if model else {}
            reg.histogram("fault_detection_latency_seconds",
                          buckets=LATENCY_BUCKETS,
                          **labels).observe(det_lat)
        if ev.outcome not in call_outcomes:
            reg.counter("ft_step_events", op=ev.op,
                        outcome=ev.outcome).inc()
            continue
        lat = (ev.extra.get("latency_seconds")
               if isinstance(ev.extra, dict) else None)
        if isinstance(lat, (int, float)):
            reg.histogram("serve_latency_seconds",
                          buckets=LATENCY_BUCKETS).observe(lat)
            bucket = ev.extra.get("bucket")
            if bucket:
                reg.histogram("serve_latency_seconds",
                              buckets=LATENCY_BUCKETS,
                              bucket=bucket).observe(lat)
        labels = {"op": ev.op}
        if ev.strategy:
            labels["strategy"] = ev.strategy
        if ev.layer:
            labels["layer"] = ev.layer
        if ev.device:
            labels["device"] = ev.device
        if isinstance(ev.extra, dict) and ev.extra.get("encode"):
            labels["encode"] = ev.extra["encode"]
        if isinstance(ev.extra, dict) and ev.extra.get("threshold_mode"):
            labels["threshold_mode"] = ev.extra["threshold_mode"]
        reg.counter("ft_calls", **labels).inc()
        reg.counter("ft_detections", **labels).inc(ev.detected)
        reg.counter("ft_corrected", **labels).inc(ev.corrected)
        reg.counter("ft_uncorrectable", **labels).inc(ev.uncorrectable)
        if ev.residual is not None:
            reg.histogram("ft_residual", **labels).observe(ev.residual)
    return reg


def format_summary(summary: dict) -> str:
    """Human-readable rendering of :func:`summarize_events` output."""
    lines = []
    t = summary["totals"]
    lines.append(f"events: {t['events']}  detected: {t['detected']}  "
                 f"corrected: {t['corrected']}  "
                 f"uncorrectable: {t['uncorrectable']}")
    if summary["outcomes"]:
        lines.append("outcomes: " + "  ".join(
            f"{k}={v}" for k, v in sorted(summary["outcomes"].items())))
    for title, table in (("per-op", summary["per_op"]),
                         ("per-layer", summary["per_layer"])):
        if not table:
            continue
        lines.append(f"{title}:")
        width = max(len(k) for k in table)
        for name in sorted(table):
            row = table[name]
            rate = (row["detected"] / row["events"]
                    if row["events"] else 0.0)
            lines.append(
                f"  {name:<{width}}  events={row['events']:<6d} "
                f"detected={row['detected']:<6d} "
                f"corrected={row['corrected']:<6d} "
                f"uncorrectable={row['uncorrectable']:<6d} "
                f"det/call={rate:.2f}")
    h = summary["residuals"]
    if h["count"]:
        lines.append(f"residual histogram ({h['count']} observations, "
                     f"mean {h['sum'] / h['count']:.3g}):")
        lo = float("-inf")
        peak = max(h["counts"]) or 1
        for ub, n in zip(h["buckets"], h["counts"]):
            if n:
                bar = "#" * max(1, round(40 * n / peak))
                lines.append(f"  ({lo:>8.1e}, {ub:>8.1e}]  {n:>6d}  {bar}")
            lo = ub
        from ft_sgemm_tpu.telemetry.registry import histogram_percentiles

        pct = histogram_percentiles(h)
        lines.append("residual percentiles (bucket upper bounds): "
                     + "  ".join(f"{k}<={v:.1e}"
                                 for k, v in pct.items()
                                 if v is not None))
    else:
        lines.append("residual histogram: no residual observations "
                     "(enable measure_residual or log residual-bearing "
                     "events)")
    return "\n".join(lines)


__all__ = ["FaultEvent", "JsonlSink", "OUTCOMES", "format_summary",
           "parse_event_line", "read_events", "registry_from_events",
           "summarize_events"]
