"""Host-side aggregation: merge per-host event shards, localize by device.

The distributed paths (``ft_sgemm_tpu.parallel``) record fault events
with per-device attribution entries (``FaultEvent.devices`` — one entry
per addressable device whose local counter was nonzero, carrying
``(host, device, coords, axes)``; DESIGN.md §8). On a multi-host pod
each process writes its OWN JSONL shard and only lists the devices it
owns, so the shards partition cleanly: merging is concatenation plus a
timestamp sort, never dedup. This module is that merge plus the two
fleet-screening views built on it:

- :func:`device_table` — per-device rollup (events, detected,
  uncorrectable, max residual) keyed by ``(host, device)``.
- :func:`rank_devices` — devices ordered by fault severity/rate, the
  "which chip do I pull" list ``python -m ft_sgemm_tpu.cli attribute``
  prints (the screening workflow of large-pod deployments,
  arXiv:2112.09017 scale).

Like :mod:`.events`, nothing here imports jax — aggregation runs on any
host, including one with no accelerator attached.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, List, Optional, Sequence, Tuple

from ft_sgemm_tpu.telemetry.events import (FaultEvent, parse_event_line,
                                           read_events)

DeviceKey = Tuple[Optional[int], str]


def merge_shards(paths: Sequence) -> List[FaultEvent]:
    """Merge per-host JSONL event shards into one stream.

    Events are ordered by their wall-clock ``ts`` when present (shards
    from different hosts interleave in real time); events without one
    (older logs) keep their per-file order and sort before timestamped
    ones, so pre-attribution logs still merge losslessly.
    """
    events: List[FaultEvent] = []
    for path in paths:
        events.extend(read_events(path))
    return sorted(events,
                  key=lambda e: (e.ts is not None, e.ts or 0.0))


def _entry_rows(ev: FaultEvent):
    """Per-device rows of one event: its ``devices`` attribution entries,
    or — for single-device / pre-attribution events — the event's own
    (host, device) labels as one synthetic entry."""
    if ev.devices:
        for d in ev.devices:
            if isinstance(d, dict) and d.get("device") is not None:
                yield d
        return
    if ev.device is not None:
        yield {"host": ev.host, "device": ev.device, "coords": None,
               "axes": None, "detected": ev.detected,
               "uncorrectable": ev.uncorrectable}


def device_table(events: Iterable[FaultEvent]) -> dict:
    """Aggregate an event stream into the per-device localization view.

    Returns ``{"calls": <total call events>, "devices": {(host, device):
    {"coords", "axes", "events", "detected", "uncorrectable",
    "max_residual"}}}``. ``events`` counts how many call events named
    the device (its fault-rate denominator is the global call count:
    clean calls list no devices by design, keeping pod-scale events
    small). ``coords`` keeps the last-seen shard coordinates — a device
    does not move between mesh positions within one log's run.
    """
    call_outcomes = ("clean", "corrected", "uncorrectable")
    calls = 0
    table: dict = {}
    for ev in events:
        if ev.outcome not in call_outcomes:
            continue
        calls += 1
        for entry in _entry_rows(ev):
            key: DeviceKey = (entry.get("host"), str(entry["device"]))
            row = table.setdefault(
                key, {"coords": None, "axes": None, "events": 0,
                      "detected": 0, "uncorrectable": 0,
                      "max_residual": None})
            row["events"] += 1
            row["detected"] += int(entry.get("detected") or 0)
            row["uncorrectable"] += int(entry.get("uncorrectable") or 0)
            if entry.get("coords") is not None:
                row["coords"] = list(entry["coords"])
            if entry.get("axes") is not None:
                row["axes"] = list(entry["axes"])
            if ev.residual is not None:
                row["max_residual"] = (
                    ev.residual if row["max_residual"] is None
                    else max(row["max_residual"], ev.residual))
    return {"calls": calls, "devices": table}


def rank_devices(table: dict) -> List[Tuple[DeviceKey, dict]]:
    """Devices of a :func:`device_table`, most suspect first.

    Severity order: uncorrectable count (unverified output shipped), then
    detected count, then fault rate (detections per call event naming the
    device) — so a chip with few but always-faulting calls outranks a
    busy healthy one at equal counts.
    """
    devs = table["devices"]

    def sev(item):
        _, row = item
        rate = row["detected"] / row["events"] if row["events"] else 0.0
        return (row["uncorrectable"], row["detected"], rate)

    return sorted(devs.items(), key=sev, reverse=True)


def format_device_table(table: dict, *, ranked: bool = False) -> str:
    """Text rendering of the per-device view (``cli telemetry
    --by-device`` / ``cli attribute``)."""
    rows = rank_devices(table) if ranked else sorted(
        table["devices"].items(),
        key=lambda kv: (kv[0][0] is None, kv[0]))
    lines = [f"calls: {table['calls']}  devices with fault events: "
             f"{len(rows)}"]
    if not rows:
        lines.append("no per-device fault attribution in this stream "
                     "(clean run, or a pre-attribution log)")
        return "\n".join(lines)
    width = max(len(str(dev)) for (_, dev), _ in rows)
    header = (f"  {'host':>4s}  {'device':<{width}s}  {'coords':<12s}"
              f"  {'events':>6s}  {'detected':>8s}  {'uncorr':>6s}"
              f"  {'det/event':>9s}  {'max_residual':>12s}")
    lines.append(header)
    for (host, dev), row in rows:
        coords = ("(" + ",".join(str(c) for c in row["coords"]) + ")"
                  if row["coords"] is not None else "-")
        if row["axes"] and row["coords"] is not None:
            coords = "(" + ",".join(
                f"{a}={c}" for a, c in zip(row["axes"], row["coords"])) + ")"
        rate = row["detected"] / row["events"] if row["events"] else 0.0
        resid = (f"{row['max_residual']:.3g}"
                 if row["max_residual"] is not None else "-")
        lines.append(
            f"  {('-' if host is None else host):>4}  {dev:<{width}s}"
            f"  {coords:<12s}  {row['events']:>6d}  {row['detected']:>8d}"
            f"  {row['uncorrectable']:>6d}  {rate:>9.2f}  {resid:>12s}")
    return "\n".join(lines)


class LiveAggregator:
    """Incremental tail+merge of per-rank JSONL event shards — the fleet
    coordinator's LIVE view (post-hoc :func:`merge_shards` promoted to a
    poll loop; fleet/worker.py rank 0 drives one of these).

    Each registered shard is tailed from a per-file byte offset that
    only ever advances past COMPLETE lines (a torn tail — a rank killed
    mid-write — is left in place and re-read once its newline lands), so
    the merged stream is strictly append-only: counters derived from it
    are monotone non-decreasing across :meth:`poll` calls, and an event
    is delivered exactly once. A shard file that does not exist yet
    (rank still booting) is polled silently until it appears.

    :meth:`feed_health` bridges the merged stream into a
    ``DeviceHealthTracker``: every per-device attribution row observed
    since the previous feed becomes one ``observe()`` call labeled
    ``host{h}:{device}`` — which is how ``device_health`` gauges (and
    the pool's drain logic behind ``/metrics`` / ``cli top``) come to
    cover devices the coordinator process cannot address.
    """

    def __init__(self):
        self._offsets: dict = {}    # path -> byte offset past complete lines
        self._hosts: dict = {}      # path -> declared host (rank) or None
        self._events: List[FaultEvent] = []
        self._fed = 0               # events already pushed to feed_health

    def add_shard(self, path, host: Optional[int] = None) -> None:
        path = os.fspath(path)
        if path not in self._offsets:
            self._offsets[path] = 0
            self._hosts[path] = host

    def poll(self) -> int:
        """Drain every shard's complete new lines; returns the number of
        events appended to the merged stream."""
        new = 0
        for path, offset in list(self._offsets.items()):
            try:
                with open(path, "r", encoding="utf-8",
                          errors="replace") as fh:
                    fh.seek(offset)
                    chunk = fh.read()
            except OSError:
                continue  # not written yet / transiently unreadable
            # Only consume up to the last newline: a torn tail stays
            # unparsed AND unconsumed until the writer completes it.
            cut = chunk.rfind("\n")
            if cut < 0:
                continue
            complete, consumed = chunk[:cut + 1], cut + 1
            self._offsets[path] = offset + len(
                complete.encode("utf-8", errors="replace"))
            for line in complete.splitlines():
                ev = parse_event_line(line)
                if ev is None:
                    continue
                if ev.host is None and self._hosts.get(path) is not None:
                    ev = dataclasses.replace(ev, host=self._hosts[path])
                self._events.append(ev)
                new += 1
        return new

    def events(self) -> List[FaultEvent]:
        return list(self._events)

    def device_table(self) -> dict:
        return device_table(self._events)

    def fleet_view(self) -> dict:
        """The merged per-host rollup ``cli fleet`` prints: which ranks
        have reported, their per-device rows, and the stream totals."""
        table = self.device_table()
        hosts: dict = {}
        for (host, dev), row in table["devices"].items():
            h = hosts.setdefault(host, {"devices": 0, "detected": 0,
                                        "uncorrectable": 0})
            h["devices"] += 1
            h["detected"] += row["detected"]
            h["uncorrectable"] += row["uncorrectable"]
        declared = sorted({h for h in self._hosts.values()
                           if h is not None})
        return {"events": len(self._events), "calls": table["calls"],
                "hosts": hosts, "ranks": declared,
                "devices": table["devices"]}

    def feed_health(self, tracker) -> int:
        """Push events merged since the last feed into a
        ``DeviceHealthTracker`` (one ``observe`` per attribution row,
        labeled ``host{h}:{device}``); returns rows fed."""
        fed_rows = 0
        call_outcomes = ("clean", "corrected", "uncorrectable")
        for ev in self._events[self._fed:]:
            if ev.outcome not in call_outcomes:
                continue
            for entry in _entry_rows(ev):
                host = entry.get("host")
                label = (f"host{host}:{entry['device']}"
                         if host is not None else str(entry["device"]))
                det = int(entry.get("detected") or 0)
                unc = int(entry.get("uncorrectable") or 0)
                tracker.observe(label, calls=1, detected=det,
                                uncorrectable=unc, residual=ev.residual)
                fed_rows += 1
        self._fed = len(self._events)
        return fed_rows


__all__ = ["LiveAggregator", "device_table", "format_device_table",
           "merge_shards", "rank_devices"]
