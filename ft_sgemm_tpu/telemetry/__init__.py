"""Process-wide fault-telemetry subsystem.

The stack's kernels uphold a *clean-or-reported* contract
(``FtSgemmResult`` / ``FtAttentionResult`` counters), but until this
module every caller summed those counters, compared to zero, and dropped
them. Telemetry turns the reports into a persistent signal stream with
three parts:

1. **Metrics registry** (:mod:`.registry`) — thread-safe counters /
   gauges / histograms keyed by name + labels (op, strategy, layer,
   device), the process-wide aggregate a fleet exporter scrapes.
2. **Structured fault-event log** (:mod:`.events`) — one JSON-lines
   record per call that detected, corrected, or failed to correct a
   fault (plus the training loop's retry / restore / raise ladder),
   carrying step, op, tile coordinates, threshold, residual magnitude,
   and outcome. ``python -m ft_sgemm_tpu.cli telemetry <log>``
   summarizes one.
3. **Profiler tracing** (:func:`trace_span`) — ``jax.profiler``
   trace annotations around the FT ops and training steps, so fault
   handling shows up in device profiles.

Zero overhead when disabled — BY CONSTRUCTION, not by promise: every
recording entry point returns before touching its arguments when
telemetry is off, and recording itself is host-side Python over
already-materialized values (never a traced op, never a callback), so the
jitted HLO of any computation is byte-identical with telemetry on, off,
or absent (``tests/test_telemetry.py`` pins this). The corollary: calls
whose results are still tracers (an FT op invoked inside a caller's
``jit``) skip event emission — recording observes values the host
actually holds, it does not reach into device programs.

Quickstart::

    from ft_sgemm_tpu import telemetry

    telemetry.configure(jsonl_path="faults.jsonl")
    res = ft_sgemm(a, b, c, inject=InjectionSpec(enabled=True))
    ...
    telemetry.disable()
    # then: python -m ft_sgemm_tpu.cli telemetry faults.jsonl
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

import numpy as np

from ft_sgemm_tpu.telemetry import aggregate, timeline, traceview
from ft_sgemm_tpu.telemetry.events import (
    FaultEvent,
    JsonlSink,
    OUTCOMES,
    format_summary,
    read_events,
    registry_from_events,
    summarize_events,
)
from ft_sgemm_tpu.telemetry.registry import (
    Counter,
    DEFAULT_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    histogram_percentiles,
    parse_prometheus,
    to_prometheus,
)


class _State:
    """The process-wide telemetry session (one per process, like logging).

    All mutation goes through :func:`configure` / :func:`disable` under
    the lock; readers take the cheap unlocked fast path on ``enabled``
    (a stale read costs one dropped or extra event at worst, never a
    crash — the sink and registry are themselves thread-safe).
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = False
        self.registry = MetricsRegistry()
        self.sink: Optional[JsonlSink] = None
        self.measure_residual = False
        self.log_clean = False
        self.step: Optional[int] = None
        # Live-event observers (telemetry/monitor.py's feed): called with
        # every recorded FaultEvent — clean calls included, independent
        # of log_clean and of whether a JSONL sink is attached. The list
        # is replaced wholesale on mutation so _emit can iterate it
        # without taking the state lock.
        self.observers: tuple = ()


_STATE = _State()


def configure(jsonl_path=None, *, registry: Optional[MetricsRegistry] = None,
              measure_residual: bool = False,
              log_clean: bool = False) -> MetricsRegistry:
    """Enable telemetry for this process.

    ``jsonl_path`` (path or open text file) attaches the structured
    fault-event sink; None records metrics only. ``measure_residual``
    additionally measures each recorded GEMM's post-call column-checksum
    residual host-side (numpy, O(MK + NK + MN) — the observability mode
    for calibration runs; it forces a host transfer of the operands, so
    leave it off on hot paths). ``log_clean`` writes an event for clean
    calls too (residual observations from clean calls are the noise-floor
    half of the calibration histogram). Returns the active registry.
    """
    global _STATE
    with _STATE.lock:
        if _STATE.sink is not None:
            _STATE.sink.close()
        if registry is not None:
            _STATE.registry = registry
        _STATE.sink = JsonlSink(jsonl_path) if jsonl_path is not None else None
        _STATE.measure_residual = bool(measure_residual)
        _STATE.log_clean = bool(log_clean)
        _STATE.enabled = True
        return _STATE.registry


def disable() -> None:
    """Turn telemetry off and close the event sink (registry is kept —
    its aggregates remain readable after a run)."""
    with _STATE.lock:
        _STATE.enabled = False
        if _STATE.sink is not None:
            _STATE.sink.close()
            _STATE.sink = None


def enabled() -> bool:
    return _STATE.enabled


def get_registry() -> MetricsRegistry:
    return _STATE.registry


def reset() -> None:
    """Disable AND drop all recorded state (tests / between runs)."""
    disable()
    with _STATE.lock:
        _STATE.registry.reset()
        _STATE.step = None
        _STATE.measure_residual = False
        _STATE.log_clean = False
        _STATE.observers = ()


def add_observer(fn) -> None:
    """Register a live-event observer: ``fn(event)`` is called for EVERY
    recorded :class:`FaultEvent` while telemetry is enabled — clean calls
    included (a health tracker needs denominators), regardless of
    ``log_clean`` or whether a JSONL sink exists. Observers must be fast
    and never raise (exceptions are swallowed — observability must not
    take down the op); the live monitor
    (:class:`ft_sgemm_tpu.telemetry.monitor.Monitor`) is the intended
    subscriber."""
    with _STATE.lock:
        if fn not in _STATE.observers:
            _STATE.observers = _STATE.observers + (fn,)


def remove_observer(fn) -> None:
    """Unregister an observer added with :func:`add_observer` (idempotent)."""
    with _STATE.lock:
        _STATE.observers = tuple(o for o in _STATE.observers if o is not fn)


def set_step(step: Optional[int]) -> None:
    """Tag subsequently recorded events with a training-step number
    (training loops call this once per step; explicit ``step=`` args to
    the record functions override it per event)."""
    _STATE.step = None if step is None else int(step)


_LOCAL = threading.local()


def _suppressed() -> bool:
    return getattr(_LOCAL, "depth", 0) > 0


@contextlib.contextmanager
def suppress():
    """Suppress call-level recording in this thread for the scope.

    Composite ops record hierarchically: attention wraps its inner FT
    GEMMs, nn layers wrap their inner ops — the OUTERMOST recorder owns
    the logical call, so one call produces exactly one event and summed
    counters are never double-counted across nesting levels. Step-ladder
    events (:func:`record_step_event`) are never suppressed: they are a
    different stream (recovery transitions, not call reports).
    """
    _LOCAL.depth = getattr(_LOCAL, "depth", 0) + 1
    try:
        yield
    finally:
        _LOCAL.depth -= 1


@contextlib.contextmanager
def session(jsonl_path=None, **kw):
    """``with telemetry.session("log.jsonl"): ...`` — configure on entry,
    disable on exit (scoped form of :func:`configure`)."""
    configure(jsonl_path, **kw)
    try:
        yield _STATE.registry
    finally:
        disable()


# ---------------------------------------------------------------------------
# Profiler tracing
# ---------------------------------------------------------------------------


def trace_span(name: str):
    """Context manager: a ``jax.profiler`` trace annotation when telemetry
    is enabled, a no-op otherwise.

    ``TraceAnnotation`` marks host activity spans that bracket device
    dispatch in profiler timelines; unlike ``jax.named_scope`` it adds
    NOTHING to the jaxpr/HLO, so the zero-cost-off guarantee (and
    HLO-identical on/off) holds. Ops wrap their dispatch in one of these
    so fault-tolerant work is attributable in a trace.
    """
    if not _STATE.enabled:
        return contextlib.nullcontext()
    import jax

    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler backend unavailable: never break the op
        return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------


def _concrete(x):
    """The host value of ``x``, or None when it is a tracer / unavailable.

    Recording only observes materialized values: inside a caller's jit
    trace the counters are abstract and the call is skipped (the jitted
    computation must not change because telemetry looked at it).
    """
    if x is None:
        return None
    import jax

    if isinstance(x, jax.core.Tracer):
        return None
    try:
        return np.asarray(x)
    except Exception:
        return None


def _int_total(x) -> Optional[int]:
    arr = _concrete(x)
    return None if arr is None else int(np.sum(arr))


def _float_or_none(x) -> Optional[float]:
    arr = _concrete(x)
    if arr is None or arr.size != 1:
        return None
    v = float(arr.reshape(()))
    return v if np.isfinite(v) else None


def _nonzero_tiles(x) -> Optional[list]:
    arr = _concrete(x)
    if arr is None or arr.ndim != 2:
        return None
    tiles = np.argwhere(arr != 0)
    return [[int(i), int(j)] for i, j in tiles] if tiles.size else None


def measure_output_residual(c_out, a, b, c_in=None, *, alpha=1.0,
                            beta=0.0) -> Optional[float]:
    """Max |column-checksum residual| of a returned GEMM output, measured
    host-side with numpy (no device work, no trace impact).

    The independent post-hoc check: ``1ᵀ C_out`` against
    ``alpha · (1ᵀ A) Bᵀ + beta · 1ᵀ C_in`` — O(MK + NK + MN) vector work.
    On clean/corrected calls this observes the run's actual noise floor
    (the calibration input ``analysis.calibrate_threshold`` needs); on
    uncorrected corruption it rises to fault scale. Returns None when any
    operand is a tracer.
    """
    co = _concrete(c_out)
    af = _concrete(a)
    bf = _concrete(b)
    if co is None or af is None or bf is None:
        return None
    af = af.astype(np.float32)
    bf = bf.astype(np.float32)
    expected = float(alpha) * (bf @ af.sum(axis=0, dtype=np.float32))
    if c_in is not None and beta != 0.0:
        ci = _concrete(c_in)
        if ci is None:
            return None
        expected = expected + float(beta) * ci.astype(np.float32).sum(
            axis=0, dtype=np.float32)
    observed = co.astype(np.float32).sum(axis=0, dtype=np.float32)
    return float(np.max(np.abs(expected - observed)))


def _emit(event: FaultEvent) -> None:
    sink = _STATE.sink
    if sink is not None and (event.outcome != "clean" or _STATE.log_clean):
        sink.write(event)
    for observer in _STATE.observers:
        try:
            observer(event)
        except Exception:  # noqa: BLE001 — observers never break the op
            pass


def _series_labels(op, strategy, layer, device, encode=None,
                   threshold_mode=None) -> dict:
    labels = {"op": op}
    if strategy:
        labels["strategy"] = strategy
    if encode:
        labels["encode"] = encode
    if threshold_mode:
        labels["threshold_mode"] = threshold_mode
    if layer:
        labels["layer"] = layer
    if device:
        labels["device"] = device
    return labels


def record_gemm(op: str, result, *, strategy: Optional[str] = None,
                encode: Optional[str] = None,
                threshold_mode: Optional[str] = None,
                variance: Optional[float] = None,
                step: Optional[int] = None, layer: Optional[str] = None,
                device: Optional[str] = None, threshold=None,
                operands=None, alpha: float = 1.0, beta: float = 0.0,
                extra: Optional[dict] = None,
                devices: Optional[list] = None,
                host: Optional[int] = None,
                epilogue: Optional[str] = None) -> Optional[FaultEvent]:
    """Record one FT-GEMM call from its materialized result counters.

    ``result`` is an :class:`~ft_sgemm_tpu.ops.ft_sgemm.FtSgemmResult`
    (or anything with ``detections`` / ``uncorrectable``, e.g. the psum'd
    counters of the sharded paths). No-op when telemetry is disabled or
    the counters are tracers (call inside a caller's jit). ``operands``
    — ``(a, b)`` or ``(a, b, c_in)`` — enables the host-side residual
    measurement when ``configure(measure_residual=True)``; ``threshold``
    is recorded when it is a concrete scalar (for adaptive-threshold
    calls the factory passes its host-recomputed full-run estimate).
    ``threshold_mode`` ("static"/"auto"/"adaptive") labels the registry
    series and lands in ``extra``, as does ``variance`` — the operand
    mean-square statistic the adaptive bound derives from. Returns the
    event (or None when nothing was recorded).
    """
    if not _STATE.enabled or _suppressed():
        return None
    det = _int_total(getattr(result, "detections", None))
    unc = _int_total(getattr(result, "uncorrectable", None))
    if det is None or unc is None:
        return None  # tracers: the caller is inside jit
    corrected = 0 if strategy == "global" else det
    outcome = ("uncorrectable" if unc else
               "corrected" if det else "clean")
    residual = None
    if _STATE.measure_residual and operands is not None:
        c_out = getattr(result, "c", getattr(result, "out", None))
        residual = measure_output_residual(
            c_out, operands[0], operands[1],
            operands[2] if len(operands) > 2 else None,
            alpha=alpha, beta=beta)
    if encode is not None or threshold_mode is not None or (
            variance is not None) or epilogue is not None:
        extra = dict(extra or {})
        if encode is not None:
            extra["encode"] = encode
        if threshold_mode is not None:
            extra["threshold_mode"] = threshold_mode
        if variance is not None:
            extra["variance"] = _float_or_none(variance)
        if epilogue is not None:
            # The fused-epilogue spelling (configs.EpilogueSpec), e.g.
            # "bias+relu" — only non-identity epilogues are recorded, so
            # default calls' events are byte-identical to pre-variant
            # builds.
            extra["epilogue"] = epilogue
    event = FaultEvent(
        outcome=outcome, op=op, detected=det, corrected=corrected,
        uncorrectable=unc,
        step=_STATE.step if step is None else step,
        strategy=strategy, layer=layer, device=device,
        threshold=_float_or_none(threshold), residual=residual,
        tiles=_nonzero_tiles(getattr(result, "detections", None)),
        extra=extra, devices=devices or None, host=host, ts=time.time())
    reg = _STATE.registry
    labels = _series_labels(op, strategy, layer, device, encode,
                            threshold_mode)
    reg.counter("ft_calls", **labels).inc()
    reg.counter("ft_detections", **labels).inc(det)
    reg.counter("ft_corrected", **labels).inc(corrected)
    reg.counter("ft_uncorrectable", **labels).inc(unc)
    if residual is not None:
        reg.histogram("ft_residual", **labels).observe(residual)
    _emit(event)
    return event


def record_attention(op: str, result, *, strategy: Optional[str] = None,
                     encode: Optional[str] = None,
                     step: Optional[int] = None,
                     layer: Optional[str] = None,
                     device: Optional[str] = None,
                     extra: Optional[dict] = None,
                     devices: Optional[list] = None,
                     host: Optional[int] = None) -> Optional[FaultEvent]:
    """Record one FT-attention call (adds the softmax-stage flags the
    GEMM record has no slot for). Same skip rules as :func:`record_gemm`.
    """
    if not _STATE.enabled or _suppressed():
        return None
    det = _int_total(getattr(result, "detections", None))
    unc = _int_total(getattr(result, "uncorrectable", None))
    flags = _int_total(getattr(result, "softmax_flags", None))
    if det is None or unc is None:
        return None
    flags = flags or 0
    outcome = ("uncorrectable" if (unc or flags) else
               "corrected" if det else "clean")
    merged = dict(extra or {})
    merged["softmax_flags"] = flags
    if encode is not None:
        merged["encode"] = encode
    event = FaultEvent(
        outcome=outcome, op=op, detected=det, corrected=det,
        uncorrectable=unc,
        step=_STATE.step if step is None else step,
        strategy=strategy, layer=layer, device=device, extra=merged,
        devices=devices or None, host=host, ts=time.time())
    reg = _STATE.registry
    labels = _series_labels(op, strategy, layer, device, encode)
    reg.counter("ft_calls", **labels).inc()
    reg.counter("ft_detections", **labels).inc(det)
    reg.counter("ft_corrected", **labels).inc(det)
    reg.counter("ft_uncorrectable", **labels).inc(unc)
    reg.counter("ft_softmax_flags", **labels).inc(flags)
    _emit(event)
    return event


# ---------------------------------------------------------------------------
# Distributed attribution (DESIGN.md §8)
# ---------------------------------------------------------------------------


def _process_index() -> Optional[int]:
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — no runtime: host identity unknown
        return None


def _device_entries(dev_detections, dev_uncorrectable,
                    axes=None) -> Optional[list]:
    """Per-device attribution entries from a mesh-sharded call's
    per-device counter arrays.

    The parallel paths emit, alongside their psum'd global counters, one
    fully mesh-sharded counter array per metric — each addressable shard
    is exactly one device's local count, and its placement index IS the
    device's mesh coordinates. Reading ``addressable_shards`` therefore
    (a) needs no collective, (b) yields only devices THIS process owns —
    per-host JSONL shards partition cleanly for
    :mod:`~ft_sgemm_tpu.telemetry.aggregate` — and (c) names the real
    ``Device`` each count came from. Returns None for tracers (caller
    inside jit) or anything without shard metadata.
    """
    import jax

    if (isinstance(dev_detections, jax.core.Tracer)
            or isinstance(dev_uncorrectable, jax.core.Tracer)):
        return None
    try:
        det_shards = list(dev_detections.addressable_shards)
        unc_by_dev = {s.device: s.data
                      for s in dev_uncorrectable.addressable_shards}
    except Exception:  # noqa: BLE001 — unsharded/foreign arrays: no view
        return None
    entries = []
    for s in det_shards:
        try:
            det = int(np.sum(np.asarray(s.data)))
            unc_data = unc_by_dev.get(s.device)
            unc = (0 if unc_data is None
                   else int(np.sum(np.asarray(unc_data))))
            coords = [int(sl.start or 0) for sl in s.index]
        except Exception:  # noqa: BLE001 — skip a shard, keep the rest
            continue
        dev = s.device
        entries.append({
            "host": int(getattr(dev, "process_index", 0)),
            "device": str(dev),
            "id": int(getattr(dev, "id", -1)),
            "coords": coords,
            "axes": list(axes) if axes else None,
            "detected": det,
            "uncorrectable": unc,
        })
    return entries or None


def _bump_device_counters(op, strategy, entries) -> None:
    """Per-device registry series (``ft_device_*``) — separate metric
    names from the call-level ``ft_*`` counters, so fleet rollups by
    device never double-count call totals."""
    reg = _STATE.registry
    for e in entries:
        labels = {"op": op, "device": e["device"],
                  "coords": ",".join(str(c) for c in e["coords"])}
        if e.get("host") is not None:
            labels["host"] = e["host"]
        if strategy:
            labels["strategy"] = strategy
        reg.counter("ft_device_calls", **labels).inc()
        reg.counter("ft_device_detections", **labels).inc(e["detected"])
        reg.counter("ft_device_uncorrectable",
                    **labels).inc(e["uncorrectable"])


def record_mesh_gemm(op: str, result, *, dev_detections=None,
                     dev_uncorrectable=None, axes=None,
                     strategy: Optional[str] = None,
                     step: Optional[int] = None,
                     device: Optional[str] = None, threshold=None,
                     operands=None, alpha: float = 1.0, beta: float = 0.0,
                     extra: Optional[dict] = None) -> Optional[FaultEvent]:
    """Record one mesh-sharded FT-GEMM call WITH per-device attribution.

    Same contract as :func:`record_gemm` (one event per logical call,
    global counters), plus: ``dev_detections`` / ``dev_uncorrectable``
    are the call's fully mesh-sharded per-device counter arrays and
    ``axes`` the mesh axis names; each addressable device's counts land
    as (a) an entry in the event's ``devices`` list when nonzero and
    (b) ``ft_device_*`` registry series labeled
    ``(op, host, device, coords)``. The event itself lists only FAULTY
    devices (a clean 256-chip step must not carry 256 entries); the
    registry counts every device's calls so rates stay computable.
    """
    if not _STATE.enabled or _suppressed():
        return None
    entries = None
    if dev_detections is not None and dev_uncorrectable is not None:
        entries = _device_entries(dev_detections, dev_uncorrectable, axes)
    faulty = [e for e in (entries or [])
              if e["detected"] or e["uncorrectable"]]
    ev = record_gemm(
        op, result, strategy=strategy, step=step, device=device,
        threshold=threshold, operands=operands, alpha=alpha, beta=beta,
        extra=extra, devices=faulty, host=_process_index())
    if ev is not None and entries:
        _bump_device_counters(op, strategy, entries)
    return ev


def record_mesh_attention(op: str, result, *, dev_detections=None,
                          dev_uncorrectable=None, axes=None,
                          strategy: Optional[str] = None,
                          step: Optional[int] = None,
                          device: Optional[str] = None,
                          extra: Optional[dict] = None
                          ) -> Optional[FaultEvent]:
    """Mesh-sharded analog of :func:`record_attention` — see
    :func:`record_mesh_gemm` for the attribution semantics."""
    if not _STATE.enabled or _suppressed():
        return None
    entries = None
    if dev_detections is not None and dev_uncorrectable is not None:
        entries = _device_entries(dev_detections, dev_uncorrectable, axes)
    faulty = [e for e in (entries or [])
              if e["detected"] or e["uncorrectable"]]
    ev = record_attention(
        op, result, strategy=strategy, step=step, device=device,
        extra=extra, devices=faulty, host=_process_index())
    if ev is not None and entries:
        _bump_device_counters(op, strategy, entries)
    return ev


def record_kv_page(outcome: str, *, op: str = "kv_page",
                   layer: Optional[str] = None,
                   device: Optional[str] = None,
                   detected: int = 0, corrected: int = 0,
                   uncorrectable: int = 0,
                   residual: Optional[float] = None,
                   tiles: Optional[list] = None,
                   extra: Optional[dict] = None) -> Optional[FaultEvent]:
    """Record one stored-state KV-page verification finding.

    The serving plane's third fault stream (after per-call GEMM reports
    and the recovery ladder): corruption detected in a CACHED page on
    read — ``corrected`` when repaired in place (single element located
    by the plain/weighted checksum-row pair, or a checksum row rebuilt),
    ``uncorrectable`` when the page needs the engine's restore ladder.
    ``tiles`` carries ``[page, row]`` blame coordinates and ``extra``
    the full ``(seq_id, layer, head, page)`` spelling plus the request's
    ``trace_id``, so one grep joins a decode request to the page that
    corrupted under it. Host-side by construction (the cache never
    touches a traced computation); never suppressed — like the ladder
    stream, it is not a call report."""
    if not _STATE.enabled:
        return None
    event = FaultEvent(
        outcome=outcome, op=op, detected=int(detected),
        corrected=int(corrected), uncorrectable=int(uncorrectable),
        step=_STATE.step, layer=layer, device=device,
        residual=residual, tiles=tiles, extra=extra, ts=time.time())
    _STATE.registry.counter("kv_page_events", op=op,
                            outcome=outcome).inc()
    _emit(event)
    return event


def record_step_event(outcome: str, *, op: str = "resilient_step",
                      step: Optional[int] = None,
                      uncorrectable: int = 0,
                      extra: Optional[dict] = None) -> Optional[FaultEvent]:
    """Record a training-loop recovery transition (``retry`` /
    ``restore`` / ``raise`` / ``exhausted``). Always host-side (the loop
    runs in Python); no-op when disabled, never suppressed (a different
    stream from call reports — see :func:`suppress`)."""
    if not _STATE.enabled:
        return None
    event = FaultEvent(
        outcome=outcome, op=op,
        uncorrectable=int(uncorrectable),
        step=_STATE.step if step is None else step, extra=extra,
        ts=time.time())
    _STATE.registry.counter(
        "ft_step_events", op=op, outcome=outcome).inc()
    _emit(event)
    return event


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "FaultEvent",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "OUTCOMES",
    "add_observer",
    "aggregate",
    "remove_observer",
    "timeline",
    "traceview",
    "configure",
    "disable",
    "enabled",
    "format_summary",
    "get_registry",
    "histogram_percentiles",
    "measure_output_residual",
    "parse_prometheus",
    "read_events",
    "record_attention",
    "record_gemm",
    "record_kv_page",
    "record_mesh_attention",
    "record_mesh_gemm",
    "record_step_event",
    "registry_from_events",
    "reset",
    "session",
    "set_step",
    "summarize_events",
    "suppress",
    "to_prometheus",
    "trace_span",
]
