"""Thread-safe metrics registry: counters, gauges, histograms.

The process-wide aggregation layer of the fault-telemetry subsystem
(:mod:`ft_sgemm_tpu.telemetry`). Metrics are keyed by ``(name, labels)``
where labels is a frozen set of ``key=value`` pairs — the Prometheus data
model, host-side only. Nothing here ever touches a JAX trace: recording
takes already-materialized Python/numpy scalars, so enabling or disabling
telemetry cannot change a jitted computation's HLO by construction (the
property ``tests/test_telemetry.py`` pins byte-for-byte).

Zero-overhead-off is enforced one layer up: :mod:`ft_sgemm_tpu.telemetry`
only calls into a registry when telemetry is enabled, and ops guard their
emission on ``telemetry.enabled()`` before doing any host transfer.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[dict]) -> LabelKey:
    """Canonical (sorted, stringified) label tuple for dict keying."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter for one ``(name, labels)`` series."""

    def __init__(self, name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value-wins gauge for one ``(name, labels)`` series."""

    def __init__(self, name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


# Default histogram buckets span the residual scales the stack actually
# produces: auto-calibrated thresholds land near 1e-2 on quantized data
# (analysis.estimate_noise_floor), the reference operating point at 9.5e3,
# injected faults at 1e4 — decades from 1e-6 up cover all of it, with a
# +inf overflow bucket so no observation is ever dropped.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    10.0 ** e for e in range(-6, 7)) + (float("inf"),)

# Latency histogram buckets (seconds) for the serving layer: half-decades
# from 10 µs to 100 s. Serve latencies span interpret-mode CPU smoke
# (hundreds of ms) down to prewarmed TPU dispatch (sub-ms); half-decade
# resolution keeps the Prometheus-style percentile estimates
# (:func:`histogram_percentiles`) within ~3x of the true value — good
# enough to gate an SLO on — while the series stays 16 buckets wide.
LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    10.0 ** (e / 2.0) for e in range(-10, 5)) + (float("inf"),)


class Histogram:
    """Fixed-bucket histogram (cumulative-style buckets, like Prometheus).

    ``observe(v)`` increments the first bucket whose upper bound is
    >= v; ``counts`` returns per-bucket (non-cumulative) counts plus
    running sum/count so means stay recoverable.
    """

    def __init__(self, name: str, labels: LabelKey,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        b = tuple(sorted(float(x) for x in buckets))
        if not b or b[-1] != float("inf"):
            b = b + (float("inf"),)
        self.name = name
        self.labels = labels
        self.buckets = b
        self._counts = [0] * len(b)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    break
            self._sum += v
            self._count += 1

    @property
    def value(self) -> dict:
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}


def histogram_percentiles(value: dict, quantiles=(0.5, 0.95)) -> dict:
    """Percentile estimates from a histogram's bucket counts.

    Takes a :attr:`Histogram.value` dict (non-cumulative per-bucket
    counts). Each quantile resolves to the upper bound of the first
    bucket whose cumulative count reaches it — the standard
    Prometheus-style estimate: exact to bucket resolution (decades
    here), never below the true percentile. Returns
    ``{"p50": ..., "p95": ..., "max": ...}``-shaped keys (one per
    requested quantile, plus ``max`` = the last nonempty bucket's upper
    bound); all None when the histogram is empty. ``inf`` means the
    observation landed in the overflow bucket."""
    buckets = value.get("buckets") or []
    counts = value.get("counts") or []
    total = sum(counts)
    out = {f"p{round(100 * q)}": None for q in quantiles}
    out["max"] = None
    if not total:
        return out
    for q in quantiles:
        need = q * total
        cum = 0
        for ub, n in zip(buckets, counts):
            cum += n
            if cum >= need:
                out[f"p{round(100 * q)}"] = ub
                break
    nonempty = [ub for ub, n in zip(buckets, counts) if n]
    out["max"] = nonempty[-1] if nonempty else None
    return out


def _prom_name(name: str) -> str:
    """Prometheus metric-name sanitization (dots and dashes to
    underscores; the exposition format allows ``[a-zA-Z_:][a-zA-Z0-9_:]*``)."""
    import re

    s = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return s if s and not s[0].isdigit() else "_" + s


def _prom_escape(v) -> str:
    """Label-value escaping per the exposition format: backslash first
    (so the other escapes aren't double-escaped), then quote and
    newline. Un-escaped newlines were the scrape-breaking bug the ISSUE-9
    satellite pins: one hostile label value would tear every later
    series off the same scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_unescape(v: str) -> str:
    out = []
    it = iter(v)
    for ch in it:
        if ch != "\\":
            out.append(ch)
            continue
        nxt = next(it, "")
        out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, "\\" + nxt))
    return "".join(out)


# ``# HELP`` strings for the families the stack emits. Prometheus
# scrapers (and humans reading a /metrics dump) get one line of intent
# per family; unknown names fall back to a generic string rather than
# omitting the line — the exposition stays uniformly self-describing.
_METRIC_HELP = {
    "ft_calls": "FT GEMM/attention calls recorded",
    "ft_detections": "ABFT fault detections (summed per-call counters)",
    "ft_corrected": "In-kernel corrected faults",
    "ft_uncorrectable": "Residual-after-correct failures (unverified output)",
    "ft_softmax_flags": "Attention softmax-stage invariant flags",
    "ft_residual": "Max |checksum residual| per measured call",
    "ft_step_events": "Recovery-ladder transitions (retry/restore/raise)",
    "ft_device_calls": "Per-device FT calls (mesh attribution)",
    "ft_device_detections": "Per-device fault detections (mesh attribution)",
    "ft_device_uncorrectable": "Per-device uncorrectable faults",
    "serve_requests": "Serve requests accepted per bucket",
    "serve_batches": "Serve batches flushed per bucket",
    "serve_retries": "Bucket-scoped serve retries",
    "serve_rejected": "Requests rejected (bucket overflow)",
    "serve_corrected_free": "Requests whose SDC was corrected in-kernel",
    "serve_uncorrectable_exhausted": "Requests still uncorrectable "
                                    "after bounded retries",
    "serve_latency_seconds": "End-to-end serve request latency",
    "serve_block_requests": "Transformer-block requests accepted per "
                            "bucket and phase",
    "serve_block_batches": "Block-serving batches flushed per bucket",
    "serve_block_retries": "Bucket-scoped block-serving retries "
                           "(in-flight attention faults)",
    "serve_block_rejected": "Block requests rejected (bucket overflow)",
    "serve_block_corrected_free": "Block requests whose fault (in flight "
                                  "or stored) was corrected en route",
    "serve_block_uncorrectable_exhausted": "Block requests still "
                                           "unverified after bounded "
                                           "retries",
    "serve_block_tokens": "Correct output tokens served "
                          "(prefill length + one per decode)",
    "serve_block_tokens_per_second": "Tokens-correct-per-second since "
                                     "the first block request",
    "serve_block_latency_seconds": "End-to-end block request latency",
    "kv_page_reads": "KV-cache stream reads (each verifies every page)",
    "kv_page_writes": "KV-cache appends (each reseals its page's "
                      "checksum rows)",
    "kv_page_faults": "Stored KV pages whose checksums flagged on read",
    "kv_page_corrected": "KV-page faults corrected in place "
                         "(single-element / checksum-row rebuild)",
    "kv_page_restores": "KV pages restored from source by the "
                        "page-scoped retry ladder",
    "kv_page_events": "kv_page fault events recorded, by outcome",
    "kv_verify_hit_rate": "Fraction of page verifications that came "
                          "back clean (1 = no stored-state faults)",
    "slo_budget_remaining": "Fraction of the rolling-window SLO error "
                            "budget left (0 = exhausted)",
    "slo_burn_rate": "SLO violation rate over allowed rate (>=1 burns "
                     "budget faster than allowed)",
    "slo_window_requests": "Requests inside the rolling SLO window",
    "slo_goodput_ratio": "OK-and-within-latency fraction of the window",
    "device_health": "Continuous per-device health score in (0, 1] "
                     "(1 = healthy; see DESIGN.md §12)",
    "device_health_drift": "Residual-distribution drift z-score per "
                           "device (creep toward the threshold)",
    "tuner_measurements": "Tuner candidate measurements taken",
    "tuner_failures": "Tuner candidate measurements that failed",
    "tuner_candidate_gflops": "Last measured GFLOP/s per tuner candidate",
    "tuner_cache_lookups": "Tile-cache dispatch lookups by hit/miss",
    "compile_cache_enabled": "Whether the persistent XLA compile cache "
                             "is active (1) or off (0)",
    "wall_total_seconds": "Total wall seconds attributed by the "
                          "timeline phase rollup",
    "lint_findings": "Static contract checker findings (cli lint)",
    "lint_seconds": "Static contract checker runtime",
    "fault_detection_latency_seconds": "Injection-to-detection latency "
                                       "per chaos fault model",
}

# Dynamically-named families (``wall.{phase}_seconds``,
# ``compile.{key}``, ``hlo.{attr}`` ...) get one curated string per
# PREFIX — longest prefix wins at lookup. The lint telemetry-schema
# pass requires every emitted family name (or its static f-string
# prefix) to resolve through _METRIC_HELP or this table, so a new
# metric cannot ship with only the generic fallback text.
_METRIC_HELP_PREFIXES = {
    "wall_": "Wall-clock phase attribution (perf/wallclock.py)",
    "compile_": "Compile probe facts (perf/hlo.py wall/cost analysis)",
    "compile_cache_": "Persistent XLA compile-cache counters "
                      "(perf/compile_cache.py)",
    "hlo_": "Optimized-HLO census facts (perf/hlo.py)",
    "tuner_": "Autotuner search/measurement counters",
    "lint_": "Static contract checker facts (ft_sgemm_tpu/lint)",
    "serve_pool_": "Multi-device serve pool: per-device placement/"
                   "queue-depth/in-flight gauges (serve/pool.py)",
    "recovery_": "Elastic recovery: data-plane checksum tier checks, "
                 "recompute-ladder rungs, and device evictions "
                 "(ft_sgemm_tpu/resilience)",
    "fleet_": "Fleet runtime: cross-host dispatch, host-slot blame/"
              "eviction, and live shard-merge counters "
              "(ft_sgemm_tpu/fleet)",
    "chaos_": "Chaos campaign: per-cell fault episodes, detections, "
              "and clean-twin outcomes (ft_sgemm_tpu/chaos)",
    "economics_": "Request cost economics: useful-vs-overhead flops "
                  "fractions and tokens-correct throughput "
                  "(perf/economics.py)",
    "coverage_": "Chaos coverage matrix rollups: per-model detection/"
                 "correction rates and latency facts "
                 "(ft_sgemm_tpu/chaos)",
}


def _metric_help(name: str) -> str:
    """The curated HELP string for one prom-sanitized family name:
    exact entry, else longest matching prefix entry, else the generic
    fallback (kept so foreign series still render self-describing)."""
    if name in _METRIC_HELP:
        return _METRIC_HELP[name]
    best = None
    for prefix, text in _METRIC_HELP_PREFIXES.items():
        if name.startswith(prefix) and (best is None
                                        or len(prefix) > len(best[0])):
            best = (prefix, text)
    if best is not None:
        return best[1]
    return f"ft_sgemm_tpu metric {name}"


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{_prom_name(k)}="{_prom_escape(v)}"'
                    for k, v in sorted(merged.items()))
    return "{" + body + "}"


def _prom_num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def to_prometheus(series: Sequence[dict]) -> str:
    """Render a :meth:`MetricsRegistry.collect` snapshot in the
    Prometheus text exposition format (v0.0.4).

    Counters and gauges map directly; histograms emit the standard
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.
    Metric names are sanitized (``compile.seconds`` ->
    ``compile_seconds``); ``# HELP`` and ``# TYPE`` lines precede each
    metric family once, and label values are fully escaped
    (backslash/quote/newline) — :func:`parse_prometheus` round-trips the
    output, the scrape-cleanliness contract the tests pin."""
    by_name: dict = {}
    for s in series:
        by_name.setdefault((_prom_name(s["name"]), s["kind"]), []).append(s)
    lines = []
    for (name, kind), group in sorted(by_name.items()):
        prom_kind = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}.get(kind, "untyped")
        help_text = _metric_help(name).replace(
            "\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {prom_kind}")
        for s in group:
            labels = s.get("labels") or {}
            v = s["value"]
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_prom_labels(labels)} {_prom_num(v)}")
                continue
            cum = 0
            for ub, n in zip(v["buckets"], v["counts"]):
                cum += n
                le = _prom_labels(labels, {"le": _prom_num(ub)})
                lines.append(f"{name}_bucket{le} {cum}")
            lines.append(f"{name}_sum{_prom_labels(labels)} "
                         f"{_prom_num(v['sum'])}")
            lines.append(f"{name}_count{_prom_labels(labels)} "
                         f"{v['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def _parse_label_block(block: str) -> dict:
    """Parse ``{k="v",...}`` honoring escaped quotes/backslashes/newlines."""
    import re

    if not block:
        return {}
    labels = {}
    for m in re.finditer(r'([a-zA-Z_:][a-zA-Z0-9_:]*)="((?:[^"\\]|\\.)*)"',
                         block):
        labels[m.group(1)] = _prom_unescape(m.group(2))
    return labels


def _parse_num(tok: str) -> float:
    if tok == "+Inf":
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    return float(tok)


def parse_prometheus(text: str) -> list:
    """Parse a text-exposition document back into
    :meth:`MetricsRegistry.collect`-shaped series dicts.

    The inverse of :func:`to_prometheus` — used by the round-trip test
    that pins the exposition scrape-clean, and by ``cli top``, which
    scrapes a live ``/metrics`` endpoint and reconstructs the registry
    view a remote process holds. Histogram ``_bucket``/``_sum``/
    ``_count`` sample families reassemble into one histogram series with
    NON-cumulative counts (the in-process representation); counters with
    integral values come back as ints. Raises ``ValueError`` on a line
    that is neither a comment nor a well-formed sample — a torn scrape
    should be loud, not silently half-parsed."""
    import re

    kinds: dict = {}
    samples = []  # (name, labels, value) in document order
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$")
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) == 4:
                kinds[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # HELP / foreign comments
        m = sample_re.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, block, num = m.groups()
        samples.append((name, _parse_label_block(block or ""),
                        _parse_num(num)))

    hist_names = {n for n, k in kinds.items() if k == "histogram"}
    out = []
    hists: dict = {}  # (name, labelkey) -> {"buckets": {...}, ...}
    for name, labels, value in samples:
        base = None
        part = None
        for nm in hist_names:
            if name == nm + "_bucket" and "le" in labels:
                base, part = nm, "bucket"
            elif name == nm + "_sum":
                base, part = nm, "sum"
            elif name == nm + "_count":
                base, part = nm, "count"
            if base:
                break
        if base is None:
            kind = kinds.get(name, "gauge")
            v = value
            if kind == "counter" and float(v).is_integer():
                v = int(v)
            out.append({"kind": "counter" if kind == "counter" else "gauge",
                        "name": name, "labels": dict(labels), "value": v})
            continue
        key_labels = {k: v for k, v in labels.items() if k != "le"}
        key = (base, tuple(sorted(key_labels.items())))
        h = hists.setdefault(key, {"labels": key_labels, "buckets": {},
                                   "sum": 0.0, "count": 0})
        if part == "bucket":
            h["buckets"][_parse_num(labels["le"])] = int(value)
        elif part == "sum":
            h["sum"] = value
        else:
            h["count"] = int(value)
    for (base, _), h in hists.items():
        ubs = sorted(h["buckets"])
        cum = [h["buckets"][ub] for ub in ubs]
        counts = [c - (cum[i - 1] if i else 0) for i, c in enumerate(cum)]
        out.append({"kind": "histogram", "name": base,
                    "labels": dict(h["labels"]),
                    "value": {"buckets": ubs, "counts": counts,
                              "sum": h["sum"], "count": h["count"]}})
    return out


class MetricsRegistry:
    """Process-wide metric store, thread-safe, keyed by name + labels.

    ``counter``/``gauge``/``histogram`` get-or-create a series (same
    name+labels always returns the same object, so hot paths may cache
    the handle). ``collect`` snapshots everything for export or the CLI
    summarizer; ``total`` aggregates one counter name across all label
    sets, optionally filtered (the query the re-run gates and tests ask).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, str, LabelKey], object] = {}

    def _get(self, kind: str, cls, name: str, labels: Optional[dict],
             **kw):
        key = (kind, name, _label_key(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = cls(name, key[2], **kw)
                self._series[key] = s
            return s

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels,
                         buckets=buckets)

    def collect(self) -> list[dict]:
        """Snapshot: one dict per series (kind, name, labels, value)."""
        with self._lock:
            series = list(self._series.items())
        return [{"kind": kind, "name": name, "labels": dict(labels),
                 "value": s.value}
                for (kind, name, labels), s in series]

    def total(self, name: str, **label_filter) -> int:
        """Sum a counter across every label set matching the filter.

        ``total("ft_detections", op="ft_sgemm")`` sums all strategies /
        layers / devices of that op; no filter sums everything under the
        name. Missing series sum to 0 (absence is a real answer).
        """
        want = {str(k): str(v) for k, v in label_filter.items()}
        out = 0
        with self._lock:
            series = list(self._series.items())
        for (kind, nm, labels), s in series:
            if kind != "counter" or nm != name:
                continue
            have = dict(labels)
            if all(have.get(k) == v for k, v in want.items()):
                out += s.value
        return out

    def reset(self) -> None:
        """Drop every series (tests; between independent runs)."""
        with self._lock:
            self._series.clear()


__all__ = ["Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram",
           "LATENCY_BUCKETS", "MetricsRegistry", "histogram_percentiles",
           "parse_prometheus", "to_prometheus"]
