"""Thread-safe metrics registry: counters, gauges, histograms.

The process-wide aggregation layer of the fault-telemetry subsystem
(:mod:`ft_sgemm_tpu.telemetry`). Metrics are keyed by ``(name, labels)``
where labels is a frozen set of ``key=value`` pairs — the Prometheus data
model, host-side only. Nothing here ever touches a JAX trace: recording
takes already-materialized Python/numpy scalars, so enabling or disabling
telemetry cannot change a jitted computation's HLO by construction (the
property ``tests/test_telemetry.py`` pins byte-for-byte).

Zero-overhead-off is enforced one layer up: :mod:`ft_sgemm_tpu.telemetry`
only calls into a registry when telemetry is enabled, and ops guard their
emission on ``telemetry.enabled()`` before doing any host transfer.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[dict]) -> LabelKey:
    """Canonical (sorted, stringified) label tuple for dict keying."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter for one ``(name, labels)`` series."""

    def __init__(self, name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value-wins gauge for one ``(name, labels)`` series."""

    def __init__(self, name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


# Default histogram buckets span the residual scales the stack actually
# produces: auto-calibrated thresholds land near 1e-2 on quantized data
# (analysis.estimate_noise_floor), the reference operating point at 9.5e3,
# injected faults at 1e4 — decades from 1e-6 up cover all of it, with a
# +inf overflow bucket so no observation is ever dropped.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    10.0 ** e for e in range(-6, 7)) + (float("inf"),)

# Latency histogram buckets (seconds) for the serving layer: half-decades
# from 10 µs to 100 s. Serve latencies span interpret-mode CPU smoke
# (hundreds of ms) down to prewarmed TPU dispatch (sub-ms); half-decade
# resolution keeps the Prometheus-style percentile estimates
# (:func:`histogram_percentiles`) within ~3x of the true value — good
# enough to gate an SLO on — while the series stays 16 buckets wide.
LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    10.0 ** (e / 2.0) for e in range(-10, 5)) + (float("inf"),)


class Histogram:
    """Fixed-bucket histogram (cumulative-style buckets, like Prometheus).

    ``observe(v)`` increments the first bucket whose upper bound is
    >= v; ``counts`` returns per-bucket (non-cumulative) counts plus
    running sum/count so means stay recoverable.
    """

    def __init__(self, name: str, labels: LabelKey,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        b = tuple(sorted(float(x) for x in buckets))
        if not b or b[-1] != float("inf"):
            b = b + (float("inf"),)
        self.name = name
        self.labels = labels
        self.buckets = b
        self._counts = [0] * len(b)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    break
            self._sum += v
            self._count += 1

    @property
    def value(self) -> dict:
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}


def histogram_percentiles(value: dict, quantiles=(0.5, 0.95)) -> dict:
    """Percentile estimates from a histogram's bucket counts.

    Takes a :attr:`Histogram.value` dict (non-cumulative per-bucket
    counts). Each quantile resolves to the upper bound of the first
    bucket whose cumulative count reaches it — the standard
    Prometheus-style estimate: exact to bucket resolution (decades
    here), never below the true percentile. Returns
    ``{"p50": ..., "p95": ..., "max": ...}``-shaped keys (one per
    requested quantile, plus ``max`` = the last nonempty bucket's upper
    bound); all None when the histogram is empty. ``inf`` means the
    observation landed in the overflow bucket."""
    buckets = value.get("buckets") or []
    counts = value.get("counts") or []
    total = sum(counts)
    out = {f"p{round(100 * q)}": None for q in quantiles}
    out["max"] = None
    if not total:
        return out
    for q in quantiles:
        need = q * total
        cum = 0
        for ub, n in zip(buckets, counts):
            cum += n
            if cum >= need:
                out[f"p{round(100 * q)}"] = ub
                break
    nonempty = [ub for ub, n in zip(buckets, counts) if n]
    out["max"] = nonempty[-1] if nonempty else None
    return out


def _prom_name(name: str) -> str:
    """Prometheus metric-name sanitization (dots and dashes to
    underscores; the exposition format allows ``[a-zA-Z_:][a-zA-Z0-9_:]*``)."""
    import re

    s = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return s if s and not s[0].isdigit() else "_" + s


def _prom_escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{_prom_name(k)}="{_prom_escape(v)}"'
                    for k, v in sorted(merged.items()))
    return "{" + body + "}"


def _prom_num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def to_prometheus(series: Sequence[dict]) -> str:
    """Render a :meth:`MetricsRegistry.collect` snapshot in the
    Prometheus text exposition format (v0.0.4).

    Counters and gauges map directly; histograms emit the standard
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.
    Metric names are sanitized (``compile.seconds`` ->
    ``compile_seconds``); a ``# TYPE`` line precedes each metric family
    once."""
    by_name: dict = {}
    for s in series:
        by_name.setdefault((_prom_name(s["name"]), s["kind"]), []).append(s)
    lines = []
    for (name, kind), group in sorted(by_name.items()):
        prom_kind = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}.get(kind, "untyped")
        lines.append(f"# TYPE {name} {prom_kind}")
        for s in group:
            labels = s.get("labels") or {}
            v = s["value"]
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_prom_labels(labels)} {_prom_num(v)}")
                continue
            cum = 0
            for ub, n in zip(v["buckets"], v["counts"]):
                cum += n
                le = _prom_labels(labels, {"le": _prom_num(ub)})
                lines.append(f"{name}_bucket{le} {cum}")
            lines.append(f"{name}_sum{_prom_labels(labels)} "
                         f"{_prom_num(v['sum'])}")
            lines.append(f"{name}_count{_prom_labels(labels)} "
                         f"{v['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsRegistry:
    """Process-wide metric store, thread-safe, keyed by name + labels.

    ``counter``/``gauge``/``histogram`` get-or-create a series (same
    name+labels always returns the same object, so hot paths may cache
    the handle). ``collect`` snapshots everything for export or the CLI
    summarizer; ``total`` aggregates one counter name across all label
    sets, optionally filtered (the query the re-run gates and tests ask).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, str, LabelKey], object] = {}

    def _get(self, kind: str, cls, name: str, labels: Optional[dict],
             **kw):
        key = (kind, name, _label_key(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = cls(name, key[2], **kw)
                self._series[key] = s
            return s

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels,
                         buckets=buckets)

    def collect(self) -> list[dict]:
        """Snapshot: one dict per series (kind, name, labels, value)."""
        with self._lock:
            series = list(self._series.items())
        return [{"kind": kind, "name": name, "labels": dict(labels),
                 "value": s.value}
                for (kind, name, labels), s in series]

    def total(self, name: str, **label_filter) -> int:
        """Sum a counter across every label set matching the filter.

        ``total("ft_detections", op="ft_sgemm")`` sums all strategies /
        layers / devices of that op; no filter sums everything under the
        name. Missing series sum to 0 (absence is a real answer).
        """
        want = {str(k): str(v) for k, v in label_filter.items()}
        out = 0
        with self._lock:
            series = list(self._series.items())
        for (kind, nm, labels), s in series:
            if kind != "counter" or nm != name:
                continue
            have = dict(labels)
            if all(have.get(k) == v for k, v in want.items()):
                out += s.value
        return out

    def reset(self) -> None:
        """Drop every series (tests; between independent runs)."""
        with self._lock:
            self._series.clear()


__all__ = ["Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram",
           "LATENCY_BUCKETS", "MetricsRegistry", "histogram_percentiles",
           "to_prometheus"]
