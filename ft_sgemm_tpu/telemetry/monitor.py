"""Live serving observability plane: /metrics, /healthz, /events, SLO
error budgets, and continuous device-health scoring.

Every telemetry surface before this module was post-hoc file analysis —
JSONL events, timelines, ``cli attribute``, ``--format=prom`` over a
finished log. Production fleet screening (the deployment story of online
ABFT, arXiv 2305.01024 / V-ABFT 2602.08043) needs the inverse: a live
plane a scraper can poll and an operator can alert on WHILE traffic
flows, so a degrading device is pulled before it ships corrupted
output. Four coupled pieces:

1. :class:`EventRing` — a bounded ring of recent fault events with
   monotone sequence numbers; ``/events?since=SEQ`` streams it as JSON,
   so the trace-ID join (request -> tile/device blame -> retry outcome)
   is assertable against a LIVE endpoint, not just a log file.
2. :class:`SloTracker` — rolling-window p99-latency + goodput
   objectives with an error budget: ``slo_budget_remaining`` /
   ``slo_burn_rate`` gauges, and a threshold-crossing ``alert`` event
   emitted into the normal JSONL stream when the burn rate first
   exceeds 1x (re-armed after recovery — alerts are edges, not levels).
3. :class:`DeviceHealthTracker` — continuous per-device scoring. Fault
   counters come from the serving engine's direct feed and (for mesh
   runs) from the registry's ``ft_device_*`` attribution series; clean-
   check residuals feed a streaming ``(n, sum, sumsq)`` moment
   accumulator per device — the PR-7 adaptive-threshold moment layout,
   host-side — plus an EWMA recent window, so residual DRIFT (creep
   toward the detection threshold) flags a device before it throws
   uncorrectables. The score is ``exp(-(w_det*det_rate +
   w_unc*unc_rate + w_drift*min(drift_z, cap)))`` in (0, 1]
   (DESIGN.md §12), exported as ``device_health{device=...}``.
4. :class:`MonitorServer` — a threaded stdlib ``http.server`` exposing
   ``/metrics`` (the registry's full Prometheus exposition, monitor
   gauges refreshed per scrape), ``/healthz`` (OK / DEGRADED / FAILING
   with named reasons; 503 on FAILING), and ``/events?since=``.

HARD CONSTRAINT — stdlib only at module scope, no package imports: like
``telemetry/timeline.py`` this file must be loadable by path in a
jax-free process (exporting metrics must never require a backend).
In-package collaborators (the metrics registry, ``to_prometheus``, the
``alert`` event emitter) are resolved lazily inside methods and can be
injected explicitly for standalone use.
"""

from __future__ import annotations

import collections
import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Event ring buffer
# ---------------------------------------------------------------------------


class EventRing:
    """Bounded ring of recent event dicts with monotone sequence numbers.

    ``append`` assigns the next sequence number; ``since(seq)`` returns
    every retained event with a HIGHER sequence, oldest first, plus the
    cursor to pass next time — the standard resumable-poll contract.
    Events older than the capacity are gone (the ring bounds memory; the
    JSONL sink is the durable record)."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"EventRing capacity={capacity} must be >= 1")
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def append(self, event: dict) -> int:
        with self._lock:
            self._seq += 1
            rec = dict(event)
            rec["seq"] = self._seq
            self._buf.append(rec)
            return self._seq

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def since(self, seq: int = 0,
              limit: Optional[int] = None) -> Tuple[List[dict], int]:
        with self._lock:
            out = [dict(r) for r in self._buf if r["seq"] > seq]
            cursor = self._seq
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out, cursor


# ---------------------------------------------------------------------------
# SLO error budget
# ---------------------------------------------------------------------------


class SloConfig:
    """One serving SLO: a p99-latency objective, a goodput objective,
    and the error budget that prices violations.

    ``budget`` is the fraction of a rolling window's requests allowed to
    violate either objective (miss the latency target, or complete
    not-OK). ``burn_rate = violation_fraction / budget``: 1.0 means the
    budget is being consumed exactly as fast as allowed; ``
    budget_remaining = max(0, 1 - burn_rate)``. Defaults are deliberately
    loose (30 s p99, 1% budget) — CPU interpret-mode smoke traffic must
    come up OK; production deployments pass their own."""

    def __init__(self, *, p99_latency_seconds: float = 30.0,
                 goodput_target: float = 0.99,
                 window_seconds: float = 600.0,
                 budget: float = 0.01,
                 failing_burn_rate: float = 10.0):
        if not (0.0 < budget <= 1.0):
            raise ValueError(f"SloConfig.budget={budget} must be in (0, 1]")
        self.p99_latency_seconds = float(p99_latency_seconds)
        self.goodput_target = float(goodput_target)
        self.window_seconds = float(window_seconds)
        self.budget = float(budget)
        self.failing_burn_rate = float(failing_burn_rate)

    def to_dict(self) -> dict:
        return {"p99_latency_seconds": self.p99_latency_seconds,
                "goodput_target": self.goodput_target,
                "window_seconds": self.window_seconds,
                "budget": self.budget,
                "failing_burn_rate": self.failing_burn_rate}


class SloTracker:
    """Rolling-window SLO accounting with edge-triggered alerts.

    ``record(latency_seconds, ok)`` per completed request; a request
    violates the SLO when it is not OK or exceeds the latency objective.
    ``on_alert`` (set by :class:`Monitor`) fires once when the burn rate
    crosses 1.0 upward and re-arms when it falls back under 0.5 — a
    flapping burn emits edges, not a level per request."""

    def __init__(self, config: Optional[SloConfig] = None,
                 on_alert: Optional[Callable[[dict], None]] = None):
        self.config = config or SloConfig()
        self.on_alert = on_alert
        self._lock = threading.Lock()
        self._window: collections.deque = collections.deque()
        self._alerted = False
        self._total = 0
        self._total_violations = 0

    def _trim(self, now: float) -> None:
        horizon = now - self.config.window_seconds
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def record(self, latency_seconds: float, ok: bool,
               now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        violation = (not ok) or (
            latency_seconds > self.config.p99_latency_seconds)
        fire = None
        with self._lock:
            self._window.append((now, float(latency_seconds), bool(ok),
                                 violation))
            self._trim(now)
            self._total += 1
            self._total_violations += int(violation)
            snap = self._snapshot_locked()
            if snap["burn_rate"] >= 1.0 and not self._alerted:
                self._alerted = True
                fire = snap
            elif snap["burn_rate"] < 0.5 and self._alerted:
                self._alerted = False
        if fire is not None and self.on_alert is not None:
            try:
                self.on_alert(fire)
            except Exception:  # noqa: BLE001 — alerting must not break serving
                pass

    def _snapshot_locked(self) -> dict:
        n = len(self._window)
        violations = sum(1 for *_, v in self._window if v)
        ok_within = sum(1 for _, lat, ok, v in self._window
                        if ok and not v)
        frac = violations / n if n else 0.0
        burn = frac / self.config.budget
        lats = sorted(lat for _, lat, _, _ in self._window)
        p99 = lats[min(n - 1, int(math.ceil(0.99 * n)) - 1)] if n else None
        return {
            "requests": n,
            "violations": violations,
            "violation_fraction": round(frac, 6),
            "burn_rate": round(burn, 6),
            "budget_remaining": round(max(0.0, 1.0 - burn), 6),
            "goodput_ratio": round(ok_within / n, 6) if n else None,
            "observed_p99_seconds": (round(p99, 6)
                                     if p99 is not None else None),
            "objectives": self.config.to_dict(),
            "total_requests": self._total,
            "total_violations": self._total_violations,
        }

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            self._trim(now)
            return self._snapshot_locked()


# ---------------------------------------------------------------------------
# Device health
# ---------------------------------------------------------------------------


class _Moments:
    """Streaming ``(n, sum, sumsq)`` — the PR-7 adaptive-threshold moment
    accumulator layout (``ops/common.variance_bound_threshold`` consumes
    exactly these three numbers), kept host-side per device."""

    __slots__ = ("n", "sum", "sumsq")

    def __init__(self):
        self.n = 0
        self.sum = 0.0
        self.sumsq = 0.0

    def observe(self, v: float) -> None:
        self.n += 1
        self.sum += v
        self.sumsq += v * v

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    @property
    def variance(self) -> float:
        if self.n < 2:
            return 0.0
        return max(0.0, self.sumsq / self.n - self.mean ** 2)

    @property
    def std(self) -> float:
        return math.sqrt(self.variance)


class HealthConfig:
    """Score weights and thresholds (the DESIGN.md §12 formula).

    ``score = exp(-(w_det * det_rate + w_unc * unc_rate
                    + w_drift * min(max(0, drift_z - drift_grace),
                                    drift_cap)))``

    with rates per call and ``drift_z`` the z-score of the recent
    (EWMA) residual mean against the device's long-run baseline —
    nonzero only after ``drift_min_n`` baseline observations, so a cold
    tracker never cries wolf. ``drift_grace`` eats the EWMA's own
    sampling noise (an EWMA over a stationary stream wanders ~1 sigma;
    only drift BEYOND the grace margin is creep, not jitter).
    ``degraded_below`` / ``failing_below`` map scores onto the /healthz
    ladder; a device only reaches FAILING with uncorrectable faults on
    the books (corrected detections alone can at worst degrade — they
    were, after all, corrected)."""

    def __init__(self, *, w_det: float = 1.0, w_unc: float = 4.0,
                 w_drift: float = 0.5, drift_grace: float = 1.0,
                 drift_cap: float = 8.0,
                 drift_min_n: int = 20, ewma_alpha: float = 0.2,
                 degraded_below: float = 0.9, failing_below: float = 0.2):
        self.w_det = w_det
        self.w_unc = w_unc
        self.w_drift = w_drift
        self.drift_grace = drift_grace
        self.drift_cap = drift_cap
        self.drift_min_n = drift_min_n
        self.ewma_alpha = ewma_alpha
        self.degraded_below = degraded_below
        self.failing_below = failing_below


class DeviceHealthTracker:
    """Continuous per-device health from counters + residual drift.

    Two count feeds, summed per device: the DIRECT feed
    (:meth:`observe` — the serving engine's per-request attribution,
    single device) and the SYNCED feed (:meth:`sync_counts` — absolute
    totals read from the registry's ``ft_device_*`` series, the mesh
    attribution path, overwritten per refresh so re-scrapes never
    double-count). Residuals (:meth:`observe_residual`) feed the
    baseline moments and the EWMA recent window that drift detection
    compares."""

    def __init__(self, config: Optional[HealthConfig] = None):
        self.config = config or HealthConfig()
        self._lock = threading.Lock()
        self._direct: Dict[str, dict] = {}
        self._synced: Dict[str, dict] = {}
        self._resid: Dict[str, dict] = {}

    def observe(self, device: str, *, calls: int = 1, detected: int = 0,
                uncorrectable: int = 0,
                residual: Optional[float] = None) -> None:
        device = str(device)
        with self._lock:
            row = self._direct.setdefault(
                device, {"calls": 0, "detected": 0, "uncorrectable": 0})
            row["calls"] += int(calls)
            row["detected"] += int(detected)
            row["uncorrectable"] += int(uncorrectable)
        if residual is not None:
            self.observe_residual(device, residual)

    def observe_residual(self, device: str, residual: float) -> None:
        device = str(device)
        v = float(residual)
        if not math.isfinite(v):
            return
        cfg = self.config
        with self._lock:
            row = self._resid.setdefault(
                device, {"baseline": _Moments(), "ewma": None})
            row["baseline"].observe(v)
            prev = row["ewma"]
            row["ewma"] = (v if prev is None
                           else (1 - cfg.ewma_alpha) * prev
                           + cfg.ewma_alpha * v)

    def sync_counts(self, device: str, *, calls: int, detected: int,
                    uncorrectable: int) -> None:
        """Absolute counter totals for one device (registry-derived;
        idempotent — last write wins, so scraping twice changes nothing)."""
        with self._lock:
            self._synced[str(device)] = {
                "calls": int(calls), "detected": int(detected),
                "uncorrectable": int(uncorrectable)}

    def _counts(self, device: str) -> dict:
        d = self._direct.get(device, {})
        s = self._synced.get(device, {})
        return {k: d.get(k, 0) + s.get(k, 0)
                for k in ("calls", "detected", "uncorrectable")}

    def drift_z(self, device: str) -> float:
        cfg = self.config
        row = self._resid.get(str(device))
        if row is None:
            return 0.0
        base = row["baseline"]
        if base.n < cfg.drift_min_n or row["ewma"] is None:
            return 0.0
        spread = base.std + 1e-12 * (1.0 + abs(base.mean))
        return max(0.0, (row["ewma"] - base.mean) / spread)

    def score(self, device: str) -> float:
        device = str(device)
        cfg = self.config
        with self._lock:
            counts = self._counts(device)
            drift = self.drift_z(device)
        calls = max(1, counts["calls"])
        det_rate = counts["detected"] / calls
        unc_rate = counts["uncorrectable"] / calls
        creep = min(max(0.0, drift - cfg.drift_grace), cfg.drift_cap)
        return math.exp(-(cfg.w_det * det_rate + cfg.w_unc * unc_rate
                          + cfg.w_drift * creep))

    def devices(self) -> List[str]:
        with self._lock:
            return sorted(set(self._direct) | set(self._synced)
                          | set(self._resid))

    def scores(self) -> Dict[str, float]:
        return {dev: round(self.score(dev), 6) for dev in self.devices()}

    def rows(self) -> Dict[str, dict]:
        """Full per-device view: counts, score, drift — the /healthz
        reason source and the artifact's ``device_health`` section."""
        out = {}
        for dev in self.devices():
            with self._lock:
                counts = self._counts(dev)
                drift = self.drift_z(dev)
            out[dev] = {**counts, "drift_z": round(drift, 4),
                        "score": round(self.score(dev), 6)}
        return out


# ---------------------------------------------------------------------------
# Monitor: the in-process aggregator the HTTP plane serves
# ---------------------------------------------------------------------------

STATUSES = ("OK", "DEGRADED", "FAILING")

# Ops whose events reach the ring DIRECTLY — the serving engine's
# observe_request/observe_retry feed and the monitor's own alerts — so
# the telemetry-observer path must skip them (one event, one ring entry;
# the monitor's record_step_event("alert") would otherwise echo back
# through the observer it itself registered).
# Ops the serving engines feed DIRECTLY (observe_request /
# observe_retry) — the telemetry-observer path skips them so one
# request never lands twice. serve_block and kv_page joined in PR 12
# (the block engine mirrors the GEMM engine's direct feed).
_SERVE_OPS = ("serve_gemm", "serve", "serve_block", "kv_page", "monitor",
              "serve_pool")


class Monitor:
    """The live observability aggregator: ring + SLO + device health,
    wired to the metrics registry and (optionally) the telemetry event
    stream.

    Feeds:

    - :meth:`observe_request` / :meth:`observe_retry` — the serving
      engine's direct per-request feed (works with telemetry fully
      disabled; the serving plane must be monitorable on its own).
    - :meth:`ingest_event` — a telemetry observer
      (:func:`ft_sgemm_tpu.telemetry.add_observer`) receiving every
      recorded FaultEvent; non-serve events (mesh attribution, training
      ladders) land in the ring and feed device health from their
      ``devices`` entries. Serve-op events are skipped here — the engine
      already fed them directly.
    - :meth:`refresh_gauges` — scrape-time: pulls ``ft_device_*``
      absolute counters from the registry (the mesh path's per-device
      attribution), recomputes scores, and (re)sets the ``slo_*`` and
      ``device_health*`` gauges, so one exporter path serves everything.

    ``registry``/``render``/``emit_alert`` default to the in-package
    telemetry machinery (lazy import); inject them for standalone use of
    a path-loaded module.
    """

    def __init__(self, *, registry=None, slo: Optional[SloConfig] = None,
                 health: Optional[HealthConfig] = None,
                 ring_capacity: int = 512,
                 render: Optional[Callable] = None,
                 emit_alert: Optional[Callable[[dict], None]] = None):
        self.ring = EventRing(ring_capacity)
        self.health = DeviceHealthTracker(health)
        self.slo = SloTracker(slo, on_alert=self._slo_alert)
        self._registry = registry
        self._render = render
        self._emit_alert = emit_alert
        self._attached = False
        self._health_alerted: set = set()
        self._economics: Optional[dict] = None
        self.started_unix = time.time()

    # -- collaborators (lazy, injectable) -----------------------------------

    def registry(self):
        if self._registry is None:
            from ft_sgemm_tpu import telemetry

            self._registry = telemetry.get_registry()
        return self._registry

    def _render_fn(self):
        if self._render is None:
            from ft_sgemm_tpu.telemetry.registry import to_prometheus

            self._render = to_prometheus
        return self._render

    def _alert(self, kind: str, extra: dict) -> None:
        """One ``alert`` event: into the ring always, into the normal
        JSONL/telemetry stream when available."""
        rec = {"outcome": "alert", "op": "monitor", "ts": time.time(),
               "extra": {"kind": kind, **extra}}
        self.ring.append(rec)
        emit = self._emit_alert
        if emit is not None:
            try:
                emit(rec)
            except Exception:  # noqa: BLE001
                pass
            return
        try:
            from ft_sgemm_tpu import telemetry

            telemetry.record_step_event(
                "alert", op="monitor", extra=rec["extra"])
        except Exception:  # noqa: BLE001 — alerting never breaks serving
            pass

    def _slo_alert(self, snapshot: dict) -> None:
        self._alert("slo_burn", {
            "burn_rate": snapshot["burn_rate"],
            "budget_remaining": snapshot["budget_remaining"],
            "violation_fraction": snapshot["violation_fraction"],
            "requests": snapshot["requests"],
            "objectives": snapshot["objectives"]})

    # -- feeds --------------------------------------------------------------

    def observe_request(self, info: dict) -> None:
        """One completed serve request (the engine's direct feed).

        ``info`` is the serve_gemm event payload shape: outcome, op,
        detected/uncorrectable, tiles, device, and an ``extra`` carrying
        trace_id / request_id / bucket / variant / retries /
        latency_seconds / ok."""
        self.ring.append(info)
        extra = info.get("extra") or {}
        lat = extra.get("latency_seconds")
        ok = bool(extra.get("ok", info.get("outcome") != "uncorrectable"))
        if isinstance(lat, (int, float)):
            self.slo.record(float(lat), ok)
        dev = info.get("device")
        if dev is not None:
            self.health.observe(
                dev, calls=1, detected=int(info.get("detected") or 0),
                uncorrectable=int(info.get("uncorrectable") or 0),
                residual=info.get("residual"))
        self._check_health_alerts()

    def observe_retry(self, info: dict) -> None:
        """One retry/exhausted ladder transition (the engine's direct
        feed) — ring only; SLO accounting happens at request completion."""
        self.ring.append(info)

    def ingest_event(self, event) -> None:
        """Telemetry-observer entry point: every recorded FaultEvent.

        Accepts a FaultEvent (dataclass with ``to_json``) or a plain
        dict. Serve-op events are skipped (the engine feeds those
        directly — see ``_SERVE_OPS``)."""
        if hasattr(event, "to_json"):
            try:
                d = json.loads(event.to_json())
            except (TypeError, ValueError):
                return
        elif isinstance(event, dict):
            d = dict(event)
        else:
            return
        if d.get("op") in _SERVE_OPS:
            return
        self.ring.append(d)
        residual = d.get("residual")
        devices = d.get("devices")
        if devices:
            # Mesh-attributed events: counts are NOT taken from the
            # entries — record_mesh_gemm already bumps the registry's
            # ft_device_* counters (for EVERY device, clean ones too),
            # which refresh_gauges syncs in as absolute totals; adding
            # the entries here would double-count. The entries only
            # route the event's residual to the implicated devices'
            # drift streams.
            if residual is not None:
                for entry in devices:
                    if isinstance(entry, dict) and "device" in entry:
                        self.health.observe_residual(entry["device"],
                                                     residual)
        elif d.get("device") is not None and d.get("host") is None:
            # Single-process events label a real device. Mesh events
            # (host is set) label the MESH ("mesh2x4"), not a chip —
            # their per-chip truth is the ft_device_* registry series
            # the sync pass reads, so they feed nothing here.
            self.health.observe(
                d["device"], calls=1,
                detected=int(d.get("detected") or 0),
                uncorrectable=int(d.get("uncorrectable") or 0),
                residual=residual)
        elif residual is not None and d.get("outcome") in (
                "clean", "corrected"):
            # Single-device process without a device label: track the
            # residual stream under the process-local pseudo-device so
            # drift detection still works.
            self.health.observe_residual("local", residual)
        self._check_health_alerts()

    def attach(self) -> "Monitor":
        """Subscribe to the live telemetry event stream (idempotent)."""
        if not self._attached:
            from ft_sgemm_tpu import telemetry

            telemetry.add_observer(self.ingest_event)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            from ft_sgemm_tpu import telemetry

            telemetry.remove_observer(self.ingest_event)
            self._attached = False

    # -- derived views ------------------------------------------------------

    def _sync_registry_devices(self) -> None:
        """Fold the registry's ``ft_device_*`` counters (the mesh
        attribution series — every device of every mesh call, not just
        faulty ones) into the health tracker as absolute totals."""
        try:
            series = self.registry().collect()
        except Exception:  # noqa: BLE001 — no registry: direct feed only
            return
        acc: Dict[str, dict] = {}
        name_to_key = {"ft_device_calls": "calls",
                       "ft_device_detections": "detected",
                       "ft_device_uncorrectable": "uncorrectable"}
        for s in series:
            key = name_to_key.get(s["name"])
            if key is None or s["kind"] != "counter":
                continue
            dev = (s.get("labels") or {}).get("device")
            if dev is None:
                continue
            row = acc.setdefault(
                dev, {"calls": 0, "detected": 0, "uncorrectable": 0})
            row[key] += int(s["value"])
        for dev, row in acc.items():
            self.health.sync_counts(dev, **row)

    def _check_health_alerts(self) -> None:
        cfg = self.health.config
        for dev, score in self.health.scores().items():
            if score < cfg.degraded_below and dev not in self._health_alerted:
                self._health_alerted.add(dev)
                self._alert("device_health", {
                    "device": dev, "score": score,
                    "drift_z": round(self.health.drift_z(dev), 4),
                    "threshold": cfg.degraded_below})
            elif score >= cfg.degraded_below:
                self._health_alerted.discard(dev)

    def refresh_gauges(self) -> None:
        """Recompute and publish the monitor's derived gauges into the
        registry (called per scrape — gauges are views, not state)."""
        self._sync_registry_devices()
        self._check_health_alerts()
        try:
            reg = self.registry()
        except Exception:  # noqa: BLE001
            return
        s = self.slo.snapshot()
        reg.gauge("slo_budget_remaining").set(s["budget_remaining"])
        reg.gauge("slo_burn_rate").set(s["burn_rate"])
        reg.gauge("slo_window_requests").set(s["requests"])
        if s["goodput_ratio"] is not None:
            reg.gauge("slo_goodput_ratio").set(s["goodput_ratio"])
        for dev, row in self.health.rows().items():
            reg.gauge("device_health", device=dev).set(row["score"])
            reg.gauge("device_health_drift", device=dev).set(row["drift_z"])

    def metrics_text(self) -> str:
        """The full /metrics exposition: monitor gauges refreshed, then
        the whole registry rendered through ONE prometheus path."""
        self.refresh_gauges()
        return self._render_fn()(self.registry().collect())

    def health_status(self) -> dict:
        """OK / DEGRADED / FAILING with named reasons (the /healthz body).

        - FAILING: any uncorrectable-result signal (``exhausted`` serve
          outcomes, a device with uncorrectable faults scoring below
          ``failing_below``) or an SLO burn rate past the failing factor.
        - DEGRADED: SLO budget burning faster than allowed (burn >= 1),
          or any device health below ``degraded_below``.
        - OK otherwise — a clean load reports OK with all-healthy scores.
        """
        reasons = []
        status = "OK"

        def worsen(to: str, reason: str):
            nonlocal status
            reasons.append(reason)
            if STATUSES.index(to) > STATUSES.index(status):
                status = to

        s = self.slo.snapshot()
        if s["burn_rate"] >= self.slo.config.failing_burn_rate and \
                s["requests"] > 0:
            worsen("FAILING",
                   f"slo burn rate {s['burn_rate']:.2f}x >= failing "
                   f"threshold {self.slo.config.failing_burn_rate:.1f}x")
        elif s["burn_rate"] >= 1.0 and s["requests"] > 0:
            worsen("DEGRADED",
                   f"slo error budget burning at {s['burn_rate']:.2f}x "
                   f"allowed rate ({s['violations']}/{s['requests']} "
                   "window requests violating)")
        cfg = self.health.config
        rows = self.health.rows()
        for dev, row in sorted(rows.items(), key=lambda kv: kv[1]["score"]):
            if row["score"] >= cfg.degraded_below:
                continue
            if row["uncorrectable"] > 0 and row["score"] < cfg.failing_below:
                worsen("FAILING",
                       f"device {dev} health {row['score']:.3f} with "
                       f"{row['uncorrectable']} uncorrectable faults")
            else:
                detail = (f"{row['detected']} detections/"
                          f"{row['calls']} calls"
                          + (f", drift z={row['drift_z']:.1f}"
                             if row["drift_z"] > 0 else ""))
                worsen("DEGRADED",
                       f"device {dev} health {row['score']:.3f} "
                       f"below {cfg.degraded_below} ({detail})")
        return {"status": status, "reasons": reasons,
                "slo": s, "devices": rows,
                "uptime_seconds": round(time.time() - self.started_unix, 3)}

    def observe_economics(self, snapshot: dict) -> None:
        """Latest cost-plane roll-up (a ``CostLedger.snapshot()`` dict)
        — kept so the SLO view and the cost view travel together in
        the artifact-embedded monitor snapshot."""
        if isinstance(snapshot, dict):
            self._economics = snapshot

    def snapshot(self) -> dict:
        """The artifact-embedded final view (``bench.py --serve`` ->
        ``context.slo`` and the RunReport SLO section)."""
        hs = self.health_status()
        scores = {d: r["score"] for d, r in hs["devices"].items()}
        if self._economics is not None:
            return dict(self._snapshot_base(hs, scores),
                        economics=self._economics)
        return self._snapshot_base(hs, scores)

    def _snapshot_base(self, hs: dict, scores: dict) -> dict:
        return {
            "status": hs["status"],
            "reasons": hs["reasons"],
            "budget_remaining": hs["slo"]["budget_remaining"],
            "burn_rate": hs["slo"]["burn_rate"],
            "goodput_ratio": hs["slo"]["goodput_ratio"],
            "observed_p99_seconds": hs["slo"]["observed_p99_seconds"],
            "objectives": hs["slo"]["objectives"],
            "window_requests": hs["slo"]["requests"],
            "violations": hs["slo"]["violations"],
            "device_health": scores,
            "device_health_min": min(scores.values()) if scores else None,
        }


# ---------------------------------------------------------------------------
# HTTP exporter
# ---------------------------------------------------------------------------


class MonitorServer:
    """Threaded stdlib HTTP exporter over a :class:`Monitor`.

    ``port=0`` binds an ephemeral port (read it back from ``.port``) —
    the CI-friendly default. Serves:

    - ``GET /metrics``  — Prometheus text exposition (version 0.0.4).
    - ``GET /healthz``  — JSON status/reasons; 200 for OK/DEGRADED,
      503 for FAILING (load balancers eject on 5xx, and a DEGRADED
      server is still producing verified results).
    - ``GET /events?since=SEQ[&limit=N]`` — recent fault events as JSON
      ``{"events": [...], "next": cursor}``; poll with the returned
      cursor.

    Runs on daemon threads (``ThreadingHTTPServer``) so scrapes never
    block the dispatch path and an abandoned server never blocks process
    exit. ``close()`` shuts the listener down."""

    def __init__(self, monitor: Monitor, port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server

        mon = monitor

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _send(self, code: int, body: str, ctype: str):
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802 — http.server API
                import urllib.parse

                url = urllib.parse.urlparse(self.path)
                try:
                    if url.path == "/metrics":
                        self._send(200, mon.metrics_text(),
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                    elif url.path == "/healthz":
                        hs = mon.health_status()
                        code = 503 if hs["status"] == "FAILING" else 200
                        self._send(code, json.dumps(hs, sort_keys=True),
                                   "application/json")
                    elif url.path == "/events":
                        q = urllib.parse.parse_qs(url.query)
                        since = int(q.get("since", ["0"])[0])
                        limit = q.get("limit")
                        events, cursor = mon.ring.since(
                            since, int(limit[0]) if limit else None)
                        self._send(200, json.dumps(
                            {"events": events, "next": cursor},
                            sort_keys=True), "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {url.path}",
                             "paths": ["/metrics", "/healthz",
                                       "/events"]}), "application/json")
                except BrokenPipeError:
                    pass  # scraper hung up mid-response
                except Exception as e:  # noqa: BLE001 — 500, never crash
                    try:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}),
                            "application/json")
                    except OSError:
                        pass

        self.monitor = monitor
        self._httpd = http.server.ThreadingHTTPServer((host, int(port)),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "MonitorServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                daemon=True, name="ft-sgemm-monitor")
            self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def start_monitor(port: int = 0, *, registry=None,
                  slo: Optional[SloConfig] = None,
                  health: Optional[HealthConfig] = None,
                  ring_capacity: int = 512,
                  attach: bool = True) -> Tuple[Monitor, MonitorServer]:
    """Convenience: build a Monitor (attached to the telemetry stream
    when ``attach``) and a started server on ``port`` (0 = ephemeral)."""
    monitor = Monitor(registry=registry, slo=slo, health=health,
                      ring_capacity=ring_capacity)
    if attach:
        monitor.attach()
    server = MonitorServer(monitor, port=port).start()
    return monitor, server


__all__ = ["DeviceHealthTracker", "EventRing", "HealthConfig", "Monitor",
           "MonitorServer", "SloConfig", "SloTracker", "STATUSES",
           "start_monitor"]
