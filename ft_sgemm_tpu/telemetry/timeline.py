"""Live run timelines: wall-clock span records streamed to JSONL.

The second observability gap after anonymous SDCs (see
``telemetry/aggregate.py``) is run *progress*: a long bench attempt that
gets deadline-killed used to take every in-flight measurement down with
it — ``BENCH_r05.json`` came back ``value: null`` although earlier
stages had finished. This module is the durable record that prevents
that: a :class:`TimelineRecorder` streams one JSON line per event —
stage/attempt/compile span starts and ends, heartbeats, kill markers —
flushed (and fsync'd when possible) the moment it happens, so whatever
kills the process, everything that *completed* is already on disk.
``bench.py``'s worker records its stages through one of these; the
supervisor reads the stream back on a deadline kill and salvages the
completed measurements into a non-null artifact
(``context.partial: true`` + ``killed_at_stage``), and
``python -m ft_sgemm_tpu.cli timeline RUN.timeline.jsonl`` renders the
post-hoc (or in-flight) view: per-stage wall time, heartbeat gaps, and
the kill point.

HARD CONSTRAINT — stdlib only, no package-relative imports: the bench
supervisor must never import jax, and it loads this file directly via
``importlib.util.spec_from_file_location`` (importing the
``ft_sgemm_tpu`` package root would pull jax in). Keep it that way.

Record schema (one JSON object per line)::

    {"kind": "stage"|"attempt"|"compile"|...,   # span family
     "name": str, "phase": "start"|"end"|"point",
     "t": <unix seconds>,
     # end records only:
     "seconds": float, "status": "ok"|"fail",
     "value": <stage result>, "error": str}

``kind="heartbeat"`` and ``kind="kill"`` are point events (the worker's
liveness beats and the supervisor's kill markers).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import IO, Iterable, List, Optional

SPAN_KINDS = ("stage", "attempt", "compile")

# Every record kind any emitter may write — the spans above plus the
# point-event families (worker heartbeats, supervisor kill markers, the
# serve engine's enqueue/retry/exhausted points, the block engine's
# ``serve_block`` request hops and ``kv_page`` stored-state findings,
# loadgen progress).
# This is the timeline half of the declared telemetry schema: the lint
# telemetry-schema pass statically checks every ``span(kind=...)`` /
# ``point(kind, ...)`` call site in the tree against this tuple, so an
# emitter cannot invent a kind the readers (summarize_timeline,
# traceview, wallclock) have never heard of.
KINDS = ("stage", "attempt", "compile", "heartbeat", "kill", "serve",
         "serve_block", "kv_page", "serve_progress", "recovery", "fleet",
         "chaos")


class TimelineRecorder:
    """Append-only JSONL span recorder, thread-safe, flushed per event.

    Accepts a path (opened lazily, parent dirs created) or an open
    text-mode file object. Every write flushes and best-effort fsyncs:
    the whole point is that a SIGKILL one instruction later loses
    nothing already emitted. Emission never raises — an unwritable
    timeline degrades to losing observability, not the run.
    """

    def __init__(self, path_or_file):
        self._lock = threading.Lock()
        if hasattr(path_or_file, "write"):
            self._fh: Optional[IO] = path_or_file
            self._path = getattr(path_or_file, "name", None)
            self._owns = False
        else:
            self._fh = None
            self._path = os.fspath(path_or_file)
            self._owns = True

    @property
    def path(self) -> Optional[str]:
        return self._path

    def _write(self, rec: dict) -> None:
        try:
            with self._lock:
                if self._fh is None:
                    if self._path is None:
                        return
                    parent = os.path.dirname(os.path.abspath(self._path))
                    os.makedirs(parent, exist_ok=True)
                    self._fh = open(self._path, "a", encoding="utf-8")
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
                try:
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError, AttributeError):
                    pass  # file objects without a real fd (StringIO)
        except (OSError, ValueError):
            pass  # never let observability take down the run

    def point(self, kind: str, name: str, **fields) -> None:
        """One instantaneous event (heartbeat, kill marker, skip note)."""
        rec = {"kind": kind, "name": name, "phase": "point",
               "t": time.time()}
        rec.update(fields)
        self._write(rec)

    @contextlib.contextmanager
    def span(self, name: str, kind: str = "stage", **fields):
        """Bracket a unit of work: a ``start`` record lands immediately
        (so a kill mid-span still names what was in flight), the ``end``
        record on exit carries wall seconds and ok/fail status.

        Yields a dict; set ``info["value"]`` inside the block to attach
        the stage's result (e.g. its GFLOPS) to the end record — the
        payload the supervisor's salvage path reads. Exceptions
        propagate after a ``status: "fail"`` end record is written.
        """
        start = {"kind": kind, "name": name, "phase": "start",
                 "t": time.time()}
        start.update(fields)
        self._write(start)
        t0 = time.monotonic()
        info: dict = {}
        try:
            yield info
        except BaseException as e:
            end = {"kind": kind, "name": name, "phase": "end",
                   "t": time.time(),
                   "seconds": round(time.monotonic() - t0, 6),
                   "status": "fail", "error": f"{type(e).__name__}: {e}"}
            self._write(end)
            raise
        end = {"kind": kind, "name": name, "phase": "end",
               "t": time.time(),
               "seconds": round(time.monotonic() - t0, 6), "status": "ok"}
        end.update(info)
        self._write(end)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and self._owns:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = None


def read_timeline(path) -> List[dict]:
    """Parse a timeline JSONL file; torn/foreign lines are skipped (the
    stream is append-only across kills, so a torn tail is expected)."""
    out = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (isinstance(rec, dict) and "kind" in rec
                    and "t" in rec and "name" in rec):
                out.append(rec)
    return out


def summarize_timeline(records: Iterable[dict]) -> dict:
    """Pair span starts/ends and derive the run-shape facts.

    Returns::

        {"spans": [{kind, name, start, end, seconds, status, value,
                    error}, ...],        # completed, in record order
         "in_flight": [{kind, name, start}, ...],  # started, never ended
         "killed_at_stage": str|None,    # last in-flight "stage" span
         "kills": [{"name": reason, "t": ...}, ...],
         "heartbeats": int, "max_heartbeat_gap": float|None,
         "t0": float|None, "t1": float|None, "wall_seconds": float|None,
         "stage_values": {name: value}}  # last ok end value per stage

    ``stage_values`` is the salvage payload: everything a killed run
    measured to completion, keyed by stage name.
    """
    records = list(records)
    spans: List[dict] = []
    open_spans: dict = {}
    kills: List[dict] = []
    beats: List[float] = []
    stage_values: dict = {}
    t0 = t1 = None
    for rec in records:
        t = rec.get("t")
        if isinstance(t, (int, float)):
            t0 = t if t0 is None else min(t0, t)
            t1 = t if t1 is None else max(t1, t)
        kind, name, phase = rec.get("kind"), rec.get("name"), rec.get("phase")
        if kind == "heartbeat":
            if isinstance(t, (int, float)):
                beats.append(t)
            continue
        if kind == "kill":
            kills.append({"name": name, "t": t})
            continue
        key = (kind, name)
        if phase == "start":
            open_spans.setdefault(key, []).append(rec)
        elif phase == "end":
            stack = open_spans.get(key)
            start = stack.pop() if stack else None
            span = {
                "kind": kind, "name": name,
                "start": start.get("t") if start else None, "end": t,
                "seconds": rec.get("seconds"),
                "status": rec.get("status"),
                "value": rec.get("value"), "error": rec.get("error")}
            # Wall-phase split: a stage span whose recorder attached the
            # compile/execute decomposition (bench_seconds_per_call's
            # phase_info) carries it through to the summary, where
            # perf/wallclock.py rolls it into per-run phase fractions.
            for extra in ("lower_seconds", "compile_seconds",
                          "execute_seconds"):
                if isinstance(rec.get(extra), (int, float)):
                    span[extra] = rec[extra]
            spans.append(span)
            if kind == "stage" and rec.get("status") == "ok" \
                    and rec.get("value") is not None:
                stage_values[name] = rec.get("value")
    in_flight = [{"kind": k, "name": n, "start": r.get("t")}
                 for (k, n), stack in open_spans.items() for r in stack]
    in_flight.sort(key=lambda s: (s["start"] is None, s["start"]))
    killed_at = None
    for s in in_flight:
        if s["kind"] == "stage":
            killed_at = s["name"]  # last-started wins
    gaps = [b - a for a, b in zip(beats, beats[1:])]
    return {
        "spans": spans, "in_flight": in_flight,
        "killed_at_stage": killed_at, "kills": kills,
        "heartbeats": len(beats),
        "max_heartbeat_gap": round(max(gaps), 3) if gaps else None,
        "t0": t0, "t1": t1,
        "wall_seconds": (round(t1 - t0, 3)
                         if t0 is not None and t1 is not None else None),
        "stage_values": stage_values,
    }


def _fmt_value(v) -> str:
    if v is None:
        return ""
    if isinstance(v, float):
        return f"  {v:.1f}"
    if isinstance(v, dict):
        inner = ", ".join(f"{k}={vv}" for k, vv in sorted(v.items())
                          if not isinstance(vv, (dict, list)))
        return f"  {{{inner[:70]}}}" if inner else ""
    return f"  {v}"


def format_timeline(summary: dict) -> str:
    """Human rendering of :func:`summarize_timeline` output: one line per
    span (relative start, duration, status, attached value), then the
    in-flight work, kill markers, and heartbeat health."""
    lines = []
    t0 = summary.get("t0")
    wall = summary.get("wall_seconds")
    lines.append(
        f"timeline: {len(summary['spans'])} completed spans, "
        f"{len(summary['in_flight'])} in flight"
        + (f", {wall:.1f}s wall" if wall is not None else ""))

    def rel(t):
        return (f"{t - t0:8.1f}s" if isinstance(t, (int, float))
                and t0 is not None else "       ?")

    for s in summary["spans"]:
        dur = s.get("seconds")
        status = s.get("status") or "?"
        split = ""
        if isinstance(s.get("compile_seconds"), (int, float)):
            split = f"  [compile {s['compile_seconds']:.2f}s"
            if isinstance(s.get("execute_seconds"), (int, float)):
                split += f" / exec {s['execute_seconds']:.2f}s"
            split += "]"
        lines.append(
            f"  [{rel(s.get('start'))}] {s['kind']:<8s} {s['name']:<28s} "
            f"{status:<4s}"
            + (f" {dur:8.2f}s" if isinstance(dur, (int, float)) else "")
            + split
            + _fmt_value(s.get("value"))
            + (f"  ({s['error']})" if s.get("error") else ""))
    for s in summary["in_flight"]:
        lines.append(
            f"  [{rel(s.get('start'))}] {s['kind']:<8s} {s['name']:<28s} "
            "IN FLIGHT (no end record)")
    for k in summary["kills"]:
        lines.append(f"  [{rel(k.get('t'))}] KILL: {k['name']}")
    if summary.get("killed_at_stage"):
        lines.append(f"killed during stage: {summary['killed_at_stage']}")
    if summary["heartbeats"]:
        gap = summary.get("max_heartbeat_gap")
        lines.append(
            f"heartbeats: {summary['heartbeats']}"
            + (f", max gap {gap:.1f}s" if gap is not None else ""))
    return "\n".join(lines)


__all__ = ["SPAN_KINDS", "TimelineRecorder", "format_timeline",
           "read_timeline", "summarize_timeline"]
