"""Unified Perfetto / ``chrome://tracing`` export of one run's streams.

The per-run streams this repo already emits are exactly the Chrome
trace-event model wearing different clothes: timeline stage/attempt/
compile spans (``telemetry/timeline.py``) are duration events, fault
events (``telemetry/events.py`` JSONL) are instants with tile-coordinate
args, and serve requests — minted a ``trace_id`` at construction
(``serve/tracing.py``) and stamped on the enqueue point, the batch-flush
span, every ``serve_gemm`` detection, and each retry-ladder event — are
flow events joined by that ID. This module merges the streams into ONE
Chrome-trace-event JSON per run, loadable directly in Perfetto or
``chrome://tracing``, so every deadline kill, retry ladder, and compile
wall becomes visually inspectable instead of a grep across three files.

Event mapping (DESIGN.md §13):

- span start/end pairs -> ``ph:"X"`` complete events on a per-kind
  track (stage / attempt / compile / tune; ``serve[...]`` batch spans
  ride the serve track), args carrying status, value, and the
  lower/compile/execute wall split when recorded;
- in-flight spans (started, never ended — the kill signature) ->
  unmatched ``ph:"B"`` begin events, which tracing UIs render as
  running to the end of the trace: the kill point is *visible*;
- timeline points -> tiny ``ph:"X"`` slices (1µs) so flow arrows have a
  slice to bind to; kill markers -> process-scoped ``ph:"i"`` instants;
  heartbeats -> thread-scoped instants on their own track;
- fault events -> ``ph:"i"`` instants with tile coords / residual /
  threshold args on the faults track;
- serve requests -> ``ph:"s"/"t"/"f"`` flow events, ``id`` = the
  request's ``trace_id``, hop sequence enqueue -> batch flush ->
  detect (``serve_gemm``) -> retry/exhausted, each hop anchored at a
  slice on the serve or faults track.

Timestamps are microseconds relative to the earliest record across both
streams, clamped non-negative, and the emitted ``traceEvents`` list is
sorted by ``ts`` (metadata first) — torn tails and foreign lines are
skipped by the underlying readers, records without a wall-clock ``t``
are counted in ``otherData.dropped`` rather than guessed at.

HARD CONSTRAINT — timeline.py discipline: stdlib only, no
package-relative imports (loadable via
``importlib.util.spec_from_file_location`` from jax-free processes).
The fault-event JSONL is parsed locally with the same skip rules as
``telemetry/events.py`` rather than importing it.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

# Fixed track (tid) layout: one lane per span family so the Perfetto
# view reads top-to-bottom as run structure, serve traffic, then faults.
TRACKS = (
    ("stage", 1), ("attempt", 2), ("compile", 3), ("tune", 4),
    ("serve", 5), ("faults", 6), ("heartbeat", 7), ("other", 8),
)
_TID = dict(TRACKS)
PID = 1


def _tid_for(kind: Optional[str], name: Optional[str]) -> int:
    if isinstance(name, str) and (name.startswith("serve[")
                                  or name.startswith("serve_block[")):
        return _TID["serve"]
    if kind in ("serve_block", "kv_page"):
        return _TID["serve"] if kind == "serve_block" else _TID["faults"]
    return _TID.get(kind or "", _TID["other"])


def _pid_of(rec: dict) -> int:
    """The Chrome-trace process a record belongs to: merged multi-rank
    input carries ``_pid`` (merge_fleet stamps one per rank); single-run
    input has none and everything lands on the classic PID."""
    pid = rec.get("_pid")
    return pid if isinstance(pid, int) and pid > 0 else PID


def _pair_spans(records) -> Tuple[List[dict], List[dict]]:
    """Pair start/end records per (pid, kind, name) stack, keeping EVERY
    field of both records (``summarize_timeline`` drops span-start extras
    like the flush span's ``trace_ids``; the trace needs them). The pid
    in the key is the multi-rank aliasing fix: two ranks emitting
    IDENTICAL span names (every rank runs ``program:smoke``) must never
    close each other's spans in a merged trace."""
    open_spans: dict = {}
    spans: List[dict] = []
    for rec in records:
        kind, name, phase = rec.get("kind"), rec.get("name"), rec.get("phase")
        key = (_pid_of(rec), kind, name)
        if phase == "start":
            open_spans.setdefault(key, []).append(rec)
        elif phase == "end":
            stack = open_spans.get(key)
            start = stack.pop() if stack else None
            merged = dict(start or {})
            merged.update({k: v for k, v in rec.items()
                           if k not in ("phase", "t")})
            merged["t_start"] = (start or {}).get("t")
            merged["t_end"] = rec.get("t")
            spans.append(merged)
    in_flight = [dict(r, t_start=r.get("t"))
                 for stack in open_spans.values() for r in stack]
    return spans, in_flight


def _read_fault_events(path) -> List[dict]:
    """Parse a fault-event JSONL with ``telemetry/events.py``'s skip
    rules (blank / torn / foreign lines dropped), kept local for the
    stdlib-only constraint."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "outcome" in d:
                out.append(d)
    return out


def _span_args(span: dict) -> dict:
    args = {}
    for key in ("status", "seconds", "value", "error", "lower_seconds",
                "compile_seconds", "execute_seconds", "trace_ids"):
        if span.get(key) is not None:
            args[key] = span[key]
    return args


def build_trace(records, events=None, *, run_id: Optional[str] = None,
                process_names: Optional[dict] = None) -> dict:
    """Merge timeline records (+ optional fault events) into one
    Chrome-trace document ``{"traceEvents": [...], "displayTimeUnit":
    "ms", "otherData": {...}}``. Never raises on hostile record shapes —
    records without a usable ``t`` are counted dropped.

    Records/events may carry ``_pid`` (merge_fleet stamps one per rank):
    each distinct pid becomes its own Chrome-trace process with its own
    track metadata, named from ``process_names[pid]`` when given. Flow
    events (``s``/``t``/``f``) keep the pid of the slice they anchor to,
    which is how one trace_id draws an arrow ACROSS process rows."""
    records = [r for r in (records or []) if isinstance(r, dict)]
    events = [e for e in (events or []) if isinstance(e, dict)]
    times = [r.get("t") for r in records] + [e.get("ts") for e in events]
    times = [t for t in times if isinstance(t, (int, float))]
    t0 = min(times) if times else 0.0

    def ts_us(t) -> Optional[int]:
        if not isinstance(t, (int, float)):
            return None
        return max(0, int(round((t - t0) * 1e6)))

    out: List[dict] = []
    dropped = 0
    proc = run_id or "ft_sgemm_run"
    pids = sorted({_pid_of(r) for r in records}
                  | {_pid_of(e) for e in events} | {PID})
    names = dict(process_names or {})
    for pid in pids:
        pname = names.get(pid) or (proc if pid == PID
                                   else f"{proc}:p{pid}")
        out.append({"ph": "M", "pid": pid, "tid": 0, "ts": 0,
                    "name": "process_name", "args": {"name": pname}})
        for track, tid in TRACKS:
            out.append({"ph": "M", "pid": pid, "tid": tid, "ts": 0,
                        "name": "thread_name", "args": {"name": track}})

    spans, in_flight = _pair_spans(records)
    # trace_id -> [(ts, pid, tid, hop_name)] — the flow hops, gathered
    # as the slices they anchor to are emitted.
    flows: dict = {}

    def hop(trace_id, ts, pid, tid, name):
        if isinstance(trace_id, str) and ts is not None:
            flows.setdefault(trace_id, []).append((ts, pid, tid, name))

    for span in spans:
        ts = ts_us(span.get("t_start"))
        te = ts_us(span.get("t_end"))
        if ts is None and te is None:
            dropped += 1
            continue
        if ts is None:
            # End with no start (torn head): a 1µs slice at the end time.
            ts = te
        sec = span.get("seconds")
        dur = (int(round(float(sec) * 1e6))
               if isinstance(sec, (int, float)) and sec > 0
               else (te - ts if te is not None and te > ts else 1))
        pid = _pid_of(span)
        tid = _tid_for(span.get("kind"), span.get("name"))
        out.append({"ph": "X", "pid": pid, "tid": tid, "ts": ts,
                    "dur": max(1, dur), "cat": span.get("kind") or "span",
                    "name": str(span.get("name")),
                    "args": _span_args(span)})
        for trace_id in (span.get("trace_ids") or []):
            # The flush hop lands 1µs INSIDE the batch slice so the
            # flow arrow binds to it, not to a neighbour.
            hop(trace_id, ts + 1, pid, tid, "flush")
    for span in in_flight:
        ts = ts_us(span.get("t_start"))
        if ts is None:
            dropped += 1
            continue
        out.append({"ph": "B", "pid": _pid_of(span),
                    "tid": _tid_for(span.get("kind"), span.get("name")),
                    "ts": ts, "cat": span.get("kind") or "span",
                    "name": str(span.get("name")),
                    "args": {"in_flight": True}})

    points = 0
    for rec in records:
        if rec.get("phase") != "point":
            continue
        ts = ts_us(rec.get("t"))
        if ts is None:
            dropped += 1
            continue
        points += 1
        kind, name = rec.get("kind"), rec.get("name")
        pid = _pid_of(rec)
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "name", "phase", "t", "_pid")}
        if kind == "kill":
            out.append({"ph": "i", "pid": pid, "tid": _TID["other"],
                        "ts": ts, "s": "p", "cat": "kill",
                        "name": f"KILL: {name}", "args": args})
            continue
        if kind == "heartbeat":
            out.append({"ph": "i", "pid": pid, "tid": _TID["heartbeat"],
                        "ts": ts, "s": "t", "cat": "heartbeat",
                        "name": str(name), "args": args})
            continue
        tid = _tid_for(kind, name)
        # Points become 1µs slices (not bare instants) so flow arrows
        # have a slice to bind to in Perfetto's legacy importer.
        out.append({"ph": "X", "pid": pid, "tid": tid, "ts": ts,
                    "dur": 1, "cat": str(kind), "name": str(name),
                    "args": args})
        if args.get("trace_id"):
            hop(args["trace_id"], ts, pid, tid, str(name))

    fault_count = 0
    for ev in events:
        ts = ts_us(ev.get("ts"))
        if ts is None:
            dropped += 1
            continue
        fault_count += 1
        pid = _pid_of(ev)
        args = {k: ev[k] for k in ("outcome", "op", "strategy", "layer",
                                   "tiles", "residual", "threshold",
                                   "detected", "corrected",
                                   "uncorrectable", "device", "extra")
                if ev.get(k) is not None}
        name = f"{ev.get('op') or 'event'}:{ev.get('outcome')}"
        out.append({"ph": "X", "pid": pid, "tid": _TID["faults"],
                    "ts": ts, "dur": 1, "cat": "fault", "name": name,
                    "args": args})
        trace_id = (ev.get("extra") or {}).get("trace_id") \
            if isinstance(ev.get("extra"), dict) else None
        hop(trace_id, ts, pid, _TID["faults"],
            "detect" if ev.get("op") in ("serve_gemm", "serve_block")
            else f"kv_{ev.get('outcome')}" if ev.get("op") == "kv_page"
            else str(ev.get("outcome")))

    flow_events = 0
    cross_process_flows = 0
    for trace_id, hops in sorted(flows.items()):
        if len(hops) < 2:
            continue  # a flow needs two ends to draw an arrow
        hops.sort()
        if len({pid for _, pid, _, _ in hops}) > 1:
            cross_process_flows += 1
        for i, (ts, pid, tid, name) in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            ev = {"ph": ph, "pid": pid, "tid": tid, "ts": ts,
                  "cat": "serve.flow", "name": "serve_request",
                  "id": trace_id, "args": {"hop": name}}
            if ph == "f":
                ev["bp"] = "e"  # bind the arrowhead to the enclosing slice
            out.append(ev)
            flow_events += 1

    # Metadata first, then strictly non-decreasing timestamps — the
    # contract tests pin (and chrome://tracing's importer prefers).
    out.sort(key=lambda e: (e["ph"] != "M", e["ts"]))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": proc,
            "spans": len(spans), "in_flight": len(in_flight),
            "points": points, "fault_events": fault_count,
            "flows": sum(1 for h in flows.values() if len(h) >= 2),
            "flow_events": flow_events,
            "processes": len(pids),
            "cross_process_flows": cross_process_flows,
            "dropped": dropped,
        },
    }


def _read_timeline(path) -> List[dict]:
    """``telemetry/timeline.py::read_timeline`` semantics, local for the
    stdlib/path-loadable constraint."""
    out = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (isinstance(rec, dict) and "kind" in rec
                    and "t" in rec and "name" in rec):
                out.append(rec)
    return out


def default_out_path(timeline_path: str) -> str:
    base = timeline_path
    for suffix in (".timeline.jsonl", ".jsonl"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
            break
    return base + ".trace.json"


def export_trace(timeline_path: str,
                 events_path: Optional[str] = None,
                 out_path: Optional[str] = None,
                 run_id: Optional[str] = None) -> Tuple[dict, str]:
    """Read one run's timeline (+ optional fault-event log), build the
    merged Chrome trace, write it, and return ``(trace, out_path)``.
    ``OSError`` from unreadable inputs propagates (the CLI maps it to
    exit 2); a MISSING events log beside a readable timeline does not —
    the trace simply carries no fault instants."""
    records = _read_timeline(timeline_path)
    events: List[dict] = []
    if events_path:
        try:
            events = _read_fault_events(events_path)
        except OSError:
            events = []
    if run_id is None:
        run_id = os.path.splitext(os.path.basename(timeline_path))[0]
        if run_id.endswith(".timeline"):
            run_id = run_id[:-len(".timeline")]
    trace = build_trace(records, events, run_id=run_id)
    path = out_path or default_out_path(timeline_path)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return trace, path


def _read_fleet_skew(workdir: str) -> dict:
    """Per-rank clock-skew estimates (rank -> seconds, remote minus
    coordinator) from the coordinator's result artifact — the last
    handshake value the dispatcher recorded per host. Missing/hostile
    shapes degrade to {} (no correction), never an error."""
    path = os.path.join(workdir, "rank0", "result.json")
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            res = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(res, dict):
        return {}
    serve = res.get("serve")
    disp = serve.get("dispatcher") if isinstance(serve, dict) else None
    per = disp.get("per_host") if isinstance(disp, dict) else None
    out: dict = {}
    for host, row in (per or {}).items():
        if not isinstance(row, dict):
            continue
        skew = row.get("clock_skew_seconds")
        if isinstance(skew, (int, float)):
            try:
                out[int(host)] = float(skew)
            except (TypeError, ValueError):
                continue
    return out


def merge_fleet(workdir: str, out_path: Optional[str] = None,
                run_id: Optional[str] = None) -> Tuple[dict, str]:
    """Stitch a fleet run's per-rank timelines (+ fault-event shards)
    and the supervisor's own timeline into ONE Perfetto trace.

    - the supervisor (``fleet.timeline.jsonl``) keeps the classic PID;
      rank ``r`` becomes Chrome-trace process ``2 + r``, every record
      namespaced ``rank{r}:`` so merged traces never alias (and
      ``_pair_spans`` keys on pid besides — identical span names across
      ranks stay separate spans);
    - remote-rank wall clocks are SKEW-CORRECTED before merging: each
      rank's timestamps shift by minus the dispatcher's last
      NTP-midpoint estimate for that host (``_read_fleet_skew``; rank 0
      is the reference clock and shifts by zero), so one trace_id's
      hops order correctly across the wire;
    - flows then join coordinator submit -> remote execute -> remote
      retry across process rows (``otherData.cross_process_flows``
      counts them).

    Returns ``(trace, out_path)`` like :func:`export_trace`; the
    default output is ``<workdir>/fleet.trace.json``.
    """
    skew = _read_fleet_skew(workdir)
    records: List[dict] = []
    events: List[dict] = []
    names = {PID: "fleet-supervisor"}
    sup = os.path.join(workdir, "fleet.timeline.jsonl")
    if os.path.exists(sup):
        for rec in _read_timeline(sup):
            rec["_pid"] = PID
            records.append(rec)
    ranks = []
    try:
        entries = sorted(os.listdir(workdir))
    except OSError:
        entries = []
    for entry in entries:
        if entry.startswith("rank") and entry[4:].isdigit():
            ranks.append(int(entry[4:]))
    for r in sorted(ranks):
        rankdir = os.path.join(workdir, f"rank{r}")
        pid = PID + 1 + r
        names[pid] = f"rank{r}"
        offset = skew.get(r, 0.0) if r != 0 else 0.0
        prefix = f"rank{r}:"
        tl_path = os.path.join(rankdir, "timeline.jsonl")
        if os.path.exists(tl_path):
            for rec in _read_timeline(tl_path):
                rec["_pid"] = pid
                if isinstance(rec.get("t"), (int, float)):
                    rec["t"] = rec["t"] - offset
                nm = rec.get("name")
                if isinstance(nm, str) and not nm.startswith(prefix):
                    rec["name"] = prefix + nm
                records.append(rec)
        for entry in sorted(os.listdir(rankdir)
                            if os.path.isdir(rankdir) else []):
            if not (entry.startswith("events") and
                    entry.endswith(".jsonl")):
                continue
            try:
                shard = _read_fault_events(os.path.join(rankdir, entry))
            except OSError:
                continue
            for ev in shard:
                ev["_pid"] = pid
                if isinstance(ev.get("ts"), (int, float)):
                    ev["ts"] = ev["ts"] - offset
                events.append(ev)
    trace = build_trace(records, events,
                        run_id=run_id or "fleet",
                        process_names=names)
    trace["otherData"]["ranks"] = sorted(ranks)
    trace["otherData"]["clock_skew_seconds"] = {
        str(h): s for h, s in sorted(skew.items())}
    path = out_path or os.path.join(workdir, "fleet.trace.json")
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return trace, path


__all__ = ["PID", "TRACKS", "build_trace", "default_out_path",
           "export_trace", "merge_fleet"]
