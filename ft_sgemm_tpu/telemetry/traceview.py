"""Unified Perfetto / ``chrome://tracing`` export of one run's streams.

The per-run streams this repo already emits are exactly the Chrome
trace-event model wearing different clothes: timeline stage/attempt/
compile spans (``telemetry/timeline.py``) are duration events, fault
events (``telemetry/events.py`` JSONL) are instants with tile-coordinate
args, and serve requests — minted a ``trace_id`` at construction
(``serve/tracing.py``) and stamped on the enqueue point, the batch-flush
span, every ``serve_gemm`` detection, and each retry-ladder event — are
flow events joined by that ID. This module merges the streams into ONE
Chrome-trace-event JSON per run, loadable directly in Perfetto or
``chrome://tracing``, so every deadline kill, retry ladder, and compile
wall becomes visually inspectable instead of a grep across three files.

Event mapping (DESIGN.md §13):

- span start/end pairs -> ``ph:"X"`` complete events on a per-kind
  track (stage / attempt / compile / tune; ``serve[...]`` batch spans
  ride the serve track), args carrying status, value, and the
  lower/compile/execute wall split when recorded;
- in-flight spans (started, never ended — the kill signature) ->
  unmatched ``ph:"B"`` begin events, which tracing UIs render as
  running to the end of the trace: the kill point is *visible*;
- timeline points -> tiny ``ph:"X"`` slices (1µs) so flow arrows have a
  slice to bind to; kill markers -> process-scoped ``ph:"i"`` instants;
  heartbeats -> thread-scoped instants on their own track;
- fault events -> ``ph:"i"`` instants with tile coords / residual /
  threshold args on the faults track;
- serve requests -> ``ph:"s"/"t"/"f"`` flow events, ``id`` = the
  request's ``trace_id``, hop sequence enqueue -> batch flush ->
  detect (``serve_gemm``) -> retry/exhausted, each hop anchored at a
  slice on the serve or faults track.

Timestamps are microseconds relative to the earliest record across both
streams, clamped non-negative, and the emitted ``traceEvents`` list is
sorted by ``ts`` (metadata first) — torn tails and foreign lines are
skipped by the underlying readers, records without a wall-clock ``t``
are counted in ``otherData.dropped`` rather than guessed at.

HARD CONSTRAINT — timeline.py discipline: stdlib only, no
package-relative imports (loadable via
``importlib.util.spec_from_file_location`` from jax-free processes).
The fault-event JSONL is parsed locally with the same skip rules as
``telemetry/events.py`` rather than importing it.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

# Fixed track (tid) layout: one lane per span family so the Perfetto
# view reads top-to-bottom as run structure, serve traffic, then faults.
TRACKS = (
    ("stage", 1), ("attempt", 2), ("compile", 3), ("tune", 4),
    ("serve", 5), ("faults", 6), ("heartbeat", 7), ("other", 8),
)
_TID = dict(TRACKS)
PID = 1


def _tid_for(kind: Optional[str], name: Optional[str]) -> int:
    if isinstance(name, str) and (name.startswith("serve[")
                                  or name.startswith("serve_block[")):
        return _TID["serve"]
    if kind in ("serve_block", "kv_page"):
        return _TID["serve"] if kind == "serve_block" else _TID["faults"]
    return _TID.get(kind or "", _TID["other"])


def _pair_spans(records) -> Tuple[List[dict], List[dict]]:
    """Pair start/end records per (kind, name) stack, keeping EVERY
    field of both records (``summarize_timeline`` drops span-start extras
    like the flush span's ``trace_ids``; the trace needs them)."""
    open_spans: dict = {}
    spans: List[dict] = []
    for rec in records:
        kind, name, phase = rec.get("kind"), rec.get("name"), rec.get("phase")
        if phase == "start":
            open_spans.setdefault((kind, name), []).append(rec)
        elif phase == "end":
            stack = open_spans.get((kind, name))
            start = stack.pop() if stack else None
            merged = dict(start or {})
            merged.update({k: v for k, v in rec.items()
                           if k not in ("phase", "t")})
            merged["t_start"] = (start or {}).get("t")
            merged["t_end"] = rec.get("t")
            spans.append(merged)
    in_flight = [dict(r, t_start=r.get("t"))
                 for stack in open_spans.values() for r in stack]
    return spans, in_flight


def _read_fault_events(path) -> List[dict]:
    """Parse a fault-event JSONL with ``telemetry/events.py``'s skip
    rules (blank / torn / foreign lines dropped), kept local for the
    stdlib-only constraint."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "outcome" in d:
                out.append(d)
    return out


def _span_args(span: dict) -> dict:
    args = {}
    for key in ("status", "seconds", "value", "error", "lower_seconds",
                "compile_seconds", "execute_seconds", "trace_ids"):
        if span.get(key) is not None:
            args[key] = span[key]
    return args


def build_trace(records, events=None, *, run_id: Optional[str] = None) -> dict:
    """Merge timeline records (+ optional fault events) into one
    Chrome-trace document ``{"traceEvents": [...], "displayTimeUnit":
    "ms", "otherData": {...}}``. Never raises on hostile record shapes —
    records without a usable ``t`` are counted dropped."""
    records = [r for r in (records or []) if isinstance(r, dict)]
    events = [e for e in (events or []) if isinstance(e, dict)]
    times = [r.get("t") for r in records] + [e.get("ts") for e in events]
    times = [t for t in times if isinstance(t, (int, float))]
    t0 = min(times) if times else 0.0

    def ts_us(t) -> Optional[int]:
        if not isinstance(t, (int, float)):
            return None
        return max(0, int(round((t - t0) * 1e6)))

    out: List[dict] = []
    dropped = 0
    proc = run_id or "ft_sgemm_run"
    out.append({"ph": "M", "pid": PID, "tid": 0, "ts": 0,
                "name": "process_name", "args": {"name": proc}})
    for track, tid in TRACKS:
        out.append({"ph": "M", "pid": PID, "tid": tid, "ts": 0,
                    "name": "thread_name", "args": {"name": track}})

    spans, in_flight = _pair_spans(records)
    # trace_id -> [(ts, tid, hop_name)] — the flow hops, gathered as the
    # slices they anchor to are emitted.
    flows: dict = {}

    def hop(trace_id, ts, tid, name):
        if isinstance(trace_id, str) and ts is not None:
            flows.setdefault(trace_id, []).append((ts, tid, name))

    for span in spans:
        ts = ts_us(span.get("t_start"))
        te = ts_us(span.get("t_end"))
        if ts is None and te is None:
            dropped += 1
            continue
        if ts is None:
            # End with no start (torn head): a 1µs slice at the end time.
            ts = te
        sec = span.get("seconds")
        dur = (int(round(float(sec) * 1e6))
               if isinstance(sec, (int, float)) and sec > 0
               else (te - ts if te is not None and te > ts else 1))
        tid = _tid_for(span.get("kind"), span.get("name"))
        out.append({"ph": "X", "pid": PID, "tid": tid, "ts": ts,
                    "dur": max(1, dur), "cat": span.get("kind") or "span",
                    "name": str(span.get("name")),
                    "args": _span_args(span)})
        for trace_id in (span.get("trace_ids") or []):
            # The flush hop lands 1µs INSIDE the batch slice so the
            # flow arrow binds to it, not to a neighbour.
            hop(trace_id, ts + 1, tid, "flush")
    for span in in_flight:
        ts = ts_us(span.get("t_start"))
        if ts is None:
            dropped += 1
            continue
        out.append({"ph": "B", "pid": PID,
                    "tid": _tid_for(span.get("kind"), span.get("name")),
                    "ts": ts, "cat": span.get("kind") or "span",
                    "name": str(span.get("name")),
                    "args": {"in_flight": True}})

    points = 0
    for rec in records:
        if rec.get("phase") != "point":
            continue
        ts = ts_us(rec.get("t"))
        if ts is None:
            dropped += 1
            continue
        points += 1
        kind, name = rec.get("kind"), rec.get("name")
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "name", "phase", "t")}
        if kind == "kill":
            out.append({"ph": "i", "pid": PID, "tid": _TID["other"],
                        "ts": ts, "s": "p", "cat": "kill",
                        "name": f"KILL: {name}", "args": args})
            continue
        if kind == "heartbeat":
            out.append({"ph": "i", "pid": PID, "tid": _TID["heartbeat"],
                        "ts": ts, "s": "t", "cat": "heartbeat",
                        "name": str(name), "args": args})
            continue
        tid = _tid_for(kind, name)
        # Points become 1µs slices (not bare instants) so flow arrows
        # have a slice to bind to in Perfetto's legacy importer.
        out.append({"ph": "X", "pid": PID, "tid": tid, "ts": ts,
                    "dur": 1, "cat": str(kind), "name": str(name),
                    "args": args})
        if args.get("trace_id"):
            hop(args["trace_id"], ts, tid, str(name))

    fault_count = 0
    for ev in events:
        ts = ts_us(ev.get("ts"))
        if ts is None:
            dropped += 1
            continue
        fault_count += 1
        args = {k: ev[k] for k in ("outcome", "op", "strategy", "layer",
                                   "tiles", "residual", "threshold",
                                   "detected", "corrected",
                                   "uncorrectable", "device", "extra")
                if ev.get(k) is not None}
        name = f"{ev.get('op') or 'event'}:{ev.get('outcome')}"
        out.append({"ph": "X", "pid": PID, "tid": _TID["faults"],
                    "ts": ts, "dur": 1, "cat": "fault", "name": name,
                    "args": args})
        trace_id = (ev.get("extra") or {}).get("trace_id") \
            if isinstance(ev.get("extra"), dict) else None
        hop(trace_id, ts, _TID["faults"],
            "detect" if ev.get("op") in ("serve_gemm", "serve_block")
            else f"kv_{ev.get('outcome')}" if ev.get("op") == "kv_page"
            else str(ev.get("outcome")))

    flow_events = 0
    for trace_id, hops in sorted(flows.items()):
        if len(hops) < 2:
            continue  # a flow needs two ends to draw an arrow
        hops.sort()
        for i, (ts, tid, name) in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            ev = {"ph": ph, "pid": PID, "tid": tid, "ts": ts,
                  "cat": "serve.flow", "name": "serve_request",
                  "id": trace_id, "args": {"hop": name}}
            if ph == "f":
                ev["bp"] = "e"  # bind the arrowhead to the enclosing slice
            out.append(ev)
            flow_events += 1

    # Metadata first, then strictly non-decreasing timestamps — the
    # contract tests pin (and chrome://tracing's importer prefers).
    out.sort(key=lambda e: (e["ph"] != "M", e["ts"]))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": proc,
            "spans": len(spans), "in_flight": len(in_flight),
            "points": points, "fault_events": fault_count,
            "flows": sum(1 for h in flows.values() if len(h) >= 2),
            "flow_events": flow_events, "dropped": dropped,
        },
    }


def _read_timeline(path) -> List[dict]:
    """``telemetry/timeline.py::read_timeline`` semantics, local for the
    stdlib/path-loadable constraint."""
    out = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (isinstance(rec, dict) and "kind" in rec
                    and "t" in rec and "name" in rec):
                out.append(rec)
    return out


def default_out_path(timeline_path: str) -> str:
    base = timeline_path
    for suffix in (".timeline.jsonl", ".jsonl"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
            break
    return base + ".trace.json"


def export_trace(timeline_path: str,
                 events_path: Optional[str] = None,
                 out_path: Optional[str] = None,
                 run_id: Optional[str] = None) -> Tuple[dict, str]:
    """Read one run's timeline (+ optional fault-event log), build the
    merged Chrome trace, write it, and return ``(trace, out_path)``.
    ``OSError`` from unreadable inputs propagates (the CLI maps it to
    exit 2); a MISSING events log beside a readable timeline does not —
    the trace simply carries no fault instants."""
    records = _read_timeline(timeline_path)
    events: List[dict] = []
    if events_path:
        try:
            events = _read_fault_events(events_path)
        except OSError:
            events = []
    if run_id is None:
        run_id = os.path.splitext(os.path.basename(timeline_path))[0]
        if run_id.endswith(".timeline"):
            run_id = run_id[:-len(".timeline")]
    trace = build_trace(records, events, run_id=run_id)
    path = out_path or default_out_path(timeline_path)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return trace, path


__all__ = ["PID", "TRACKS", "build_trace", "default_out_path",
           "export_trace"]
