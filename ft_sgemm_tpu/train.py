"""Failure handling for training loops: retry, then restore.

The kernels' contract is *clean-or-reported*: every GEMM either produces
a verified output or raises its ``uncorrectable`` count
(residual-after-correct re-check, ops/ft_sgemm.py). What a TRAINING LOOP
should do with a report is policy, and every example was hand-rolling
the same one — this module packages it:

1. **Retry** the step from the pre-step state (SDC is overwhelmingly
   transient: a re-run of the same step on the same data is the cheapest
   recovery, and the pre-step state is untainted by construction — the
   report gated the corrupted update from being applied).
2. **Restore** from the newest clean checkpoint when reports persist
   (a persistent report suggests the fault is not transient — bad
   memory, a poisoned input batch — so replaying from checkpointed
   history is the sound fallback; the
   :class:`ft_sgemm_tpu.checkpoint.FtCheckpointer` gate guarantees
   whatever it holds was verified clean).
3. **Raise** when there is nothing to restore: never train on, or
   checkpoint, a state built from an unverified update.

The reference has no training loop at all (it is a kernel study); this
is the aux "failure detection / recovery" subsystem of the task brief,
built on the framework's own report channels.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.checkpoint import gate_total

__all__ = ["UncorrectableStepError", "StepReport", "resilient_step"]


class UncorrectableStepError(RuntimeError):
    """A step kept reporting uncorrectable faults and no clean state was
    available to fall back to."""


class StepReport:
    """What :func:`resilient_step` did to produce the returned state.

    Attributes: ``retries`` (attempts beyond the first — every one of
    them forced by a reported fault), ``restored_step`` (checkpoint step
    resumed from, or None), ``uncorrectable`` (the final attempt's
    count — 0 unless ``raise_on_failure=False``), ``evicted`` (True
    when the ``on_persistent_fault`` hook rebuilt the step on a
    surviving device set and the recovery attempt ran on it).
    """

    def __init__(self, retries: int, restored_step: Optional[int],
                 uncorrectable: int, evicted: bool = False):
        self.retries = retries
        self.restored_step = restored_step
        self.uncorrectable = uncorrectable
        self.evicted = evicted

    def __repr__(self):
        return (f"StepReport(retries={self.retries}, "
                f"restored_step={self.restored_step}, "
                f"uncorrectable={self.uncorrectable}, "
                f"evicted={self.evicted})")


def resilient_step(
    step_fn: Callable[[Any], Tuple[Any, Any, Any]],
    state: Any,
    *,
    max_retries: int = 2,
    checkpointer=None,
    restore_target: Any = None,
    raise_on_failure: bool = True,
    on_persistent_fault: Optional[Callable[[int, Any],
                                           Optional[Callable]]] = None,
) -> Tuple[Any, Any, StepReport]:
    """Run one training step under the clean-or-reported contract.

    ``step_fn(state) -> (new_state, metrics, uncorrectable)`` is the
    caller's (usually jitted) step; ``uncorrectable`` is the step's
    UNCORRECTABLE total only — e.g.
    ``total_count(counts, "uncorrectable") + bwd[1]`` (corrected
    ``detections`` are the ABFT success case; a report tree containing
    them is rejected loudly, since treating benign corrected faults as
    failures would burn every retry). The step must NOT apply side
    effects it cannot discard: on a report, ``new_state`` is dropped and
    ``state`` is re-used.

    On a report: retry up to ``max_retries`` times from the same
    pre-step state. If every attempt reports and ``checkpointer`` is
    given, restore its newest clean checkpoint (``restore_target``
    supplies the pytree structure/shardings, defaulting to ``state``)
    and run ONE attempt from there. If that also reports — or there is
    no checkpoint — raise :class:`UncorrectableStepError` (or, with
    ``raise_on_failure=False``, return the LAST CLEAN ``state`` with
    ``metrics=None`` and the report, so the caller owns the policy;
    neither the unverified ``new_state`` nor metrics computed by a
    reporting attempt are ever returned).

    Returns ``(new_state, metrics, StepReport)``. ``uncorrectable`` may
    be a scalar, an array, or a pytree — as long as every leaf counts
    uncorrectable intervals.

    ``on_persistent_fault(attempts, unc)`` is the EVICTION hook
    (resilience/elastic.py — the serving pool's device-eviction path,
    offered to the training loop): it fires once, after the same-state
    retries are exhausted but BEFORE any checkpoint restore, because a
    persistent report usually means a sick DEVICE, not a poisoned
    history — evicting the device and replaying the same step on the
    survivors is cheaper than rewinding time. The hook evicts the
    blamed device, rebuilds the step on the surviving mesh
    (:func:`~ft_sgemm_tpu.resilience.elastic.surviving_mesh` + the
    ordinary factories — that recompile is the re-AOT window), and
    returns the rebuilt ``step_fn`` (or None to decline). One attempt
    runs on the rebuilt step; success returns with
    ``report.evicted=True``, failure falls through to the checkpoint
    ladder USING the rebuilt step for its recovery attempt. The hook's
    transition lands as an ``evicted`` telemetry event (op ``train``).
    """

    def attempt(s):
        with telemetry.trace_span("resilient_step.attempt"):
            new_state, metrics, unc = step_fn(s)
        return new_state, metrics, gate_total(unc)

    attempts = 0
    for i in range(max_retries + 1):
        new_state, metrics, unc = attempt(state)
        attempts += 1
        if unc == 0:
            return new_state, metrics, StepReport(attempts - 1, None, 0)
        if i < max_retries:
            # A reported fault forces the next attempt from the same
            # pre-step state: one telemetry record per forced retry.
            telemetry.record_step_event(
                "retry", uncorrectable=unc, extra={"attempt": attempts})

    evicted = False
    if on_persistent_fault is not None:
        rebuilt = on_persistent_fault(attempts, unc)
        if rebuilt is not None:
            evicted = True
            telemetry.record_step_event(
                "evicted", op="train", uncorrectable=unc,
                extra={"attempt": attempts})

            def attempt(s, _fn=rebuilt):  # noqa: F811 — the rebuilt step
                with telemetry.trace_span("resilient_step.attempt"):
                    new_state, metrics, unc2 = _fn(s)
                return new_state, metrics, gate_total(unc2)

            new_state, metrics, unc = attempt(state)
            attempts += 1
            if unc == 0:
                return new_state, metrics, StepReport(
                    attempts - 1, None, 0, evicted=True)

    restored_step = None
    if checkpointer is not None:
        restored_step = checkpointer.latest_step
        if restored_step is not None:
            telemetry.record_step_event(
                "restore", uncorrectable=unc,
                extra={"restored_step": int(restored_step),
                       "attempt": attempts})
            target = state if restore_target is None else restore_target
            state = checkpointer.restore(restored_step, target)
            new_state, metrics, unc = attempt(state)
            attempts += 1
            if unc == 0:
                return new_state, metrics, StepReport(
                    attempts - 1, restored_step, 0, evicted=evicted)

    telemetry.record_step_event(
        "raise" if raise_on_failure else "exhausted",
        uncorrectable=unc,
        extra={"attempt": attempts,
               "restored_step": (None if restored_step is None
                                 else int(restored_step))})
    if raise_on_failure:
        raise UncorrectableStepError(
            f"step reported uncorrectable faults through {attempts} "
            f"attempt(s)"
            + (f" incl. one from checkpoint step {restored_step}"
               if restored_step is not None else
               " and no clean checkpoint was available"))
    # metrics from a reporting attempt were computed by unverified GEMMs:
    # suppress them along with new_state.
    return state, None, StepReport(attempts - 1, restored_step, unc,
                                   evicted=evicted)
