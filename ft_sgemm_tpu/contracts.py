"""Static contracts: the hand-maintained invariants, declared as data.

Several of the repo's hardest-won disciplines existed only as prose in
DESIGN.md and as reactive fixes (the PR-8 tuner-cache single-flight race
was found at runtime, not review). This module turns them into literal
tables a machine can read — the declaration half of the static contract
checker (``ft_sgemm_tpu/lint``, DESIGN.md §14), which parses this file
with ``ast`` (never imports it alongside jax) and cross-checks the
claims below against the actual source tree on every CI run.

Everything here is a pure literal: the checker extracts values
statically, and the module itself is one of its own stdlib-only targets
(loadable by file path from the jax-free bench supervisor and the CI
path-loadability smoke, ``scripts/stdlib_smoke.py``).
"""

from __future__ import annotations

# --- stdlib-only / path-loadable modules -------------------------------
#
# Modules the jax-free supervisor side of the system (bench.py's
# monitor process, scripts/{ingest_ledger,regen_results,summarize_bench},
# CI smoke steps) loads by FILE PATH via importlib — so they must import
# ONLY the standard library at module scope (collaborators lazy +
# injectable), use no relative imports anywhere, and stay loadable in a
# bare ``python -S`` process with no site-packages at all. The lint
# subsystem's import-graph pass enforces all three statically; the CI
# smoke proves it dynamically. Paths are repo-relative.
STDLIB_ONLY_MODULES = (
    "ft_sgemm_tpu/chaos/models.py",
    "ft_sgemm_tpu/chaos/policy.py",
    "ft_sgemm_tpu/contracts.py",
    "ft_sgemm_tpu/fleet/launch.py",
    "ft_sgemm_tpu/lint/core.py",
    "ft_sgemm_tpu/perf/compile_cache.py",
    "ft_sgemm_tpu/perf/economics.py",
    "ft_sgemm_tpu/perf/ledger.py",
    "ft_sgemm_tpu/perf/trend.py",
    "ft_sgemm_tpu/perf/wallclock.py",
    "ft_sgemm_tpu/serve/tracing.py",
    "ft_sgemm_tpu/telemetry/monitor.py",
    "ft_sgemm_tpu/telemetry/timeline.py",
    "ft_sgemm_tpu/telemetry/traceview.py",
)

# --- SMEM scalar-operand slot map --------------------------------------
#
# Every FT Pallas kernel body receives ONE flat SMEM scalar operand
# (``inj_ref``) carrying the injection spec and the runtime thresholds
# (ops/ft_sgemm.py builds it; thresholds ride as runtime scalars so
# auto/traced thresholds cost zero recompiles). The slot assignments are
# a cross-kernel ABI: two kernel bodies reading the same index MUST mean
# the same thing by it, or a silent mis-parameterization ships. The
# table maps each slot to its canonical meaning and the accepted
# binding spellings (the variable or keyword name a kernel body binds
# the read to — how the lint smem-slots pass verifies meaning
# statically). Slots 0-3 are the injection spec (PR 1), 4-6 the
# detect/correct thresholds (PR 3), 7 the adaptive margin (PR 7).
SCALAR_SLOTS = {
    0: ("inject_enabled", ("enabled",)),
    1: ("inject_every", ("every",)),
    2: ("inject_magnitude", ("magnitude",)),
    3: ("inject_col_stride", ("col_stride",)),
    4: ("detect_threshold", ("threshold",)),
    5: ("moment1_recheck_threshold", ("thr_m1",)),
    6: ("moment2_recheck_threshold", ("thr_m2",)),
    7: ("adaptive_margin", ("margin",)),
}

# Total scalar-operand length when every slot rides along (4 injection
# + 3 threshold slots always; slot 7 appended in adaptive mode).
N_SCALAR_SLOTS = 8

# --- transformer-block serving declarations ----------------------------
#
# The block-serving plane's own axis: every serve_block event labels its
# phase with one of these spellings, and telemetry's
# ``events.AXIS_LABELS["block_phase"]`` MIRRORS this tuple (the same
# import-free mirror discipline as the kernel axes — the lint axis-drift
# pass cross-checks the two). ``serve/blocks.py::PHASES`` is the runtime
# spelling of the same declaration.
BLOCK_PHASES = ("prefill", "decode")

# Rows appended to every KV-cache page tensor on write: the plain column
# sum and the weighted (w_i = i + 1) column sum — the ABFT row-locator
# pair that lets a read CORRECT a located single-element corruption in
# place (serve/kv_cache.py mirrors this as CHECKSUM_ROWS; DESIGN.md §15
# documents the layout).
KV_PAGE_CHECKSUM_ROWS = 2

# --- searched kernel-variant axes --------------------------------------
#
# The variant axes the tuner searches beyond the block tile (PR 13):
# pipeline depth, grid traversal order, Mosaic dimension semantics of the
# output dims, and the fused-epilogue activation/quantize families. Each
# tuple here MIRRORS the runtime declaration in ``configs.py``
# (PIPELINE_DEPTHS / GRID_ORDERS / DIM_SEMANTICS / EPILOGUE_ACTIVATIONS /
# EPILOGUE_QUANTIZE) — the same import-free mirror discipline as
# BLOCK_PHASES; the lint axis-drift pass cross-checks the two spellings,
# the tuner-key components, the telemetry label schema, and the CLI flag
# spellings against this table. The detect/correct cadence axis has no
# closed value set (any positive K-grid-step count, or the strategy
# default) so it appears only in the key-marker list below.
VARIANT_AXES = {
    "pipeline_depth": (2, 3),
    "grid_order": ("mn", "nm"),
    "dim_semantics": ("parallel", "arbitrary"),
    "epilogue_activation": ("none", "relu", "gelu"),
    "epilogue_quantize": ("none", "int8", "float8_e4m3fn"),
    # Ring collective hop schedule (PR 14): serial = compute-then-rotate,
    # overlap = double-buffered rotate-ahead (the ppermute producing the
    # next hop's shard is issued before the hop's local FT-GEMM, hiding
    # ICI behind the MXU). Mirrors configs.RING_OVERLAP_MODES.
    "ring_overlap": ("serial", "overlap"),
}

# The f-string markers the tuner cache key (schema 5) must carry for the
# variant axes — cross-checked against ``tuner/cache.py::make_key`` by
# the lint axis-drift pass exactly like the historical ``enc=``/``thr=``/
# ``inj=`` components. ``cad=`` is the detect/correct cadence, ``epi=``
# the epilogue spelling, ``ring=`` the ring hop schedule.
TUNER_VARIANT_KEY_MARKERS = ("pipe=", "grid=", "cad=", "epi=", "ring=")

# --- elastic recovery declarations -------------------------------------
#
# The DATA-PLANE checksum tiers (resilience/tiers.py::TIERS is the
# runtime spelling; telemetry's ``events.AXIS_LABELS["recovery_tier"]``
# mirrors this tuple — the BLOCK_PHASES import-free mirror discipline,
# cross-checked by the lint axis-drift pass). Every tier-of-detection
# label a tiered checksum check emits is one of these spellings, ordered
# cheapest-communication first: "device" = the per-device residual
# vector (no collective), "host" = after the first staged (ICI) axis,
# "global" = after every mesh axis (the arXiv 2112.09017 panel
# structure applied to checksum rows, not just counters).
RECOVERY_TIERS = ("device", "host", "global")

# The recovery-ladder rungs (resilience/recompute.py::LADDER_RUNGS is
# the runtime spelling; ``events.AXIS_LABELS["ladder_rung"]`` mirrors
# it), ordered cheapest-flops first. A recovery NEVER skips a cheaper
# rung whose localization precondition holds; each rung re-verifies
# through the resident checksums before the ladder stops:
#   element_correct   single located element repaired from its residual
#   panel_recompute   only the implicated output panel(s) recomputed
#                     from the resident A/B shards
#   shard_restore     the blamed device's whole output shard recomputed
#   full_retry        nothing local sufficed — the caller re-runs the
#                     whole distributed GEMM
LADDER_RUNGS = ("element_correct", "panel_recompute", "shard_restore",
                "full_retry")

# --- multi-device serve pool -------------------------------------------
#
# Placement policies of the serving layer's device pool
# (``serve/pool.py::PLACEMENTS`` is the runtime spelling of the same
# declaration — the BLOCK_PHASES mirror discipline): "health" steers
# each batch to the healthiest least-loaded device and DRAINS devices
# whose DeviceHealthTracker score falls below the pool's threshold;
# "round_robin" ignores health (the A/B control and the no-tracker
# fallback). Every pool placement event labels ``pool_placement`` with
# one of these spellings, and telemetry's
# ``events.AXIS_LABELS["pool_placement"]`` mirrors this tuple.
POOL_PLACEMENTS = ("health", "round_robin")

# --- fleet runtime ------------------------------------------------------
#
# Interconnect tier of a fleet host slot relative to the dispatching
# coordinator (``fleet/dispatch.py::HOST_TIERS`` is the runtime spelling
# — the BLOCK_PHASES mirror discipline; ``events.AXIS_LABELS
# ["host_tier"]`` mirrors this tuple): "local" = the coordinator's own
# process (no DCN hop), "dcn" = a remote rank reached over the
# data-center network. The dispatcher's placement cost multiplies load
# by the tier's DCN distance, so equal-load ties break toward local.
HOST_TIERS = ("local", "dcn")

# Placement policies of the cross-host fleet dispatcher
# (``fleet/dispatch.py::FLEET_PLACEMENTS`` runtime spelling;
# ``events.AXIS_LABELS["fleet_placement"]`` mirrors): "dcn_cost" scores
# each host slot by (load+1) * (1 + dcn_distance) / health — the
# 2112.09017 panel asymmetry as a placement cost term; "round_robin"
# ignores distance and health (the A/B control).
FLEET_PLACEMENTS = ("dcn_cost", "round_robin")

# The per-hop latency decomposition of one fleet-dispatched request
# (``fleet/dispatch.py::FLEET_HOPS`` is the runtime spelling — the
# BLOCK_PHASES import-free mirror discipline; ``events.AXIS_LABELS
# ["hop"]`` mirrors this tuple and the lint axis-drift pass
# cross-checks all three). Each hop is one ``fleet_hop_<hop>_seconds``
# histogram family, ordered along the request's path:
#   queue_wait      submit -> coordinator slot-worker dequeue
#   rtt             DCN wire round trip minus the remote's wall time
#                   (the 2112.09017 ICI/DCN asymmetry, measured)
#   remote_queue    remote wire-receive -> remote execute start
#   remote_execute  the remote rank's own execute wall time
#   retry           extra wall spent re-executing after detection
FLEET_HOPS = ("queue_wait", "rtt", "remote_queue", "remote_execute",
              "retry")

# --- request cost economics ---------------------------------------------
#
# The closed overhead-cause axis of the cost plane
# (``perf/economics.py::OVERHEAD_CAUSES`` is the runtime spelling — the
# BLOCK_PHASES mirror discipline; ``events.AXIS_LABELS
# ["overhead_cause"]`` mirrors this tuple and the lint axis-drift pass
# cross-checks all three). Every non-productive flop a request spends
# is attributed to exactly one of these causes, and every
# ``economics_overhead_flops_fraction{overhead_cause=}`` gauge and
# ledger overhead-fraction key is one of these spellings:
#   encode       ABFT checksum-row encode (the always-on premium)
#   check        detect/correct epilogue flops (always-on premium)
#   retry        full re-execution of bounded retry attempts
#   recompute    recovery-ladder rung flops (recover_local's pinned
#                accounting)
#   kv_reverify  stored-state re-verification + KV page restores
OVERHEAD_CAUSES = ("encode", "check", "retry", "recompute",
                   "kv_reverify")

# --- chaos campaign fault models ----------------------------------------
#
# The declarative fault-model axis of the chaos campaign plane
# (``chaos/models.py::FAULT_MODELS`` is the runtime spelling of the same
# declaration — the BLOCK_PHASES import-free mirror discipline;
# ``events.AXIS_LABELS["fault_model"]`` mirrors this tuple and the lint
# axis-drift pass cross-checks all three). Every campaign cell, coverage
# row, and ``chaos.<model>.*`` ledger measurement is keyed by one of
# these spellings:
#   bit_flip            transient single accumulator upset (in-kernel
#                       correctable — the reference's SDC)
#   stuck_device        persistent same-column fault pinned to one
#                       device (defeats localization; eviction path)
#   multi_device_burst  correlated sub-threshold corruption across
#                       devices in one instant (host/global tiers)
#   residual_drift      slow sub-static-threshold residual creep (the
#                       adaptive-threshold motivation, arXiv 2602.08043)
#   kv_rot              stored KV-cache page corruption at rest
#   throughput_sag      DVFS-style per-device slowdown/health decay
#                       (no data corruption; the health plane's model)
FAULT_MODELS = ("bit_flip", "stuck_device", "multi_device_burst",
                "residual_drift", "kv_rot", "throughput_sag")

# --- kernel-axis declaration sources -----------------------------------
#
# The six places the kernel axes (strategy x encode x dtype x threshold
# x bucket) are spelled — ROADMAP item 5's hand-threading surface. The
# lint axis-drift pass reads every one of these files and cross-checks
# the spellings; a new axis value added in one place but not the others
# is a finding. Paths are repo-relative.
AXIS_DECLARATION_SOURCES = (
    "ft_sgemm_tpu/configs.py",          # the axis tuples + legality tables
    "ft_sgemm_tpu/ops/vmem.py",         # per-variant VMEM footprint names
    "ft_sgemm_tpu/tuner/cache.py",      # cache-key components (enc=/thr=)
    "ft_sgemm_tpu/telemetry/events.py",  # event label schema mirror
    "ft_sgemm_tpu/serve/buckets.py",    # bucket legality + dtype routing
    "ft_sgemm_tpu/cli.py",              # user-facing flag spellings
)
