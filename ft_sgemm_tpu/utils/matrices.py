"""Matrix generation / verification helpers (reference ``utils/utils.cu``).

Same names and semantics as the reference host utilities, minus its known
defects (SURVEY.md §4): ``verify_vector`` here returns a real boolean (the
reference returns a function pointer, ``utils.cu:58``), and the copy helpers
drop the no-op ``src + i`` pointer-truthiness guards (``utils.cu:36,42``).

The value distribution matters: inputs are quantized to ±{0, 0.1, ..., 0.9}
(``utils.cu:23-31``) so that checksum accumulation noise stays far below the
fault-detection threshold.
"""

from __future__ import annotations

import numpy as np

_DEFAULT_SEED = 10  # reference: srand(10), sgemm.cu:12


def generate_random_matrix(n: int, m: int | None = None, seed: int | None = None,
                           rng: np.random.Generator | None = None) -> np.ndarray:
    """(n, m) f32 matrix with entries uniform over ±{0, 0.1, ..., 0.9}.

    Mirrors ``utils.cu:23-31``: magnitude ``(rand() % 10) * 0.1``, sign from
    a second draw. Uses numpy's Generator rather than libc rand (the native
    runtime offers exact-stream parity when needed).
    """
    m = n if m is None else m
    if rng is None:
        rng = np.random.default_rng(_DEFAULT_SEED if seed is None else seed)
    mag = rng.integers(0, 10, size=(n, m)).astype(np.float32) * np.float32(0.1)
    sign = np.where(rng.integers(0, 2, size=(n, m)) == 0, 1.0, -1.0).astype(np.float32)
    return mag * sign


def generate_random_vector(n: int, seed: int | None = None,
                           rng: np.random.Generator | None = None) -> np.ndarray:
    """(n,) f32 vector with entries ±(a*0.01 + b*0.001), a,b in {0..4}
    (``utils.cu:15-21``)."""
    if rng is None:
        rng = np.random.default_rng(_DEFAULT_SEED if seed is None else seed)
    a = rng.integers(0, 5, size=n).astype(np.float32) * np.float32(0.01)
    b = rng.integers(0, 5, size=n).astype(np.float32) * np.float32(0.001)
    sign = np.where(rng.integers(0, 2, size=n) == 0, 1.0, -1.0).astype(np.float32)
    return (a + b) * sign


def fill_vector(val: float, size: int) -> np.ndarray:
    """Constant f32 vector (``utils.cu:2-6``)."""
    return np.full((size,), val, dtype=np.float32)


def copy_vector(src: np.ndarray) -> np.ndarray:
    return np.array(src, dtype=np.float32, copy=True)


def copy_matrix(src: np.ndarray) -> np.ndarray:
    return np.array(src, dtype=np.float32, copy=True)


def verify_matrix(ref: np.ndarray, out: np.ndarray, verbose: bool = True,
                  abs_tol: float = 0.01, rel_tol: float = 0.01):
    """Reference tolerance policy: an element fails iff its absolute error
    > abs_tol AND its relative error (vs ref) > rel_tol (defaults from
    ``utils.cu:61-77``).

    Returns (ok, num_bad, first_bad_index_or_None). Vectorized instead of
    the reference's early-exit double loop; same accept/reject set.
    """
    ref = np.asarray(ref, dtype=np.float64)
    out = np.asarray(out, dtype=np.float64)
    diff = np.abs(ref - out)
    denom = np.abs(ref)
    with np.errstate(divide="ignore", invalid="ignore"):
        rel = np.where(denom > 0, diff / denom, np.inf)
    bad = (diff > abs_tol) & (rel > rel_tol)
    num_bad = int(bad.sum())
    ok = num_bad == 0
    first = None
    if not ok:
        first = tuple(int(x) for x in np.argwhere(bad)[0])
        if verbose:
            i = first
            print(
                f"error is {diff[i]:8.5f}, relative error is {rel[i]:8.5f}, "
                f"{ref[i]:8.5f},{out[i]:8.5f}. id: {', '.join(map(str, i))}"
            )
    return ok, num_bad, first


def verify_vector(ref: np.ndarray, out: np.ndarray):
    """Vector tolerance: fail iff abs > 1e-2 AND rel > 5e-3
    (``utils.cu:47-59``; the reference's return value is broken — it returns
    ``cudaSetDeviceFlags`` — this one returns the actual flag)."""
    ref = np.asarray(ref, dtype=np.float64)
    out = np.asarray(out, dtype=np.float64)
    diff = np.abs(ref - out)
    with np.errstate(divide="ignore", invalid="ignore"):
        rel = np.where(ref != 0, diff / np.abs(ref), np.inf)
    bad = (diff > 1e-2) & (rel > 5e-3)
    return not bool(bad.any()), int(bad.sum())


def print_matrix(mat: np.ndarray) -> str:
    """Pretty print (reference ``utils.cu:91`` prints its column-major
    buffers; our arrays are row-major numpy, so this prints them as laid
    out)."""
    mat = np.asarray(mat)
    lines = []
    for i in range(mat.shape[0]):
        lines.append("  ".join(f"{mat[i, j]:8.5f}" for j in range(mat.shape[1])))
    text = "\n".join(lines)
    print(text)
    return text
