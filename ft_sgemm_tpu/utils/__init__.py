"""Host-side utilities: matrix generation/verification and timing."""

from ft_sgemm_tpu.utils.matrices import (
    generate_random_matrix,
    generate_random_vector,
    fill_vector,
    copy_matrix,
    copy_vector,
    verify_matrix,
    verify_vector,
    print_matrix,
)
from ft_sgemm_tpu.utils.timing import Timer, time_fn, gflops

__all__ = [
    "generate_random_matrix",
    "generate_random_vector",
    "fill_vector",
    "copy_matrix",
    "copy_vector",
    "verify_matrix",
    "verify_vector",
    "print_matrix",
    "Timer",
    "time_fn",
    "gflops",
]
