"""Timing helpers (reference: cudaEvent timing ``sgemm.cu:253-265`` and the
unused ``saxpy_timer`` chrono class ``utils.cuh:20-41``).

On TPU the device boundary is ``block_until_ready``; GFLOPS bookkeeping
mirrors the reference protocol: ``2 * reps * M * N * K / elapsed`` with 5
timed reps (``sgemm.cu:21-24,431-434``).
"""

from __future__ import annotations

import time

import jax

NUM_TESTS = 5  # reference num_tests, sgemm.cu:21


class Timer:
    """Start/elapsed wall-clock timer (reference ``saxpy_timer``)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def elapsed_ms(self) -> float:
        return self.elapsed() * 1e3


def time_fn(fn, *args, reps: int = NUM_TESTS, warmup: int = 1) -> float:
    """Seconds for ``reps`` synchronous executions of ``fn(*args)``.

    Mirrors the reference loop shape: sync, launch, sync per rep
    (``sgemm.cu:258-262``). ``warmup`` runs first (compile + cache) and is
    excluded — the reference gets this implicitly from its verification pass.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def median_seconds_per_call(fn, *args, reps: int = NUM_TESTS,
                            samples: int = 3, warmup: int = 1) -> float:
    """Median-of-``samples`` seconds-per-call of ``fn(*args)``.

    The autotuner's measurement discipline (``ft_sgemm_tpu.tuner.measure``):
    ``warmup`` excluded runs absorb compilation and caches, then each
    sample times ``reps`` synchronous executions (:func:`time_fn`) and the
    median sample divided by ``reps`` is returned — the median is robust
    to the one-off scheduling hiccups that poison a min- or mean-of-one
    reading, while staying far cheaper than the full
    :func:`bench_seconds_per_call` protocol (which exists for tunnel-grade
    dispatch overhead, not for ranking dozens of candidates).
    """
    import statistics

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = [time_fn(fn, *args, reps=reps, warmup=0)
             for _ in range(max(1, samples))]
    return statistics.median(times) / max(1, reps)


def gflops(m: int, n: int, k: int, seconds: float, reps: int = NUM_TESTS) -> float:
    """GFLOPS under the reference's formula (``sgemm.cu:431-434``)."""
    if seconds <= 0:
        return float("inf")
    return (2.0 * reps * m * n * k) / 1e9 / seconds


def _make_rep_loop(fn):
    """The jitted dynamic-trip rep loop shared by the timing path and the
    AOT compile probe — ONE constructor so both produce byte-identical
    HLO and therefore share persistent-compile-cache entries (a probe
    compile is then a guaranteed cache hit for the later timed run)."""
    import jax as _jax
    import jax.numpy as jnp

    @_jax.jit
    def loop(a, b, c, reps, salt):
        def body(i, t):
            # The barrier makes a/c "depend" on the carry so XLA cannot
            # hoist the (otherwise loop-invariant) call out of the loop.
            a2, c2, t2 = _jax.lax.optimization_barrier((a, c, t + salt))
            y = fn(a2, b, c2)
            # Dynamic (value-dependent, always-0-but-unprovable) index:
            # defeats static slice-of-dot simplification.
            idx = jnp.remainder(t2.astype(jnp.int32), y.shape[0])
            row = _jax.lax.dynamic_index_in_dim(y, idx, axis=0,
                                                keepdims=False)
            return t2 + 1e-30 * row[0].astype(jnp.float32)
        return _jax.lax.fori_loop(0, reps, body, jnp.float32(0))

    return loop


def compile_bench_loop(fn, a, b, c) -> None:
    """AOT-compile the exact executable ``bench_seconds_per_call`` would
    run for ``fn`` at these operand shapes, WITHOUT executing it.

    ``a``/``b``/``c`` may be ``jax.ShapeDtypeStruct``s — no data touches
    the device; on the axon tunnel, Mosaic/XLA compilation happens in the
    chipless remote compile helper, so this needs only the tunnel's
    compile service. With the persistent compile cache configured, every
    probe compile is banked for the later timed run
    (``scripts/compile_probe.py`` — the window-open ladder proof of
    VERDICT r5 #1a). Raises on compile failure (e.g. a Mosaic
    scoped-VMEM OOM), which is the probe's entire point.
    """
    import jax.numpy as jnp

    # Same arg classes as the timing path: python-int reps (weak i32),
    # f32 scalar salt — identical avals, identical HLO, identical cache
    # key.
    _make_rep_loop(fn).lower(a, b, c, NUM_TESTS, jnp.float32(0)).compile()


def bench_seconds_per_call(fn, a, b, c, *, min_device_time: float = 1.0,
                           max_reps: int = 1 << 16,
                           phase_info: dict = None) -> float:
    """Robust seconds-per-call of ``fn(a, b, c) -> array`` on device.

    The reference brackets 5 launches with cudaEvents (``sgemm.cu:253-265``);
    over a tunneled TPU a dispatch roundtrip costs ~50 ms, so instead the rep
    loop runs *inside* one jitted computation with a **dynamic trip count**
    (one compile, any rep count). Reps scale until device time >=
    ``min_device_time``; a zero-rep dispatch measures fixed overhead, which
    is subtracted.

    Iteration chaining uses ``optimization_barrier`` + a scalar carry — NOT
    elementwise work on the operands. An earlier version chained by damping
    the full C feedback (``x * 1e-3``) and salting A (``a * s``): ~190 MB of
    per-rep HBM traffic that XLA fuses into its own dot's epilogue but can
    NEVER fuse into an opaque Pallas custom call, silently penalizing every
    Pallas row ~5 % (f32) to ~20 % (bf16) against the ``xla_dot`` row. The
    barrier fakes the loop-carried dependence at zero data movement, so both
    kernel families are timed bare. The carry consumes one output element at
    a RUNTIME-DEPENDENT index (derived from the carry itself), so XLA's
    algebraic simplifier cannot statically rewrite slice-of-dot into a
    cheap dot-of-slices for the pure-XLA rows — the full product stays
    load-bearing every iteration.

    For bf16 kernels pass pre-cast bf16 ``a``/``b``: the wrappers' casts
    then trace to no-ops instead of per-rep device work.

    ``phase_info`` (optional dict, filled in place) receives the stage's
    wall-clock decomposition — ``lower_seconds`` / ``compile_seconds``
    (the explicit ``lower()``/``.compile()`` separation; with the
    persistent compile cache warm, "compile" is mostly cache retrieval)
    and ``execute_seconds`` (everything after the executable existed) —
    the split the bench timeline streams per stage span and
    ``perf/wallclock.py`` rolls into per-run phase fractions. The AOT
    executable from that one compile is what every timed dispatch calls,
    so the split costs no second compile and the timed path runs the
    byte-identical module :func:`compile_bench_loop` pre-banks.
    """
    import itertools

    import jax.numpy as jnp

    loop = _make_rep_loop(fn)
    info = {} if phase_info is None else phase_info

    # Same arg spelling as compile_bench_loop (python-int reps, f32 salt):
    # identical avals => identical HLO => shared persistent-cache key.
    t0 = time.perf_counter()
    lowered = loop.lower(a, b, c, NUM_TESTS, jnp.float32(0))
    info["lower_seconds"] = round(time.perf_counter() - t0, 6)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    info["compile_seconds"] = round(time.perf_counter() - t0, 6)

    # A fresh salt per dispatch defeats any result caching of identical
    # executions in the runtime (observed over the axon tunnel).
    counter = itertools.count(1)

    def run(reps):
        salt = jnp.float32(next(counter) * 1e-7)
        t0 = time.perf_counter()
        float(compiled(a, b, c, reps, salt))
        return time.perf_counter() - t0

    t_exec = time.perf_counter()
    run(1)  # warmup (compile already paid above; device caches settle)
    overhead = min(run(0) for _ in range(3))
    reps = NUM_TESTS
    t = run(reps)
    while t - overhead < min_device_time and reps < max_reps:
        scale = min_device_time / max(t - overhead, 1e-4)
        reps = min(max_reps, max(reps + 1, int(reps * min(scale, 8.0)) + 1))
        t = run(reps)
    best = min(t, *[run(reps) for _ in range(2)])
    info["execute_seconds"] = round(time.perf_counter() - t_exec, 6)
    return max((best - overhead) / reps, 1e-9)
