"""Timing helpers (reference: cudaEvent timing ``sgemm.cu:253-265`` and the
unused ``saxpy_timer`` chrono class ``utils.cuh:20-41``).

On TPU the device boundary is ``block_until_ready``; GFLOPS bookkeeping
mirrors the reference protocol: ``2 * reps * M * N * K / elapsed`` with 5
timed reps (``sgemm.cu:21-24,431-434``).
"""

from __future__ import annotations

import time

import jax

NUM_TESTS = 5  # reference num_tests, sgemm.cu:21


class Timer:
    """Start/elapsed wall-clock timer (reference ``saxpy_timer``)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def elapsed_ms(self) -> float:
        return self.elapsed() * 1e3


def time_fn(fn, *args, reps: int = NUM_TESTS, warmup: int = 1) -> float:
    """Seconds for ``reps`` synchronous executions of ``fn(*args)``.

    Mirrors the reference loop shape: sync, launch, sync per rep
    (``sgemm.cu:258-262``). ``warmup`` runs first (compile + cache) and is
    excluded — the reference gets this implicitly from its verification pass.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def gflops(m: int, n: int, k: int, seconds: float, reps: int = NUM_TESTS) -> float:
    """GFLOPS under the reference's formula (``sgemm.cu:431-434``)."""
    if seconds <= 0:
        return float("inf")
    return (2.0 * reps * m * n * k) / 1e9 / seconds


def bench_seconds_per_call(fn, a, b, c, *, min_device_time: float = 1.0,
                           max_reps: int = 1 << 16) -> float:
    """Robust seconds-per-call of ``fn(a, b, c) -> array`` on device.

    The reference brackets 5 launches with cudaEvents (``sgemm.cu:253-265``);
    over a tunneled TPU a dispatch roundtrip costs ~50 ms, so instead the rep
    loop runs *inside* one jitted computation with a **dynamic trip count**
    (one compile, any rep count), chained data-dependently (C feeds back) so
    no iteration can be elided. Reps scale until device time >=
    ``min_device_time``; a zero-rep dispatch measures fixed overhead, which
    is subtracted.
    """
    import itertools

    import jax.numpy as jnp
    import jax as _jax

    @_jax.jit
    def loop(a, b, c, reps, salt):
        def body(i, x):
            # Thread a negligible x-dependency into A so XLA cannot hoist
            # the (otherwise loop-invariant) matmul out of the rep loop,
            # and damp x so the chain stays bounded at any rep count
            # (|x'| <= |A@B.T| + |beta|*1e-3*|x| converges; undamped,
            # beta=-1.5 grows |x| 1.5x/rep and overflows f32 by rep ~205).
            s = 1.0 + 1e-30 * jnp.sum(x)
            return fn(a * s, b, x * 1e-3)
        return jnp.sum(_jax.lax.fori_loop(0, reps, body, c + salt))

    # A fresh salt per dispatch defeats any result caching of identical
    # executions in the runtime (observed over the axon tunnel).
    counter = itertools.count(1)

    def run(reps):
        salt = jnp.float32(next(counter) * 1e-6)
        t0 = time.perf_counter()
        float(loop(a, b, c, reps, salt))
        return time.perf_counter() - t0

    run(1)  # compile + warmup
    overhead = min(run(0) for _ in range(3))
    reps = NUM_TESTS
    t = run(reps)
    while t - overhead < min_device_time and reps < max_reps:
        scale = min_device_time / max(t - overhead, 1e-4)
        reps = min(max_reps, max(reps + 1, int(reps * min(scale, 8.0)) + 1))
        t = run(reps)
    best = min(t, *[run(reps) for _ in range(2)])
    return max((best - overhead) / reps, 1e-9)
