"""MTBF-driven policy selection: measured coverage -> recommended knobs.

The campaign measures, per fault model, what was detected, where, how
fast, and at what cost (``chaos/campaign.py``). This module turns those
measurements plus the model's MTBF into the three knobs the rest of the
stack already exposes, with the derivation recorded next to the number
(DESIGN.md §20):

- **check cadence** (``check_every``, the PR-13 searched axis): the
  detect/correct check costs overhead ~ 1/every per step, while a
  sparser cadence widens the detection window and with it the expected
  rework after a fault (window x fault rate x MTTR). Minimizing
  ``c/every + every * window_cost / mtbf`` gives the square-root law
  ``every* ~ sqrt(mtbf)`` — MONOTONE in MTBF: rarer faults buy sparser
  (cheaper) checking. We use the measured detection window (p95
  detection latency, floored by MTTR) as the per-fault cost unit.
- **threshold mode** (the PR-7 static/adaptive tradeoff): adaptive
  wins exactly when the model's measured static detection rate falls
  below its adaptive rate (the residual-drift case) — otherwise static
  is free and recommended.
- **tier config**: hierarchical data-plane checks are worth their
  collectives only for models whose measured tier-of-detection
  includes host/global findings (per-device checks would have missed
  them); eviction is recommended for persistent/degradation models.

HARD CONSTRAINT — stdlib only, no package-relative imports
(``contracts.STDLIB_ONLY_MODULES`` lists this file): inputs are the
plain dicts the campaign emits, so the policy layer runs in the
jax-free supervisor and in tests without building any workload.
"""

from __future__ import annotations

import math
from typing import Optional

# Cadence clamp: every=1 is the densest legal detect/correct cadence;
# 64 K-steps is the sparsest any shipped grid sustains (beyond it the
# check never runs on small problems).
MIN_CHECK_EVERY = 1
MAX_CHECK_EVERY = 64

# The overhead unit: measured PR-13 cadence sweeps put one
# detect/correct check at ~1% of a K-step's MXU work, so the
# square-root law is scaled such that an MTBF of ~1 minute of calls
# still checks densely while multi-hour MTBFs saturate the clamp.
CHECK_COST_SECONDS = 0.01


def recommend_cadence(mtbf_seconds: float,
                      window_seconds: Optional[float] = None) -> int:
    """The square-root-law cadence for one measured model.

    ``every* = sqrt(mtbf / window)`` scaled by the check-cost unit,
    clamped to the legal range. ``window_seconds`` is the measured
    per-fault cost (p95 detection latency floored by MTTR); None or
    non-positive falls back to 1s — the clamp still guarantees
    monotonicity in MTBF, the property the tests pin.
    """
    if mtbf_seconds <= 0:
        return MIN_CHECK_EVERY
    window = window_seconds if window_seconds and window_seconds > 0 \
        else 1.0
    every = math.sqrt(mtbf_seconds * CHECK_COST_SECONDS / window * 100.0)
    return max(MIN_CHECK_EVERY, min(MAX_CHECK_EVERY, int(round(every))))


def recommend(model: dict, rollup: dict) -> dict:
    """The per-model policy: (cadence, threshold mode, tier config)
    with its measured justification.

    ``model`` is a :meth:`FaultModel.to_dict` dict (``mtbf_seconds``,
    ``temporal``, ``correctable``); ``rollup`` is the campaign's
    per-model rollup (``p95_detection_latency_seconds``,
    ``mttr_seconds``, ``detection_rate``, ``static_detection_rate``
    when the cell A/B'd threshold modes, ``tier_of_detection``).
    Returns a plain dict recorded verbatim in COVERAGE.json.
    """
    mtbf = float(model.get("mtbf_seconds") or 0.0)
    p95 = rollup.get("p95_detection_latency_seconds")
    mttr = rollup.get("mttr_seconds")
    window = max(float(p95 or 0.0), float(mttr or 0.0)) or None
    every = recommend_cadence(mtbf, window)

    det = rollup.get("detection_rate")
    static_det = rollup.get("static_detection_rate")
    adaptive = (static_det is not None and det is not None
                and float(static_det) < float(det))
    threshold_mode = "adaptive" if adaptive else "static"

    tiers = dict(rollup.get("tier_of_detection") or {})
    staged = (tiers.get("host", 0) or 0) + (tiers.get("global", 0) or 0)
    tier_config = "tiered" if staged > 0 else "device"
    evict = model.get("temporal") in ("persistent", "drift") \
        and not model.get("correctable", False)

    just = [f"mtbf={mtbf:.0f}s"]
    if window is not None:
        just.append(f"detect_window={window:.3f}s")
    just.append(f"sqrt-law cadence every={every}")
    if adaptive:
        just.append(
            f"static detection {float(static_det):.2f} <"
            f" adaptive {float(det):.2f} -> adaptive threshold")
    else:
        just.append("static threshold sufficient at measured rates")
    if staged:
        just.append(
            f"{staged} host/global-tier detections -> tiered checks")
    if evict:
        just.append("persistent/degradation model -> eviction enabled")

    return {
        "check_every": every,
        "threshold_mode": threshold_mode,
        "tier_config": tier_config,
        "evict": bool(evict),
        "justification": "; ".join(just),
    }


__all__ = ["MAX_CHECK_EVERY", "MIN_CHECK_EVERY", "recommend",
           "recommend_cadence"]
