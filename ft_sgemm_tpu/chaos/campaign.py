"""The chaos campaign runner: fault model x workload -> measured coverage.

Sweeps the declared fault models (``chaos/models.py``) across the real
workloads — the GEMM serve engine, the transformer-block engine with
its checked KV cache, ``train.resilient_step``, and the health-steered
device pool — and measures, per (model, workload) cell:

- **detection rate** and **detection latency** (injection-to-event wall
  time, observed live through :func:`telemetry.add_observer` and
  recorded into the ``fault_detection_latency_seconds`` histogram);
- **tier-of-detection** distribution (device / host / global /
  kv_page / health — where the stack first saw the fault);
- **correction rate** and **MTTR** (injection to verified-correct
  output, whatever the recovery path: in-kernel correction, retry,
  eviction, recompute);
- **false-positive rate** on CLEAN TWINS (the same harness, no fault —
  any detection there is a false alarm);
- **goodput retention** (faulted throughput relative to clean).

The result is the coverage matrix artifact (``COVERAGE.json``): an
artifact-shaped doc (``metric: chaos_coverage``) whose context carries
the full matrix, so ``perf/ledger.py`` ingests it directly and
``perf/trend.py`` gates per-model regressions. ``chaos/policy.py``
turns each model's measurements into a recommended (cadence, threshold
mode, tier config) recorded alongside.

Threading note (``lint.core.THREADED_MODULES`` lists this file): the
telemetry observer runs on whatever thread records the event — engine
workers included — so the event buffer lives on the instance behind
``self._lock``; episodes themselves run sequentially on the caller's
thread, which is what makes injection-to-event matching unambiguous.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Iterable, Optional, Sequence

import numpy as np

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.chaos import policy as _policy
from ft_sgemm_tpu.chaos.models import (
    FAULT_MODELS,
    MODELS,
    WORKLOADS,
    draw_episode,
)
from ft_sgemm_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    MetricsRegistry,
)

# Detection outcomes a recovery-plane event may carry: any of these in
# an episode's window counts as "the stack saw the fault".
_FAULT_OUTCOMES = ("uncorrectable", "retry", "restore", "exhausted",
                   "evicted")

# COVERAGE.json schema version (bumped on breaking layout changes; the
# render and the ledger chaos block read this).
COVERAGE_SCHEMA = 1


def _is_detection(ev) -> bool:
    """Does one observed FaultEvent indicate a fault finding (as opposed
    to a clean call report)?"""
    if (getattr(ev, "detected", 0) or 0) > 0:
        return True
    if (getattr(ev, "uncorrectable", 0) or 0) > 0:
        return True
    return getattr(ev, "outcome", None) in _FAULT_OUTCOMES


class _CellStats:
    """Accumulator for one (model, workload) cell's episodes."""

    def __init__(self):
        self.faults = 0
        self.detections = 0
        self.corrections = 0
        self.recoveries = 0
        self.incorrect = 0
        self.latencies: list = []
        self.mttrs: list = []
        self.fault_walls: list = []
        self.clean_walls: list = []
        self.clean_episodes = 0
        self.false_positives = 0
        self.tiers: dict = {}
        self.extra: dict = {}

    def add_fault(self, *, detected: bool, corrected: bool,
                  recovered: bool, latency: Optional[float],
                  mttr: Optional[float], tier: Optional[str],
                  incorrect: bool, wall: float) -> None:
        self.faults += 1
        self.fault_walls.append(wall)
        if detected:
            self.detections += 1
            if latency is not None:
                self.latencies.append(float(latency))
            if tier:
                self.tiers[tier] = self.tiers.get(tier, 0) + 1
        if corrected:
            self.corrections += 1
        if recovered:
            self.recoveries += 1
        if mttr is not None:
            self.mttrs.append(float(mttr))
        if incorrect:
            self.incorrect += 1

    def add_clean(self, *, false_positive: bool, wall: float) -> None:
        self.clean_episodes += 1
        self.clean_walls.append(wall)
        if false_positive:
            self.false_positives += 1

    def _goodput_retention(self) -> Optional[float]:
        if "goodput_retention" in self.extra:
            return self.extra["goodput_retention"]
        if not self.fault_walls or not self.clean_walls:
            return None
        clean = float(np.mean(self.clean_walls))
        fault = float(np.mean(self.fault_walls))
        if fault <= 0:
            return 1.0
        return round(min(1.0, clean / fault), 4)

    def finalize(self) -> dict:
        lat = np.asarray(self.latencies, dtype=np.float64)
        cell = {
            "episodes": self.faults + self.clean_episodes,
            "faults_injected": self.faults,
            "detections": self.detections,
            "detection_rate": (round(self.detections / self.faults, 4)
                               if self.faults else None),
            "corrections": self.corrections,
            "correction_rate": (round(self.corrections / self.faults, 4)
                                if self.faults else None),
            "recoveries": self.recoveries,
            "detection_latency_seconds": (
                {"mean": round(float(lat.mean()), 6),
                 "p95": round(float(np.percentile(lat, 95.0)), 6),
                 "max": round(float(lat.max()), 6)}
                if lat.size else None),
            "mttr_seconds": (round(float(np.mean(self.mttrs)), 6)
                             if self.mttrs else None),
            "clean_episodes": self.clean_episodes,
            "false_positives": self.false_positives,
            "false_positive_rate": (
                round(self.false_positives / self.clean_episodes, 4)
                if self.clean_episodes else None),
            "goodput_retention": self._goodput_retention(),
            "tier_of_detection": dict(self.tiers),
            "incorrect_results": self.incorrect,
        }
        for k, v in self.extra.items():
            if k != "goodput_retention":
                cell[k] = v
        return cell


class ChaosCampaign:
    """One campaign: selected fault models across their workloads.

    ``episodes`` faulted + ``clean_episodes`` clean-twin runs per cell,
    all drawn from one ``random.Random(seed)`` stream per cell (seeded
    determinism: same seed, same schedule). ``registry`` receives the
    ``chaos_*`` counters, the ``coverage_*`` gauges, and the
    ``fault_detection_latency_seconds`` histogram; ``timeline`` (a
    :class:`~ft_sgemm_tpu.telemetry.timeline.TimelineRecorder`) gets
    one ``chaos`` span per cell.
    """

    def __init__(self, *, models: Optional[Iterable[str]] = None,
                 workloads: Optional[Iterable[str]] = None,
                 episodes: int = 3, clean_episodes: int = 2,
                 seed: int = 10,
                 registry: Optional[MetricsRegistry] = None,
                 timeline=None):
        names = tuple(models) if models else FAULT_MODELS
        for name in names:
            if name not in MODELS:
                raise ValueError(
                    f"unknown fault model {name!r} (declared:"
                    f" {FAULT_MODELS})")
        self.models = names
        self.workloads = tuple(workloads) if workloads else WORKLOADS
        for w in self.workloads:
            if w not in WORKLOADS:
                raise ValueError(
                    f"unknown workload {w!r} (known: {WORKLOADS})")
        if episodes < 1:
            raise ValueError(f"episodes={episodes} must be >= 1")
        self.episodes = int(episodes)
        self.clean_episodes = int(clean_episodes)
        self.seed = int(seed)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.timeline = timeline
        self._lock = threading.Lock()
        self._events: list = []

    # -- live detection observation -------------------------------------

    def _observe(self, ev) -> None:
        # Runs on whatever thread recorded the event (engine workers
        # included) — append-only under the instance lock, scanned by
        # the sequential episode loop.
        ts = time.time()
        with self._lock:
            self._events.append((ts, ev))

    def _detection_ts(self, t0: float,
                      ops: Optional[Sequence[str]] = None
                      ) -> Optional[float]:
        """Wall timestamp of the first fault-indicating event at or
        after ``t0`` (optionally restricted to ops), or None."""
        with self._lock:
            snapshot = list(self._events)
        for ts, ev in snapshot:
            if ts < t0:
                continue
            if ops is not None and getattr(ev, "op", None) not in ops:
                continue
            if _is_detection(ev):
                return ts
        return None

    def _saw_detection(self, t0: float) -> bool:
        return self._detection_ts(t0) is not None

    # -- per-episode bookkeeping ----------------------------------------

    def _note_detection(self, model_name: str, workload: str,
                        latency: float) -> None:
        """One measured detection: histogram observation + the campaign
        event (``alert``) whose extra lets ``registry_from_events``
        rebuild the same histogram from the JSONL log."""
        self.registry.histogram(
            "fault_detection_latency_seconds", buckets=LATENCY_BUCKETS,
            fault_model=model_name).observe(float(latency))
        self.registry.counter("chaos_detections", fault_model=model_name,
                              workload=workload).inc()
        telemetry.record_step_event(
            "alert", op="chaos",
            extra={"fault_model": model_name, "workload": workload,
                   "detection_latency_seconds": round(float(latency), 6)})

    def _span(self, name: str):
        if self.timeline is None:
            return contextlib.nullcontext({})
        return self.timeline.span(name, kind="chaos")

    # -- workload harnesses ---------------------------------------------

    def _cell_gemm_serve(self, model, rng) -> dict:
        from ft_sgemm_tpu.serve.buckets import default_bucket_set
        from ft_sgemm_tpu.serve.engine import ServeEngine, ServeRequest

        stats = _CellStats()
        engine = ServeEngine(default_bucket_set(sizes=(256,)),
                             threshold="static", max_batch=1,
                             max_wait=0.01, registry=self.registry)
        engine.start()
        engine.prewarm(variants=("clean", "inject"))
        try:
            for i in range(self.episodes):
                draw_episode(model, rng)  # keep the stream aligned
                a, b = _operands(self.seed + i, 64, 64, 256)
                t0 = time.time()
                res = engine.submit(
                    ServeRequest(a, b, variant="inject")).result(300.0)
                wall = time.time() - t0
                detected = res.detections > 0
                det_ts = self._detection_ts(t0)
                latency = ((det_ts - t0) if det_ts is not None
                           else (res.latency_seconds if detected
                                 else None))
                # atol=1.0: ABFT correction subtracts a checksum
                # estimate of a ~1e4 fault, leaving float noise well
                # under 1; an UNcorrected fault leaves ~1e4.
                incorrect = bool(
                    res.ok and not np.allclose(
                        res.c, a.astype(np.float64)
                        @ b.astype(np.float64).T,
                        rtol=1e-3, atol=1.0))
                stats.add_fault(
                    detected=detected,
                    corrected=bool(res.corrected and res.ok),
                    recovered=bool(res.ok), latency=latency,
                    mttr=res.latency_seconds if res.ok else None,
                    tier="device" if detected else None,
                    incorrect=incorrect, wall=wall)
                if detected and latency is not None:
                    self._note_detection(model.name, "gemm_serve",
                                         latency)
            for i in range(self.clean_episodes):
                a, b = _operands(self.seed + 100 + i, 64, 64, 256)
                t0 = time.time()
                res = engine.submit(
                    ServeRequest(a, b, variant="clean")).result(300.0)
                wall = time.time() - t0
                stats.add_clean(
                    false_positive=bool(res.detections > 0
                                        or self._saw_detection(t0)),
                    wall=wall)
        finally:
            engine.close()
        return stats.finalize()

    def _cell_block_serve(self, model, rng) -> dict:
        from ft_sgemm_tpu.ops.attention import attention_reference
        from ft_sgemm_tpu.serve.blocks import BlockEngine, BlockRequest
        from ft_sgemm_tpu.serve.buckets import default_block_bucket_set

        stats = _CellStats()
        engine = BlockEngine(
            default_block_bucket_set((128,), d=64, dv=64),
            max_batch=1, max_wait=0.01, kv_page_size=16,
            registry=self.registry)
        engine.start()
        engine.prewarm(variants=("clean",))

        def one_sequence(ep_seed, corrupt):
            nrng = np.random.default_rng(ep_seed)
            L = 24
            q = nrng.standard_normal((L, 64)).astype(np.float32)
            k = nrng.standard_normal((L, 64)).astype(np.float32)
            v = nrng.standard_normal((L, 64)).astype(np.float32)
            pre = BlockRequest("prefill", q, k, v)
            sid = pre.seq_id
            engine.submit(pre).result(300.0)
            t0 = time.time()
            if corrupt is not None:
                engine.corrupt_kv(
                    sid, row=corrupt["row"], cols=(corrupt["col"],),
                    magnitude=corrupt["magnitude"],
                    which=corrupt["which"])
            dq = nrng.standard_normal((1, 64)).astype(np.float32)
            dk = nrng.standard_normal((1, 64)).astype(np.float32)
            dv = nrng.standard_normal((1, 64)).astype(np.float32)
            res = engine.submit(
                BlockRequest("decode", dq, dk, dv,
                             seq_id=sid)).result(300.0)
            wall = time.time() - t0
            k_all = np.concatenate([k, dk])
            v_all = np.concatenate([v, dv])
            want = np.asarray(attention_reference(dq, k_all, v_all,
                                                  causal=True))
            correct = bool(np.allclose(np.asarray(res.out), want,
                                       rtol=1e-3, atol=1e-3))
            return t0, res, wall, correct

        try:
            for i in range(self.episodes):
                draw = draw_episode(model, rng)
                t0, res, wall, correct = one_sequence(
                    self.seed + i, draw)
                detected = res.kv_faults > 0
                det_ts = self._detection_ts(t0, ops=("kv_page",))
                latency = ((det_ts - t0) if det_ts is not None
                           else (res.latency_seconds if detected
                                 else None))
                stats.add_fault(
                    detected=detected,
                    corrected=bool(res.kv_corrected > 0 and res.ok),
                    recovered=bool(res.ok and correct), latency=latency,
                    mttr=res.latency_seconds if res.ok else None,
                    tier="kv_page" if detected else None,
                    incorrect=bool(res.ok and not correct), wall=wall)
                if detected and latency is not None:
                    self._note_detection(model.name, "block_serve",
                                         latency)
            for i in range(self.clean_episodes):
                t0, res, wall, correct = one_sequence(
                    self.seed + 100 + i, None)
                stats.add_clean(
                    false_positive=bool(res.kv_faults > 0
                                        or res.detections > 0),
                    wall=wall)
        finally:
            engine.close()
        return stats.finalize()

    def _cell_train_step(self, model, rng) -> dict:
        if model.name == "multi_device_burst":
            return self._train_burst(model, rng)
        if model.name == "residual_drift":
            return self._train_drift(model, rng)
        return self._train_inject(model, rng)

    def _train_inject(self, model, rng) -> dict:
        """bit_flip / stuck_device through ``resilient_step``: a real
        FT-GEMM step whose injection spec realizes the model; the
        persistent model survives retries and recovers through the
        eviction hook (the rebuilt step drops the sick device's
        injection)."""
        from ft_sgemm_tpu.configs import KernelShape
        from ft_sgemm_tpu.injection import InjectionSpec
        from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
        from ft_sgemm_tpu.train import resilient_step

        tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
        persistent = model.temporal == "persistent"
        # Persistent same-column faults need several K-steps landing in
        # one column; the transient upset needs exactly one.
        k_dim = 512 if persistent else 128
        ft = make_ft_sgemm(tile, alpha=1.0, beta=0.0,
                           threshold="static")
        stats = _CellStats()
        # Uncounted warm-up: keep first-call jit compile out of the
        # faulted episode's wall (goodput retention compares walls).
        wa, wb = _operands(self.seed + 999, 128, 128, k_dim)
        ft(wa, wb, np.zeros((128, 128), np.float32))

        def run_episode(ep_seed, spec, allow_evict):
            a, b = _operands(ep_seed, 128, 128, k_dim)
            c0 = np.zeros((128, 128), np.float32)
            seen = {"det": 0, "out": None}
            live = {"spec": spec}

            def step_fn(state):
                r = ft(a, b, c0, live["spec"])
                seen["det"] += int(r.num_detected)
                seen["out"] = np.asarray(r.c)
                return state, {"detections": int(r.num_detected)}, \
                    int(r.num_uncorrectable)

            def on_persistent(attempts, unc):
                # The eviction hook: drop the sick device (here: its
                # injection) and hand back the rebuilt step.
                live["spec"] = None
                return step_fn

            t0 = time.time()
            _, metrics, report = resilient_step(
                step_fn, (0,), max_retries=1,
                on_persistent_fault=(on_persistent if allow_evict
                                     else None),
                raise_on_failure=False)
            wall = time.time() - t0
            return t0, metrics, report, seen, wall, (a, b)

        for i in range(self.episodes):
            draw = draw_episode(model, rng)
            spec = InjectionSpec(enabled=True, every=int(draw["every"]),
                                 magnitude=float(draw["magnitude"]),
                                 col_stride=int(draw["col_stride"]))
            t0, metrics, report, seen, wall, (a, b) = run_episode(
                self.seed + i, spec, allow_evict=persistent)
            detected = seen["det"] > 0 or report.retries > 0 \
                or report.evicted
            recovered = metrics is not None \
                and report.uncorrectable == 0
            corrected = (not persistent) and recovered \
                and report.retries == 0 and seen["det"] > 0
            det_ts = self._detection_ts(t0)
            latency = ((det_ts - t0) if det_ts is not None
                       else (wall if detected else None))
            # atol=1.0 vs the ~1e4 fault: correction noise is < 1,
            # a silently missed fault is not.
            incorrect = bool(recovered and seen["out"] is not None
                             and not np.allclose(
                                 seen["out"],
                                 a.astype(np.float64)
                                 @ b.astype(np.float64).T,
                                 rtol=1e-3, atol=1.0))
            stats.add_fault(
                detected=detected, corrected=corrected,
                recovered=recovered, latency=latency,
                mttr=wall if recovered else None, tier="device",
                incorrect=incorrect, wall=wall)
            if detected and latency is not None:
                self._note_detection(model.name, "train_step", latency)
            if persistent and report.evicted:
                stats.extra["evictions"] = \
                    stats.extra.get("evictions", 0) + 1
        for i in range(self.clean_episodes):
            t0, metrics, report, seen, wall, _ = run_episode(
                self.seed + 100 + i, None, allow_evict=False)
            stats.add_clean(
                false_positive=bool(seen["det"] > 0
                                    or report.retries > 0),
                wall=wall)
        return stats.finalize()

    def _train_drift(self, model, rng) -> dict:
        """residual_drift: the same sub-static-threshold fault under the
        shipped static threshold (expected miss) and the adaptive
        variance-scaled bound (expected catch) — the A/B that justifies
        the policy picker's threshold recommendation."""
        from ft_sgemm_tpu.configs import KernelShape
        from ft_sgemm_tpu.injection import InjectionSpec
        from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm

        tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
        ft_static = make_ft_sgemm(tile, alpha=1.0, beta=0.0,
                                  threshold="static")
        ft_adaptive = make_ft_sgemm(tile, alpha=1.0, beta=0.0,
                                    threshold="adaptive")
        stats = _CellStats()
        static_hits = 0
        # Uncounted warm-up (see _train_inject).
        wa, wb = _operands(self.seed + 999, 128, 128, 128)
        w0 = np.zeros((128, 128), np.float32)
        ft_static(wa, wb, w0)
        ft_adaptive(wa, wb, w0)

        for i in range(self.episodes):
            draw = draw_episode(model, rng)
            spec = InjectionSpec(enabled=True, every=int(draw["every"]),
                                 magnitude=float(draw["magnitude"]),
                                 col_stride=int(draw["col_stride"]))
            a, b = _operands(self.seed + i, 128, 128, 128)
            c0 = np.zeros((128, 128), np.float32)
            r_static = ft_static(a, b, c0, spec)
            if int(r_static.num_detected) > 0:
                static_hits += 1
            t0 = time.time()
            r = ft_adaptive(a, b, c0, spec)
            detected = int(r.num_detected) > 0
            wall = time.time() - t0
            det_ts = self._detection_ts(t0)
            latency = ((det_ts - t0) if det_ts is not None
                       else (wall if detected else None))
            recovered = detected and int(r.num_uncorrectable) == 0
            incorrect = bool(recovered and not np.allclose(
                np.asarray(r.c),
                a.astype(np.float64) @ b.astype(np.float64).T,
                rtol=1e-3, atol=1.0))
            stats.add_fault(
                detected=detected, corrected=recovered,
                recovered=recovered, latency=latency,
                mttr=wall if recovered else None, tier="device",
                incorrect=incorrect, wall=wall)
            if detected and latency is not None:
                self._note_detection(model.name, "train_step", latency)
        for i in range(self.clean_episodes):
            a, b = _operands(self.seed + 100 + i, 128, 128, 128)
            c0 = np.zeros((128, 128), np.float32)
            t0 = time.time()
            r = ft_adaptive(a, b, c0)
            wall = time.time() - t0
            stats.add_clean(
                false_positive=int(r.num_detected) > 0, wall=wall)
        stats.extra["static_detection_rate"] = (
            round(static_hits / self.episodes, 4))
        return stats.finalize()

    def _train_burst(self, model, rng) -> dict:
        """multi_device_burst: correlated sub-threshold data-plane
        corruption across one mesh row's sibling devices — invisible to
        each device's own residual, crossed at the staged host/global
        reduce (``tiered_ft_sgemm``). Recovery = recompute (a clean
        re-run), so MTTR covers detection plus the rerun."""
        from ft_sgemm_tpu.configs import KernelShape
        from ft_sgemm_tpu.parallel.sharded import make_mesh
        from ft_sgemm_tpu.resilience.tiers import (
            checksum_tolerance,
            tiered_ft_sgemm,
        )

        tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
        mesh = make_mesh(8)
        mx, my = mesh.shape["x"], mesh.shape["y"]
        m, n, k = 256, 128, 512
        a, b = _operands(self.seed, m, n, k)
        c = np.zeros((m, n), np.float32)
        tol0 = checksum_tolerance(m // mx, k // my,
                                  float(np.abs(a).max()),
                                  float(np.abs(b).max()))
        stats = _CellStats()

        for i in range(self.episodes):
            draw = draw_episode(model, rng)
            x = int(draw["row"]) % mx
            coord = tuple(draw["coord"])
            corrupt = tuple(((x, y), coord, float(draw["frac"]) * tol0)
                            for y in range(my))
            t0 = time.time()
            _, rep = tiered_ft_sgemm(a, b, c, mesh, tile,
                                     registry=self.registry,
                                     tier_corrupt=corrupt)
            t_detect = time.time()
            detected = rep.detected
            det_ts = self._detection_ts(t0, ops=("data_tiers",))
            latency = ((det_ts - t0) if det_ts is not None
                       else ((t_detect - t0) if detected else None))
            recovered = False
            if detected:
                # Recompute: the clean re-run IS the recovery path for
                # a data-plane strike (nothing resident to repair).
                _, rep2 = tiered_ft_sgemm(a, b, c, mesh, tile,
                                          registry=self.registry)
                recovered = not rep2.detected
            wall = time.time() - t0
            stats.add_fault(
                detected=detected, corrected=False,
                recovered=recovered, latency=latency,
                mttr=wall if recovered else None,
                tier=rep.tier if detected else None,
                incorrect=False, wall=wall)
            if detected and latency is not None:
                self._note_detection(model.name, "train_step", latency)
        for i in range(self.clean_episodes):
            t0 = time.time()
            _, rep = tiered_ft_sgemm(a, b, c, mesh, tile,
                                     registry=self.registry)
            wall = time.time() - t0
            stats.add_clean(false_positive=rep.detected, wall=wall)
        return stats.finalize()

    def _cell_pool_evict(self, model, rng) -> dict:
        """throughput_sag (drain) / stuck_device (evict) against the
        health-steered device pool: the fault is health decay, detection
        is the device leaving ``eligible()``, goodput retention is the
        surviving placement fraction."""
        from ft_sgemm_tpu.serve.pool import DevicePool

        n_dev = 8
        labels = tuple(f"vdev:{i}" for i in range(n_dev))
        evict = model.name == "stuck_device"
        stats = _CellStats()
        surviving: list = []

        for i in range(self.episodes):
            draw = draw_episode(model, rng)
            pool = DevicePool(labels, placement="health",
                              drain_below=0.5)
            idx = int(draw["device"]) % n_dev
            t0 = time.time()
            pool.mark_sick(idx, calls=int(draw.get("calls", 100)))
            detected = idx not in pool.eligible()
            t_detect = time.time()
            latency = (t_detect - t0) if detected else None
            recovered = detected
            if evict and detected:
                pool.evict(idx)
                recovered = idx in pool.evicted
            wall = time.time() - t0
            surviving.append(len(pool.eligible()) / n_dev)
            stats.add_fault(
                detected=detected, corrected=False,
                recovered=recovered, latency=latency,
                mttr=wall if recovered else None,
                tier="health" if detected else None,
                incorrect=False, wall=wall)
            if detected and latency is not None:
                self._note_detection(model.name, "pool_evict", latency)
        for i in range(self.clean_episodes):
            pool = DevicePool(labels, placement="health",
                              drain_below=0.5)
            t0 = time.time()
            ok = len(pool.eligible()) == n_dev
            stats.add_clean(false_positive=not ok,
                            wall=time.time() - t0)
        stats.extra["goodput_retention"] = (
            round(float(np.mean(surviving)), 4) if surviving else None)
        if evict:
            stats.extra["evictions"] = sum(
                1 for s in surviving if s < 1.0)
        return stats.finalize()

    # -- the sweep -------------------------------------------------------

    def _run_cell(self, model, workload: str) -> dict:
        # str seeding is SHA-512-derived — deterministic across
        # processes, unlike hash() of a str tuple.
        rng = random.Random(f"{self.seed}:{model.name}:{workload}")
        runner = {
            "gemm_serve": self._cell_gemm_serve,
            "block_serve": self._cell_block_serve,
            "train_step": self._cell_train_step,
            "pool_evict": self._cell_pool_evict,
        }[workload]
        with self._span(f"{model.name}:{workload}") as info:
            cell = runner(model, rng)
            if isinstance(info, dict):
                info["value"] = {
                    "detection_rate": cell.get("detection_rate"),
                    "faults": cell.get("faults_injected"),
                    "incorrect": cell.get("incorrect_results")}
        self.registry.counter(
            "chaos_episodes", fault_model=model.name,
            workload=workload).inc(cell["episodes"])
        if cell["false_positives"]:
            self.registry.counter(
                "chaos_false_positives", fault_model=model.name,
                workload=workload).inc(cell["false_positives"])
        return cell

    def run(self) -> dict:
        """Run the sweep; returns the COVERAGE artifact doc."""
        t_start = time.time()
        own_session = not telemetry.enabled()
        if own_session:
            telemetry.configure(registry=self.registry)
        telemetry.add_observer(self._observe)
        matrix: dict = {}
        used_workloads: set = set()
        try:
            for name in self.models:
                model = MODELS[name]
                cells = {}
                for workload in model.workloads:
                    if workload not in self.workloads:
                        continue
                    cells[workload] = self._run_cell(model, workload)
                    used_workloads.add(workload)
                if not cells:
                    continue
                rollup = _rollup(cells)
                spec = model.to_dict()
                matrix[name] = {
                    "spec": spec,
                    "mtbf_seconds": spec["mtbf_seconds"],
                    "cells": cells,
                    "rollup": rollup,
                    "policy": _policy.recommend(spec, rollup),
                }
                self.registry.gauge(
                    "coverage_detection_rate", fault_model=name).set(
                    rollup.get("detection_rate") or 0.0)
                self.registry.gauge(
                    "coverage_goodput_retention", fault_model=name).set(
                    rollup.get("goodput_retention") or 0.0)
        finally:
            telemetry.remove_observer(self._observe)
            if own_session:
                telemetry.disable()

        rates = [m["rollup"]["detection_rate"] for m in matrix.values()
                 if m["rollup"].get("detection_rate") is not None]
        overall = round(float(np.mean(rates)), 4) if rates else None
        return {
            "schema": COVERAGE_SCHEMA,
            "metric": "chaos_coverage",
            "value": overall,
            "unit": "rate",
            "vs_baseline": None,
            "context": {
                "chaos": {
                    "models": matrix,
                    "workloads": sorted(used_workloads),
                    "seed": self.seed,
                    "episodes": self.episodes,
                    "clean_episodes": self.clean_episodes,
                    "wall_seconds": round(time.time() - t_start, 3),
                },
            },
        }


def _rollup(cells: dict) -> dict:
    """Per-model rollup across workload cells — worst case on purpose
    (a model 'covered' only where it is easiest is not covered)."""
    def vals(key):
        return [c[key] for c in cells.values()
                if c.get(key) is not None]

    def worst_min(key):
        v = vals(key)
        return min(v) if v else None

    def worst_max(key):
        v = vals(key)
        return max(v) if v else None

    tiers: dict = {}
    for c in cells.values():
        for t, n in (c.get("tier_of_detection") or {}).items():
            tiers[t] = tiers.get(t, 0) + n
    p95s = [c["detection_latency_seconds"]["p95"]
            for c in cells.values()
            if c.get("detection_latency_seconds")]
    rollup = {
        "detection_rate": worst_min("detection_rate"),
        "correction_rate": worst_min("correction_rate"),
        "p95_detection_latency_seconds": (max(p95s) if p95s else None),
        "mttr_seconds": worst_max("mttr_seconds"),
        "false_positive_rate": worst_max("false_positive_rate"),
        "goodput_retention": worst_min("goodput_retention"),
        "incorrect_results": sum(vals("incorrect_results")),
        "tier_of_detection": tiers,
    }
    static = vals("static_detection_rate")
    if static:
        rollup["static_detection_rate"] = min(static)
    return rollup


def _operands(seed: int, m: int, n: int, k: int):
    from ft_sgemm_tpu.utils.matrices import generate_random_matrix

    rng = np.random.default_rng(seed)
    return (generate_random_matrix(m, k, rng=rng),
            generate_random_matrix(n, k, rng=rng))


def run_campaign(**kwargs) -> dict:
    """One-call convenience: build a :class:`ChaosCampaign`, run it,
    return the COVERAGE artifact doc."""
    return ChaosCampaign(**kwargs).run()


def render_coverage(doc: dict) -> str:
    """Human rendering of a COVERAGE artifact doc (``cli coverage``)."""
    chaos = (doc.get("context") or {}).get("chaos") or {}
    models = chaos.get("models") or {}
    lines = [
        f"chaos coverage: {len(models)} models x"
        f" {len(chaos.get('workloads') or [])} workloads"
        f"  (overall detection {doc.get('value')})",
        f"{'model':<20s} {'workload':<12s} {'det':>5s} {'corr':>5s}"
        f" {'p95 lat':>9s} {'mttr':>8s} {'fp':>5s} {'goodput':>8s}"
        f"  tier",
    ]

    def fmt(v, pat="{:.2f}", none="-"):
        return pat.format(v) if isinstance(v, (int, float)) else none

    for name, entry in models.items():
        for workload, cell in (entry.get("cells") or {}).items():
            lat = (cell.get("detection_latency_seconds") or {})
            tiers = ",".join(
                f"{t}:{n}" for t, n in sorted(
                    (cell.get("tier_of_detection") or {}).items()))
            lines.append(
                f"{name:<20s} {workload:<12s}"
                f" {fmt(cell.get('detection_rate')):>5s}"
                f" {fmt(cell.get('correction_rate')):>5s}"
                f" {fmt(lat.get('p95'), '{:.4f}'):>9s}"
                f" {fmt(cell.get('mttr_seconds'), '{:.3f}'):>8s}"
                f" {fmt(cell.get('false_positive_rate')):>5s}"
                f" {fmt(cell.get('goodput_retention')):>8s}"
                f"  {tiers or '-'}")
        pol = entry.get("policy") or {}
        lines.append(
            f"{'':<20s} policy: every={pol.get('check_every')}"
            f" threshold={pol.get('threshold_mode')}"
            f" tiers={pol.get('tier_config')}"
            f" evict={pol.get('evict')}")
    return "\n".join(lines)


__all__ = ["COVERAGE_SCHEMA", "ChaosCampaign", "render_coverage",
           "run_campaign"]
