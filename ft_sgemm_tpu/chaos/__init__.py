"""Chaos campaign plane: declarative fault models, the measured
coverage matrix, and MTBF-driven policy selection.

Import surface stays light: ``models``/``policy`` are stdlib-only; the
campaign runner (which pulls in numpy and, lazily, jax workloads) only
loads when :class:`ChaosCampaign` is first touched.
"""

from __future__ import annotations

from ft_sgemm_tpu.chaos.models import (
    FAULT_MODELS,
    MODELS,
    WORKLOADS,
    FaultModel,
    draw_episode,
)
from ft_sgemm_tpu.chaos.policy import (
    recommend,
    recommend_cadence,
)

__all__ = [
    "FAULT_MODELS",
    "MODELS",
    "WORKLOADS",
    "ChaosCampaign",
    "FaultModel",
    "draw_episode",
    "recommend",
    "recommend_cadence",
    "render_coverage",
    "run_campaign",
]


def __getattr__(name):
    if name in ("ChaosCampaign", "run_campaign", "render_coverage"):
        from ft_sgemm_tpu.chaos import campaign as _campaign

        return getattr(_campaign, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
