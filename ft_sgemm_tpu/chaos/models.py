"""Fault models as first-class specs: the chaos campaign's declaration
layer.

Every detection/recovery knob in the repro was validated against its
OWN synthetic fault (the injection spec against the kernels, the tier
corruptor against the staged reduce, ``mark_sick`` against the pool...),
so "handles faults" really meant "handles the fault each subsystem
injects for itself". This module declares a SHARED family of fault
models — each a :class:`FaultModel` naming its site, magnitude
distribution, and temporal process — that the campaign runner
(``chaos/campaign.py``) compiles onto the EXISTING actuators
(:class:`~ft_sgemm_tpu.injection.InjectionSpec`, ``tier_corrupt``,
``BlockEngine.corrupt_kv``, ``DevicePool.mark_sick``); no kernel
changes, no new injection machinery.

``FAULT_MODELS`` is the runtime spelling of ``contracts.FAULT_MODELS``
(the BLOCK_PHASES import-free mirror discipline; the lint axis-drift
pass cross-checks this tuple, the contracts declaration, and
``events.AXIS_LABELS["fault_model"]`` against each other).

HARD CONSTRAINT — stdlib only, no package-relative imports
(``contracts.STDLIB_ONLY_MODULES`` lists this file): every draw is a
plain dict of actuator parameters; the campaign (which may import jax)
materializes them. Seeded determinism is the contract: the same
``random.Random(seed)`` produces the same episode schedule, so a
coverage regression is a CODE change, never draw noise.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional, Tuple

# Mirror of contracts.FAULT_MODELS (lint-cross-checked; keep literal).
FAULT_MODELS = ("bit_flip", "stuck_device", "multi_device_burst",
                "residual_drift", "kv_rot", "throughput_sag")

# The campaign's workload axis (not a lint-declared axis: workloads are
# harness names, not event labels — they ride ``extra["workload"]``).
WORKLOADS = ("gemm_serve", "block_serve", "train_step", "pool_evict")


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """One declarative fault model.

    ``site`` names WHERE the fault physically lands (accumulator
    element, whole device, mesh-wide data plane, stored KV page, device
    health); ``actuator`` names WHICH existing injection knob realizes
    it; ``workloads`` lists the campaign harnesses that exercise it.
    ``magnitude`` is ``(kind, lo, hi)`` — ``"absolute"`` draws a raw
    value, ``"tolerance"`` draws a multiple of the workload's detection
    tolerance (how sub-threshold models like the burst and the drift
    stay sub-threshold at any operand scale). ``temporal`` is the
    arrival process: ``"transient"`` (one upset per episode),
    ``"persistent"`` (present on every attempt until evicted/repaired),
    ``"burst"`` (one correlated multi-site instant), ``"drift"``
    (a slow creep below the static threshold). ``rate_per_hour`` is the
    model's assumed field arrival rate — the MTBF prior the policy
    picker scales by measured goodput (DESIGN.md §20).
    ``correctable`` marks models whose faults the existing machinery
    must CORRECT (not merely detect) — the CI grep pins their measured
    detection rate at 1.0.
    """

    name: str
    site: str
    actuator: str
    workloads: Tuple[str, ...]
    magnitude: Tuple
    temporal: str
    rate_per_hour: float
    correctable: bool
    description: str

    def __post_init__(self):
        if self.name not in FAULT_MODELS:
            raise ValueError(
                f"FaultModel.name={self.name!r} must be one of"
                f" {FAULT_MODELS} (contracts.FAULT_MODELS is the"
                " declared axis)")
        for w in self.workloads:
            if w not in WORKLOADS:
                raise ValueError(
                    f"FaultModel {self.name}: unknown workload {w!r}"
                    f" (must be one of {WORKLOADS})")
        if self.rate_per_hour <= 0:
            raise ValueError(
                f"FaultModel {self.name}: rate_per_hour"
                f" {self.rate_per_hour} must be > 0")

    def mtbf_seconds(self) -> float:
        """The model's prior mean-time-between-faults."""
        return 3600.0 / self.rate_per_hour

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["workloads"] = list(self.workloads)
        d["magnitude"] = list(self.magnitude)
        d["mtbf_seconds"] = self.mtbf_seconds()
        return d


def _uniform(rng: random.Random, lo: float, hi: float) -> float:
    return lo + (hi - lo) * rng.random()


def draw_episode(model: FaultModel, rng: random.Random) -> dict:
    """One seeded episode's actuator parameters, as a plain dict.

    Deterministic given the ``random.Random`` state — the campaign
    feeds one shared stream per (model, workload) cell, so episode i of
    cell (m, w) draws identically across runs with the same seed.
    Dict keys are actuator-specific; the campaign's harnesses consume
    them (``magnitude``/``every``/``col_stride`` for the injection
    spec, ``frac`` for tolerance-relative data-plane strikes, ``row``/
    ``col``/``which`` for KV pages, ``device``/``calls`` for health).
    """
    kind, lo, hi = model.magnitude
    mag = _uniform(rng, float(lo), float(hi))
    if model.name == "bit_flip":
        return {"actuator": model.actuator, "magnitude": mag,
                "every": 1, "col_stride": 61}
    if model.name == "stuck_device":
        # col_stride=0 pins every fault to one column — the adversarial
        # schedule that defeats per-column localization (persistent).
        return {"actuator": model.actuator, "magnitude": mag,
                "every": 1, "col_stride": 0,
                "device": rng.randrange(8)}
    if model.name == "multi_device_burst":
        # Correlated sub-threshold strike: one mesh row, every sibling
        # device, each below the device tolerance (frac < 1) so only a
        # staged (host/global) reduce crosses threshold.
        return {"actuator": model.actuator, "frac": mag,
                "row": rng.randrange(2), "coord": (1, 3)}
    if model.name == "residual_drift":
        # Far below the shipped static threshold, far above the
        # in-kernel adaptive (variance-scaled) bound.
        return {"actuator": model.actuator, "magnitude": mag,
                "every": 1, "col_stride": 61}
    if model.name == "kv_rot":
        return {"actuator": model.actuator, "magnitude": mag,
                "row": rng.randrange(8), "col": rng.randrange(8),
                "which": "k" if rng.random() < 0.5 else "v"}
    if model.name == "throughput_sag":
        return {"actuator": model.actuator,
                "device": rng.randrange(8),
                "calls": int(round(mag))}
    raise ValueError(f"unknown fault model {model.name!r}")


def _build_models() -> dict:
    return {m.name: m for m in (
        FaultModel(
            name="bit_flip", site="accumulator",
            actuator="injection_spec",
            workloads=("gemm_serve", "train_step"),
            magnitude=("absolute", 8000.0, 12000.0),
            temporal="transient", rate_per_hour=60.0, correctable=True,
            description=("transient single accumulator upset — the"
                         " reference's SDC; in-kernel located and"
                         " corrected, zero retries")),
        FaultModel(
            name="stuck_device", site="device",
            actuator="injection_spec",
            workloads=("train_step", "pool_evict"),
            magnitude=("absolute", 8000.0, 12000.0),
            temporal="persistent", rate_per_hour=0.2, correctable=False,
            description=("persistent same-column fault pinned to one"
                         " device — defeats per-column localization,"
                         " survives retries; the eviction path")),
        FaultModel(
            name="multi_device_burst", site="mesh",
            actuator="tier_corrupt",
            workloads=("train_step",),
            magnitude=("tolerance", 0.85, 0.95),
            temporal="burst", rate_per_hour=1.0, correctable=False,
            description=("correlated sub-threshold corruption across"
                         " sibling devices in one instant — invisible"
                         " per device, crosses threshold at the staged"
                         " host/global reduce")),
        FaultModel(
            name="residual_drift", site="accumulator",
            actuator="injection_spec",
            workloads=("train_step",),
            magnitude=("absolute", 200.0, 600.0),
            temporal="drift", rate_per_hour=6.0, correctable=True,
            description=("slow sub-static-threshold residual creep —"
                         " the adaptive-threshold motivation (arXiv"
                         " 2602.08043): static misses it, the"
                         " variance-scaled bound catches it")),
        FaultModel(
            name="kv_rot", site="kv_page",
            actuator="kv_corrupt",
            workloads=("block_serve",),
            magnitude=("absolute", 500.0, 2000.0),
            temporal="transient", rate_per_hour=12.0, correctable=True,
            description=("stored KV-cache page corruption at rest —"
                         " caught by the page checksum rows on the"
                         " next decode read, corrected in place")),
        FaultModel(
            name="throughput_sag", site="health",
            actuator="mark_sick",
            workloads=("pool_evict",),
            magnitude=("absolute", 100.0, 200.0),
            temporal="drift", rate_per_hour=0.5, correctable=False,
            description=("DVFS-style per-device degradation — no data"
                         " corruption; the health tracker's score"
                         " collapses and placement drains the"
                         " device")),
    )}


MODELS = _build_models()


__all__ = ["FAULT_MODELS", "WORKLOADS", "FaultModel", "MODELS",
           "draw_episode"]
