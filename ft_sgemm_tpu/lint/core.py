"""ftlint — the repo-native static contract checker.

Every hand-maintained invariant this codebase runs on — stdlib-only /
path-loadable supervisor modules, the kernel-axis spellings threaded
through six subsystems (ROADMAP item 5), lock-guarded shared state, the
SMEM scalar-slot ABI, the declared telemetry schema — used to live as
prose in DESIGN.md and get enforced by review (or, as with the PR-8
tuner-cache single-flight race, by a runtime failure). This module makes
them machine-verified at commit time: five AST-based passes over the
source tree, cross-checking the code against the literal declarations in
``ft_sgemm_tpu/contracts.py``, ``configs.py``, ``telemetry/events.py``,
``telemetry/timeline.py`` and ``telemetry/registry.py``.

Passes (check names; ``--only=`` selects a subset):

  import-graph      stdlib-only modules import nothing but the standard
                    library at module scope (and nothing jax-importing
                    transitively), no relative imports anywhere in them,
                    and the whole package's module-level import graph is
                    acyclic.
  axis-drift        every spelling of the strategy/encode/dtype/threshold
                    axes — configs tables, vmem variant names, tuner
                    cache-key components, telemetry label schema, serve
                    routing, CLI flag docs and axis-named assignments —
                    agrees with the configs declarations.
  lock-discipline   module-level mutable state written from any function
                    reachable from a ``threading.Thread`` target or the
                    serve/monitor request paths must be written under a
                    ``with <lock>:`` in the same function (audited-safe
                    cases ride the committed allowlist).
  smem-slots        every ``inj_ref[<const>]`` read in a Pallas kernel
                    body matches the declared scalar-slot table
                    (``contracts.SCALAR_SLOTS``): no undeclared slot, no
                    slot silently claimed for a different meaning.
  telemetry-schema  every emitted event outcome, timeline kind, and
                    metric family appears in the declared schema and has
                    a curated ``# HELP`` string.

Exit contract (the ``perf/compare.py`` convention): 0 clean, 1 findings,
2 internal error. ``lint-allowlist.json`` at the repo root suppresses
audited-safe findings — each entry carries a one-line justification, and
a stale entry (nothing matches it anymore) is itself a finding so the
allowlist can only shrink honestly.

HARD CONSTRAINT — stdlib only, fully self-contained: this file is one of
its own stdlib-only targets. It imports ONLY the standard library, never
imports the package it checks (declarations are read via ``ast``), and
runs by file path (``python ft_sgemm_tpu/lint/core.py``) in a bare
interpreter with no jax — which is exactly how the CI static-analysis
job invokes it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

LINT_VERSION = 1

# Relative paths (from the repo root) of the declaration sources every
# run must be able to read; a missing one is an internal error, not a
# clean pass.
CONTRACTS_PATH = "ft_sgemm_tpu/contracts.py"
CONFIGS_PATH = "ft_sgemm_tpu/configs.py"
VMEM_PATH = "ft_sgemm_tpu/ops/vmem.py"
TUNER_CACHE_PATH = "ft_sgemm_tpu/tuner/cache.py"
EVENTS_PATH = "ft_sgemm_tpu/telemetry/events.py"
TIMELINE_PATH = "ft_sgemm_tpu/telemetry/timeline.py"
REGISTRY_PATH = "ft_sgemm_tpu/telemetry/registry.py"
BUCKETS_PATH = "ft_sgemm_tpu/serve/buckets.py"
CLI_PATH = "ft_sgemm_tpu/cli.py"
CHAOS_MODELS_PATH = "ft_sgemm_tpu/chaos/models.py"
FLEET_DISPATCH_PATH = "ft_sgemm_tpu/fleet/dispatch.py"
ECONOMICS_PATH = "ft_sgemm_tpu/perf/economics.py"

DEFAULT_ALLOWLIST = "lint-allowlist.json"

# Modules whose every function is treated as running on a request/serve
# thread (the lock-discipline threat roots, beyond explicit
# ``threading.Thread(target=...)`` sites).
THREADED_MODULES = ("ft_sgemm_tpu/serve/engine.py",
                    "ft_sgemm_tpu/serve/blocks.py",
                    "ft_sgemm_tpu/serve/kv_cache.py",
                    "ft_sgemm_tpu/serve/pool.py",
                    "ft_sgemm_tpu/resilience/elastic.py",
                    "ft_sgemm_tpu/telemetry/monitor.py",
                    "ft_sgemm_tpu/fleet/dispatch.py",
                    "ft_sgemm_tpu/fleet/worker.py",
                    "ft_sgemm_tpu/chaos/campaign.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation: which check, where, and what drifted."""

    check: str
    path: str
    line: int
    symbol: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def key(self) -> Tuple[str, str, str]:
        """The allowlist identity: (check, path, symbol) — line numbers
        churn with unrelated edits and deliberately do not key."""
        return (self.check, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.check}] {self.symbol}: "
                f"{self.message}")


class Repo:
    """The parsed source tree one lint run checks.

    Scans ``ft_sgemm_tpu/**/*.py`` plus ``bench.py`` and ``scripts/*.py``
    when present (the emission checks cover the supervisor and tooling
    too). Trees are parsed once and shared by every pass.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.trees: Dict[str, ast.Module] = {}
        self.sources: Dict[str, str] = {}
        self.errors: List[Finding] = []
        pkg = os.path.join(self.root, "ft_sgemm_tpu")
        paths = []
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    paths.append(os.path.join(dirpath, name))
        for extra in ("bench.py",):
            p = os.path.join(self.root, extra)
            if os.path.isfile(p):
                paths.append(p)
        scripts = os.path.join(self.root, "scripts")
        if os.path.isdir(scripts):
            paths.extend(os.path.join(scripts, n)
                         for n in sorted(os.listdir(scripts))
                         if n.endswith(".py"))
        for path in paths:
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    src = fh.read()
                self.sources[rel] = src
                self.trees[rel] = ast.parse(src, filename=rel)
            except (OSError, SyntaxError) as e:
                self.errors.append(Finding(
                    "internal", rel, getattr(e, "lineno", 0) or 0,
                    "parse", f"unparseable source: {e}"))

    def package_files(self) -> List[str]:
        return [p for p in self.trees if p.startswith("ft_sgemm_tpu/")]

    def tree(self, rel: str) -> Optional[ast.Module]:
        return self.trees.get(rel)

    def module_name(self, rel: str) -> Optional[str]:
        """Dotted module name for a package file, None outside it."""
        if not rel.startswith("ft_sgemm_tpu/"):
            return None
        mod = rel[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


# --- small AST helpers --------------------------------------------------

def module_literals(tree: ast.Module) -> Dict[str, object]:
    """Module-level ``NAME = <literal>`` assignments, best-effort
    evaluated (non-literal values are skipped, never an error)."""
    out: Dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                out[node.targets[0].id] = ast.literal_eval(node.value)
            except (ValueError, SyntaxError, TypeError):
                pass
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_fragments(node: ast.AST) -> List[str]:
    """The constant string fragments of a JoinedStr (or a plain str)."""
    if isinstance(node, ast.JoinedStr):
        return [v.value for v in node.values
                if isinstance(v, ast.Constant) and isinstance(v.value, str)]
    s = str_const(node)
    return [s] if s is not None else []


def iter_functions(tree: ast.Module):
    """Yield ``(qualname, class_name_or_None, node)`` for every function
    and method (qualname is ``Class.method`` for methods)."""
    def walk(body, prefix, cls):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (prefix + node.name, cls, node)
                yield from walk(node.body, prefix + node.name + ".", cls)
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, prefix + node.name + ".",
                                node.name)
    yield from walk(tree.body, "", None)


def stdlib_names() -> frozenset:
    return frozenset(sys.stdlib_module_names) | {"__future__"}


# --- checker registry ---------------------------------------------------

CHECKERS: Dict[str, Callable] = {}
CHECK_ORDER: List[str] = []


def checker(name: str):
    """Register one pass. A checker is ``fn(repo, decls) -> (findings,
    sources_read)`` — adding a pass is one decorated function (DESIGN.md
    §14 documents the extension contract)."""
    def deco(fn):
        CHECKERS[name] = fn
        CHECK_ORDER.append(name)
        return fn
    return deco


class Declarations:
    """The literal contract tables, AST-extracted from their owning
    modules (the linter never imports the package it checks)."""

    def __init__(self, repo: Repo):
        self.missing: List[str] = []

        def lits(rel):
            tree = repo.tree(rel)
            if tree is None:
                self.missing.append(rel)
                return {}
            return module_literals(tree)

        contracts = lits(CONTRACTS_PATH)
        configs = lits(CONFIGS_PATH)
        vmem = lits(VMEM_PATH)
        events = lits(EVENTS_PATH)
        timeline = lits(TIMELINE_PATH)
        registry = lits(REGISTRY_PATH)
        tuner = lits(TUNER_CACHE_PATH)

        self.stdlib_only = tuple(contracts.get("STDLIB_ONLY_MODULES", ()))
        self.scalar_slots = dict(contracts.get("SCALAR_SLOTS", {}))
        self.n_scalar_slots = contracts.get("N_SCALAR_SLOTS", 0)
        self.axis_sources = tuple(
            contracts.get("AXIS_DECLARATION_SOURCES", ()))
        self.block_phases = tuple(contracts.get("BLOCK_PHASES", ()))
        self.variant_axes = dict(contracts.get("VARIANT_AXES", {}))
        self.variant_key_markers = tuple(
            contracts.get("TUNER_VARIANT_KEY_MARKERS", ()))
        self.pool_placements = tuple(contracts.get("POOL_PLACEMENTS", ()))
        self.recovery_tiers = tuple(contracts.get("RECOVERY_TIERS", ()))
        self.ladder_rungs = tuple(contracts.get("LADDER_RUNGS", ()))
        self.host_tiers = tuple(contracts.get("HOST_TIERS", ()))
        self.fleet_placements = tuple(
            contracts.get("FLEET_PLACEMENTS", ()))
        self.fault_models = tuple(contracts.get("FAULT_MODELS", ()))
        self.fleet_hops = tuple(contracts.get("FLEET_HOPS", ()))
        self.overhead_causes = tuple(
            contracts.get("OVERHEAD_CAUSES", ()))

        self.strategies = tuple(configs.get("STRATEGIES", ()))
        self.encode_modes = tuple(configs.get("ENCODE_MODES", ()))
        self.threshold_modes = tuple(configs.get("THRESHOLD_MODES", ()))
        self.in_dtypes = tuple(configs.get("IN_DTYPES", ()))
        self.dtype_aliases = dict(configs.get("_IN_DTYPE_ALIASES", {}))
        self.strategy_legality = dict(configs.get("STRATEGY_LEGALITY", {}))
        self.encode_legality = dict(configs.get("ENCODE_LEGALITY", {}))
        self.default_strategy = dict(configs.get("DEFAULT_STRATEGY", {}))
        # Searched kernel-variant axes (PR 13): the runtime spellings the
        # contracts.VARIANT_AXES mirror is checked against.
        self.configs_variant_axes = {
            "pipeline_depth": tuple(configs.get("PIPELINE_DEPTHS", ())),
            "grid_order": tuple(configs.get("GRID_ORDERS", ())),
            "dim_semantics": tuple(configs.get("DIM_SEMANTICS", ())),
            "epilogue_activation": tuple(
                configs.get("EPILOGUE_ACTIVATIONS", ())),
            "epilogue_quantize": tuple(
                configs.get("EPILOGUE_QUANTIZE", ())),
            # Ring hop schedule (PR 14): searched like the PR-13 axes.
            "ring_overlap": tuple(
                configs.get("RING_OVERLAP_MODES", ())),
        }

        self.vmem_variants = tuple(vmem.get("TEMP_TILE_FACTORS", {}))
        self.vmem_smem = tuple(vmem.get("_SMEM_SCRATCH_BYTES", {}))

        self.outcomes = tuple(events.get("OUTCOMES", ()))
        self.axis_labels = dict(events.get("AXIS_LABELS", {}))
        self.timeline_kinds = tuple(timeline.get("KINDS", ()))
        self.metric_help = dict(registry.get("_METRIC_HELP", {}))
        self.metric_help_prefixes = dict(
            registry.get("_METRIC_HELP_PREFIXES", {}))
        self.tuner_schema_version = tuner.get("SCHEMA_VERSION")

    def dtype_spellings(self) -> frozenset:
        return frozenset(self.in_dtypes) | frozenset(self.dtype_aliases)


# --- pass 1: import-graph ----------------------------------------------

def _module_level_imports(tree: ast.Module):
    """Every import statement NOT nested in a function: ``(node,
    module-name, relative-level, from-names)``. Class bodies and
    module-level if/try blocks count (they execute at import time).
    ``from-names`` lets the resolver distinguish ``from pkg import
    submodule`` (an edge to the submodule) from a symbol import (an
    edge to ``pkg`` itself)."""
    out = []

    def walk(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out.append((node, alias.name, None, ()))
            elif isinstance(node, ast.ImportFrom):
                out.append((node, node.module or "", node.level,
                            tuple(a.name for a in node.names)))
            for child_body in (getattr(node, "body", []),
                               getattr(node, "orelse", []),
                               getattr(node, "finalbody", [])):
                if isinstance(child_body, list):
                    walk(child_body)
            for handler in getattr(node, "handlers", []):
                walk(handler.body)
    walk(tree.body)
    return out


@checker("import-graph")
def check_import_graph(repo: Repo, decls: Declarations):
    findings: List[Finding] = []
    sources = [CONTRACTS_PATH]
    stdlib = stdlib_names()

    # Module-level intra-package import graph over dotted names.
    mod_of = {}  # dotted module -> rel path
    for rel in repo.package_files():
        mod = repo.module_name(rel)
        if mod:
            mod_of[mod] = rel
    edges: Dict[str, List[str]] = {}
    nonstd: Dict[str, List[str]] = {}  # rel -> non-stdlib top imports
    for rel in repo.package_files():
        tree = repo.tree(rel)
        mod = repo.module_name(rel)
        if tree is None or mod is None:
            continue
        edges.setdefault(mod, [])
        for node, name, level, from_names in _module_level_imports(tree):
            if level:  # relative import at module level
                base = mod.split(".")
                # level=1 from a module strips the module name itself.
                target = ".".join(base[:-level] + ([name] if name else []))
            else:
                target = name
            top = target.split(".")[0]
            if top == "ft_sgemm_tpu":
                # ``from pkg import sub`` binds the SUBMODULE when one
                # exists — edge to it, not to pkg's __init__ (the
                # aggregator-root idiom is not a cycle).
                targets = []
                for fn in from_names or ("",):
                    cand = f"{target}.{fn}" if fn else target
                    t = cand
                    while t and t not in mod_of and "." in t:
                        t = t.rsplit(".", 1)[0]
                    if t in mod_of and t != mod:
                        targets.append(t)
                edges[mod].extend(sorted(set(targets)))
            elif top not in stdlib:
                nonstd.setdefault(rel, []).append(
                    f"{target} (line {node.lineno})")

    # Cycle detection (module-level edges only — a cycle there is an
    # import-time hazard; lazy in-function cycles are the sanctioned
    # escape and are not flagged).
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {m: WHITE for m in edges}
    stack: List[str] = []

    def dfs(m):
        color[m] = GRAY
        stack.append(m)
        for dep in edges.get(m, ()):
            if color.get(dep, WHITE) == GRAY:
                cyc = stack[stack.index(dep):] + [dep]
                findings.append(Finding(
                    "import-graph", mod_of.get(m, m), 1,
                    "cycle:" + "->".join(cyc),
                    "module-level import cycle: " + " -> ".join(cyc)))
            elif color.get(dep, WHITE) == WHITE:
                dfs(dep)
        stack.pop()
        color[m] = BLACK

    for m in sorted(edges):
        if color[m] == WHITE:
            dfs(m)

    # Transitive jax/third-party reachability per module (module-level).
    def reaches_nonstd(mod, seen):
        rel = mod_of.get(mod)
        if rel in nonstd:
            return [rel]
        seen.add(mod)
        for dep in edges.get(mod, ()):
            if dep in seen:
                continue
            chain = reaches_nonstd(dep, seen)
            if chain is not None:
                return [mod_of.get(mod, mod)] + chain
        return None

    declared = set(decls.stdlib_only)
    for rel in sorted(declared):
        sources.append(rel)
        if not rel.startswith("ft_sgemm_tpu/"):
            continue
        tree = repo.tree(rel)
        if tree is None:
            findings.append(Finding(
                "import-graph", CONTRACTS_PATH, 1, rel,
                "declared stdlib-only module does not exist"))
            continue
        mod = repo.module_name(rel)
        # (a) direct non-stdlib imports at module scope.
        for msg in nonstd.get(rel, ()):
            findings.append(Finding(
                "import-graph", rel, int(msg.rsplit("line ", 1)[1][:-1]),
                msg.split(" ")[0],
                "stdlib-only module imports a non-stdlib module at module"
                f" scope: {msg} (lazy + injectable is the discipline)"))
        # (b) intra-package module-level imports: allowed only toward
        # other DECLARED stdlib-only modules (anything else could pull
        # jax transitively and always breaks path-loading).
        for dep in edges.get(mod, ()):
            dep_rel = mod_of.get(dep, dep)
            if dep_rel not in declared:
                findings.append(Finding(
                    "import-graph", rel, 1, dep,
                    f"stdlib-only module imports sibling {dep} at module"
                    " scope, which is not itself declared stdlib-only"
                    " (transitive jax risk; breaks path-loading)"))
            else:
                chain = reaches_nonstd(dep, set())
                if chain:
                    findings.append(Finding(
                        "import-graph", rel, 1, dep,
                        "stdlib-only module transitively reaches a"
                        " non-stdlib import: " + " -> ".join(chain)))
        # (c) path-loadability: relative imports anywhere in the file
        # (even lazy ones explode when the file is loaded by path).
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level:
                findings.append(Finding(
                    "import-graph", rel, node.lineno,
                    f"from {'.' * node.level}{node.module or ''}",
                    "relative import in a path-loadable module (the"
                    " jax-free supervisor loads this file by path; it"
                    " has no package context)"))
    return findings, sources


# --- pass 2: axis-drift -------------------------------------------------

# Variable / keyword names whose string values ARE axis values.
# grid_order / dim_semantics joined with the variant axes (PR 13);
# "auto" is the tuner-key spelling for an unconstrained axis.
AXIS_VAR_SETS = {
    "strategy": "strategies",
    "encode": "encode_modes",
    "threshold_mode": "threshold_modes",
    "in_dtype": "dtypes",
    "grid_order": "grid_orders",
    "dim_semantics": "dim_semantics",
    "ring_overlap": "ring_overlap_modes",
    "pool_placement": "pool_placements",
    "recovery_tier": "recovery_tiers",
    "ladder_rung": "ladder_rungs",
    "host_tier": "host_tiers",
    "fleet_placement": "fleet_placements",
    "fault_model": "fault_models",
    "hop": "fleet_hops",
    "overhead_cause": "overhead_causes",
}


def _value_consts(node: ast.AST) -> List[ast.Constant]:
    """String constants an expression can EVALUATE TO: a bare constant,
    the branches of a ternary, the arms of an ``or`` chain. Function
    arguments and subscripts inside the expression are deliberately not
    walked (``f.split("=")`` must not read as an axis value)."""
    if str_const(node) is not None:
        return [node]  # type: ignore[list-item]
    if isinstance(node, ast.IfExp):
        return _value_consts(node.body) + _value_consts(node.orelse)
    if isinstance(node, ast.BoolOp):
        out = []
        for v in node.values:
            out.extend(_value_consts(v))
        return out
    return []


def _axis_value_uses(tree: ast.Module):
    """Yield ``(axis_var, value, lineno)`` for string constants bound to
    axis-named variables: assignments (incl. ternaries), equality /
    membership comparisons, and keyword arguments."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            name = None
            t = node.targets[0]
            if isinstance(t, ast.Name):
                name = t.id
            if name in AXIS_VAR_SETS:
                for sub in _value_consts(node.value):
                    yield name, sub.value, sub.lineno
        elif isinstance(node, ast.Compare):
            left = node.left
            lname = left.id if isinstance(left, ast.Name) else (
                left.attr if isinstance(left, ast.Attribute) else None)
            if lname in AXIS_VAR_SETS:
                for comp in node.comparators:
                    vals = ([comp] if str_const(comp) is not None else
                            list(getattr(comp, "elts", [])))
                    for v in vals:
                        s = str_const(v)
                        if s is not None:
                            yield lname, s, v.lineno
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in AXIS_VAR_SETS:
                    s = str_const(kw.value)
                    if s is not None:
                        yield kw.arg, s, kw.value.lineno


def _cli_doc_axes(doc: str):
    """``--strategy=a|b`` style spellings from the CLI module docstring:
    yields (flag, token, approximate_line)."""
    import re

    for lineno, line in enumerate(doc.splitlines(), 2):
        for m in re.finditer(
                r"--(strategy|encode|threshold|dtype|grid-order"
                r"|dim-semantics|ring-overlap)=([A-Za-z0-9_.|]+)",
                line):
            flag = m.group(1)
            for token in m.group(2).split("|"):
                if token and not token.startswith("..."):
                    yield flag, token, lineno


@checker("axis-drift")
def check_axis_drift(repo: Repo, decls: Declarations):
    findings: List[Finding] = []
    sources = list(decls.axis_sources) or [
        CONFIGS_PATH, VMEM_PATH, TUNER_CACHE_PATH, EVENTS_PATH,
        BUCKETS_PATH, CLI_PATH]

    def f(path, line, symbol, message):
        findings.append(Finding("axis-drift", path, line, symbol, message))

    strategies = set(decls.strategies)
    encodes = set(decls.encode_modes)
    thresholds = set(decls.threshold_modes)
    dtypes = set(decls.in_dtypes)
    if not (strategies and encodes and thresholds and dtypes):
        f(CONFIGS_PATH, 1, "declarations",
          "configs axis declarations missing or non-literal "
          "(STRATEGIES/ENCODE_MODES/THRESHOLD_MODES/IN_DTYPES)")
        return findings, sources

    # (1) configs' own tables are closed over the declared axes.
    for tname, table, domain, universe in (
            ("STRATEGY_LEGALITY", decls.strategy_legality, dtypes,
             strategies),
            ("ENCODE_LEGALITY", decls.encode_legality, dtypes, encodes)):
        if set(table) != domain:
            f(CONFIGS_PATH, 1, tname,
              f"{tname} keys {sorted(table)} != IN_DTYPES"
              f" {sorted(domain)}")
        for k, legal in table.items():
            extra = set(legal) - universe
            if extra:
                f(CONFIGS_PATH, 1, f"{tname}[{k}]",
                  f"undeclared axis values {sorted(extra)}")
    if set(decls.default_strategy) != dtypes:
        f(CONFIGS_PATH, 1, "DEFAULT_STRATEGY",
          f"keys {sorted(decls.default_strategy)} != IN_DTYPES"
          f" {sorted(dtypes)}")
    for k, v in decls.default_strategy.items():
        if v not in set(decls.strategy_legality.get(k, ())):
            f(CONFIGS_PATH, 1, f"DEFAULT_STRATEGY[{k}]",
              f"default {v!r} is not legal for {k}"
              f" ({decls.strategy_legality.get(k)})")

    # (2) vmem variant names cover exactly the kernel family.
    expected_variants = ({"plain", "weighted_precomp"} | strategies
                         | {s + "_mxu" for s in strategies
                            if s in ("rowcol", "global")})
    got = set(decls.vmem_variants)
    if got != expected_variants:
        f(VMEM_PATH, 1, "TEMP_TILE_FACTORS",
          f"variant names {sorted(got)} != expected"
          f" {sorted(expected_variants)} (derived from"
          " configs.STRATEGIES; a new strategy needs a calibrated"
          " footprint factor)")
    if set(decls.vmem_smem) != got:
        f(VMEM_PATH, 1, "_SMEM_SCRATCH_BYTES",
          f"keys {sorted(decls.vmem_smem)} != TEMP_TILE_FACTORS keys"
          f" {sorted(got)}")

    # (3) tuner cache key carries every axis component.
    tree = repo.tree(TUNER_CACHE_PATH)
    make_key = None
    if tree is not None:
        for _, _, fn in iter_functions(tree):
            if fn.name == "make_key":
                make_key = fn
                break
    if make_key is None:
        f(TUNER_CACHE_PATH, 1, "make_key",
          "tuner cache-key builder not found")
    else:
        frags: List[str] = []
        strs: List[str] = []
        stmts = list(make_key.body)
        if stmts and isinstance(stmts[0], ast.Expr) \
                and str_const(stmts[0].value) is not None:
            # The docstring DESCRIBES the key components; it must never
            # satisfy the marker check in place of the key template
            # itself (a removed f-string component would otherwise hide
            # behind its own documentation).
            stmts = stmts[1:]
        for stmt in stmts:
            for node in ast.walk(stmt):
                frags.extend(fstring_fragments(node))
                s = str_const(node)
                if s is not None:
                    strs.append(s)
        blob = "|".join(frags)
        variant_markers = tuple(
            (mk, mk.rstrip("=")) for mk in decls.variant_key_markers)
        for marker, axis in (("enc=", "encode"), ("thr=", "threshold"),
                             ("inj=", "injection"), *variant_markers):
            if marker not in blob:
                f(TUNER_CACHE_PATH, make_key.lineno, "make_key",
                  f"cache key is missing the {axis} component"
                  f" ({marker!r} not in the key template) — two {axis}"
                  " modes' winners would silently collide")
        if not decls.variant_key_markers:
            f(CONTRACTS_PATH, 1, "TUNER_VARIANT_KEY_MARKERS",
              "variant-axis key markers missing from contracts (the"
              " schema-4 pipe=/grid=/cad=/epi= components must be"
              " declared so this pass can cross-check make_key)")
        for s in strs:
            if s in ("plain",) or s in strategies or s in encodes:
                continue
            if s in ("static", "adaptive") and s not in thresholds:
                f(TUNER_CACHE_PATH, make_key.lineno, f"make_key:{s}",
                  f"threshold spelling {s!r} not in THRESHOLD_MODES"
                  f" {sorted(thresholds)}")
        if not isinstance(decls.tuner_schema_version, int):
            f(TUNER_CACHE_PATH, 1, "SCHEMA_VERSION",
              "tuner cache SCHEMA_VERSION missing or non-literal")

    # (3b) the kernel-variant axes (PR 13): contracts.VARIANT_AXES must
    # MIRROR the configs declarations exactly — one spelling, declared
    # twice on purpose (runtime + import-free), drift is a finding both
    # ways; and the vmem footprint model must actually price the
    # pipeline axis.
    if not decls.variant_axes:
        f(CONTRACTS_PATH, 1, "VARIANT_AXES",
          "kernel-variant axis declarations missing from contracts")
    for axis, cfg_values in decls.configs_variant_axes.items():
        want = tuple(decls.variant_axes.get(axis, ()))
        if not cfg_values:
            f(CONFIGS_PATH, 1, axis,
              f"configs declaration for variant axis {axis!r} missing"
              " or non-literal")
        elif decls.variant_axes and cfg_values != want:
            f(CONTRACTS_PATH, 1, f"VARIANT_AXES[{axis}]",
              f"contracts mirror {want} != configs declaration"
              f" {cfg_values}")
    extra_axes = set(decls.variant_axes) - set(decls.configs_variant_axes)
    if extra_axes:
        f(CONTRACTS_PATH, 1, "VARIANT_AXES",
          f"contracts declares variant axes {sorted(extra_axes)} that"
          " have no configs counterpart")
    vtree = repo.tree(VMEM_PATH)
    if vtree is not None:
        vnames = {n.id for n in ast.walk(vtree)
                  if isinstance(n, ast.Name)}
        vnames |= {n.arg for n in ast.walk(vtree)
                   if isinstance(n, ast.arg)}
        if "pipeline_depth" not in vnames:
            f(VMEM_PATH, 1, "pipeline_depth",
              "the VMEM footprint model no longer prices the pipeline"
              " axis (no 'pipeline_depth' parameter) — depth-3 windows"
              " would reach Mosaic unbudgeted")

    # (4) telemetry label schema mirrors configs (and, for the
    # block-serving phase axis, contracts.BLOCK_PHASES).
    mirror = {"strategy": decls.strategies, "encode": decls.encode_modes,
              "threshold_mode": decls.threshold_modes}
    if decls.block_phases:
        mirror["block_phase"] = decls.block_phases
    # The closed variant axes carry telemetry label sets too (the
    # composite epilogue SPELLING rides event extras; its per-axis value
    # sets are what the label schema enumerates). pipeline_depth is
    # integer-valued and deliberately not a label axis.
    for axis in ("grid_order", "dim_semantics", "epilogue_activation",
                 "epilogue_quantize", "ring_overlap"):
        values = decls.configs_variant_axes.get(axis)
        if values:
            mirror[axis] = values
    # The serve pool's placement-policy axis mirrors contracts directly
    # (no configs counterpart — serving-plane axis, like block_phase).
    if decls.pool_placements:
        mirror["pool_placement"] = decls.pool_placements
    # The elastic-recovery axes (PR 15) mirror contracts directly too:
    # RECOVERY_TIERS / LADDER_RUNGS are recovery-plane declarations with
    # no configs counterpart.
    if decls.recovery_tiers:
        mirror["recovery_tier"] = decls.recovery_tiers
    if decls.ladder_rungs:
        mirror["ladder_rung"] = decls.ladder_rungs
    # The fleet axes (PR 16): host-tier placement + fleet placement
    # policy, contracts-direct like the serve/recovery planes.
    if decls.host_tiers:
        mirror["host_tier"] = decls.host_tiers
    if decls.fleet_placements:
        mirror["fleet_placement"] = decls.fleet_placements
    # The chaos-campaign fault-model axis (PR 19): contracts-direct like
    # the serve/recovery/fleet planes (chaos/models.py holds the runtime
    # spelling).
    if decls.fault_models:
        mirror["fault_model"] = decls.fault_models
    # The fleet-hop and overhead-cause axes (PR 20): contracts-direct
    # like the fleet/chaos planes (fleet/dispatch.py::FLEET_HOPS and
    # perf/economics.py::OVERHEAD_CAUSES hold the runtime spellings,
    # checked in (4b) below).
    if decls.fleet_hops:
        mirror["hop"] = decls.fleet_hops
    if decls.overhead_causes:
        mirror["overhead_cause"] = decls.overhead_causes
    if not decls.axis_labels:
        f(EVENTS_PATH, 1, "AXIS_LABELS",
          "telemetry axis-label schema missing")
    for axis, want in mirror.items():
        have = tuple(decls.axis_labels.get(axis, ()))
        if decls.axis_labels and have != tuple(want):
            f(EVENTS_PATH, 1, f"AXIS_LABELS[{axis}]",
              f"telemetry labels {have} != configs declaration {want}")

    # (4b) the chaos runtime spelling mirrors contracts exactly: the
    # fault-model axis is declared three times on purpose (contracts,
    # AXIS_LABELS — both checked above — and chaos/models.py, the only
    # copy the campaign imports); drift in the runtime copy is a
    # finding too.
    chaos_tree = repo.tree(CHAOS_MODELS_PATH)
    if decls.fault_models and chaos_tree is not None:
        runtime = tuple(
            module_literals(chaos_tree).get("FAULT_MODELS", ()))
        if runtime != decls.fault_models:
            f(CHAOS_MODELS_PATH, 1, "FAULT_MODELS",
              f"runtime fault-model spelling {runtime} !="
              f" contracts.FAULT_MODELS {decls.fault_models}")
    # Same triple-declaration discipline for the PR-20 axes: the fleet
    # hop taxonomy (fleet/dispatch.py names the histogram families from
    # it) and the cost-plane overhead causes (perf/economics.py is the
    # only copy the ledger validates against).
    for path, symbol, want in (
            (FLEET_DISPATCH_PATH, "FLEET_HOPS", decls.fleet_hops),
            (ECONOMICS_PATH, "OVERHEAD_CAUSES", decls.overhead_causes)):
        tree = repo.tree(path)
        if want and tree is not None:
            runtime = tuple(module_literals(tree).get(symbol, ()))
            if runtime != want:
                f(path, 1, symbol,
                  f"runtime {symbol} spelling {runtime} !="
                  f" contracts.{symbol} {want}")

    # (5) serve routing reads the hoisted tables.
    btree = repo.tree(BUCKETS_PATH)
    if btree is not None:
        refs = {n.id for n in ast.walk(btree) if isinstance(n, ast.Name)}
        refs |= {n.attr for n in ast.walk(btree)
                 if isinstance(n, ast.Attribute)}
        for needed in ("check_kernel_legality", "DEFAULT_STRATEGY"):
            if needed not in refs:
                f(BUCKETS_PATH, 1, needed,
                  f"serve bucket routing no longer references"
                  f" configs.{needed} — per-dtype legality/routing must"
                  " derive from the declared tables")

    # (6) CLI flag documentation + axis-named string uses everywhere.
    cli_tree = repo.tree(CLI_PATH)
    if cli_tree is not None:
        doc = ast.get_docstring(cli_tree) or ""
        alias_ok = dtypes | set(decls.dtype_aliases)
        grid_orders = set(decls.configs_variant_axes.get("grid_order", ()))
        dim_sems = set(decls.configs_variant_axes.get("dim_semantics", ()))
        ring_modes = set(
            decls.configs_variant_axes.get("ring_overlap", ()))
        for flag, token, line in _cli_doc_axes(doc):
            ok = {
                "strategy": lambda t: t in strategies,
                "encode": lambda t: t in encodes,
                "threshold": lambda t: t in thresholds or t == "FLOAT",
                "dtype": lambda t: t in alias_ok,
                "grid-order": lambda t: t in grid_orders,
                "dim-semantics": lambda t: t in dim_sems,
                "ring-overlap": lambda t: t in ring_modes or t == "auto",
            }[flag](token)
            if not ok:
                f(CLI_PATH, line, f"--{flag}={token}",
                  f"CLI usage documents {flag} spelling {token!r} that"
                  " the declared axis does not contain")

    # The internal ``strategy`` spelling sometimes carries the encode-
    # resolved VARIANT name (rowcol_mxu, weighted_precomp, plain — the
    # vmem/cost-model vocabulary), which part (2) above pins against
    # STRATEGIES; accept that whole checked family here.
    axis_universe = {"strategy": strategies | {"plain"}
                     | set(decls.vmem_variants),
                     "encode": encodes,
                     "threshold_mode": thresholds,
                     "in_dtype": dtypes | set(decls.dtype_aliases),
                     # "auto" is the unconstrained tuner-key spelling of
                     # every searched variant axis.
                     "grid_order": set(
                         decls.configs_variant_axes.get("grid_order", ()))
                     | {"auto"},
                     "dim_semantics": set(
                         decls.configs_variant_axes.get(
                             "dim_semantics", ())) | {"auto"},
                     "ring_overlap": set(
                         decls.configs_variant_axes.get(
                             "ring_overlap", ())) | {"auto"},
                     "pool_placement": set(decls.pool_placements),
                     "recovery_tier": set(decls.recovery_tiers),
                     "ladder_rung": set(decls.ladder_rungs),
                     "host_tier": set(decls.host_tiers),
                     "fleet_placement": set(decls.fleet_placements),
                     "fault_model": set(decls.fault_models),
                     "hop": set(decls.fleet_hops),
                     "overhead_cause": set(decls.overhead_causes)}
    for rel in sorted(repo.trees):
        if not (rel.startswith("ft_sgemm_tpu/") or rel == "bench.py"
                or rel.startswith("scripts/")):
            continue
        tree = repo.tree(rel)
        if tree is None:
            continue
        for axis, value, line in _axis_value_uses(tree):
            if value not in axis_universe[axis]:
                f(rel, line, f"{axis}={value!r}",
                  f"axis value {value!r} is not declared in the"
                  f" {axis} axis ({sorted(axis_universe[axis])}) — add"
                  " it to the configs declaration first or fix the"
                  " spelling")
    return findings, sources


# --- pass 3: lock-discipline -------------------------------------------

_MUTABLE_CALLS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                  "deque", "Counter"}
_MUTATING_METHODS = {"append", "add", "update", "pop", "popitem",
                     "clear", "extend", "insert", "remove", "discard",
                     "setdefault", "appendleft", "extendleft"}


def _is_mutable_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        return name.split(".")[-1] in _MUTABLE_CALLS
    return False


def _subscript_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _writes_in(fn: ast.AST, mutable: frozenset):
    """Yield ``(name, node)`` for writes to module-level mutable names
    inside ``fn`` (subscript stores, mutating method calls, global
    rebinds, del of an item)."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    root = _subscript_root(t)
                    if root in mutable:
                        yield root, node
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    root = _subscript_root(t)
                    if root in mutable:
                        yield root, node
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            root = node.func.value
            root = _subscript_root(root) if isinstance(
                root, ast.Subscript) else (
                root.id if isinstance(root, ast.Name) else None)
            if root in mutable:
                yield root, node


def _lock_guarded(fn: ast.AST, write: ast.AST, lock_names: frozenset)\
        -> bool:
    """Whether ``write`` sits inside a ``with`` whose context expression
    names a lock (a module lock, an attribute/call containing 'lock')
    within the same function."""
    # Build parent links lazily per function.
    parents = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    node = write
    while node is not None and node is not fn:
        node = parents.get(node)
        if isinstance(node, ast.With):
            for item in node.items:
                expr = item.context_expr
                name = dotted_name(expr)
                if name is None and isinstance(expr, ast.Call):
                    name = dotted_name(expr.func)
                if name and ("lock" in name.lower()
                             or name.split(".")[-1] in lock_names):
                    return True
    return False


@checker("lock-discipline")
def check_lock_discipline(repo: Repo, decls: Declarations):
    findings: List[Finding] = []
    sources: List[str] = []

    # Per-module facts.
    mutable: Dict[str, frozenset] = {}
    locks: Dict[str, frozenset] = {}
    funcs: Dict[Tuple[str, str], ast.AST] = {}  # (rel, qual) -> node
    by_name: Dict[str, List[Tuple[str, str]]] = {}
    calls: Dict[Tuple[str, str], set] = {}
    threat_roots: List[Tuple[str, str]] = []

    for rel in repo.package_files():
        tree = repo.tree(rel)
        if tree is None:
            continue
        mut, lk = set(), set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if _is_mutable_ctor(node.value):
                    mut.add(name)
                dn = dotted_name(node.value.func) if isinstance(
                    node.value, ast.Call) else None
                if dn and dn.split(".")[-1] in ("Lock", "RLock"):
                    lk.add(name)
        mutable[rel] = frozenset(mut)
        locks[rel] = frozenset(lk)
        for qual, cls, fn in iter_functions(tree):
            funcs[(rel, qual)] = fn
            by_name.setdefault(fn.name, []).append((rel, qual))
            callees = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func)
                    if dn:
                        callees.add(dn.split(".")[-1])
                # threading.Thread(target=X) marks X a threat root.
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func) or ""
                    if dn.split(".")[-1] == "Thread":
                        for kw in node.keywords:
                            if kw.arg == "target":
                                tn = dotted_name(kw.value)
                                if tn:
                                    threat_roots.append(
                                        (rel, tn.split(".")[-1]))
            calls[(rel, qual)] = callees
        if rel in THREADED_MODULES:
            sources.append(rel)
            for qual, cls, fn in iter_functions(tree):
                threat_roots.append((rel, fn.name))

    # Reachability over the best-effort name-matched call graph: seed
    # with the threat roots, close over callee names (same module first,
    # then any module exporting the name — over-approximate on purpose;
    # the allowlist absorbs audited over-matches).
    reachable: set = set()
    frontier: List[Tuple[str, str]] = []
    for rel, fname in threat_roots:
        for key in by_name.get(fname, []):
            if key not in reachable:
                reachable.add(key)
                frontier.append(key)
    while frontier:
        key = frontier.pop()
        for callee in calls.get(key, ()):
            for cand in by_name.get(callee, []):
                if cand not in reachable:
                    reachable.add(cand)
                    frontier.append(cand)

    for (rel, qual), fn in sorted(funcs.items()):
        if (rel, qual) not in reachable:
            continue
        mut = mutable.get(rel, frozenset())
        if not mut:
            continue
        lock_names = locks.get(rel, frozenset())
        for name, node in _writes_in(fn, mut):
            if not _lock_guarded(fn, node, lock_names):
                findings.append(Finding(
                    "lock-discipline", rel, node.lineno,
                    f"{qual}:{name}",
                    f"module-level mutable {name!r} written without an"
                    f" enclosing lock in {qual}(), which is reachable"
                    " from a thread target / request path — guard it or"
                    " allowlist with an audit note"))
    return findings, sources


# --- pass 4: smem-slots -------------------------------------------------

@checker("smem-slots")
def check_smem_slots(repo: Repo, decls: Declarations):
    findings: List[Finding] = []
    sources = [CONTRACTS_PATH, "ft_sgemm_tpu/ops/ft_sgemm.py"]
    slots = decls.scalar_slots
    if not slots:
        findings.append(Finding(
            "smem-slots", CONTRACTS_PATH, 1, "SCALAR_SLOTS",
            "declared scalar-slot table missing or non-literal"))
        return findings, sources
    accepted = {int(k): tuple(v[1]) for k, v in slots.items()}
    meanings = {int(k): v[0] for k, v in slots.items()}

    for rel in repo.package_files():
        tree = repo.tree(rel)
        if tree is None:
            continue
        for qual, _, fn in iter_functions(tree):
            argnames = {a.arg for a in list(fn.args.args)
                        + list(fn.args.posonlyargs)
                        + list(fn.args.kwonlyargs)}
            if "inj_ref" not in argnames:
                continue
            parents = {}
            for node in ast.walk(fn):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Subscript)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "inj_ref"):
                    continue
                idx = node.slice
                if not (isinstance(idx, ast.Constant)
                        and isinstance(idx.value, int)):
                    continue
                slot = idx.value
                if slot not in accepted:
                    findings.append(Finding(
                        "smem-slots", rel, node.lineno,
                        f"{qual}:slot{slot}",
                        f"kernel reads undeclared scalar slot {slot}"
                        f" (declared: {sorted(accepted)}) — claim it in"
                        " contracts.SCALAR_SLOTS first"))
                    continue
                # Find the binding spelling: nearest enclosing Assign
                # target or keyword argument.
                spelling = None
                p = node
                while p is not None and p is not fn:
                    parent = parents.get(p)
                    if isinstance(parent, ast.keyword):
                        spelling = parent.arg
                        break
                    if isinstance(parent, ast.Assign) \
                            and len(parent.targets) == 1 \
                            and isinstance(parent.targets[0], ast.Name):
                        spelling = parent.targets[0].id
                        break
                    p = parent
                if spelling is not None \
                        and spelling not in accepted[slot]:
                    findings.append(Finding(
                        "smem-slots", rel, node.lineno,
                        f"{qual}:slot{slot}",
                        f"scalar slot {slot} bound as {spelling!r} but"
                        f" declared {meanings[slot]!r} (accepted"
                        f" spellings {accepted[slot]}) — two kernels"
                        " must never claim one slot for different"
                        " meanings"))
    return findings, sources


# --- pass 5: telemetry-schema ------------------------------------------

@checker("telemetry-schema")
def check_telemetry_schema(repo: Repo, decls: Declarations):
    findings: List[Finding] = []
    sources = [EVENTS_PATH, TIMELINE_PATH, REGISTRY_PATH]
    outcomes = set(decls.outcomes)
    kinds = set(decls.timeline_kinds)
    help_exact = set(decls.metric_help)
    help_prefixes = tuple(decls.metric_help_prefixes)

    def prom(name: str) -> str:
        return "".join(c if c.isalnum() or c == "_" else "_"
                       for c in name)

    def curated(name: str) -> bool:
        p = prom(name)
        return p in help_exact or any(p.startswith(pref)
                                      for pref in help_prefixes)

    if not outcomes:
        findings.append(Finding(
            "telemetry-schema", EVENTS_PATH, 1, "OUTCOMES",
            "declared outcome schema missing"))
    if not kinds:
        findings.append(Finding(
            "telemetry-schema", TIMELINE_PATH, 1, "KINDS",
            "declared timeline-kind schema missing"))

    for rel in sorted(repo.trees):
        tree = repo.tree(rel)
        if tree is None or rel == EVENTS_PATH:
            continue  # the schema module's own tuples are declarations
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            last = fname.split(".")[-1]
            # Event outcomes: FaultEvent("x", ...) / outcome="x".
            if last == "FaultEvent" and outcomes:
                out = None
                if node.args:
                    out = str_const(node.args[0])
                for kw in node.keywords:
                    if kw.arg == "outcome":
                        out = str_const(kw.value)
                if out is not None and out not in outcomes:
                    findings.append(Finding(
                        "telemetry-schema", rel, node.lineno,
                        f"outcome={out!r}",
                        f"event outcome {out!r} is not declared in"
                        " telemetry.events.OUTCOMES"))
            # Timeline kinds: .span(name, kind=K) / .point(K, name).
            if last == "span" and kinds:
                k = "stage"
                for kw in node.keywords:
                    if kw.arg == "kind":
                        k = str_const(kw.value) or None
                if k is not None and k not in kinds:
                    findings.append(Finding(
                        "telemetry-schema", rel, node.lineno,
                        f"kind={k!r}",
                        f"timeline span kind {k!r} is not declared in"
                        " telemetry.timeline.KINDS"))
            if (last == "point" or last.endswith("_point")) and kinds \
                    and node.args:
                k = str_const(node.args[0])
                if k is not None and k not in kinds:
                    findings.append(Finding(
                        "telemetry-schema", rel, node.lineno,
                        f"kind={k!r}",
                        f"timeline point kind {k!r} is not declared in"
                        " telemetry.timeline.KINDS"))
            # Metric families: .counter/.gauge/.histogram("name").
            if last in ("counter", "gauge", "histogram") \
                    and isinstance(node.func, ast.Attribute) and node.args:
                arg = node.args[0]
                name = str_const(arg)
                if name is not None:
                    if not curated(name):
                        findings.append(Finding(
                            "telemetry-schema", rel, node.lineno,
                            f"metric={name!r}",
                            f"metric family {name!r} has no curated"
                            " # HELP string (telemetry.registry"
                            "._METRIC_HELP / _METRIC_HELP_PREFIXES)"))
                elif isinstance(arg, ast.JoinedStr):
                    frags = fstring_fragments(arg)
                    prefix = frags[0] if frags and isinstance(
                        arg.values[0], ast.Constant) else ""
                    if not prefix or not any(
                            prom(prefix).startswith(p) or
                            p.startswith(prom(prefix))
                            for p in help_prefixes):
                        findings.append(Finding(
                            "telemetry-schema", rel, node.lineno,
                            f"metric=f{prefix!r}...",
                            "dynamically-named metric family has no"
                            " matching curated # HELP prefix entry"
                            " (telemetry.registry._METRIC_HELP_PREFIXES)"))
    return findings, sources


# --- allowlist + driver -------------------------------------------------

@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    stale_entries: List[dict]
    seconds: float
    sources: Dict[str, List[str]]
    checks_run: List[str]
    internal_error: Optional[str] = None

    @property
    def exit_code(self) -> int:
        if self.internal_error:
            return 2
        return 1 if (self.findings or self.stale_entries) else 0

    def to_dict(self) -> dict:
        return {
            "version": LINT_VERSION,
            "seconds": round(self.seconds, 3),
            "checks_run": self.checks_run,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "stale_allowlist_entries": self.stale_entries,
            "sources": self.sources,
            "internal_error": self.internal_error,
            "exit_code": self.exit_code,
        }


def load_allowlist(path: str) -> List[dict]:
    """The committed audited-safe entries; [] when absent. Each entry is
    ``{"check", "path", "symbol", "reason"}`` — reason is REQUIRED (an
    allowlist without justifications is just a mute button)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return []
    entries = doc.get("entries") if isinstance(doc, dict) else None
    out = []
    for e in entries or []:
        if isinstance(e, dict) and e.get("check") and e.get("path") \
                and e.get("symbol") and e.get("reason"):
            out.append(e)
    return out


def run_lint(root: str, *, only: Optional[Sequence[str]] = None,
             allowlist_path: Optional[str] = None) -> LintResult:
    """Run the registered passes over the tree at ``root``.

    ``only`` limits to a subset of check names; ``allowlist_path``
    defaults to ``<root>/lint-allowlist.json``. Never raises: an
    internal checker failure lands as ``internal_error`` with exit 2.
    """
    t0 = time.monotonic()
    selected = list(only) if only else list(CHECK_ORDER)
    unknown = [c for c in selected if c not in CHECKERS]
    if unknown:
        return LintResult([], [], [], time.monotonic() - t0, {}, [],
                          internal_error=f"unknown checks: {unknown}"
                          f" (available: {CHECK_ORDER})")
    repo = Repo(root)
    decls = Declarations(repo)
    findings: List[Finding] = list(repo.errors)
    sources: Dict[str, List[str]] = {}
    internal = None
    if decls.missing:
        internal = ("declaration sources unreadable: "
                    + ", ".join(decls.missing))
    for name in selected:
        if internal:
            break
        try:
            found, read = CHECKERS[name](repo, decls)
            findings.extend(found)
            sources[name] = sorted(set(read))
        except Exception as e:  # noqa: BLE001 — exit-2 contract
            internal = f"checker {name} crashed: {type(e).__name__}: {e}"
    allow = load_allowlist(allowlist_path or
                           os.path.join(root, DEFAULT_ALLOWLIST))
    allowed_keys = {(e["check"], e["path"], e["symbol"]): e
                    for e in allow}
    kept, suppressed = [], []
    matched = set()
    for f in findings:
        if f.key() in allowed_keys:
            suppressed.append(f)
            matched.add(f.key())
        else:
            kept.append(f)
    stale = [e for k, e in sorted(allowed_keys.items())
             if k not in matched] if not only or set(selected) == set(
        CHECK_ORDER) else []
    stale_findings = [dict(e, stale=True) for e in stale]
    kept.sort(key=lambda f: (f.path, f.line, f.check, f.symbol))
    return LintResult(kept, suppressed, stale_findings,
                      time.monotonic() - t0, sources, selected,
                      internal_error=internal)


def format_text(result: LintResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f.render())
    for e in result.stale_entries:
        lines.append(f"{e['path']}: [allowlist] {e['check']}:"
                     f"{e['symbol']}: stale allowlist entry (nothing"
                     " matches it anymore) — remove it")
    if result.internal_error:
        lines.append(f"ftlint: internal error: {result.internal_error}")
    lines.append(
        f"ftlint: {len(result.findings)} finding(s),"
        f" {len(result.suppressed)} allowlisted,"
        f" {len(result.stale_entries)} stale allowlist entr(y/ies),"
        f" {len(result.checks_run)} check(s)"
        f" in {result.seconds:.2f}s")
    return "\n".join(lines)


def lint_facts(root: str) -> dict:
    """The two longitudinal lint measurements the bench manifest and run
    ledger record: post-allowlist finding count and checker wall time
    (``lint.findings`` / ``lint.seconds`` ledger series)."""
    result = run_lint(root)
    return {"findings": len(result.findings) + len(result.stale_entries),
            "seconds": round(result.seconds, 3),
            "internal_error": result.internal_error}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fmt = "text"
    only = None
    allowlist = None
    root = None
    for a in argv:
        if a.startswith("--format="):
            fmt = a.split("=", 1)[1]
            if fmt not in ("text", "json"):
                print(f"--format must be text or json, got {fmt!r}",
                      file=sys.stderr)
                return 2
        elif a.startswith("--only="):
            only = [c for c in a.split("=", 1)[1].split(",") if c]
        elif a.startswith("--allowlist="):
            allowlist = a.split("=", 1)[1]
        elif a.startswith("--root="):
            root = a.split("=", 1)[1]
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            print(f"unknown argument {a!r} (try --help)",
                  file=sys.stderr)
            return 2
    if root is None:
        # Default: the repo root this file lives in (…/ft_sgemm_tpu/lint/
        # core.py -> two levels up), falling back to cwd when the layout
        # is foreign (an installed wheel).
        here = os.path.dirname(os.path.abspath(__file__))
        cand = os.path.dirname(os.path.dirname(here))
        root = cand if os.path.isdir(
            os.path.join(cand, "ft_sgemm_tpu")) else os.getcwd()
    result = run_lint(root, only=only, allowlist_path=allowlist)
    if fmt == "json":
        print(json.dumps(result.to_dict(), indent=1, sort_keys=True))
    else:
        print(format_text(result))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
