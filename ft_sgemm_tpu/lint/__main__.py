"""``python -m ft_sgemm_tpu.lint`` — the in-process linter entry.

(For the zero-jax invocation CI uses, run the file by path instead:
``python ft_sgemm_tpu/lint/core.py``.)
"""

import sys

from ft_sgemm_tpu.lint.core import main

if __name__ == "__main__":
    sys.exit(main())
