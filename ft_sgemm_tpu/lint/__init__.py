"""Static contract checking (ftlint) — see :mod:`.core` for the passes.

``core.py`` is deliberately self-contained and stdlib-only (it is one of
its own declared stdlib-only targets, ``contracts.STDLIB_ONLY_MODULES``):
CI and the jax-free bench supervisor run it BY FILE PATH
(``python ft_sgemm_tpu/lint/core.py``). This package init exists for the
ergonomic in-process spellings — ``python -m ft_sgemm_tpu.cli lint`` and
``from ft_sgemm_tpu.lint import run_lint`` — which accept the package
import cost (including jax, via the package root) that the path-loaded
entry avoids.
"""

from ft_sgemm_tpu.lint.core import (
    CHECK_ORDER,
    Finding,
    LintResult,
    format_text,
    lint_facts,
    load_allowlist,
    main,
    run_lint,
)

__all__ = ["CHECK_ORDER", "Finding", "LintResult", "format_text",
           "lint_facts", "load_allowlist", "main", "run_lint"]
