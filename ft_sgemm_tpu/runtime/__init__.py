"""Native host runtime: ctypes bindings for csrc/hostutils.cpp.

The reference's host layer (``utils/utils.cu``) is native; this module is
its TPU-build counterpart. The shared library is compiled on demand with
g++ (no pip/pybind11 dependency) and cached; every entry point has a pure
numpy fallback so the package works without a toolchain.

Public surface mirrors utils/matrices.py but with reference-exact libc
``rand()`` streams: ``generate_random_matrix_native(n, m, seed=10)``
reproduces bit-for-bit the inputs the reference driver builds after
``srand(10)`` (``sgemm.cu:12,57-60``).
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import warnings
from typing import Optional, Tuple

import numpy as np

_CSRC = pathlib.Path(__file__).resolve().parent.parent / "csrc"
_BUILD = _CSRC / "_build"
_SO = _BUILD / "libftsgemm_hostutils.so"

_lib = None
_lib_tried = False


def _compile() -> Optional[pathlib.Path]:
    src = _CSRC / "hostutils.cpp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", str(src), "-o", str(_SO)]
    try:
        _BUILD.mkdir(parents=True, exist_ok=True)
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except (OSError, subprocess.SubprocessError) as e:
        warnings.warn(f"native hostutils build failed ({e}); numpy fallback")
        return None


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None. Never raises:
    any build/load failure engages the numpy fallback."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    src = _CSRC / "hostutils.cpp"
    stale = (_SO.exists() and src.exists()
             and _SO.stat().st_mtime < src.stat().st_mtime)
    path = _SO if _SO.exists() and not stale else _compile()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:  # truncated/stale artifact: rebuild once
        warnings.warn(f"native hostutils load failed ({e}); rebuilding")
        try:
            _SO.unlink(missing_ok=True)
        except OSError:
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    lib.ftsg_generate_random_matrix.argtypes = [
        f32p, ctypes.c_int, ctypes.c_int, ctypes.c_uint, ctypes.c_int]
    lib.ftsg_generate_random_vector.argtypes = [
        f32p, ctypes.c_int, ctypes.c_uint, ctypes.c_int]
    lib.ftsg_verify_matrix.argtypes = [
        f32p, f32p, ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, i64p]
    lib.ftsg_verify_matrix.restype = ctypes.c_longlong
    lib.ftsg_cpu_gemm.argtypes = [
        ctypes.c_float, ctypes.c_float, f32p, f32p, f32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.ftsg_checksum_residual.argtypes = [
        f32p, f64p, f64p, ctypes.c_int, ctypes.c_int, f64p]
    lib.ftsg_checksum_residual.restype = ctypes.c_double
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _f32p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def generate_random_matrix_native(n: int, m: Optional[int] = None,
                                  seed: int = 10) -> np.ndarray:
    """Reference-exact (n, m) input matrix via libc srand/rand
    (``utils.cu:23-31``, seeded as ``sgemm.cu:12``). Falls back to the
    numpy quantized generator when no native toolchain exists (same value
    set, different stream)."""
    m = n if m is None else m
    lib = load()
    if lib is None:
        from ft_sgemm_tpu.utils.matrices import generate_random_matrix
        return generate_random_matrix(n, m, seed=seed)
    out = np.empty((n, m), dtype=np.float32)
    lib.ftsg_generate_random_matrix(_f32p(out), n, m, seed, 1)
    return out


def generate_reference_driver_inputs(size: int, seed: int = 10
                                     ) -> Tuple[np.ndarray, np.ndarray]:
    """A and B exactly as the reference driver builds them: one srand(seed),
    then two consecutive full-matrix draws (``sgemm.cu:57-58``)."""
    lib = load()
    if lib is None:
        from ft_sgemm_tpu.utils.matrices import generate_random_matrix
        rng = np.random.default_rng(seed)
        return (generate_random_matrix(size, size, rng=rng),
                generate_random_matrix(size, size, rng=rng))
    a = np.empty((size, size), dtype=np.float32)
    b = np.empty((size, size), dtype=np.float32)
    lib.ftsg_generate_random_matrix(_f32p(a), size, size, seed, 1)
    lib.ftsg_generate_random_matrix(_f32p(b), size, size, 0, 0)  # continue stream
    return a, b


def verify_matrix_native(ref: np.ndarray, out: np.ndarray,
                         abs_tol: float = 0.01, rel_tol: float = 0.01):
    """Native scan under the ``utils.cu:61-77`` tolerance; returns
    (ok, num_bad, first_bad_flat_index_or_None)."""
    lib = load()
    ref = np.ascontiguousarray(ref, dtype=np.float32)
    out = np.ascontiguousarray(out, dtype=np.float32)
    if ref.shape != out.shape or ref.ndim != 2:
        raise ValueError(
            f"verify_matrix_native: shape mismatch {ref.shape} vs {out.shape}")
    if lib is None:
        from ft_sgemm_tpu.utils.matrices import verify_matrix
        ok, nbad, first = verify_matrix(ref, out, verbose=False,
                                        abs_tol=abs_tol, rel_tol=rel_tol)
        flat = None if first is None else int(np.ravel_multi_index(first, ref.shape))
        return ok, nbad, flat
    first = ctypes.c_longlong(-1)
    m, n = ref.shape
    nbad = lib.ftsg_verify_matrix(_f32p(ref), _f32p(out), m, n,
                                  abs_tol, rel_tol, ctypes.byref(first))
    return nbad == 0, int(nbad), (None if first.value < 0 else int(first.value))


def cpu_gemm_native(alpha: float, beta: float, a: np.ndarray, b: np.ndarray,
                    c: np.ndarray) -> np.ndarray:
    """Native naive GEMM oracle ``C = alpha*A@B + beta*C``
    (``utils.cu:79-89``)."""
    lib = load()
    a = np.ascontiguousarray(a, dtype=np.float32)
    b = np.ascontiguousarray(b, dtype=np.float32)
    out = np.array(c, dtype=np.float32, copy=True)
    m, k = a.shape
    kb, n = b.shape
    if k != kb or out.shape != (m, n):
        raise ValueError(
            f"cpu_gemm_native: incompatible shapes A{a.shape} B{b.shape}"
            f" C{out.shape}")
    if lib is None:
        from ft_sgemm_tpu.ops.reference import cpu_gemm
        return cpu_gemm(alpha, beta, a, b, out)
    lib.ftsg_cpu_gemm(alpha, beta, _f32p(a), _f32p(b), _f32p(out), m, n, k)
    return out


def checksum_residual_native(c: np.ndarray, expected_row: np.ndarray,
                             expected_col: np.ndarray):
    """Host-side two-pass checksum residuals (native analog of the checksum
    math in ``include/baseline_ft_sgemm.cuh:9-31``): returns
    (max |expected_row - rowsum(C)|, max |expected_col - colsum(C)|).
    Independent oracle for the in-kernel ABFT residual math."""
    c = np.ascontiguousarray(c, dtype=np.float32)
    er = np.ascontiguousarray(expected_row, dtype=np.float64)
    ec = np.ascontiguousarray(expected_col, dtype=np.float64)
    m, n = c.shape
    assert er.shape == (m,) and ec.shape == (n,), (er.shape, ec.shape, c.shape)
    lib = load()
    if lib is None:
        c64 = c.astype(np.float64)
        return (float(np.max(np.abs(er - c64.sum(axis=1)))),
                float(np.max(np.abs(ec - c64.sum(axis=0)))))
    col_res = ctypes.c_double(0.0)
    f64p = ctypes.POINTER(ctypes.c_double)
    row_res = lib.ftsg_checksum_residual(
        _f32p(c), er.ctypes.data_as(f64p), ec.ctypes.data_as(f64p),
        m, n, ctypes.byref(col_res))
    return float(row_res), float(col_res.value)


__all__ = [
    "available",
    "load",
    "generate_random_matrix_native",
    "generate_reference_driver_inputs",
    "verify_matrix_native",
    "cpu_gemm_native",
    "checksum_residual_native",
]
