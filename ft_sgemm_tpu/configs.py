"""Kernel shape configuration family.

The reference drives a string-templating code generator with a 7-parameter
tile description ``[ms, ns, ks, mw, nw, mr, nr]`` (block tile, warp tile,
thread tile — ``code_gen/main.py:8-16``, ``code_gen/code_gen.py:5-30``) and
instantiates 6 named shapes x {plain, fused-ABFT}.

On TPU there is no warp/thread level: the MXU consumes whole 128x128 tiles
and the unit of scheduling is the Pallas grid step. The family therefore
collapses to a 3-parameter block tile ``(bm, bn, bk)`` per named shape,
chosen to be legal and efficient on the MXU (f32 min tile 8x128; lane dim
128). The reference's 7 parameters are recorded verbatim for provenance in
``ref_params``. Where the reference shape is sub-MXU (e.g. ``small`` is a
16x16 block) the TPU tile is the nearest MXU-friendly shape and the
perf-characteristic (small vs large blocks, tall vs wide aspect) is kept,
not the literal numbers — see SURVEY.md §7 "Hard parts".
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KernelShape:
    """A named block-tiling configuration for the SGEMM kernel family.

    Attributes:
      name: shape family name (reference ``main.py:8-16`` table key).
      bm, bn, bk: Pallas block tile (rows of C, cols of C, K-depth per
        grid step). All multiples of 128 so f32 tiles map onto the MXU.
      ref_params: the reference's ``[ms, ns, ks, mw, nw, mr, nr]`` for
        this name, for provenance/docs only.
    """

    name: str
    bm: int
    bn: int
    bk: int
    ref_params: Tuple[int, int, int, int, int, int, int]

    def __post_init__(self):
        for field in ("bm", "bn", "bk"):
            v = getattr(self, field)
            if v % 128 != 0 or v <= 0:
                raise ValueError(
                    f"KernelShape.{field}={v} must be a positive multiple of"
                    " 128 (f32 MXU tiling)"
                )

    @property
    def block(self) -> Tuple[int, int, int]:
        return (self.bm, self.bn, self.bk)

    def aug_block(self, aug_a: int = 0, aug_b: int = 0) -> Tuple[int, int, int]:
        """The (a_rows, b_rows, bk) the kernel's BlockSpecs use when operand
        augmentation rides checksum rows on the A/B tiles (``encode="mxu"``
        and the fused strategy).

        ``aug_a``/``aug_b`` are the appended checksum-row counts (see
        ``aug_rows``). Validates that the augmented sublane dims stay
        legal Mosaic tiles — every block dim is a multiple of 128, so any
        augmentation that is itself a multiple of the dtype's sublane
        granule (8 for f32, 16 for bf16 — which ``aug_rows`` guarantees)
        is legal; a hand-rolled augmentation that is not gets a loud error
        here instead of an opaque Mosaic layout failure.
        """
        for label, aug in (("aug_a", aug_a), ("aug_b", aug_b)):
            if aug < 0 or aug % 8 != 0:
                raise ValueError(
                    f"KernelShape.aug_block: {label}={aug} must be a"
                    " non-negative multiple of 8 (f32 sublane granule);"
                    " use configs.aug_rows for the dtype-correct count")
        return (self.bm + aug_a, self.bn + aug_b, self.bk)


# Checksum-row augmentation ("mxu" encode / the fused strategy): moment rows
# appended to an operand tile must keep the tile's sublane dim aligned, so
# the row count is padded to the dtype's sublane granule — 8 rows for f32
# (3 moment rows padded), 16 for bf16 (up to 9 hi/lo/lo2 term rows padded;
# bf16 sublane tiling is 16), 32 for the 1-byte dtypes (sublane tiling is
# 32; the MXU encode itself is ILLEGAL there — check_kernel_legality — but
# the granule is the per-dtype KernelShape constraint every layout-facing
# consumer keys on). One source for the kernels (ops/ft_sgemm) and the
# VMEM footprint model (ops/vmem).
def aug_rows(in_itemsize: int) -> int:
    """Sublane-aligned augmented-row count for one operand's checksum rows."""
    return {4: 8, 2: 16, 1: 32}[in_itemsize]


def sublane_granule(in_itemsize: int) -> int:
    """Mosaic's minimum sublane tile for one input width: (8, 128) f32,
    (16, 128) bf16, (32, 128) int8/fp8. Every ``KernelShape`` block dim is
    a multiple of 128, so all shipped tiles are legal at every dtype; the
    granule is exported for tuner-space validation and the augmentation
    row padding (:func:`aug_rows`)."""
    return {4: 8, 2: 16, 1: 32}[in_itemsize]


# Fused-ABFT checksum strategies of the FT kernel family (ops/ft_sgemm;
# hoisted here so every axis of the kernel family — strategy, encode,
# threshold, dtype — has ONE declaration module the static contract
# checker (ft_sgemm_tpu/lint, "axis-drift" pass) can read and cross-check
# against the tuner key, vmem variants, telemetry labels, serve routing,
# and CLI spellings). ops/ft_sgemm re-exports this name unchanged.
STRATEGIES = ("rowcol", "global", "weighted", "fused")

# Checksum-encode modes of the FT kernel family (ops/ft_sgemm):
#   "vpu" — per-K-step whole-tile VPU reductions build the expected
#           checksums (the original design; the default).
#   "mxu" — the expected checksums ride the systolic array as augmented
#           operand rows: one dot_general per K step yields the partial
#           product AND the expected-checksum accumulators.
ENCODE_MODES = ("vpu", "mxu")

# Detection-threshold modes of the FT kernel family (ops/ft_sgemm):
#   "static"   — one fixed threshold for the whole run (the reference's
#                9500 operating point; the default, spelled either as a
#                float or as the literal "static").
#   "auto"     — one threshold PER CALL, traced from the full inputs'
#                moments (margin x the calibrated noise-floor bound,
#                analysis.estimate_noise_floor). Same kernel program as
#                static: the threshold rides the runtime SMEM scalars.
#   "adaptive" — one threshold PER TILE PER CHECK, derived INSIDE the
#                kernel from running per-tile moment statistics (sum +
#                sum-of-squares -> variance bound, V-ABFT style,
#                arXiv 2602.08043) accumulated during the checksum-encode
#                pass. The mode that makes detection calibrated under
#                heterogeneous/varying operand statistics — the blocker
#                for ABFT at bf16 and below (DESIGN.md §10).
THRESHOLD_MODES = ("static", "auto", "adaptive")

# Input-dtype family of the kernels. f32 is the dtype-of-record; bf16 the
# MXU's full-rate input mode; fp8_e4m3 / int8 the low-precision serving
# dtypes (2-8x MXU throughput on parts that accelerate them). Accumulation
# is always dtype-legal-widened: f32 for the float dtypes, int32 for int8
# (exact — integer checksum residuals are identically zero on clean runs).
IN_DTYPES = ("float32", "bfloat16", "float8_e4m3fn", "int8")

# Accepted spellings for the fp8 dtype (jax's canonical name is the
# e4m3fn variant; papers and CLI flags commonly drop the suffix).
_IN_DTYPE_ALIASES = {
    "fp8": "float8_e4m3fn",
    "fp8_e4m3": "float8_e4m3fn",
    "float8_e4m3": "float8_e4m3fn",
}

# Per-dtype axis legality as STATIC tables (DESIGN.md §10 derives each
# constraint; :func:`check_kernel_legality` raises the derivations as
# errors). These are data, not code, so the static contract checker can
# cross-check them against every other spelling of the axes without
# importing anything:
#   - 1-byte dtypes cannot carry checksum rows (encode="mxu" /
#     strategy="fused" saturate/overflow the operand dtype);
#   - int8 ships only the non-ratio-localizing strategies (wrapping int32
#     checksums cannot guarantee the weighted-residual ratio).
STRATEGY_LEGALITY = {
    "float32": ("rowcol", "global", "weighted", "fused"),
    "bfloat16": ("rowcol", "global", "weighted", "fused"),
    "float8_e4m3fn": ("rowcol", "global", "weighted"),
    "int8": ("rowcol", "global"),
}
ENCODE_LEGALITY = {
    "float32": ("vpu", "mxu"),
    "bfloat16": ("vpu", "mxu"),
    "float8_e4m3fn": ("vpu",),
    "int8": ("vpu",),
}
# The strategy an entry point defaults to when the caller names only a
# dtype: the family flagship (weighted — deferred localization, lowest
# overhead) wherever legal, rowcol for int8 (the exact path ships no
# ratio localization). serve/buckets.py and the CLI route from THIS
# table — one declaration, machine-checked, instead of per-site
# ``"rowcol" if dtype == "int8" else "weighted"`` spellings.
DEFAULT_STRATEGY = {
    "float32": "weighted",
    "bfloat16": "weighted",
    "float8_e4m3fn": "weighted",
    "int8": "rowcol",
}


def canonical_in_dtype(in_dtype) -> str:
    """The canonical ``IN_DTYPES`` name for one in-dtype spelling.

    Raises a ValueError naming the legal family for anything else, so
    every entry point (kernel factories, CLI flags, tuner keys) rejects a
    bad dtype with the same message.
    """
    if isinstance(in_dtype, str):
        name = _IN_DTYPE_ALIASES.get(in_dtype, in_dtype)
    else:
        # dtype objects / scalar types (np, jnp, ml_dtypes all register
        # with numpy's dtype machinery).
        import numpy as np

        try:
            name = np.dtype(in_dtype).name
        except TypeError:
            name = str(in_dtype)
    if name not in IN_DTYPES:
        raise ValueError(
            f"in_dtype must be one of {IN_DTYPES} (aliases:"
            f" {tuple(sorted(_IN_DTYPE_ALIASES))}), got {in_dtype!r}")
    return name


def check_kernel_legality(*, strategy: str, encode: str, in_dtype,
                          threshold_mode: str = "static",
                          multifault: Optional[bool] = None) -> str:
    """Validate one (strategy, encode, dtype, threshold-mode) combination.

    Returns the canonical dtype name. The constraints themselves live in
    the static :data:`STRATEGY_LEGALITY` / :data:`ENCODE_LEGALITY`
    tables (machine-checked by the lint subsystem); this function turns
    a violation into the explanatory error. The low-precision
    constraints are representational, not policy (DESIGN.md §10 derives
    each):

    - **1-byte dtypes cannot carry checksum rows** (``encode="mxu"`` /
      ``strategy="fused"``): an augmented-operand checksum row holds sums
      of up to ``bm`` elements — magnitude ~``bm * max|x|`` — which
      saturates fp8_e4m3 (max 448) and overflows int8 (max 127) for every
      legal tile. The VPU encode computes the same checksums in the
      32-bit accumulation domain, so it is the low-precision encode.
    - **int8 localizing strategies**: ``weighted``/``fused`` (and the
      rowcol multifault extension) localize the fault row by the
      weighted-residual RATIO — exact only while the weighted int32
      checksum stream has not wrapped, which weights up to ``bm`` (and
      ``bm^2`` for the re-check moment) cannot guarantee. int8 therefore
      ships ``rowcol`` (plain row+col intersection, exact in wrapping
      int32 arithmetic) and ``global``.
    """
    dtype_name = canonical_in_dtype(in_dtype)
    if threshold_mode not in THRESHOLD_MODES:
        raise ValueError(
            f"unknown threshold mode {threshold_mode!r}; pick from"
            f" {THRESHOLD_MODES}")
    if "mxu" not in ENCODE_LEGALITY[dtype_name]:
        if encode == "mxu" or strategy == "fused":
            raise ValueError(
                f"encode='mxu' (and strategy='fused') is illegal for"
                f" {dtype_name}: checksum rows of magnitude ~bm * max|x|"
                " are not representable in a 1-byte operand dtype; use"
                " encode='vpu' (checksums are computed in the 32-bit"
                " accumulation domain there)")
    if dtype_name == "int8":
        if strategy not in STRATEGY_LEGALITY["int8"]:
            raise ValueError(
                f"strategy {strategy!r} is illegal for int8: weighted-"
                "ratio fault localization needs non-wrapping moment"
                f" checksums; int8 supports {STRATEGY_LEGALITY['int8']}")
        if multifault:
            raise ValueError(
                "multifault=True is illegal for int8: the multifault"
                " extension localizes by the weighted-residual ratio,"
                " which wrapping int32 checksums cannot guarantee")
    return dtype_name


# The 6 shipped shapes (+ the reference's unused "test" shape), mirroring the
# canonical table at reference code_gen/main.py:8-16. TPU tile choices:
#   - "small"/"medium": minimum legal MXU tiles, differing in K depth —
#     preserves the small-block / shallow-K character.
#   - "large": 256x256 blocks.
#   - "tall"/"wide": 4:1 / 1:4 aspect blocks (reference: 128x32 / 32x128).
#   - "huge": the flagship big-block kernel (reference: 128x128x8,
#     README.md:46 — beats cuBLAS; ours targets XLA's native dot).
# large/huge K-depths picked by a live-v5e sweep (scripts/tune_tiles.py,
# M=N=K=4096): bk=512 beats bk=256 by ~2% plain and ~5-14% fused-ABFT
# (fewer K steps => fewer detect/correct epilogues); larger tiles exceed
# the ~16 MB VMEM budget with double buffering and fail to compile.
SHAPES = {
    "small": KernelShape("small", 128, 128, 128, (16, 16, 16, 8, 16, 2, 2)),
    "medium": KernelShape("medium", 128, 128, 256, (32, 32, 8, 16, 32, 4, 4)),
    "large": KernelShape("large", 256, 256, 512, (64, 64, 8, 32, 64, 8, 8)),
    "tall": KernelShape("tall", 512, 128, 256, (128, 32, 8, 64, 16, 8, 4)),
    "wide": KernelShape("wide", 128, 512, 256, (32, 128, 8, 16, 64, 4, 8)),
    "huge": KernelShape("huge", 512, 512, 512, (128, 128, 8, 32, 64, 8, 8)),
    "test": KernelShape("test", 128, 128, 128, (64, 64, 8, 16, 32, 4, 4)),
}

SHAPE_ORDER = ("small", "medium", "large", "tall", "wide", "huge")

# Per-kernel VMEM budget passed to Mosaic. The compiler's default scoped-vmem
# limit is 16 MiB, and the FT kernels' round-3 additions (runtime-threshold
# SMEM operand, checksum pads, re-check scratch) sit 0.3-2 MiB past it at the
# tuned 4096 tiles — a compile-time OOM on hardware that interpret-mode CPU
# runs can never see. v5e cores have 128 MiB of physical VMEM; 64 MiB clears
# every shipped tile with room for the tuner to explore larger ones.
VMEM_LIMIT_BYTES = 64 * 1024 * 1024


def vmem_limit_bytes() -> int:
    """The scoped-VMEM budget to compile kernels against, per device.

    The 64 MiB default assumes a v4/v5-class part (128 MiB physical VMEM
    per core). Older generations have 16 MiB total — on those, a raised
    compiler bound would only defer the failure from a clear compile-time
    scoped-vmem error to a runtime allocation failure, so the limit is
    derived from the live device kind (matched as a standalone ``v2``/
    ``v3`` token — a bare substring test would misfire on any future kind
    string that merely contains the characters). ``FT_SGEMM_VMEM_LIMIT_
    BYTES`` overrides both (trace-time; takes effect on the next compile).
    The resolution is cached per env-var value: every kernel trace calls
    this, and the device query must not be re-paid each time.
    """
    import os

    return _resolve_vmem_limit(os.environ.get("FT_SGEMM_VMEM_LIMIT_BYTES"))


@functools.lru_cache(maxsize=None)
def _resolve_vmem_limit(env: Optional[str]) -> int:
    if env:
        try:
            value = int(env)
        except ValueError:
            raise ValueError(
                f"FT_SGEMM_VMEM_LIMIT_BYTES must be an integer byte count,"
                f" got {env!r}") from None
        if value <= 0:
            raise ValueError(
                f"FT_SGEMM_VMEM_LIMIT_BYTES must be positive, got {env!r}")
        return value
    kind = ""
    try:
        import jax

        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend yet: assume the default
        pass
    tokens = re.split(r"[^a-z0-9]+", kind)
    if "v2" in tokens or "v3" in tokens:
        return 16 * 1024 * 1024
    return VMEM_LIMIT_BYTES

# bf16 input mode re-tunes the flagship tile (live-v5e sweep,
# scripts/tune_tiles.py --bf16 [--ft], M=N=K=4096): halved A/B tile bytes
# let the plain kernel go K-deep (512x512x2048, ~138 TFLOPS vs ~124 at the
# f32 tile), while the fused-ABFT kernel prefers a wide tile
# (512x1024x256, ~110 TFLOPS vs ~101) — wider bn amortizes the per-check
# detect/correct reductions over more columns. Applied automatically when a
# *named* shape is used with in_dtype="bfloat16"; explicit KernelShape
# objects are always respected. Keyed by (shape name, is_ft).
BF16_TILE_OVERRIDES = {
    ("huge", False): (512, 512, 2048),
    ("huge", True): (512, 1024, 256),
}


def shape_for_dtype(shape: KernelShape, is_ft: bool,
                    in_dtype) -> KernelShape:
    """Swap in the bf16-tuned tile for a named shape, when one exists."""
    import dataclasses

    import jax.numpy as jnp

    if jnp.dtype(in_dtype) != jnp.bfloat16:
        return shape
    tile = BF16_TILE_OVERRIDES.get((shape.name, is_ft))
    if tile is None:
        return shape
    return dataclasses.replace(shape, bm=tile[0], bn=tile[1], bk=tile[2])

# Kernel-id table, matching the driver's dispatch ladder and perf-table rows
# (reference sgemm.cu:105-199 and sgemm.cu:235-237). Id 0 is the vendor
# library (cuBLAS there, XLA's native dot here); ids 1-6 the plain shapes;
# id 10 the non-fused two-pass ABFT baseline; ids 11-16 the fused-ABFT
# shapes. Ids 7-9 are unused, as in the reference.
KERNEL_TABLE = {
    0: ("xla_dot", None, False),
    1: ("kernel_sgemm_small", "small", False),
    2: ("kernel_sgemm_medium", "medium", False),
    3: ("kernel_sgemm_large", "large", False),
    4: ("kernel_sgemm_tall", "tall", False),
    5: ("kernel_sgemm_wide", "wide", False),
    6: ("kernel_sgemm_huge", "huge", False),
    10: ("abft_baseline", None, True),
    11: ("abft_kernel_small", "small", True),
    12: ("abft_kernel_medium", "medium", True),
    13: ("abft_kernel_large", "large", True),
    14: ("abft_kernel_tall", "tall", True),
    15: ("abft_kernel_wide", "wide", True),
    16: ("abft_kernel_huge", "huge", True),
}

PERF_ROW_IDS = (0, 1, 2, 3, 4, 5, 6, 10, 11, 12, 13, 14, 15, 16)


def kernel_for_id(kernel_id: int) -> Tuple[str, Optional[KernelShape], bool]:
    """Resolve a kernel id to (display name, shape or None, is_abft)."""
    if kernel_id not in KERNEL_TABLE:
        raise KeyError(f"unknown kernel id {kernel_id}")
    name, shape_name, is_abft = KERNEL_TABLE[kernel_id]
    shape = SHAPES[shape_name] if shape_name is not None else None
    return name, shape, is_abft
