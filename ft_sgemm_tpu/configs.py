"""Kernel shape configuration family.

The reference drives a string-templating code generator with a 7-parameter
tile description ``[ms, ns, ks, mw, nw, mr, nr]`` (block tile, warp tile,
thread tile — ``code_gen/main.py:8-16``, ``code_gen/code_gen.py:5-30``) and
instantiates 6 named shapes x {plain, fused-ABFT}.

On TPU there is no warp/thread level: the MXU consumes whole 128x128 tiles
and the unit of scheduling is the Pallas grid step. The family therefore
collapses to a 3-parameter block tile ``(bm, bn, bk)`` per named shape,
chosen to be legal and efficient on the MXU (f32 min tile 8x128; lane dim
128). The reference's 7 parameters are recorded verbatim for provenance in
``ref_params``. Where the reference shape is sub-MXU (e.g. ``small`` is a
16x16 block) the TPU tile is the nearest MXU-friendly shape and the
perf-characteristic (small vs large blocks, tall vs wide aspect) is kept,
not the literal numbers — see SURVEY.md §7 "Hard parts".
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KernelShape:
    """A named block-tiling configuration for the SGEMM kernel family.

    Attributes:
      name: shape family name (reference ``main.py:8-16`` table key).
      bm, bn, bk: Pallas block tile (rows of C, cols of C, K-depth per
        grid step). All multiples of 128 so f32 tiles map onto the MXU.
      ref_params: the reference's ``[ms, ns, ks, mw, nw, mr, nr]`` for
        this name, for provenance/docs only.
    """

    name: str
    bm: int
    bn: int
    bk: int
    ref_params: Tuple[int, int, int, int, int, int, int]

    def __post_init__(self):
        for field in ("bm", "bn", "bk"):
            v = getattr(self, field)
            if v % 128 != 0 or v <= 0:
                raise ValueError(
                    f"KernelShape.{field}={v} must be a positive multiple of"
                    " 128 (f32 MXU tiling)"
                )

    @property
    def block(self) -> Tuple[int, int, int]:
        return (self.bm, self.bn, self.bk)

    def aug_block(self, aug_a: int = 0, aug_b: int = 0) -> Tuple[int, int, int]:
        """The (a_rows, b_rows, bk) the kernel's BlockSpecs use when operand
        augmentation rides checksum rows on the A/B tiles (``encode="mxu"``
        and the fused strategy).

        ``aug_a``/``aug_b`` are the appended checksum-row counts (see
        ``aug_rows``). Validates that the augmented sublane dims stay
        legal Mosaic tiles — every block dim is a multiple of 128, so any
        augmentation that is itself a multiple of the dtype's sublane
        granule (8 for f32, 16 for bf16 — which ``aug_rows`` guarantees)
        is legal; a hand-rolled augmentation that is not gets a loud error
        here instead of an opaque Mosaic layout failure.
        """
        for label, aug in (("aug_a", aug_a), ("aug_b", aug_b)):
            if aug < 0 or aug % 8 != 0:
                raise ValueError(
                    f"KernelShape.aug_block: {label}={aug} must be a"
                    " non-negative multiple of 8 (f32 sublane granule);"
                    " use configs.aug_rows for the dtype-correct count")
        return (self.bm + aug_a, self.bn + aug_b, self.bk)


# Checksum-row augmentation ("mxu" encode / the fused strategy): moment rows
# appended to an operand tile must keep the tile's sublane dim aligned, so
# the row count is padded to the dtype's sublane granule — 8 rows for f32
# (3 moment rows padded), 16 for bf16 (up to 9 hi/lo/lo2 term rows padded;
# bf16 sublane tiling is 16), 32 for the 1-byte dtypes (sublane tiling is
# 32; the MXU encode itself is ILLEGAL there — check_kernel_legality — but
# the granule is the per-dtype KernelShape constraint every layout-facing
# consumer keys on). One source for the kernels (ops/ft_sgemm) and the
# VMEM footprint model (ops/vmem).
def aug_rows(in_itemsize: int) -> int:
    """Sublane-aligned augmented-row count for one operand's checksum rows."""
    return {4: 8, 2: 16, 1: 32}[in_itemsize]


def sublane_granule(in_itemsize: int) -> int:
    """Mosaic's minimum sublane tile for one input width: (8, 128) f32,
    (16, 128) bf16, (32, 128) int8/fp8. Every ``KernelShape`` block dim is
    a multiple of 128, so all shipped tiles are legal at every dtype; the
    granule is exported for tuner-space validation and the augmentation
    row padding (:func:`aug_rows`)."""
    return {4: 8, 2: 16, 1: 32}[in_itemsize]


# Fused-ABFT checksum strategies of the FT kernel family (ops/ft_sgemm;
# hoisted here so every axis of the kernel family — strategy, encode,
# threshold, dtype — has ONE declaration module the static contract
# checker (ft_sgemm_tpu/lint, "axis-drift" pass) can read and cross-check
# against the tuner key, vmem variants, telemetry labels, serve routing,
# and CLI spellings). ops/ft_sgemm re-exports this name unchanged.
STRATEGIES = ("rowcol", "global", "weighted", "fused")

# Checksum-encode modes of the FT kernel family (ops/ft_sgemm):
#   "vpu" — per-K-step whole-tile VPU reductions build the expected
#           checksums (the original design; the default).
#   "mxu" — the expected checksums ride the systolic array as augmented
#           operand rows: one dot_general per K step yields the partial
#           product AND the expected-checksum accumulators.
ENCODE_MODES = ("vpu", "mxu")

# Detection-threshold modes of the FT kernel family (ops/ft_sgemm):
#   "static"   — one fixed threshold for the whole run (the reference's
#                9500 operating point; the default, spelled either as a
#                float or as the literal "static").
#   "auto"     — one threshold PER CALL, traced from the full inputs'
#                moments (margin x the calibrated noise-floor bound,
#                analysis.estimate_noise_floor). Same kernel program as
#                static: the threshold rides the runtime SMEM scalars.
#   "adaptive" — one threshold PER TILE PER CHECK, derived INSIDE the
#                kernel from running per-tile moment statistics (sum +
#                sum-of-squares -> variance bound, V-ABFT style,
#                arXiv 2602.08043) accumulated during the checksum-encode
#                pass. The mode that makes detection calibrated under
#                heterogeneous/varying operand statistics — the blocker
#                for ABFT at bf16 and below (DESIGN.md §10).
THRESHOLD_MODES = ("static", "auto", "adaptive")

# Input-dtype family of the kernels. f32 is the dtype-of-record; bf16 the
# MXU's full-rate input mode; fp8_e4m3 / int8 the low-precision serving
# dtypes (2-8x MXU throughput on parts that accelerate them). Accumulation
# is always dtype-legal-widened: f32 for the float dtypes, int32 for int8
# (exact — integer checksum residuals are identically zero on clean runs).
IN_DTYPES = ("float32", "bfloat16", "float8_e4m3fn", "int8")

# --- searched kernel-variant axes (DESIGN.md §16) ------------------------
#
# The tuner searches more than the block tile: these tuples declare the
# pipeline/grid/epilogue axes of the full kernel variant descriptor
# (:class:`KernelVariant`). Each is mirrored by ``contracts.VARIANT_AXES``
# (the lint axis-drift pass cross-checks the two spellings) and appears in
# the tuner cache key (``pipe=``/``grid=``/``cad=``/``epi=``, schema 4),
# the telemetry label schema, and the CLI flag spellings.
#
# ``PIPELINE_DEPTHS``: K panels the Pallas pipeline holds per operand
# stream. 2 is Mosaic's automatic double buffer (one (bm, bk) window, two
# buffers — the historical assumption ops/vmem priced as "2x block
# bytes"). 3 deepens the prefetch horizon by widening each buffered
# window to TWO K panels (the kernel body unrolls two sub-panel dots per
# grid step); Mosaic double-buffers the wider window, so 4 panels are
# resident and the footprint model prices exactly that
# (``estimate_vmem_bytes(pipeline_depth=...)``). When a native Mosaic
# buffer-count knob lands, the realization can swap without changing the
# axis contract.
PIPELINE_DEPTHS = (2, 3)

# ``GRID_ORDERS``: traversal order of the two PARALLEL grid dims — "mn"
# (M-major, the historical order) or "nm" (N-major). K-major traversal is
# NOT a legal member: every kernel in the family accumulates in the
# resident output block across the K sweep (ops/sgemm.py's rationale), so
# K must stay the innermost grid dim; the legal orders permute only the
# output-tile walk (which changes HBM streaming locality: "mn" re-reads B
# panels per row of output tiles, "nm" re-reads A panels per column).
GRID_ORDERS = ("mn", "nm")

# ``DIM_SEMANTICS``: the Mosaic dimension semantics of the two output
# grid dims ("parallel" lets the compiler partition them across cores;
# "arbitrary" forces sequential execution — occasionally a win when the
# parallel partition fragments VMEM). The K dim is always "arbitrary"
# (it carries the accumulation dependency) and is not part of the axis.
DIM_SEMANTICS = ("parallel", "arbitrary")

# ``RING_OVERLAP_MODES``: the hop schedule of the ring collective paths
# (parallel/ring.py, parallel/ring_attention.py). "serial" computes hop
# t's local FT-GEMM, then rotates the visiting shard (hop t+1 waits on
# the ICI transfer — the historical schedule). "overlap" is the
# double-buffered rotate-ahead pipeline: the ppermute producing hop
# t+1's shard is issued BEFORE hop t's local compute, so XLA's async
# collective-permute hides the ICI transfer behind the MXU dot, at the
# cost of a second resident copy of each rotating operand. The two
# schedules run identical local GEMMs on identical shard values, so
# outputs and per-device counters are byte-value equal (test-pinned).
# A searched tuner axis (``ring=`` key component, schema 5); dispatch
# spells the unconstrained lookup "auto" like every other variant axis.
RING_OVERLAP_MODES = ("serial", "overlap")

# Fused-epilogue axes: the detect-correct epilogue of every kernel can
# fuse a bias add, an activation, and an int8/fp8 quantize-rescale —
# applied strictly AFTER correction, so the ABFT checksums verify the
# pre-epilogue accumulator (DESIGN.md §16; oracle-pinned in
# tests/test_variants.py). Quantized outputs stay in f32 storage carrying
# exactly representable target-grid values (round+clamp for int8, an
# fp8 cast round-trip for fp8_e4m3fn): the serving layer's egress cast is
# then value-exact, and the kernel's f32 output block / C aliasing is
# untouched.
EPILOGUE_ACTIVATIONS = ("none", "relu", "gelu")
EPILOGUE_QUANTIZE = ("none", "int8", "float8_e4m3fn")

# Spelling tokens for the quantize modes in the compact epilogue spelling
# (EpilogueSpec.spelling / .parse): "qint8" / "qfp8".
_EPI_QUANT_TOKENS = {"int8": "qint8", "float8_e4m3fn": "qfp8"}


@dataclasses.dataclass(frozen=True)
class EpilogueSpec:
    """A fused-epilogue request: what the kernel applies to the corrected
    ``alpha*acc + beta*C`` tile before writing it back.

    ``bias`` adds a per-output-column bias row; ``activation`` is one of
    :data:`EPILOGUE_ACTIVATIONS`; ``quantize`` one of
    :data:`EPILOGUE_QUANTIZE` with ``scale`` the quantize-rescale
    multiplier (output = round/clamp of ``x * scale`` onto the target
    grid, in f32 storage). Order of application: bias -> activation ->
    quantize — the standard serving epilogue shape.

    The canonical compact spelling (:meth:`spelling` / :meth:`parse`) is
    what rides the tuner cache key (``epi=``), telemetry extras, bucket
    keys, and CLI flags: ``"none"`` for the identity, else ``+``-joined
    tokens, e.g. ``"bias+relu"``, ``"bias+gelu+qint8"``,
    ``"qfp8x0.5"`` (a non-unit scale is appended as ``x<scale>``).
    """

    bias: bool = False
    activation: str = "none"
    quantize: str = "none"
    scale: float = 1.0

    def __post_init__(self):
        if self.activation not in EPILOGUE_ACTIVATIONS:
            raise ValueError(
                f"EpilogueSpec.activation={self.activation!r} must be one"
                f" of {EPILOGUE_ACTIVATIONS}")
        if self.quantize not in EPILOGUE_QUANTIZE:
            raise ValueError(
                f"EpilogueSpec.quantize={self.quantize!r} must be one of"
                f" {EPILOGUE_QUANTIZE}")
        if self.scale != 1.0 and self.quantize == "none":
            raise ValueError(
                "EpilogueSpec.scale is the quantize-rescale multiplier;"
                " set quantize to use it")
        if not self.scale > 0.0:
            raise ValueError(
                f"EpilogueSpec.scale={self.scale!r} must be positive")

    @property
    def is_identity(self) -> bool:
        return (not self.bias and self.activation == "none"
                and self.quantize == "none")

    @property
    def spelling(self) -> str:
        if self.is_identity:
            return "none"
        parts = []
        if self.bias:
            parts.append("bias")
        if self.activation != "none":
            parts.append(self.activation)
        if self.quantize != "none":
            tok = _EPI_QUANT_TOKENS[self.quantize]
            if self.scale != 1.0:
                tok += f"x{self.scale:g}"
            parts.append(tok)
        return "+".join(parts)

    @classmethod
    def parse(cls, spec) -> "EpilogueSpec":
        """An :class:`EpilogueSpec` from a spelling (or pass one through).

        Accepts ``None`` / ``"none"`` (identity) and ``+``-joined tokens
        (see :meth:`spelling`); raises a ValueError naming the legal
        tokens for anything else — one parser for the CLI, the tuner key,
        and the serve bucket field.
        """
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if not isinstance(spec, str):
            raise ValueError(
                f"epilogue must be an EpilogueSpec or a spelling string,"
                f" got {spec!r}")
        s = spec.strip().lower()
        if s in ("", "none"):
            return cls()
        bias = False
        activation = "none"
        quantize = "none"
        scale = 1.0
        quant_by_token = {v: k for k, v in _EPI_QUANT_TOKENS.items()}
        for tok in s.split("+"):
            if tok == "bias":
                bias = True
            elif tok in EPILOGUE_ACTIVATIONS and tok != "none":
                activation = tok
            else:
                base, _, sc = tok.partition("x")
                if base in quant_by_token:
                    quantize = quant_by_token[base]
                    if sc:
                        try:
                            scale = float(sc)
                        except ValueError:
                            raise ValueError(
                                f"epilogue quantize scale {sc!r} in"
                                f" {spec!r} is not a number") from None
                else:
                    raise ValueError(
                        f"unknown epilogue token {tok!r} in {spec!r};"
                        " legal tokens: bias, "
                        + ", ".join(a for a in EPILOGUE_ACTIVATIONS
                                    if a != "none")
                        + ", " + ", ".join(sorted(quant_by_token))
                        + " (optionally qint8x<scale>)")
        return cls(bias=bias, activation=activation, quantize=quantize,
                   scale=scale)


DEFAULT_EPILOGUE = EpilogueSpec()


@dataclasses.dataclass(frozen=True)
class KernelVariant:
    """The full kernel variant descriptor the tuner searches end to end.

    Everything beyond the block tile that parameterizes a kernel's
    lowering: the pipeline depth (:data:`PIPELINE_DEPTHS`), the parallel-
    dim traversal order (:data:`GRID_ORDERS`), the Mosaic dimension
    semantics of the output dims (:data:`DIM_SEMANTICS`), the
    detect/correct cadence (``check_every`` in K-grid steps; ``None`` =
    the strategy's default — the reference's ~K/20 rule for rowcol/
    global, a single deferred final check for weighted/fused), the
    fused epilogue (an :class:`EpilogueSpec` SPELLING, kept as a string
    so the descriptor stays hashable/jit-static), and the ring hop
    schedule (:data:`RING_OVERLAP_MODES` — consumed by the ring
    collective wrappers, ignored by the single-device kernel factories).

    ``KernelVariant()`` is the exact historical behavior: dispatching
    with it (or with ``variant=None``) emits byte-identical HLO to the
    pre-variant build (pinned in tests/test_variants.py).
    """

    pipeline_depth: int = 2
    grid_order: str = "mn"
    dim_semantics: str = "parallel"
    check_every: Optional[int] = None
    epilogue: str = "none"
    ring_overlap: str = "serial"

    def __post_init__(self):
        if self.pipeline_depth not in PIPELINE_DEPTHS:
            raise ValueError(
                f"KernelVariant.pipeline_depth={self.pipeline_depth!r}"
                f" must be one of {PIPELINE_DEPTHS}")
        if self.grid_order not in GRID_ORDERS:
            raise ValueError(
                f"KernelVariant.grid_order={self.grid_order!r} must be"
                f" one of {GRID_ORDERS}")
        if self.dim_semantics not in DIM_SEMANTICS:
            raise ValueError(
                f"KernelVariant.dim_semantics={self.dim_semantics!r}"
                f" must be one of {DIM_SEMANTICS}")
        if self.check_every is not None and (
                not isinstance(self.check_every, int)
                or self.check_every < 1):
            raise ValueError(
                f"KernelVariant.check_every={self.check_every!r} must be"
                " a positive int (K-grid steps) or None for the"
                " strategy default")
        if self.ring_overlap not in RING_OVERLAP_MODES:
            raise ValueError(
                f"KernelVariant.ring_overlap={self.ring_overlap!r} must"
                f" be one of {RING_OVERLAP_MODES}")
        # Canonicalize the epilogue spelling through the one parser so
        # "Bias+ReLU" and "bias+relu" key identically everywhere.
        object.__setattr__(
            self, "epilogue", EpilogueSpec.parse(self.epilogue).spelling)

    @property
    def is_default(self) -> bool:
        return self == KernelVariant()

    @property
    def epilogue_spec(self) -> EpilogueSpec:
        return EpilogueSpec.parse(self.epilogue)

    @property
    def grid_spelling(self) -> str:
        """The combined ``grid=`` cache-key component:
        ``<order>.<semantics>`` (e.g. ``mn.parallel``)."""
        return f"{self.grid_order}.{self.dim_semantics}"

    @property
    def cadence_spelling(self) -> str:
        """The ``cad=`` cache-key component: ``auto`` (strategy default)
        or the explicit K-grid-step cadence."""
        return "auto" if self.check_every is None else str(self.check_every)


DEFAULT_VARIANT = KernelVariant()


def canonical_variant(variant) -> KernelVariant:
    """A :class:`KernelVariant` from None (the default), a variant, or a
    dict of its fields (the tuner-cache record form)."""
    if variant is None:
        return DEFAULT_VARIANT
    if isinstance(variant, KernelVariant):
        return variant
    if isinstance(variant, dict):
        fields = {f.name for f in dataclasses.fields(KernelVariant)}
        extra = set(variant) - fields
        if extra:
            raise ValueError(
                f"unknown KernelVariant fields {sorted(extra)};"
                f" legal: {sorted(fields)}")
        return KernelVariant(**variant)
    raise ValueError(
        f"variant must be a KernelVariant, a field dict, or None,"
        f" got {variant!r}")

# Accepted spellings for the fp8 dtype (jax's canonical name is the
# e4m3fn variant; papers and CLI flags commonly drop the suffix).
_IN_DTYPE_ALIASES = {
    "fp8": "float8_e4m3fn",
    "fp8_e4m3": "float8_e4m3fn",
    "float8_e4m3": "float8_e4m3fn",
}

# Per-dtype axis legality as STATIC tables (DESIGN.md §10 derives each
# constraint; :func:`check_kernel_legality` raises the derivations as
# errors). These are data, not code, so the static contract checker can
# cross-check them against every other spelling of the axes without
# importing anything:
#   - 1-byte dtypes cannot carry checksum rows (encode="mxu" /
#     strategy="fused" saturate/overflow the operand dtype);
#   - int8 ships only the non-ratio-localizing strategies (wrapping int32
#     checksums cannot guarantee the weighted-residual ratio).
STRATEGY_LEGALITY = {
    "float32": ("rowcol", "global", "weighted", "fused"),
    "bfloat16": ("rowcol", "global", "weighted", "fused"),
    "float8_e4m3fn": ("rowcol", "global", "weighted"),
    "int8": ("rowcol", "global"),
}
ENCODE_LEGALITY = {
    "float32": ("vpu", "mxu"),
    "bfloat16": ("vpu", "mxu"),
    "float8_e4m3fn": ("vpu",),
    "int8": ("vpu",),
}
# The strategy an entry point defaults to when the caller names only a
# dtype: the family flagship (weighted — deferred localization, lowest
# overhead) wherever legal, rowcol for int8 (the exact path ships no
# ratio localization). serve/buckets.py and the CLI route from THIS
# table — one declaration, machine-checked, instead of per-site
# ``"rowcol" if dtype == "int8" else "weighted"`` spellings.
DEFAULT_STRATEGY = {
    "float32": "weighted",
    "bfloat16": "weighted",
    "float8_e4m3fn": "weighted",
    "int8": "rowcol",
}


def canonical_in_dtype(in_dtype) -> str:
    """The canonical ``IN_DTYPES`` name for one in-dtype spelling.

    Raises a ValueError naming the legal family for anything else, so
    every entry point (kernel factories, CLI flags, tuner keys) rejects a
    bad dtype with the same message.
    """
    if isinstance(in_dtype, str):
        name = _IN_DTYPE_ALIASES.get(in_dtype, in_dtype)
    else:
        # dtype objects / scalar types (np, jnp, ml_dtypes all register
        # with numpy's dtype machinery).
        import numpy as np

        try:
            name = np.dtype(in_dtype).name
        except TypeError:
            name = str(in_dtype)
    if name not in IN_DTYPES:
        raise ValueError(
            f"in_dtype must be one of {IN_DTYPES} (aliases:"
            f" {tuple(sorted(_IN_DTYPE_ALIASES))}), got {in_dtype!r}")
    return name


def check_kernel_legality(*, strategy: str, encode: str, in_dtype,
                          threshold_mode: str = "static",
                          multifault: Optional[bool] = None) -> str:
    """Validate one (strategy, encode, dtype, threshold-mode) combination.

    Returns the canonical dtype name. The constraints themselves live in
    the static :data:`STRATEGY_LEGALITY` / :data:`ENCODE_LEGALITY`
    tables (machine-checked by the lint subsystem); this function turns
    a violation into the explanatory error. The low-precision
    constraints are representational, not policy (DESIGN.md §10 derives
    each):

    - **1-byte dtypes cannot carry checksum rows** (``encode="mxu"`` /
      ``strategy="fused"``): an augmented-operand checksum row holds sums
      of up to ``bm`` elements — magnitude ~``bm * max|x|`` — which
      saturates fp8_e4m3 (max 448) and overflows int8 (max 127) for every
      legal tile. The VPU encode computes the same checksums in the
      32-bit accumulation domain, so it is the low-precision encode.
    - **int8 localizing strategies**: ``weighted``/``fused`` (and the
      rowcol multifault extension) localize the fault row by the
      weighted-residual RATIO — exact only while the weighted int32
      checksum stream has not wrapped, which weights up to ``bm`` (and
      ``bm^2`` for the re-check moment) cannot guarantee. int8 therefore
      ships ``rowcol`` (plain row+col intersection, exact in wrapping
      int32 arithmetic) and ``global``.
    """
    dtype_name = canonical_in_dtype(in_dtype)
    if threshold_mode not in THRESHOLD_MODES:
        raise ValueError(
            f"unknown threshold mode {threshold_mode!r}; pick from"
            f" {THRESHOLD_MODES}")
    if "mxu" not in ENCODE_LEGALITY[dtype_name]:
        if encode == "mxu" or strategy == "fused":
            raise ValueError(
                f"encode='mxu' (and strategy='fused') is illegal for"
                f" {dtype_name}: checksum rows of magnitude ~bm * max|x|"
                " are not representable in a 1-byte operand dtype; use"
                " encode='vpu' (checksums are computed in the 32-bit"
                " accumulation domain there)")
    if dtype_name == "int8":
        if strategy not in STRATEGY_LEGALITY["int8"]:
            raise ValueError(
                f"strategy {strategy!r} is illegal for int8: weighted-"
                "ratio fault localization needs non-wrapping moment"
                f" checksums; int8 supports {STRATEGY_LEGALITY['int8']}")
        if multifault:
            raise ValueError(
                "multifault=True is illegal for int8: the multifault"
                " extension localizes by the weighted-residual ratio,"
                " which wrapping int32 checksums cannot guarantee")
    return dtype_name


# The 6 shipped shapes (+ the reference's unused "test" shape), mirroring the
# canonical table at reference code_gen/main.py:8-16. TPU tile choices:
#   - "small"/"medium": minimum legal MXU tiles, differing in K depth —
#     preserves the small-block / shallow-K character.
#   - "large": 256x256 blocks.
#   - "tall"/"wide": 4:1 / 1:4 aspect blocks (reference: 128x32 / 32x128).
#   - "huge": the flagship big-block kernel (reference: 128x128x8,
#     README.md:46 — beats cuBLAS; ours targets XLA's native dot).
# large/huge K-depths picked by a live-v5e sweep (scripts/tune_tiles.py,
# M=N=K=4096): bk=512 beats bk=256 by ~2% plain and ~5-14% fused-ABFT
# (fewer K steps => fewer detect/correct epilogues); larger tiles exceed
# the ~16 MB VMEM budget with double buffering and fail to compile.
SHAPES = {
    "small": KernelShape("small", 128, 128, 128, (16, 16, 16, 8, 16, 2, 2)),
    "medium": KernelShape("medium", 128, 128, 256, (32, 32, 8, 16, 32, 4, 4)),
    "large": KernelShape("large", 256, 256, 512, (64, 64, 8, 32, 64, 8, 8)),
    "tall": KernelShape("tall", 512, 128, 256, (128, 32, 8, 64, 16, 8, 4)),
    "wide": KernelShape("wide", 128, 512, 256, (32, 128, 8, 16, 64, 4, 8)),
    "huge": KernelShape("huge", 512, 512, 512, (128, 128, 8, 32, 64, 8, 8)),
    "test": KernelShape("test", 128, 128, 128, (64, 64, 8, 16, 32, 4, 4)),
}

SHAPE_ORDER = ("small", "medium", "large", "tall", "wide", "huge")

# Per-kernel VMEM budget passed to Mosaic. The compiler's default scoped-vmem
# limit is 16 MiB, and the FT kernels' round-3 additions (runtime-threshold
# SMEM operand, checksum pads, re-check scratch) sit 0.3-2 MiB past it at the
# tuned 4096 tiles — a compile-time OOM on hardware that interpret-mode CPU
# runs can never see. v5e cores have 128 MiB of physical VMEM; 64 MiB clears
# every shipped tile with room for the tuner to explore larger ones.
VMEM_LIMIT_BYTES = 64 * 1024 * 1024


def vmem_limit_bytes() -> int:
    """The scoped-VMEM budget to compile kernels against, per device.

    The 64 MiB default assumes a v4/v5-class part (128 MiB physical VMEM
    per core). Older generations have 16 MiB total — on those, a raised
    compiler bound would only defer the failure from a clear compile-time
    scoped-vmem error to a runtime allocation failure, so the limit is
    derived from the live device kind (matched as a standalone ``v2``/
    ``v3`` token — a bare substring test would misfire on any future kind
    string that merely contains the characters). ``FT_SGEMM_VMEM_LIMIT_
    BYTES`` overrides both (trace-time; takes effect on the next compile).
    The resolution is cached per env-var value: every kernel trace calls
    this, and the device query must not be re-paid each time.
    """
    import os

    return _resolve_vmem_limit(os.environ.get("FT_SGEMM_VMEM_LIMIT_BYTES"))


@functools.lru_cache(maxsize=None)
def _resolve_vmem_limit(env: Optional[str]) -> int:
    if env:
        try:
            value = int(env)
        except ValueError:
            raise ValueError(
                f"FT_SGEMM_VMEM_LIMIT_BYTES must be an integer byte count,"
                f" got {env!r}") from None
        if value <= 0:
            raise ValueError(
                f"FT_SGEMM_VMEM_LIMIT_BYTES must be positive, got {env!r}")
        return value
    kind = ""
    try:
        import jax

        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend yet: assume the default
        pass
    tokens = re.split(r"[^a-z0-9]+", kind)
    if "v2" in tokens or "v3" in tokens:
        return 16 * 1024 * 1024
    return VMEM_LIMIT_BYTES

# bf16 input mode re-tunes the flagship tile (live-v5e sweep,
# scripts/tune_tiles.py --bf16 [--ft], M=N=K=4096): halved A/B tile bytes
# let the plain kernel go K-deep (512x512x2048, ~138 TFLOPS vs ~124 at the
# f32 tile), while the fused-ABFT kernel prefers a wide tile
# (512x1024x256, ~110 TFLOPS vs ~101) — wider bn amortizes the per-check
# detect/correct reductions over more columns. Applied automatically when a
# *named* shape is used with in_dtype="bfloat16"; explicit KernelShape
# objects are always respected. Keyed by (shape name, is_ft).
BF16_TILE_OVERRIDES = {
    ("huge", False): (512, 512, 2048),
    ("huge", True): (512, 1024, 256),
}


def shape_for_dtype(shape: KernelShape, is_ft: bool,
                    in_dtype) -> KernelShape:
    """Swap in the bf16-tuned tile for a named shape, when one exists."""
    import dataclasses

    import jax.numpy as jnp

    if jnp.dtype(in_dtype) != jnp.bfloat16:
        return shape
    tile = BF16_TILE_OVERRIDES.get((shape.name, is_ft))
    if tile is None:
        return shape
    return dataclasses.replace(shape, bm=tile[0], bn=tile[1], bk=tile[2])

# Kernel-id table, matching the driver's dispatch ladder and perf-table rows
# (reference sgemm.cu:105-199 and sgemm.cu:235-237). Id 0 is the vendor
# library (cuBLAS there, XLA's native dot here); ids 1-6 the plain shapes;
# id 10 the non-fused two-pass ABFT baseline; ids 11-16 the fused-ABFT
# shapes. Ids 7-9 are unused, as in the reference.
KERNEL_TABLE = {
    0: ("xla_dot", None, False),
    1: ("kernel_sgemm_small", "small", False),
    2: ("kernel_sgemm_medium", "medium", False),
    3: ("kernel_sgemm_large", "large", False),
    4: ("kernel_sgemm_tall", "tall", False),
    5: ("kernel_sgemm_wide", "wide", False),
    6: ("kernel_sgemm_huge", "huge", False),
    10: ("abft_baseline", None, True),
    11: ("abft_kernel_small", "small", True),
    12: ("abft_kernel_medium", "medium", True),
    13: ("abft_kernel_large", "large", True),
    14: ("abft_kernel_tall", "tall", True),
    15: ("abft_kernel_wide", "wide", True),
    16: ("abft_kernel_huge", "huge", True),
}

PERF_ROW_IDS = (0, 1, 2, 3, 4, 5, 6, 10, 11, 12, 13, 14, 15, 16)


def kernel_for_id(kernel_id: int) -> Tuple[str, Optional[KernelShape], bool]:
    """Resolve a kernel id to (display name, shape or None, is_abft)."""
    if kernel_id not in KERNEL_TABLE:
        raise KeyError(f"unknown kernel id {kernel_id}")
    name, shape_name, is_abft = KERNEL_TABLE[kernel_id]
    shape = SHAPES[shape_name] if shape_name is not None else None
    return name, shape, is_abft
