"""Mesh-sharded fault-tolerant GEMM via ``shard_map`` + XLA collectives.

The reference is strictly single-GPU — no NCCL/MPI; its only "communication"
is warp shuffles and shared memory inside one kernel (SURVEY.md §5). On TPU
the natural scaling axis is a `jax.sharding.Mesh`: this module runs the
fused-ABFT Pallas kernel per device over a 2-D ``(x, y)`` mesh and lets XLA
place the collectives on ICI:

  - **x axis — output-row parallelism (dp over M):** A and C row-sharded;
    no communication for the product.
  - **y axis — contraction parallelism (K sharded):** A and B column-sharded
    along K; partial products are combined with a ``psum`` over ``y``.
    Crucially each device runs its *local* ABFT detect/correct BEFORE the
    psum — a corrupted partial is corrected while it is still localized to
    one chip, instead of being smeared across the reduction.
  - Detection counts are ``psum``-aggregated across the whole mesh, so the
    caller sees one global fault count over ICI.

Everything compiles under `jit` over the mesh; with
``xla_force_host_platform_device_count=N`` the same code runs on N virtual
CPU devices (the test/dry-run story — SURVEY.md §4).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import resolve_in_dtype
from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
from ft_sgemm_tpu.ops.sgemm import make_sgemm
from ft_sgemm_tpu.parallel.reduce import hierarchical_psum


def shard_map(f, *, mesh, in_specs, out_specs):
    # Replication/varying-axes checking is off either way: pallas_call
    # outputs don't carry the metadata the checker requires. The kwarg
    # spells check_vma on jax>=0.8 and check_rep before the rename.
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: Optional[int] = None,
              axis_sizes: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build a 2-D ``(x, y)`` mesh over the first ``n_devices`` devices.

    Default factorization: the most-square split of n (e.g. 8 -> 4x2).
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if axis_sizes is None:
        x = int(np.floor(np.sqrt(n)))
        while n % x:
            x -= 1
        axis_sizes = (x, n // x)
    x, y = axis_sizes
    if x * y != n:
        raise ValueError(f"axis_sizes {axis_sizes} != {n} devices")
    return Mesh(np.asarray(devs[:n]).reshape(x, y), ("x", "y"))


def _check_divisible(name, dim, parts):
    if dim % parts:
        raise ValueError(
            f"{name} dimension {dim} must divide evenly over {parts} mesh"
            f" shards (pad inputs before sharding)"
        )


def shard_local_ft(local_ft, inject, inject_coords, mesh_axes):
    """Run the local FT kernel, optionally restricting injection to the
    one device at ``inject_coords`` (mesh coordinates along
    ``mesh_axes``).

    The single-shard mode is the attribution self-test of the
    distributed paths: inject a known SDC on exactly one chip, then
    assert the merged telemetry names that chip (tests; DESIGN.md §8).
    Gating happens with ``lax.cond`` on ``axis_index`` — both branches
    compile once, each device executes only its own — because the
    injection spec is a trace-time constant of the kernel factory and
    cannot vary per device any other way.
    """

    def run(a_loc, b_loc, zeros):
        if inject_coords is None or not inject.enabled:
            return local_ft(a_loc, b_loc, zeros, inject)
        if len(inject_coords) != len(mesh_axes):
            raise ValueError(
                f"inject_coords {inject_coords} must give one coordinate "
                f"per mesh axis {mesh_axes}")
        is_target = jnp.bool_(True)
        for ax, coord in zip(mesh_axes, inject_coords):
            is_target = jnp.logical_and(
                is_target, jax.lax.axis_index(ax) == coord)
        return jax.lax.cond(
            is_target,
            lambda ops: local_ft(*ops, inject),
            lambda ops: local_ft(*ops, InjectionSpec.none()),
            (a_loc, b_loc, zeros))

    return run


def make_ft_step(local_ft, alpha, beta, inject, scatter_output, det_axes,
                 *, mesh_axes=("x", "y"), inject_coords=None):
    """Per-device FT-GEMM step shared by the 2-D and multi-host meshes.

    Runs the local fused-ABFT kernel on the device's shard (corrects BEFORE
    any collective), combines K-partials over mesh axis "y" with psum or
    psum_scatter, applies alpha/beta once, and reduces detection and
    uncorrectable-interval counts over ``det_axes`` HIERARCHICALLY
    (``parallel/reduce.py``): one axis at a time, innermost/ICI first,
    so on the multi-host mesh the only counter values crossing DCN are
    one already-combined set per host slot — detection traffic stays
    O(local) as the mesh grows (the arXiv 2112.09017 panel structure
    applied to the counter plane; count-equality vs the flat psum is
    test-pinned). ``det_axes`` order is therefore a contract: ICI axes
    before "host".

    Besides the psum'd global counters, the step returns each device's
    LOCAL detection/uncorrectable sums as size-1-per-axis arrays laid
    out ``P(*mesh_axes)`` — the fully sharded per-device counter grids
    whose shard placement encodes the mesh coordinates
    (``telemetry._device_entries`` reads them back without any
    collective). They are produced unconditionally: a few scalars per
    device, and the HLO must not depend on whether telemetry is enabled.

    ``inject_coords`` restricts injection to one device's mesh position
    (see :func:`shard_local_ft`).
    """
    run_local = shard_local_ft(local_ft, inject, inject_coords, mesh_axes)
    dev_shape = (1,) * len(mesh_axes)

    def step(a_loc, b_loc, c_loc):
        zeros = jnp.zeros((a_loc.shape[0], b_loc.shape[0]), jnp.float32)
        res = run_local(a_loc, b_loc, zeros)
        if scatter_output:
            partial = jax.lax.psum_scatter(
                res.c, "y", scatter_dimension=1, tiled=True)
        else:
            partial = jax.lax.psum(res.c, "y")
        out = alpha * partial + beta * c_loc
        dev_det = jnp.sum(res.detections).reshape(dev_shape)
        dev_unc = jnp.sum(res.uncorrectable).reshape(dev_shape)
        det = hierarchical_psum(res.detections, det_axes)
        unc = hierarchical_psum(res.uncorrectable, det_axes)
        return out, det, unc, dev_det, dev_unc

    return step


def make_tiered_ft_step(local_ft, alpha, beta, inject, det_axes,
                        *, mesh_axes=("x", "y"), tier_axes=("y", "x"),
                        inject_coords=None, tier_corrupt=(),
                        dcn_corrupt=(), gather_stages=False):
    """:func:`make_ft_step` + per-device DATA-PLANE checksum residual
    vectors staged one mesh axis at a time — the tier emission half of
    ``resilience/tiers.py`` (the arXiv 2112.09017 panel structure
    applied to checksum ROWS, not just the int32 counter plane).

    Each device computes the plain column-sum checksum of its local
    K-partial two ways — observed (``sum_rows(partial)``) and expected
    (``sum_rows(A_loc) @ B_loc.T``, the classic ABFT encode identity) —
    and emits their signed difference ``r`` (an f32 vector of length n).
    ``r`` is then reduced ONE AXIS AT A TIME in ``tier_axes`` order
    (innermost/ICI first, the ``hierarchical_psum`` staging discipline),
    and every stage's partial is returned as a fully sharded per-device
    grid, so the host sees the residual at each tier: per-device
    (tier "device", no collective), after the first staged axis
    (tier "host"), after every axis (tier "global"). Unlike the counter
    plane the staged values are FLOATS: staged == flat only up to f32
    reassociation, which is why tier detection is tolerance-gated
    (``resilience/tiers.py::checksum_tolerance``) while counter staging
    is exact.

    The residual is taken on the PRE-REDUCTION partial on purpose: the
    in-kernel check already verified the kernel's own output, so a
    nonzero ``r`` means corruption that struck AFTER the check — in the
    partial buffer, in the reduction's in-flight values, or in a
    resident shard — exactly the between-kernels window the in-kernel
    ABFT cannot see. ``tier_corrupt`` is the self-test knob for that
    window: trace-time ``((mesh coords), (i, j), delta)`` entries added
    to the named device's local partial AFTER the kernel check and
    BEFORE the reduction (the data-plane analog of ``inject_coords``).
    ``dcn_corrupt`` entries (``((mesh coords), j, delta)``) instead
    strike the staged residual IN FLIGHT between the last ICI stage and
    the final ``tier_axes`` hop — on a multihost mesh that final hop is
    the DCN ``host`` axis, so the corruption is invisible to every
    narrower stage and detectable ONLY at the post-DCN (global) tier:
    the fleet localization self-test for "seen only across DCN".

    The step returns ``(out, det, unc, dev_det, dev_unc, r_dev, *r_stages)``
    with every ``r_*`` reshaped to one vector per device
    (``P(*mesh_axes, None)`` grids — ``telemetry._device_entries``'s
    shard-placement trick, applied to f32 vectors).

    ``gather_stages=True`` instead all-gathers each stage into a fully
    REPLICATED ``(*mesh extents, n)`` grid (out_specs all-None): on a
    real multi-process mesh the sharded grids span non-addressable
    devices, and replication is what lets EVERY rank run host-side tier
    detection on the complete grid — the residual vectors are the
    detection plane's few KB, the traffic DCN is budgeted for.
    """
    run_local = shard_local_ft(local_ft, inject, inject_coords, mesh_axes)
    dev_shape = (1,) * len(mesh_axes)

    def step(a_loc, b_loc, c_loc):
        zeros = jnp.zeros((a_loc.shape[0], b_loc.shape[0]), jnp.float32)
        res = run_local(a_loc, b_loc, zeros)
        part = res.c
        for coords, (ci, cj), delta in tier_corrupt:
            on = jnp.bool_(True)
            for ax, cc in zip(mesh_axes, coords):
                on = jnp.logical_and(on, jax.lax.axis_index(ax) == cc)
            part = part.at[ci, cj].add(
                jnp.where(on, jnp.float32(delta), jnp.float32(0.0)))
        # The data-plane checksum pair: observed vs encoded column sums
        # of the local partial, both f32.
        obs = jnp.sum(part, axis=0)
        exp = jnp.sum(a_loc.astype(jnp.float32), axis=0) @ \
            b_loc.astype(jnp.float32).T
        r = (obs - exp).astype(jnp.float32)
        vec_shape = dev_shape + (r.shape[0],)

        def emit(v):
            if not gather_stages:
                return v.reshape(vec_shape)
            g = v
            for axis in reversed(mesh_axes):
                g = jax.lax.all_gather(g, axis)
            return g

        r_stages = [emit(r)]
        staged = r
        for si, ax in enumerate(tier_axes):
            if si == len(tier_axes) - 1:
                # In-flight corruption of the final (DCN on multihost
                # meshes) hop: struck AFTER every narrower stage was
                # recorded clean, BEFORE the last psum carries it into
                # the post-DCN residual.
                for coords, cj, delta in dcn_corrupt:
                    on = jnp.bool_(True)
                    for axis, cc in zip(mesh_axes, coords):
                        on = jnp.logical_and(
                            on, jax.lax.axis_index(axis) == cc)
                    staged = staged.at[cj].add(
                        jnp.where(on, jnp.float32(delta),
                                  jnp.float32(0.0)))
            staged = jax.lax.psum(staged, ax)
            r_stages.append(emit(staged))
        partial = jax.lax.psum(part, "y")
        out = alpha * partial + beta * c_loc
        dev_det = jnp.sum(res.detections).reshape(dev_shape)
        dev_unc = jnp.sum(res.uncorrectable).reshape(dev_shape)
        det = hierarchical_psum(res.detections, det_axes)
        unc = hierarchical_psum(res.uncorrectable, det_axes)
        return (out, det, unc, dev_det, dev_unc, *r_stages)

    return step


def sharded_ft_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    precision: str = "highest",
    in_dtype: str = "float32",
    scatter_output: bool = False,
    interpret: Optional[bool] = None,
    inject_coords: Optional[Tuple[int, int]] = None,
    donate_c: bool = False,
) -> FtSgemmResult:
    """Fused-ABFT ``C = alpha*A@B.T + beta*C`` over a 2-D device mesh.

    Sharding: A (M, K) -> P("x", "y"); B (N, K) -> P(None, "y");
    C (M, N) -> P("x", None). Each device corrects its own K-partial
    locally, then partials ``psum`` over ``y`` and detection counts ``psum``
    over the whole mesh. With telemetry enabled, each device's local
    counts are additionally recorded per ``(host, device, shard coords)``
    (``telemetry.record_mesh_gemm`` — the SDC-localization view;
    DESIGN.md §8). ``inject_coords=(i, j)`` restricts fault injection to
    the device at mesh position ``(x=i, y=j)`` — the attribution
    self-test.

    ``scatter_output=True`` replaces the ``psum`` with a ``psum_scatter``
    over ``y`` (a reduce-scatter on the ICI ring): the output lands sharded
    P("x", "y") — N split over ``y`` — so the post-reduction C buffer (and
    the beta*C input) shrinks by the ``y`` factor per device. (The local
    pre-reduction partial is still (M/x, N) — it feeds the reduce-scatter.)
    This is the layout for outputs that feed further sharded computation;
    the returned array is still the assembled global C (XLA keeps it
    sharded until the caller forces it).

    ``donate_c=True`` donates the C operand's buffer to the output at
    the jit boundary (the PR-3 ``input_output_aliases`` C->output
    aliasing inside the per-device Pallas kernel, extended to the OUTER
    call): C is read exactly once by the ``beta*C`` epilogue and the
    output shares its sharding (when ``scatter_output=False``), so XLA
    reuses the HBM buffer instead of allocating a second (M, N) array
    per call — the natural contract for an in-place-style GEMM update.
    The caller's ``c`` array is invalidated by the call (jax donation
    semantics); pass a fresh/numpy C or accept the invalidation. Off by
    default for drop-in compatibility.
    """
    # String shapes stay names: make_ft_sgemm resolves them through the
    # per-dtype tile overrides (configs.BF16_TILE_OVERRIDES).
    inject = inject or InjectionSpec.none()
    # Cast A/B once BEFORE sharding: bf16 shards then move over ICI at half
    # the bytes and the per-device kernels skip a per-call (ring: per-hop)
    # re-cast.
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    mx, my = mesh.shape["x"], mesh.shape["y"]
    _check_divisible("M", m, mx)
    _check_divisible("K", k, my)
    if scatter_output:
        _check_divisible("N", n, my)

    # Local kernel computes the raw K-partial (alpha/beta applied after the
    # psum, once, by the wrapper).
    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy, threshold=threshold,
        precision=precision, in_dtype=in_dtype, interpret=interpret,
    )
    step = make_ft_step(local_ft, alpha, beta, inject, scatter_output,
                        det_axes=("y", "x"),
                        inject_coords=inject_coords)

    c_spec = P("x", "y") if scatter_output else P("x", None)
    fn = shard_map(
        step,
        mesh=mesh,
        in_specs=(P("x", "y"), P(None, "y"), c_spec),
        out_specs=(c_spec, P(None, None), P(None, None),
                   P("x", "y"), P("x", "y")),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    with telemetry.trace_span("sharded_ft_sgemm"):
        out, det, unc, dev_det, dev_unc = jax.jit(fn, **jit_kwargs)(a, b, c)
    result = FtSgemmResult(out, det, unc)
    if telemetry.enabled():
        # Counters arrive already psum-aggregated across the mesh; the
        # device label records the mesh extent, and the fully sharded
        # per-device grids attribute each count to the chip that
        # produced it (host/device/coords labels — DESIGN.md §8).
        telemetry.record_mesh_gemm(
            "sharded_ft_sgemm", result, strategy=strategy,
            device=f"mesh{mx}x{my}", operands=(a, b, c),
            alpha=alpha, beta=beta,
            dev_detections=dev_det, dev_uncorrectable=dev_unc,
            axes=("x", "y"))
    return result


def sharded_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    donate_c: bool = False,
) -> jax.Array:
    """Plain (non-FT) mesh-sharded SGEMM with the same layout.

    ``donate_c=True`` donates C's buffer to the output at the jit
    boundary (see :func:`sharded_ft_sgemm`); the caller's ``c`` is
    invalidated."""
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    mx, my = mesh.shape["x"], mesh.shape["y"]
    _check_divisible("M", a.shape[0], mx)
    _check_divisible("K", a.shape[1], my)

    local = make_sgemm(shape, alpha=1.0, beta=0.0, precision=precision,
                       in_dtype=in_dtype, interpret=interpret)

    def step(a_loc, b_loc, c_loc):
        zeros = jnp.zeros((a_loc.shape[0], b_loc.shape[0]), jnp.float32)
        partial = jax.lax.psum(local(a_loc, b_loc, zeros), "y")
        return alpha * partial + beta * c_loc

    fn = shard_map(
        step,
        mesh=mesh,
        in_specs=(P("x", "y"), P(None, "y"), P("x", None)),
        out_specs=P("x", None),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    return jax.jit(fn, **jit_kwargs)(a, b, c)
