"""Multi-host (DCN + ICI) fault-tolerant GEMM.

The reference is a single-GPU study — no multi-process anything (SURVEY.md
§5 "Distributed communication backend: none"). This module supplies the
scaling story a TPU-native framework needs beyond one host: a 3-axis
hierarchical mesh and a sharding layout chosen so that **every heavy
collective rides ICI and only scalar detection counts cross DCN**.

Mesh axes, outermost first:

  - ``host`` — one slot per process/slice, connected over DCN. Used ONLY
    for output-row (data) parallelism: no tensor communication crosses it
    for the product itself.
  - ``x``    — ICI output-row parallelism within a slice.
  - ``y``    — ICI contraction (K) parallelism; K-partials combine with a
    ``psum`` (or ``psum_scatter``) scoped to ``y`` alone, so the reduction
    stays on the intra-slice ICI torus.

Layout: A (M, K) -> P(("host", "x"), "y"); B (N, K) -> P(None, "y");
C (M, N) -> P(("host", "x"), None). Each device runs the fused-ABFT kernel
on its local shard and corrects faults BEFORE any collective, exactly as in
``parallel/sharded.py``; the global fault count is the single value psummed
across all three axes (a few bytes over DCN per step).

On real multi-host deployments call :func:`initialize` first (a thin
wrapper over ``jax.distributed.initialize``) and build the mesh with
:func:`make_multihost_mesh`; every host then executes the same program on
global arrays. Single-process with N local (or virtual CPU) devices works
identically — ``host`` simply becomes another local axis, which is how the
tests and the driver dry-run exercise this module without a pod.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import resolve_in_dtype
from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
from ft_sgemm_tpu.parallel.sharded import make_ft_step, shard_map


def _distributed_is_initialized() -> bool:
    """Version-tolerant ``jax.distributed.is_initialized``: the public
    accessor only exists on newer jax; older versions expose the same
    state through the distributed client singleton."""
    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — no detectable runtime: not inited
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (no-op if already initialized).

    Thin wrapper over ``jax.distributed.initialize`` so callers depend on
    this module's surface, not on JAX internals. With no arguments, JAX
    auto-detects TPU pod topology from the environment.
    """
    # Ask the runtime directly instead of string-matching the double-init
    # RuntimeError (whose wording varies across JAX versions).
    if _distributed_is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_multihost_mesh(
    hosts: Optional[int] = None,
    ici_axes: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """3-axis ("host", "x", "y") mesh over all addressable devices.

    ``hosts`` defaults to ``jax.process_count()``; the per-host device
    count is factored into the most-square ``(x, y)`` split unless
    ``ici_axes`` pins it.

    The module's core guarantee — heavy collectives (the ``y``-axis psum)
    stay on ICI, only scalars cross DCN — requires each ``host`` slot to
    hold exactly one process's devices. ``jax.devices()`` ordering is not
    contractually process-contiguous on every topology, so devices are
    explicitly grouped by ``process_index`` here, and slot purity is
    asserted whenever the job really spans processes. (Single-process
    meshes — tests, the driver dry-run — can split their local devices
    into any number of "host" slots; there is no DCN to protect.)
    """
    devs = jax.devices()
    h = hosts or max(jax.process_count(), 1)
    if len(devs) % h:
        raise ValueError(f"{len(devs)} devices do not split over {h} hosts")
    per_host = len(devs) // h
    if ici_axes is None:
        x = int(np.floor(np.sqrt(per_host)))
        while per_host % x:
            x -= 1
        ici_axes = (x, per_host // x)
    x, y = ici_axes
    if x * y != per_host:
        raise ValueError(
            f"ici_axes {ici_axes} != {per_host} devices per host")
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    if jax.process_count() > 1:
        slots = _host_slots(devs, h, per_host)
        arr = np.asarray(slots, dtype=object).reshape(h, x, y)
        for slot in range(h):
            procs = {d.process_index for d in arr[slot].flat}
            assert len(procs) == 1, (slot, sorted(procs))
    else:
        arr = np.asarray(devs).reshape(h, x, y)
    return Mesh(arr, ("host", "x", "y"))


def _host_slots(devs, h, per_host):
    """Group ``devs`` into ``h`` process-pure slots of ``per_host``.

    Devices are grouped by ``process_index`` and each process's devices
    are subdivided into contiguous slots (global device ids are NOT
    contiguous across processes, so a flat reshape of the sorted list
    can straddle a process boundary whenever per-process counts are
    uneven — grouping first is the only ordering that is always pure).
    Valid exactly when every process's device count is a multiple of
    ``per_host``; ``hosts = jax.process_count()`` and any multiple of it
    that divides each process's count evenly both work.
    """
    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    slots = []
    for proc in sorted(by_proc):
        local = by_proc[proc]
        if len(local) % per_host:
            raise ValueError(
                f"host slots of {per_host} devices cannot subdivide"
                f" process {proc} ({len(local)} local devices): the"
                f" y-axis psum would cross DCN. Pick hosts= so that"
                f" every process's device count is a multiple of"
                f" devices-per-slot (hosts=jax.process_count() when"
                f" counts are uneven).")
        for i in range(0, len(local), per_host):
            slots.append(local[i:i + per_host])
    assert len(slots) == h, (len(slots), h)
    return slots


def make_multihost_ring_mesh() -> Mesh:
    """1-D ring over ALL addressable devices, host-major — the
    long-context mesh for sequences larger than one host's HBM.

    Use with the ring family unchanged (``ring_ft_attention``,
    :func:`ft_sgemm_tpu.parallel.make_ring_ft_attention_diff`,
    ``ring_ft_sgemm``, :class:`ft_sgemm_tpu.nn.FtRingSelfAttention`):
    they only need a mesh with axis ``"x"``, and the mesh constructor
    decides which of its ``ppermute`` hops cross DCN. Host-major
    ordering makes ring neighbors process-contiguous, so of the D hops
    in a full ring cycle exactly ``process_count`` are host boundaries
    riding DCN and the rest stay on intra-host ICI — the minimum any
    single ring over P processes can have. (The reference has no
    distributed anything, SURVEY.md §5; this extends the first-class
    long-context axis to pod scale.)

    The ordering lives in :func:`ft_sgemm_tpu.parallel.make_ring_mesh`
    (every ring is host-major); this alias simply documents and pins
    the all-devices pod-scale usage.
    """
    from ft_sgemm_tpu.parallel.ring import make_ring_mesh

    return make_ring_mesh()


def _check_divisible(name, dim, parts, axis):
    if dim % parts:
        raise ValueError(
            f"{name} dimension {dim} must divide evenly over the {parts}"
            f" shards of mesh axis {axis!r} (pad inputs before sharding)"
        )


def multihost_ft_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    encode: str = "vpu",
    threshold: "float | str" = REFERENCE_THRESHOLD,
    precision: str = "highest",
    in_dtype: str = "float32",
    scatter_output: bool = False,
    interpret: Optional[bool] = None,
    inject_coords: Optional[Tuple[int, int, int]] = None,
    donate_c: bool = False,
    variant=None,
) -> FtSgemmResult:
    """Fused-ABFT ``C = alpha*A@B.T + beta*C`` over a ("host", "x", "y") mesh.

    M rows are sharded over host x ICI-x (pure data parallelism — zero
    tensor traffic over DCN); K over ICI-y (psum stays on ICI). Faults are
    corrected per device before the psum; only the int32 detection count
    crosses DCN. ``scatter_output=True`` reduce-scatters the K-partials so
    C lands additionally N-sharded over ``y``.

    With telemetry enabled, each process records per-device attribution
    for ITS OWN devices only (``telemetry.record_mesh_gemm`` reads the
    per-device counter grids through ``addressable_shards``), so the
    per-host JSONL event shards partition cleanly and
    ``telemetry.aggregate.merge_shards`` reassembles the pod-wide view
    without dedup (DESIGN.md §8). ``inject_coords=(h, i, j)`` restricts
    injection to the device at that mesh position — the cross-host
    localization self-test. ``donate_c=True`` donates C's buffer to the
    output at the jit boundary (C is read once by the ``beta*C``
    epilogue; the caller's ``c`` is invalidated — see
    :func:`~ft_sgemm_tpu.parallel.sharded.sharded_ft_sgemm`).
    """
    # Keep string shapes as names: make_ft_sgemm resolves them through the
    # per-dtype tile overrides (configs.BF16_TILE_OVERRIDES).
    inject = inject or InjectionSpec.none()
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    h, mx, my = (mesh.shape["host"], mesh.shape["x"], mesh.shape["y"])
    _check_divisible("M", m, h * mx, "host*x")
    _check_divisible("K", k, my, "y")
    if scatter_output:
        _check_divisible("N", n, my, "y")

    # encode= / threshold="adaptive" / variant= ride through exactly as on
    # the single-host paths; make_ft_sgemm consults tuner.lookup_winner at
    # trace time with the LOCAL c.shape, which inside shard_map is the
    # per-device shard — so tuned winners are keyed by shard shape, not
    # the global problem size.
    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy, encode=encode,
        threshold=threshold, precision=precision, in_dtype=in_dtype,
        interpret=interpret, variant=variant,
    )
    # K-partials psum over "y" (ICI only). Detection counters reduce in
    # STAGES (parallel/reduce.py): per-device -> "y" (ICI ring) -> "x"
    # (ICI) -> "host" (DCN) — axes ordered innermost-first is the
    # staging contract, so the only counter values crossing DCN are one
    # already-combined int32 set per host slot (O(local) detection
    # traffic; the 2112.09017 panel structure).
    step = make_ft_step(local_ft, alpha, beta, inject, scatter_output,
                        det_axes=("y", "x", "host"),
                        mesh_axes=("host", "x", "y"),
                        inject_coords=inject_coords)

    rows = P(("host", "x"), "y")
    c_spec = (P(("host", "x"), "y") if scatter_output
              else P(("host", "x"), None))
    fn = shard_map(
        step,
        mesh=mesh,
        in_specs=(rows, P(None, "y"), c_spec),
        out_specs=(c_spec, P(None, None), P(None, None),
                   P("host", "x", "y"), P("host", "x", "y")),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    with telemetry.trace_span("multihost_ft_sgemm"):
        out, det, unc, dev_det, dev_unc = jax.jit(fn, **jit_kwargs)(a, b, c)
    result = FtSgemmResult(out, det, unc)
    if telemetry.enabled():
        # Each process attributes ITS addressable devices' counts; the
        # device label carries the full mesh extent for topology rollups.
        telemetry.record_mesh_gemm(
            "multihost_ft_sgemm", result, strategy=strategy,
            device=f"mesh{h}x{mx}x{my}", operands=(a, b, c),
            alpha=alpha, beta=beta,
            dev_detections=dev_det, dev_uncorrectable=dev_unc,
            axes=("host", "x", "y"))
    return result


__all__ = ["initialize", "make_multihost_mesh", "make_multihost_ring_mesh",
           "multihost_ft_sgemm"]
