"""Ring collective-matmul FT-SGEMM: ``ppermute`` pipeline over a 1-D mesh.

The 2-D mesh path (``parallel/sharded.py``) K-shards the contraction and
combines partials with one ``psum``. This module is the other canonical TPU
distribution: a **ring collective matmul**. Every device keeps only its own
row shard of A and one visiting shard of B at a time; B shards rotate around
the ICI ring with ``jax.lax.ppermute`` while each hop's partial product is
computed locally. Nothing ever materializes the full B per device, so the
per-device working set stays O((M + N)/D * K) — the long-"context" scaling
pattern (this is exactly the dataflow of ring attention, applied to the
GEMM that is this framework's domain; SURVEY.md §5 notes the reference has
no distributed backend at all).

Fault tolerance composes per hop: each visiting shard's partial C columns
are produced by the fused-ABFT kernel and corrected locally BEFORE the
shard moves on, so a corrupted accumulator never propagates around the
ring. Detection counts ``psum`` over the ring at the end.

Layout (D = ring size):
  A  (M, K)  -> P("x", None): row shards, stationary.
  B  (N, K)  -> P("x", None): row shards (= column blocks of C), rotating.
  C  (M, N)  -> P("x", None): each device owns full-width rows; at hop t a
               device writes the column block belonging to the shard it is
               visiting.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import resolve_in_dtype
from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
from ft_sgemm_tpu.ops.sgemm import make_sgemm
from ft_sgemm_tpu.parallel.sharded import shard_local_ft, shard_map


def make_ring_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D ring mesh over the first n devices (ICI ring on real pods).

    Host-major ordering (sorted by ``(process_index, id)``): ring
    neighbors are process-contiguous, so on a multi-process pod a full
    ``ppermute`` cycle crosses DCN exactly ``process_count`` times — the
    minimum any single ring over P processes can have — and every other
    hop stays on ICI. Single-process ordering is unchanged
    (``jax.devices()`` is already id-sorted there).
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n = n_devices or len(devs)
    import numpy as np

    return Mesh(np.asarray(devs[:n]), ("x",))


def _check_divisible(name, dim, parts):
    if dim % parts:
        raise ValueError(
            f"{name} dimension {dim} must divide evenly over the {parts}-"
            f"device ring (pad inputs first)"
        )


def ring_ft_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    inject_coords: Optional[tuple] = None,
    donate_c: bool = False,
) -> FtSgemmResult:
    """Fused-ABFT ``C = alpha*A@B.T + beta*C`` as a ring collective matmul.

    Detections are aggregated over all hops and devices; the returned
    ``detections`` array is the global scalar count reshaped to (1, 1)
    (per-tile attribution is not preserved across hops — but per-DEVICE
    attribution is: each device's hop-summed counts are recorded with
    its ring position and host when telemetry is enabled, DESIGN.md §8).
    ``inject_coords=(i,)`` restricts injection to ring position ``i``
    (every hop on that device injects; all other devices run clean).
    ``donate_c=True`` donates C's buffer to the output at the jit
    boundary — C is read once by the ``beta*C`` epilogue and the output
    shares its P("x", None) sharding, so XLA reuses the HBM buffer
    (the caller's ``c`` is invalidated; see
    :func:`~ft_sgemm_tpu.parallel.sharded.sharded_ft_sgemm`).
    """
    # String shapes stay names: make_ft_sgemm resolves them through the
    # per-dtype tile overrides (configs.BF16_TILE_OVERRIDES).
    inject = inject or InjectionSpec.none()
    # Cast once before sharding: a bf16 B shard crosses the ICI ring at half
    # the bytes per ppermute hop, and the stationary A shard is not re-cast
    # on every one of the d hops.
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    d = mesh.shape["x"]
    _check_divisible("M", m, d)
    _check_divisible("N", n, d)
    nb = n // d  # visiting-shard width = one C column block

    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy, threshold=threshold,
        precision=precision, in_dtype=in_dtype, interpret=interpret,
    )
    perm = [(i, (i + 1) % d) for i in range(d)]  # shift shards up the ring
    run_local = shard_local_ft(local_ft, inject, inject_coords, ("x",))

    def step_fn(a_loc, b_loc, c_loc):
        my = jax.lax.axis_index("x")
        zeros = jnp.zeros((a_loc.shape[0], nb), jnp.float32)

        def hop(t, carry):
            out, b_vis, det, unc = carry
            res = run_local(a_loc, b_vis, zeros)
            # perm shifts shards UP the ring, so after t rotations a device
            # holds the shard that started at position my - t => that
            # shard's C columns start at its owner's offset.
            col0 = jnp.mod(my - t, d) * nb
            out = jax.lax.dynamic_update_slice(out, res.c, (0, col0))
            det = det + jnp.sum(res.detections)
            unc = unc + jnp.sum(res.uncorrectable)
            # Rotate AFTER computing so hop t uses the t-shifted shard; the
            # final rotation returns shards to their owners.
            b_vis = jax.lax.ppermute(b_vis, "x", perm)
            return out, b_vis, det, unc

        out0 = jnp.zeros((a_loc.shape[0], n), jnp.float32)
        out, _, det, unc = jax.lax.fori_loop(
            0, d, hop, (out0, b_loc, jnp.int32(0), jnp.int32(0)))
        out = alpha * out + beta * c_loc
        # Per-device counts (summed over this device's hops) keep their
        # ring position via the P("x") layout; the psum'd globals follow.
        dev_det = det.reshape(1)
        dev_unc = unc.reshape(1)
        det = jax.lax.psum(det, "x")
        unc = jax.lax.psum(unc, "x")
        return out, det.reshape(1, 1), unc.reshape(1, 1), dev_det, dev_unc

    fn = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P("x", None)),
        out_specs=(P("x", None), P(None, None), P(None, None),
                   P("x"), P("x")),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    with telemetry.trace_span("ring_ft_sgemm"):
        out, det, unc, dev_det, dev_unc = jax.jit(fn, **jit_kwargs)(a, b, c)
    result = FtSgemmResult(out, det, unc)
    if telemetry.enabled():
        # Ring counts psum over all hops and devices; the device label
        # carries the ring extent, and the sharded per-device counts
        # attribute each hop-summed total to its ring position.
        telemetry.record_mesh_gemm(
            "ring_ft_sgemm", result, strategy=strategy,
            device=f"ring{d}", operands=(a, b, c),
            alpha=alpha, beta=beta,
            dev_detections=dev_det, dev_uncorrectable=dev_unc,
            axes=("x",))
    return result


def ring_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    donate_c: bool = False,
) -> jax.Array:
    """Plain (non-FT) ring collective matmul with the same layout.

    ``donate_c=True`` donates C's buffer to the output at the jit
    boundary (caller's ``c`` invalidated)."""
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    d = mesh.shape["x"]
    _check_divisible("M", m, d)
    _check_divisible("N", n, d)
    nb = n // d

    local = make_sgemm(shape, alpha=1.0, beta=0.0, precision=precision,
                       in_dtype=in_dtype, interpret=interpret)
    perm = [(i, (i + 1) % d) for i in range(d)]

    def step_fn(a_loc, b_loc, c_loc):
        my = jax.lax.axis_index("x")
        zeros = jnp.zeros((a_loc.shape[0], nb), jnp.float32)

        def hop(t, carry):
            out, b_vis = carry
            part = local(a_loc, b_vis, zeros)
            col0 = jnp.mod(my - t, d) * nb
            out = jax.lax.dynamic_update_slice(out, part, (0, col0))
            b_vis = jax.lax.ppermute(b_vis, "x", perm)
            return out, b_vis

        out0 = jnp.zeros((a_loc.shape[0], n), jnp.float32)
        out, _ = jax.lax.fori_loop(0, d, hop, (out0, b_loc))
        return alpha * out + beta * c_loc

    fn = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P("x", None)),
        out_specs=P("x", None),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    return jax.jit(fn, **jit_kwargs)(a, b, c)


__all__ = ["make_ring_mesh", "ring_ft_sgemm", "ring_sgemm"]
