"""Ring collective-matmul FT-SGEMM: ``ppermute`` pipeline over a 1-D mesh.

The 2-D mesh path (``parallel/sharded.py``) K-shards the contraction and
combines partials with one ``psum``. This module is the other canonical TPU
distribution: a **ring collective matmul**. Every device keeps only its own
row shard of A and one visiting shard of B at a time; B shards rotate around
the ICI ring with ``jax.lax.ppermute`` while each hop's partial product is
computed locally. Nothing ever materializes the full B per device, so the
per-device working set stays O((M + N)/D * K) — the long-"context" scaling
pattern (this is exactly the dataflow of ring attention, applied to the
GEMM that is this framework's domain; SURVEY.md §5 notes the reference has
no distributed backend at all).

Fault tolerance composes per hop: each visiting shard's partial C columns
are produced by the fused-ABFT kernel and corrected locally BEFORE the
shard moves on, so a corrupted accumulator never propagates around the
ring. Detection counts reduce hierarchically over the ring at the end
(``parallel/reduce.py``).

**Hop schedules** (the ``ring_overlap`` axis, searched by the tuner —
DESIGN.md §17): ``overlap=False`` is the historical serial schedule —
compute hop t, then rotate, so hop t+1's local GEMM waits on hop t's
``ppermute``. ``overlap=True`` is the double-buffered rotate-ahead
schedule: the ``ppermute`` that produces hop t+1's shard is issued BEFORE
hop t's local FT-GEMM, so XLA's async collective-permute (start/done
pair) has a full hop of MXU compute to hide the ICI transfer behind —
the paper's fault-tolerance-is-free argument (arXiv 2305.01024) applied
to the ring's communication plane. The two schedules run the SAME local
GEMMs on the SAME shard values in the SAME order, so their outputs and
per-device counters are byte-value identical (test-pinned); overlap pays
one extra resident copy of each rotating operand (the double buffer) and
one extra rotation's ICI traffic.

Layout (D = ring size):
  A  (M, K)  -> P("x", None): row shards, stationary.
  B  (N, K)  -> P("x", None): row shards (= column blocks of C), rotating.
  C  (M, N)  -> P("x", None): each device owns full-width rows; at hop t a
               device writes the column block belonging to the shard it is
               visiting.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import resolve_in_dtype
from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
from ft_sgemm_tpu.ops.sgemm import make_sgemm
from ft_sgemm_tpu.parallel.reduce import hierarchical_psum
from ft_sgemm_tpu.parallel.sharded import shard_local_ft, shard_map


def make_ring_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D ring mesh over the first n devices (ICI ring on real pods).

    Host-major ordering (sorted by ``(process_index, id)``): ring
    neighbors are process-contiguous, so on a multi-process pod a full
    ``ppermute`` cycle crosses DCN exactly ``process_count`` times — the
    minimum any single ring over P processes can have — and every other
    hop stays on ICI. Single-process ordering is unchanged
    (``jax.devices()`` is already id-sorted there).
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n = n_devices or len(devs)
    import numpy as np

    return Mesh(np.asarray(devs[:n]), ("x",))


def _check_divisible(name, dim, parts):
    if dim % parts:
        raise ValueError(
            f"{name} dimension {dim} must divide evenly over the {parts}-"
            f"device ring (pad inputs first)"
        )


def rotate_ahead_loop(dnum, perm, hop_body, rotating, carry, *,
                      overlap=False, axis="x"):
    """Run ``hop_body(t, rotating, carry) -> carry`` for ``t`` in
    ``[0, dnum)``, rotating ``rotating`` (a tuple of arrays) one ring
    position between hops with ``ppermute``. The ONE hop loop every ring
    path in this package runs — FT and plain GEMM, the attention
    forward — so each schedule is implemented once, not per caller.

    ``overlap=False`` — the serial schedule: compute hop t with the
    t-rotated shards, then rotate. The loop-carried dependency makes hop
    t+1's compute wait on hop t's transfer.

    ``overlap=True`` — double-buffered rotate-ahead: the loop carries
    BOTH hop t's shards and hop t+1's (already in flight), and each
    iteration issues the rotation producing hop t+2's shards BEFORE
    running hop t's compute. No data dependence ties that ``ppermute``
    to the local GEMM, and its consumer is a full iteration away, so
    XLA's async collective-permute overlaps the ICI transfer with the
    MXU dot. Hop t's compute sees exactly the t-rotated shards under
    both schedules — value-identical by construction — at the cost of a
    second resident copy of each rotating operand and one extra
    (prologue) rotation's traffic.
    """
    def rot(ops):
        return tuple(jax.lax.ppermute(x, axis, perm) for x in ops)

    if not overlap:
        def hop(t, state):
            ops, car = state
            car = hop_body(t, ops, car)
            return rot(ops), car

        _, carry = jax.lax.fori_loop(0, dnum, hop, (rotating, carry))
        return carry

    def hop(t, state):
        cur, nxt, car = state
        fut = rot(nxt)  # hop t+2's shards: issued BEFORE hop t's compute
        car = hop_body(t, cur, car)
        return nxt, fut, car

    ahead = rot(rotating)  # prologue: hop 1's shards start moving now
    _, _, carry = jax.lax.fori_loop(0, dnum, hop, (rotating, ahead, carry))
    return carry


def _make_ring_gemm_step(run_local, d, nb, n, perm, *, alpha, beta, ft,
                         overlap):
    """The shard_map-able per-device ring-GEMM step, parameterized over
    the FT/plain axis and the hop schedule — ONE hop body serves all
    four (ft x overlap) spellings, so a schedule change can never drift
    between the FT and plain paths (the historical near-duplicate
    bodies this replaces)."""

    def step_fn(a_loc, b_loc, c_loc):
        my = jax.lax.axis_index("x")
        zeros = jnp.zeros((a_loc.shape[0], nb), jnp.float32)

        def hop_body(t, rotating, carry):
            (b_vis,) = rotating
            out, det, unc = carry
            # perm shifts shards UP the ring, so after t rotations a
            # device holds the shard that started at position my - t =>
            # that shard's C columns start at its owner's offset.
            col0 = jnp.mod(my - t, d) * nb
            if ft:
                res = run_local(a_loc, b_vis, zeros)
                out = jax.lax.dynamic_update_slice(out, res.c, (0, col0))
                det = det + jnp.sum(res.detections)
                unc = unc + jnp.sum(res.uncorrectable)
            else:
                part = run_local(a_loc, b_vis, zeros)
                out = jax.lax.dynamic_update_slice(out, part, (0, col0))
            return out, det, unc

        out0 = jnp.zeros((a_loc.shape[0], n), jnp.float32)
        carry0 = (out0, jnp.int32(0), jnp.int32(0))
        out, det, unc = rotate_ahead_loop(
            d, perm, hop_body, (b_loc,), carry0, overlap=overlap)
        out = alpha * out + beta * c_loc
        if not ft:
            return out
        # Per-device counts (summed over this device's hops) keep their
        # ring position via the P("x") layout; the staged reduction
        # (one axis — the ring degenerates to the flat psum) yields the
        # globals.
        dev_det = det.reshape(1)
        dev_unc = unc.reshape(1)
        det = hierarchical_psum(det, ("x",))
        unc = hierarchical_psum(unc, ("x",))
        return out, det.reshape(1, 1), unc.reshape(1, 1), dev_det, dev_unc

    return step_fn


def _resolve_ring_overlap(ring_overlap, m, n, k, d, *, strategy, in_dtype):
    """One resolver for the ``ring_overlap`` dispatch axis: an explicit
    mode passes through; ``None``/"auto" consults the tuner cache for a
    searched winner (``tuner.lookup_ring_overlap``, keyed on the
    PER-DEVICE local shard problem so the ring size rides the key) and
    falls back to the serial schedule — the historical behavior — on a
    miss or with tuning disabled."""
    from ft_sgemm_tpu.configs import RING_OVERLAP_MODES

    if ring_overlap in (None, "auto"):
        from ft_sgemm_tpu import tuner

        win = tuner.lookup_ring_overlap(
            m // d, n // d, k, strategy=strategy, in_dtype=in_dtype)
        return win or "serial"
    if ring_overlap not in RING_OVERLAP_MODES:
        raise ValueError(
            f"ring_overlap={ring_overlap!r} must be one of"
            f" {RING_OVERLAP_MODES} (or None/'auto' for the tuner)")
    return ring_overlap


def make_ring_ft_sgemm_fn(
    mesh: Mesh,
    d: int,
    nb: int,
    n: int,
    shape: KernelShape | str,
    *,
    alpha: float,
    beta: float,
    inject: InjectionSpec,
    strategy: str,
    threshold,
    precision: str,
    in_dtype: str,
    interpret: Optional[bool],
    inject_coords: Optional[tuple],
    overlap: bool,
):
    """The un-jitted shard_map'd ring-FT executor:
    ``fn(a, b, c) -> (out, det, unc, dev_det, dev_unc)``.

    The factory form exists for callers that need jit-once reuse across
    many calls — :func:`ring_ft_sgemm` wraps one call, while the tuner's
    ring-schedule search (``tuner.tune_ring``) times BOTH hop schedules
    through one compiled executable each (a fresh closure per timed call
    would re-pay trace+compile and measure the compiler, not the ring).
    """
    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy, threshold=threshold,
        precision=precision, in_dtype=in_dtype, interpret=interpret,
    )
    perm = [(i, (i + 1) % d) for i in range(d)]  # shift shards up the ring
    run_local = shard_local_ft(local_ft, inject, inject_coords, ("x",))
    step_fn = _make_ring_gemm_step(
        run_local, d, nb, n, perm, alpha=alpha, beta=beta, ft=True,
        overlap=overlap)
    return shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P("x", None)),
        out_specs=(P("x", None), P(None, None), P(None, None),
                   P("x"), P("x")),
    )


def ring_ft_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    inject_coords: Optional[tuple] = None,
    donate_c: bool = False,
    ring_overlap: Optional[str] = None,
) -> FtSgemmResult:
    """Fused-ABFT ``C = alpha*A@B.T + beta*C`` as a ring collective matmul.

    Detections are aggregated over all hops and devices; the returned
    ``detections`` array is the global scalar count reshaped to (1, 1)
    (per-tile attribution is not preserved across hops — but per-DEVICE
    attribution is: each device's hop-summed counts are recorded with
    its ring position and host when telemetry is enabled, DESIGN.md §8).
    ``inject_coords=(i,)`` restricts injection to ring position ``i``
    (every hop on that device injects; all other devices run clean).
    ``ring_overlap`` selects the hop schedule
    (``configs.RING_OVERLAP_MODES``): ``"serial"`` computes then
    rotates, ``"overlap"`` is the double-buffered rotate-ahead pipeline
    (module docstring), and ``None``/``"auto"`` consults the tuner cache
    (``tuner.tune_ring`` banks winners) falling back to serial. Both
    schedules are byte-value identical in outputs AND per-device
    counters. ``donate_c=True`` donates C's buffer to the output at the
    jit boundary — C is read once by the ``beta*C`` epilogue and the
    output shares its P("x", None) sharding, so XLA reuses the HBM
    buffer (the caller's ``c`` is invalidated; see
    :func:`~ft_sgemm_tpu.parallel.sharded.sharded_ft_sgemm`).
    """
    # String shapes stay names: make_ft_sgemm resolves them through the
    # per-dtype tile overrides (configs.BF16_TILE_OVERRIDES).
    inject = inject or InjectionSpec.none()
    # Cast once before sharding: a bf16 B shard crosses the ICI ring at half
    # the bytes per ppermute hop, and the stationary A shard is not re-cast
    # on every one of the d hops.
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    d = mesh.shape["x"]
    _check_divisible("M", m, d)
    _check_divisible("N", n, d)
    nb = n // d  # visiting-shard width = one C column block
    overlap = _resolve_ring_overlap(ring_overlap, m, n, k, d,
                                    strategy=strategy, in_dtype=in_dtype)

    fn = make_ring_ft_sgemm_fn(
        mesh, d, nb, n, shape, alpha=alpha, beta=beta, inject=inject,
        strategy=strategy, threshold=threshold, precision=precision,
        in_dtype=in_dtype, interpret=interpret,
        inject_coords=inject_coords, overlap=overlap == "overlap")
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    with telemetry.trace_span("ring_ft_sgemm"):
        out, det, unc, dev_det, dev_unc = jax.jit(fn, **jit_kwargs)(a, b, c)
    result = FtSgemmResult(out, det, unc)
    if telemetry.enabled():
        # Ring counts reduce over all hops and devices; the device label
        # carries the ring extent, and the sharded per-device counts
        # attribute each hop-summed total to its ring position.
        telemetry.record_mesh_gemm(
            "ring_ft_sgemm", result, strategy=strategy,
            device=f"ring{d}", operands=(a, b, c),
            alpha=alpha, beta=beta,
            dev_detections=dev_det, dev_uncorrectable=dev_unc,
            axes=("x",), extra={"ring_overlap": overlap})
    return result


def ring_sgemm(
    a,
    b,
    c,
    mesh: Mesh,
    shape: KernelShape | str = "huge",
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    donate_c: bool = False,
    ring_overlap: Optional[str] = None,
) -> jax.Array:
    """Plain (non-FT) ring collective matmul with the same layout.

    ``ring_overlap`` selects the hop schedule exactly as in
    :func:`ring_ft_sgemm` (the plain path keys the tuner lookup with
    ``strategy=None``). ``donate_c=True`` donates C's buffer to the
    output at the jit boundary (caller's ``c`` invalidated)."""
    cast_dtype, _ = resolve_in_dtype(in_dtype, precision)
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    d = mesh.shape["x"]
    _check_divisible("M", m, d)
    _check_divisible("N", n, d)
    nb = n // d
    overlap = _resolve_ring_overlap(ring_overlap, m, n, k, d,
                                    strategy=None, in_dtype=in_dtype)

    local = make_sgemm(shape, alpha=1.0, beta=0.0, precision=precision,
                       in_dtype=in_dtype, interpret=interpret)
    perm = [(i, (i + 1) % d) for i in range(d)]
    step_fn = _make_ring_gemm_step(
        local, d, nb, n, perm, alpha=alpha, beta=beta, ft=False,
        overlap=overlap == "overlap")

    fn = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P("x", None)),
        out_specs=P("x", None),
    )
    jit_kwargs = {"donate_argnums": (2,)} if donate_c else {}
    return jax.jit(fn, **jit_kwargs)(a, b, c)


__all__ = ["make_ring_ft_sgemm_fn", "make_ring_mesh", "ring_ft_sgemm",
           "ring_sgemm", "rotate_ahead_loop"]
