"""Multi-chip execution: mesh-sharded fault-tolerant GEMM over ICI."""

from ft_sgemm_tpu.parallel.multihost import (
    initialize,
    make_multihost_mesh,
    make_multihost_ring_mesh,
    multihost_ft_sgemm,
)
from ft_sgemm_tpu.parallel.reduce import hierarchical_psum
from ft_sgemm_tpu.parallel.ring import (
    make_ring_mesh,
    ring_ft_sgemm,
    ring_sgemm,
)
from ft_sgemm_tpu.parallel.ring_attention import (
    make_ring_ft_attention_diff, ring_ft_attention)
from ft_sgemm_tpu.parallel.sharded import (
    make_mesh,
    sharded_ft_sgemm,
    sharded_sgemm,
)

__all__ = [
    "hierarchical_psum",
    "initialize",
    "make_mesh",
    "make_multihost_mesh",
    "make_multihost_ring_mesh",
    "multihost_ft_sgemm",
    "make_ring_mesh",
    "make_ring_ft_attention_diff",
    "ring_ft_attention",
    "ring_ft_sgemm",
    "ring_sgemm",
    "sharded_ft_sgemm",
    "sharded_sgemm",
]
