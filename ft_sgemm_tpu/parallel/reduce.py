"""Hierarchical (staged) reduction of detection counters over a mesh.

The flat spelling — one ``psum`` over every mesh axis at once — lowers to
a single all-reduce in which EVERY device of the mesh participates
directly. For the payloads this package reduces that way (int32
detection/uncorrectable counters, a few scalars per device) the cost is
not the bytes, it is the participation: on a multi-host mesh the flat
all-reduce's communication pattern spans DCN with full device fan-in, so
detection overhead grows with the mesh instead of staying O(local).

*Large Scale Distributed Linear Algebra With TPUs* (PAPERS.md,
arXiv 2112.09017) structures its checksums hierarchically — per-panel
sums combined per host, then globally — precisely so verification traffic
composes along the machine's own hierarchy. :func:`hierarchical_psum` is
that panel structure applied to this package's counter plane: the
reduction runs ONE AXIS AT A TIME, innermost (ICI) first, so each stage
combines values that are already partial sums of the previous stage.
On the 3-axis multi-host mesh (``parallel/multihost.py``) the staging is

    per-device -> psum over "y"  (intra-slice ICI ring)
               -> psum over "x"  (intra-slice ICI)
               -> psum over "host" (DCN — already-reduced scalars only)

so the only values crossing DCN are one already-combined counter set per
host slot — detection cost stays O(local) as the mesh grows. Counters
are integers, so the staged sum is EXACTLY the flat sum (no float
reassociation concerns; equality is test-pinned on an 8-vdev mesh).

Axis order is the caller's contract: pass axes innermost-first (ICI
before DCN). A single-axis mesh degenerates to the flat psum — the ring
paths route through here anyway so every counter reduction in
``parallel/`` shares one spelling.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax


def hierarchical_psum(x, axes: Union[str, Sequence[str]]):
    """Staged ``psum`` over ``axes``, one axis at a time, in order.

    ``axes`` should run innermost-first (ICI axes before the DCN
    ``host`` axis) so later — wider — stages reduce already-combined
    values. For integer counters the result equals the flat
    ``jax.lax.psum(x, tuple(axes))`` exactly.
    """
    if isinstance(axes, str):
        axes = (axes,)
    for ax in axes:
        x = jax.lax.psum(x, ax)
    return x


__all__ = ["hierarchical_psum"]
