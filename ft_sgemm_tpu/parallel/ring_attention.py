"""Ring attention with ABFT-protected GEMMs: long-context sequence
parallelism over an ICI ring.

True ring attention (the long-context scaling pattern the task calls
first-class; the ring-GEMM module ``parallel/ring.py`` applies the same
dataflow to plain GEMM): Q row-shards stay put, K/V shards rotate around the
ring with ``jax.lax.ppermute``, and each hop folds one key/value block into a
running **online softmax** (numerically stable streaming max/denominator, the
flash/ring-attention recurrence). Per-device working set stays
O((L_q + L_k)/D * d) — no device ever materializes the full (L_q, L_k) score
matrix.

Fault tolerance composes per hop exactly like the ring GEMM: both of the
hop's GEMMs (``Q K_t^T`` and ``P_t V_t``) run through the fused-ABFT kernels
and are corrected locally BEFORE their results enter the online-softmax
recurrence — a corrupted accumulator never contaminates the running
(m, l, o) state or crosses the ring. Detection counts ``psum`` over the ring.

The recurrence per visiting block t (rows = local queries):

    s_t = scale * Q K_t^T                       [FT GEMM 1]
    m'  = max(m, rowmax(s_t))
    a   = exp(m - m')                           # rescale old state
    p_t = exp(s_t - m')
    o   = a * o + p_t V_t                       [FT GEMM 2]
    l   = a * l + rowsum(p_t)
    m   = m'
  final: O = o / l
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.ops.attention import (
    FtAttentionResult, PV_SHAPE, QK_SHAPE)
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.parallel.ring import _check_divisible, make_ring_mesh
from ft_sgemm_tpu.parallel.sharded import shard_map


def ring_ft_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
) -> FtAttentionResult:
    """Fault-tolerant ring attention over a 1-D mesh.

    ``q`` (L, d), ``k`` (Lk, d), ``v`` (Lk, dv); L and Lk must divide over
    the ring (pad first). Returns the full (L, dv) output row-sharded over
    the mesh, the global corrected-fault count, and ``softmax_flags`` =
    number of rows whose online-softmax denominator ``l`` ended non-finite
    or non-positive — the streaming analog of the single-device
    rowsum==1 invariant (detect-only; 0 on clean runs).
    """
    inject = inject or InjectionSpec.none()
    dt = jnp.dtype(in_dtype)
    q = jnp.asarray(q, dt)
    k = jnp.asarray(k, dt)
    v = jnp.asarray(v, dt)
    (lq, d_head), (lk, _), (_, dv) = q.shape, k.shape, v.shape
    dnum = mesh.shape["x"]
    _check_divisible("L_q", lq, dnum)
    _check_divisible("L_k", lk, dnum)
    if causal:
        from ft_sgemm_tpu.ops.attention import _check_causal_lengths

        _check_causal_lengths(lq, lk)
    sc = (1.0 / math.sqrt(d_head)) if scale is None else scale

    qk = make_ft_sgemm(qk_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    pv = make_ft_sgemm(pv_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    perm = [(i, (i + 1) % dnum) for i in range(dnum)]

    def step_fn(q_loc, k_loc, vt_loc):
        my = jax.lax.axis_index("x")
        nq = q_loc.shape[0]
        nk_blk = k_loc.shape[0]
        zs = jnp.zeros((nq, nk_blk), jnp.float32)
        zo = jnp.zeros((nq, dv), jnp.float32)
        # Global positions, end-aligned (decoding convention): local query
        # row r sits at key position my*nq + r + (lk - lq).
        qpos = (my * nq + jnp.arange(nq) + (lk - lq))[:, None]

        def hop(t, carry):
            m, l, o, k_vis, vt_vis, det, unc = carry
            s_res = qk(q_loc, k_vis, zs, inject)
            s_t = sc * s_res.c
            if causal:
                # The visiting block started at device mod(my - t, dnum);
                # mask runs AFTER the QK kernel's detect/correct, so faults
                # at masked positions are corrected, then silenced.
                owner = jnp.mod(my - t, dnum)
                kpos = owner * nk_blk + jnp.arange(nk_blk)[None, :]
                s_t = jnp.where(kpos <= qpos, s_t, -jnp.inf)
            # Masked-block-safe online softmax: m_new may stay -inf while a
            # device has only future keys; exp() then sees finite args only.
            m_new = jnp.maximum(m, jnp.max(s_t, axis=1, keepdims=True))
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            a = jnp.where(m == m_new, 1.0, jnp.exp(m - m_safe))
            p_t = jnp.exp(s_t - m_safe)
            o_res = pv(p_t, vt_vis, zo, inject)
            o = a * o + o_res.c
            l = a * l + jnp.sum(p_t, axis=1, keepdims=True)
            det = det + jnp.sum(s_res.detections) + jnp.sum(o_res.detections)
            unc = unc + jnp.sum(s_res.uncorrectable) + jnp.sum(
                o_res.uncorrectable)
            k_vis = jax.lax.ppermute(k_vis, "x", perm)
            vt_vis = jax.lax.ppermute(vt_vis, "x", perm)
            return m_new, l, o, k_vis, vt_vis, det, unc

        m0 = jnp.full((nq, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((nq, 1), jnp.float32)
        m, l, o, _, _, det, unc = jax.lax.fori_loop(
            0, dnum, hop,
            (m0, l0, zo, k_loc, vt_loc, jnp.int32(0), jnp.int32(0)))
        # Normalization invariant of the streaming softmax: l aggregates
        # exp(s - m) > 0 over all Lk keys; non-finite or non-positive rows
        # mean corrupted softmax state (detect-only, like the single-device
        # rowsum invariant).
        flags = jnp.sum(jnp.logical_not(
            jnp.isfinite(l) & (l > 0.0)).astype(jnp.int32))
        out = o / l
        det = jax.lax.psum(det, "x")
        flags = jax.lax.psum(flags, "x")
        unc = jax.lax.psum(unc, "x")
        return out, det.reshape(1, 1), flags.reshape(1, 1), unc.reshape(1, 1)

    fn = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P(None, "x")),
        out_specs=(P("x", None), P(None, None), P(None, None),
                   P(None, None)),
    )
    # V rides the ring pre-transposed: the PV kernel consumes B = V^T and a
    # (dv, Lk/D) shard halves nothing but avoids a per-hop transpose.
    out, det, flags, unc = jax.jit(fn)(q, k, jnp.swapaxes(v, 0, 1))
    return FtAttentionResult(out, det[0, 0], flags[0, 0], unc[0, 0])


__all__ = ["make_ring_mesh", "ring_ft_attention"]
