"""Ring attention with ABFT-protected GEMMs: long-context sequence
parallelism over an ICI ring.

True ring attention (the long-context scaling pattern the task calls
first-class; the ring-GEMM module ``parallel/ring.py`` applies the same
dataflow to plain GEMM): Q row-shards stay put, K/V shards rotate around the
ring with ``jax.lax.ppermute``, and each hop folds one key/value block into a
running **online softmax** (numerically stable streaming max/denominator, the
flash/ring-attention recurrence). Per-device working set stays
O((L_q + L_k)/D * d) — no device ever materializes the full (L_q, L_k) score
matrix.

Fault tolerance composes per hop exactly like the ring GEMM: both of the
hop's GEMMs (``Q K_t^T`` and ``P_t V_t``) run through the fused-ABFT kernels
and are corrected locally BEFORE their results enter the online-softmax
recurrence — a corrupted accumulator never contaminates the running
(m, l, o) state or crosses the ring. Detection counts ``psum`` over the ring.

The recurrence per visiting block t (rows = local queries):

    s_t = scale * Q K_t^T                       [FT GEMM 1]
    m'  = max(m, rowmax(s_t))
    a   = exp(m - m')                           # rescale old state
    p_t = exp(s_t - m')
    o   = a * o + p_t V_t                       [FT GEMM 2]
    l   = a * l + rowsum(p_t)
    m   = m'
  final: O = o / l

**Training (round 4):** :func:`make_ring_ft_attention_diff` makes the
long-context path differentiable — a ``jax.custom_vjp`` whose backward is a
SECOND ring pass (the flash-attention backward distributed the same way):
with the forward's (m, l) statistics saved per query row, each hop
recomputes its normalized probability block through the FT QK kernel and
runs the four gradient GEMMs through FT kernels too,

    p_t  = exp(scale * Q K_t^T - m) / l         [FT GEMM, recompute]
    dV_t = p_tᵀ g                               [FT GEMM]
    dP_t = g V_tᵀ                               [FT GEMM]
    dS_t = p_t ⊙ (dP_t − rowsum(g ⊙ O)) · scale  (softmax bwd, VPU)
    dQ  += dS_t K_t                             [FT GEMM]
    dK_t = dS_tᵀ Q                              [FT GEMM]

with dK_t/dV_t accumulators ROTATING alongside their K/V blocks, so after a
full cycle every gradient shard arrives back at its home device — gradients
never need a gather. Backward fault counts ride the gradient side-channel
(``with_bwd_counts``; mechanism in ops/autodiff.py's module docstring).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.attention import (
    FtAttentionResult, PV_SHAPE, QK_SHAPE)
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.parallel.reduce import hierarchical_psum
from ft_sgemm_tpu.parallel.ring import (
    _check_divisible, make_ring_mesh, rotate_ahead_loop)
from ft_sgemm_tpu.parallel.sharded import shard_local_ft, shard_map


def _ring_geometry(q, k, v, mesh, scale, causal, in_dtype):
    """Shared validation + dtype conversion for the fwd and diff paths."""
    dt = jnp.dtype(in_dtype)
    q = jnp.asarray(q, dt)
    k = jnp.asarray(k, dt)
    v = jnp.asarray(v, dt)
    (lq, d_head), (lk, _), (_, dv) = q.shape, k.shape, v.shape
    dnum = mesh.shape["x"]
    _check_divisible("L_q", lq, dnum)
    _check_divisible("L_k", lk, dnum)
    if causal:
        from ft_sgemm_tpu.ops.attention import _check_causal_lengths

        _check_causal_lengths(lq, lk)
    sc = (1.0 / math.sqrt(d_head)) if scale is None else scale
    return q, k, v, lq, lk, dv, dnum, sc


def _masked_scores(s_res, sc, causal, my, t, dnum, qpos, nk_blk):
    """Scale + (causal) mask one visiting block's scores. The mask runs
    AFTER the QK kernel's detect/correct, so faults at masked positions
    are corrected, then silenced."""
    s_t = sc * s_res.c
    if causal:
        owner = jnp.mod(my - t, dnum)
        kpos = owner * nk_blk + jnp.arange(nk_blk)[None, :]
        s_t = jnp.where(kpos <= qpos, s_t, -jnp.inf)
    return s_t


def _build_forward(mesh, *, scale, causal, inject, strategy, threshold,
                   qk_shape, pv_shape, in_dtype, interpret, lq, lk, dv,
                   dnum, inject_coords=None, overlap=False):
    """The shard_map'd forward ring; returns
    (out, m, l, det, flags, unc, dev_det, dev_unc) with (m, l)
    row-sharded like the output — the residuals the differentiable
    path's backward ring needs — and the trailing pair the P("x")
    per-device counter arrays telemetry attribution reads
    (DESIGN.md §8). ``inject_coords=(i,)`` restricts injection to ring
    position ``i`` (both of that device's hop GEMMs inject).
    ``overlap=True`` runs the double-buffered rotate-ahead hop schedule
    (``parallel/ring.py::rotate_ahead_loop``): the K/V blocks' next-hop
    ``ppermute`` is issued before the hop's QK/PV FT-GEMMs, so the ICI
    transfer hides behind the MXU work; the online-softmax recurrence
    consumes the same block values in the same order either way, so the
    two schedules are byte-value identical."""
    inject = inject or InjectionSpec.none()
    sc_causal = causal
    qk = make_ft_sgemm(qk_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    pv = make_ft_sgemm(pv_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    run_qk = shard_local_ft(qk, inject, inject_coords, ("x",))
    run_pv = shard_local_ft(pv, inject, inject_coords, ("x",))
    perm = [(i, (i + 1) % dnum) for i in range(dnum)]
    sc = scale

    def step_fn(q_loc, k_loc, vt_loc):
        my = jax.lax.axis_index("x")
        nq = q_loc.shape[0]
        nk_blk = k_loc.shape[0]
        zs = jnp.zeros((nq, nk_blk), jnp.float32)
        zo = jnp.zeros((nq, dv), jnp.float32)
        # Global positions, end-aligned (decoding convention): local query
        # row r sits at key position my*nq + r + (lk - lq).
        qpos = (my * nq + jnp.arange(nq) + (lk - lq))[:, None]

        def hop_body(t, rotating, carry):
            k_vis, vt_vis = rotating
            m, l, o, det, unc = carry
            s_res = run_qk(q_loc, k_vis, zs)
            s_t = _masked_scores(s_res, sc, sc_causal, my, t, dnum, qpos,
                                 nk_blk)
            # Masked-block-safe online softmax: m_new may stay -inf while a
            # device has only future keys; exp() then sees finite args only.
            m_new = jnp.maximum(m, jnp.max(s_t, axis=1, keepdims=True))
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            a = jnp.where(m == m_new, 1.0, jnp.exp(m - m_safe))
            p_t = jnp.exp(s_t - m_safe)
            o_res = run_pv(p_t, vt_vis, zo)
            o = a * o + o_res.c
            l = a * l + jnp.sum(p_t, axis=1, keepdims=True)
            det = det + jnp.sum(s_res.detections) + jnp.sum(o_res.detections)
            unc = unc + jnp.sum(s_res.uncorrectable) + jnp.sum(
                o_res.uncorrectable)
            return m_new, l, o, det, unc

        m0 = jnp.full((nq, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((nq, 1), jnp.float32)
        m, l, o, det, unc = rotate_ahead_loop(
            dnum, perm, hop_body, (k_loc, vt_loc),
            (m0, l0, zo, jnp.int32(0), jnp.int32(0)), overlap=overlap)
        # Normalization invariant of the streaming softmax: l aggregates
        # exp(s - m) > 0 over all Lk keys; non-finite or non-positive rows
        # mean corrupted softmax state (detect-only, like the single-device
        # rowsum invariant).
        flags = jnp.sum(jnp.logical_not(
            jnp.isfinite(l) & (l > 0.0)).astype(jnp.int32))
        out = o / l
        # Per-device counts keep their ring position via P("x") before
        # the staged reduction collapses the global totals (the ring's
        # one axis degenerates to the flat psum; parallel/reduce.py).
        dev_det = det.reshape(1)
        dev_unc = unc.reshape(1)
        det = hierarchical_psum(det, ("x",))
        flags = hierarchical_psum(flags, ("x",))
        unc = hierarchical_psum(unc, ("x",))
        return (out, m, l, det.reshape(1, 1), flags.reshape(1, 1),
                unc.reshape(1, 1), dev_det, dev_unc)

    return shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P("x", None), P("x", None), P(None, "x")),
        out_specs=(P("x", None), P("x", None), P("x", None), P(None, None),
                   P(None, None), P(None, None), P("x"), P("x")),
    )


def make_ring_ft_attention(
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    inject_coords: Optional[tuple] = None,
    ring_overlap: Optional[str] = "serial",
):
    """Build a REUSABLE ring-attention executor: ``fn(q, k, v) ->
    (out, det, flags, unc, dev_det, dev_unc)`` raw arrays.

    The factory form exists for callers that dispatch MANY calls through
    one executable — the block serving engine AOT-compiles ``jax.jit(fn)``
    once per (bucket, variant) and reuses it for every request, which a
    per-call ``jax.jit`` of a fresh closure (the one-shot
    :func:`ring_ft_attention` path) cannot do. The shard_map'd forward is
    constructed at trace time from the call's static shapes, so one
    ``fn`` serves exactly one padded geometry — precisely the bucket
    contract. ``dev_det`` / ``dev_unc`` are the ``P("x")`` per-device
    counter arrays (one entry per ring position) telemetry attribution
    reads; ``inject_coords=(i,)`` restricts injection to ring position
    ``i``, the per-device fault-localization knob the sharded GEMM paths
    established. ``ring_overlap`` selects the hop schedule: ``"serial"``
    (compute-then-rotate, the historical default) or ``"overlap"`` (the
    double-buffered rotate-ahead pipeline — the K/V ``ppermute`` rides
    under the QK/PV FT-GEMMs); ``None``/``"auto"`` consults the tuner
    cache on the per-device QK problem. Both schedules are byte-value
    identical (test-pinned)."""

    def fn(q, k, v):
        from ft_sgemm_tpu.parallel.ring import _resolve_ring_overlap

        q2, k2, v2, lq, lk, dv, dnum, sc = _ring_geometry(
            q, k, v, mesh, scale, causal, in_dtype)
        overlap = _resolve_ring_overlap(
            ring_overlap, lq, lk, q2.shape[1], dnum, strategy=strategy,
            in_dtype=in_dtype)
        fwd = _build_forward(
            mesh, scale=sc, causal=causal, inject=inject,
            strategy=strategy, threshold=threshold, qk_shape=qk_shape,
            pv_shape=pv_shape, in_dtype=in_dtype, interpret=interpret,
            lq=lq, lk=lk, dv=dv, dnum=dnum, inject_coords=inject_coords,
            overlap=overlap == "overlap")
        out, _, _, det, flags, unc, dev_det, dev_unc = fwd(
            q2, k2, jnp.swapaxes(v2, 0, 1))
        return (out, det[0, 0], flags[0, 0], unc[0, 0], dev_det, dev_unc)

    fn.strategy = strategy
    fn.in_dtype = in_dtype
    fn.causal = causal
    return fn


def ring_ft_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    inject: Optional[InjectionSpec] = None,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    inject_coords: Optional[tuple] = None,
    ring_overlap: Optional[str] = "serial",
) -> FtAttentionResult:
    """Fault-tolerant ring attention over a 1-D mesh.

    ``q`` (L, d), ``k`` (Lk, d), ``v`` (Lk, dv); L and Lk must divide over
    the ring (pad first). Returns the full (L, dv) output row-sharded over
    the mesh, the global corrected-fault count, and ``softmax_flags`` =
    number of rows whose online-softmax denominator ``l`` ended non-finite
    or non-positive — the streaming analog of the single-device
    rowsum==1 invariant (detect-only; 0 on clean runs). With telemetry
    enabled, each device's hop-summed counts are recorded against its
    ring position and host (``telemetry.record_mesh_attention``);
    ``inject_coords=(i,)`` restricts injection to ring position ``i``;
    ``ring_overlap`` selects the hop schedule (see
    :func:`make_ring_ft_attention`).
    """
    fn = make_ring_ft_attention(
        mesh, scale=scale, causal=causal, inject=inject,
        strategy=strategy, threshold=threshold, qk_shape=qk_shape,
        pv_shape=pv_shape, in_dtype=in_dtype, interpret=interpret,
        inject_coords=inject_coords, ring_overlap=ring_overlap)
    dnum = mesh.shape["x"]
    with telemetry.trace_span("ring_ft_attention"):
        out, det, flags, unc, dev_det, dev_unc = jax.jit(fn)(q, k, v)
    result = FtAttentionResult(out, det, flags, unc)
    if telemetry.enabled():
        telemetry.record_mesh_attention(
            "ring_ft_attention", result, strategy=strategy,
            device=f"ring{dnum}",
            dev_detections=dev_det, dev_uncorrectable=dev_unc,
            axes=("x",))
    return result


def make_ring_ft_attention_diff(
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    strategy: str = "weighted",
    threshold: float | str = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float | str] = None,
    inject: Optional[InjectionSpec] = None,
    inject_bwd: Optional[InjectionSpec] = None,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    with_counts: bool = False,
    with_bwd_counts: bool = False,
):
    """Differentiable FT ring attention: the long-context path can train.

    Returns ``fn(q, k, v)`` (global arrays; sharding as in
    :func:`ring_ft_attention`) as a ``jax.custom_vjp`` whose backward is a
    second ring pass (module docstring): all 2 + 5·hops-per-device GEMM
    executions — forward QK/PV and the backward recompute + four gradient
    products of every hop — run through the fused-ABFT kernels, with dK/dV
    accumulators rotating home alongside their blocks. Extends the
    single-device ``make_ft_attention_diff`` pattern (ops/attention.py) to
    the ring recurrence — VERDICT r3 item 7.

    ``with_counts=True`` returns the :class:`FtAttentionResult` pytree
    (forward counts; int leaves take zero cotangents).
    ``with_bwd_counts=True`` adds a trailing ``bwd_sink`` argument whose
    gradient is ``[detections, uncorrectable]`` psum'd over every backward
    GEMM on every device (the gradient side-channel of ops/autodiff.py).
    ``inject``/``inject_bwd`` drive the forward / backward kernels
    respectively (static; self-test). ``bwd_threshold`` tightens the
    gradient GEMMs' detection threshold (cotangent scale; or use
    ``threshold="auto"``).

    Both passes run the SERIAL hop schedule: the backward's dK/dV
    accumulators are OUTPUTS of each hop's gradient GEMMs and rotate
    alongside their blocks, so the rotation genuinely depends on the
    hop's compute — there is nothing for a rotate-ahead schedule to
    issue early without breaking that dependency, and the forward pass
    of a custom_vjp must match its recompute exactly.
    """
    if strategy == "global":
        raise ValueError(
            "make_ring_ft_attention_diff requires a CORRECTING strategy: "
            "'global' only detects — a detect-only backward fault would be "
            "shipped into gradients/optimizer state (with_bwd_counts can "
            "report it but nothing corrects it). Pick 'rowcol' or "
            "'weighted', or use ring_ft_attention for detect-only runs.")
    inj = inject or InjectionSpec.none()
    inj_b = inj if inject_bwd is None else inject_bwd
    bthr = threshold if bwd_threshold is None else bwd_threshold
    dnum = mesh.shape["x"]
    perm = [(i, (i + 1) % dnum) for i in range(dnum)]

    mk = lambda shp, thr: make_ft_sgemm(  # noqa: E731
        shp, alpha=1.0, beta=0.0, strategy=strategy, threshold=thr,
        in_dtype=in_dtype, interpret=interpret)
    # Backward kernel profiles mirror the single-device diff factory:
    # long-contraction products (dV, dQ, dK over nq/nk_blk) use the PV
    # profile, the short-contraction dP (over dv) uses the QK profile.
    # The probability RECOMPUTE mirrors the forward QK product — its
    # operands and residuals are activation-scale, so it keeps the
    # forward threshold (a cotangent-tight bwd_threshold there would
    # false-positive on clean checksum noise and trip the re-run gate).
    qk_b = mk(qk_shape, threshold)
    b_long = mk(pv_shape, bthr)
    # Same shape and threshold => same kernel: reuse the recompute kernel
    # for dP, as the single-device factory does (ops/attention.py).
    b_short = qk_b if bthr == threshold else mk(qk_shape, bthr)

    def _forward(q, k, v):
        q2, k2, v2, lq, lk, dv, _, sc = _ring_geometry(
            q, k, v, mesh, scale, causal, in_dtype)
        fn = _build_forward(
            mesh, scale=sc, causal=causal, inject=inj, strategy=strategy,
            threshold=threshold, qk_shape=qk_shape, pv_shape=pv_shape,
            in_dtype=in_dtype, interpret=interpret, lq=lq, lk=lk, dv=dv,
            dnum=dnum)
        out, m, l, det, flags, unc, _, _ = fn(q2, k2,
                                              jnp.swapaxes(v2, 0, 1))
        res = FtAttentionResult(out, det[0, 0], flags[0, 0], unc[0, 0])
        # Residuals keep the CALLER's arrays (original dtype, like the
        # single-device factory): cotangents must match the primals'
        # dtype, not in_dtype's — the backward kernels re-round per call.
        saved = (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), out, m, l,
                 sc)
        return (res if with_counts else out), saved

    def _backward(saved, g):
        q, k, v, out, m, l, sc = saved
        if with_counts:
            g = g[0]  # counts leaves carry zero (float0) cotangents
        lq, lk = q.shape[0], k.shape[0]
        d_head, dv = q.shape[1], v.shape[1]

        def bwd_fn(q_loc, g_loc, o_loc, m_loc, l_loc, k_loc, vt_loc):
            my = jax.lax.axis_index("x")
            nq = q_loc.shape[0]
            nk_blk = k_loc.shape[0]
            zs = jnp.zeros((nq, nk_blk), jnp.float32)
            qpos = (my * nq + jnp.arange(nq) + (lk - lq))[:, None]
            m_safe = jnp.where(jnp.isfinite(m_loc), m_loc, 0.0)
            # Flash-backward rescaling term, one VPU reduce per row.
            d_row = jnp.sum(g_loc * o_loc, axis=1, keepdims=True)

            def hop(t, carry):
                (k_vis, vt_vis, dk_blk, dvt_blk, dq, det, unc) = carry
                # Recompute this block's NORMALIZED probabilities from the
                # saved (m, l) statistics — through the FT QK kernel, so
                # the recompute is protected like the forward was.
                s_res = qk_b(q_loc, k_vis, zs, inj_b)
                s_t = _masked_scores(s_res, sc, causal, my, t, dnum, qpos,
                                     nk_blk)
                p_t = jnp.exp(s_t - m_safe) / l_loc
                # dV_t = p_tᵀ g: contracts over nq.
                rv = b_long(jnp.swapaxes(p_t, 0, 1),
                            jnp.swapaxes(g_loc, 0, 1),
                            jnp.zeros((nk_blk, dv), jnp.float32), inj_b)
                # dP_t = g V_tᵀ: contracts over dv.
                rp = b_short(g_loc, jnp.swapaxes(vt_vis, 0, 1),
                             jnp.zeros((nq, nk_blk), jnp.float32), inj_b)
                ds_t = p_t * (rp.c - d_row) * sc
                # dQ += dS_t K_t: contracts over nk_blk.
                rq = b_long(ds_t, jnp.swapaxes(k_vis, 0, 1),
                            jnp.zeros((nq, d_head), jnp.float32), inj_b)
                # dK_t = dS_tᵀ Q: contracts over nq.
                rk = b_long(jnp.swapaxes(ds_t, 0, 1),
                            jnp.swapaxes(q_loc, 0, 1),
                            jnp.zeros((nk_blk, d_head), jnp.float32),
                            inj_b)
                dq = dq + rq.c
                # The block's gradient accumulators ROTATE with the block:
                # after the full cycle they arrive back at its home device.
                dk_blk = dk_blk + rk.c
                dvt_blk = dvt_blk + jnp.swapaxes(rv.c, 0, 1)
                for r in (s_res, rv, rp, rq, rk):
                    det = det + jnp.sum(r.detections)
                    unc = unc + jnp.sum(r.uncorrectable)
                k_vis = jax.lax.ppermute(k_vis, "x", perm)
                vt_vis = jax.lax.ppermute(vt_vis, "x", perm)
                dk_blk = jax.lax.ppermute(dk_blk, "x", perm)
                dvt_blk = jax.lax.ppermute(dvt_blk, "x", perm)
                return (k_vis, vt_vis, dk_blk, dvt_blk, dq, det, unc)

            zero_dk = jnp.zeros((nk_blk, d_head), jnp.float32)
            zero_dvt = jnp.zeros((dv, nk_blk), jnp.float32)
            zero_dq = jnp.zeros((nq, d_head), jnp.float32)
            (_, _, dk_blk, dvt_blk, dq, det, unc) = jax.lax.fori_loop(
                0, dnum, hop,
                (k_loc, vt_loc, zero_dk, zero_dvt, zero_dq,
                 jnp.int32(0), jnp.int32(0)))
            det = jax.lax.psum(det, "x")
            unc = jax.lax.psum(unc, "x")
            return (dq, dk_blk, dvt_blk, det.reshape(1, 1),
                    unc.reshape(1, 1))

        fn = shard_map(
            bwd_fn,
            mesh=mesh,
            in_specs=(P("x", None), P("x", None), P("x", None),
                      P("x", None), P("x", None), P("x", None),
                      P(None, "x")),
            out_specs=(P("x", None), P("x", None), P(None, "x"),
                       P(None, None), P(None, None)),
        )
        dq, dk, dvt, det, unc = fn(q, g, out, m, l, k,
                                   jnp.swapaxes(v, 0, 1))
        grads = (dq.astype(q.dtype), dk.astype(k.dtype),
                 jnp.swapaxes(dvt, 0, 1).astype(v.dtype))
        return grads, det[0, 0], unc[0, 0]

    from ft_sgemm_tpu.ops.autodiff import sink_vjp

    return sink_vjp(lambda q, k, v: _forward(q, k, v)[0], _forward,
                    _backward, with_bwd_counts)


__all__ = ["make_ring_ft_attention", "make_ring_ft_attention_diff",
           "make_ring_mesh", "ring_ft_attention"]
