// Native host utilities for ft_sgemm_tpu (reference: utils/utils.cu).
//
// The reference's host layer is native CUDA/C++; this is its TPU-build
// counterpart, exposed to Python through ctypes (see
// ft_sgemm_tpu/runtime/__init__.py). Two things justify native code here:
//
//  1. Bit-exact input parity: the reference seeds libc rand (srand(10),
//     sgemm.cu:12) and draws two rand() calls per element
//     (utils.cu:23-31). Reproducing that stream from Python is fragile;
//     calling the same libc here is exact.
//  2. Host-side verification/generation speed on big sweeps (6144^2
//     matrices) without holding the GIL.
//
// Build: g++ -O3 -shared -fPIC hostutils.cpp -o libftsgemm_hostutils.so

#include <cmath>
#include <cstdint>
#include <cstdlib>

extern "C" {

// Reference utils.cu:23-31 — element = (rand()%10)*0.1, negated when a
// second draw is odd; row-major double loop over (n, m). The reference is
// square (n x n); m generalizes it.
void ftsg_generate_random_matrix(float* target, int n, int m,
                                 unsigned int seed, int reseed) {
  if (reseed) srand(seed);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < m; ++j) {
      float tmp = (float)(rand() % 10) * 0.1f;
      tmp = (rand() % 2 == 0) ? tmp : -tmp;
      target[i * m + j] = tmp;
    }
  }
}

// Reference utils.cu:15-21.
void ftsg_generate_random_vector(float* target, int n, unsigned int seed,
                                 int reseed) {
  if (reseed) srand(seed);
  for (int i = 0; i < n; ++i) {
    float tmp = (float)(rand() % 5) * 0.01f + (float)(rand() % 5) * 0.001f;
    tmp = (rand() % 2 == 0) ? tmp : -tmp;
    target[i] = tmp;
  }
}

// Reference utils.cu:61-77 tolerance: an element fails iff
// abs diff > 0.01 AND relative diff (vs ref) > 0.01. Returns the number of
// failing elements; *first_bad gets the flat index of the first failure
// (or -1). Unlike the reference (early exit, printf), this scans fully.
long long ftsg_verify_matrix(const float* ref, const float* out, int m, int n,
                             double abs_tol, double rel_tol,
                             long long* first_bad) {
  long long bad = 0;
  *first_bad = -1;
  const long long total = (long long)m * n;
  for (long long idx = 0; idx < total; ++idx) {
    double diff = std::fabs((double)ref[idx] - (double)out[idx]);
    double denom = std::fabs((double)ref[idx]);
    double rel = denom > 0.0 ? diff / denom : (diff > 0.0 ? INFINITY : 0.0);
    if (diff > abs_tol && rel > rel_tol) {
      if (*first_bad < 0) *first_bad = idx;
      ++bad;
    }
  }
  return bad;
}

// Reference utils.cu:79-89 — naive triple loop, C = alpha*A@B + beta*C,
// row-major (m x k)(k x n). Double accumulator like the reference's float
// temp widened for orderliness of the oracle.
void ftsg_cpu_gemm(float alpha, float beta, const float* a, const float* b,
                   float* c, int m, int n, int k) {
  for (int i = 0; i < m; ++i) {
    for (int j = 0; j < n; ++j) {
      double t = 0.0;
      for (int p = 0; p < k; ++p) {
        t += (double)a[i * k + p] * (double)b[p * n + j];
      }
      c[i * n + j] = alpha * (float)t + beta * c[i * n + j];
    }
  }
}

// Two-pass ABFT residual check on a host buffer (the native analog of the
// checksum math in include/baseline_ft_sgemm.cuh:9-31): returns max
// |rowsum(C) - expected_row| over rows, writing the column-side max via
// *col_residual. expected vectors have length m and n respectively.
double ftsg_checksum_residual(const float* c, const double* expected_row,
                              const double* expected_col, int m, int n,
                              double* col_residual) {
  double max_r = 0.0;
  for (int i = 0; i < m; ++i) {
    double s = 0.0;
    for (int j = 0; j < n; ++j) s += (double)c[i * n + j];
    double r = std::fabs(expected_row[i] - s);
    if (r > max_r) max_r = r;
  }
  double max_c = 0.0;
  for (int j = 0; j < n; ++j) {
    double s = 0.0;
    for (int i = 0; i < m; ++i) s += (double)c[i * n + j];
    double r = std::fabs(expected_col[j] - s);
    if (r > max_c) max_c = r;
  }
  *col_residual = max_c;
  return max_r;
}

}  // extern "C"
