"""Cross-host serve dispatch: per-process pools behind one placer.

The PR-14/15 serve plane stops at one process: ``serve/pool.py`` places
batches on the devices ONE process can address, and eviction
(``resilience/elastic.py``) sheds a chip. A fleet adds a level to both:
each process runs its own pool (its local vdevs, its own prewarmed
executables), and this module's :class:`FleetDispatcher` — driven by
the coordinator rank — places whole requests on those per-process
pools, with DCN distance as a placement COST TERM rather than a wall:
the ICI/DCN panel asymmetry of arXiv 2112.09017, applied to serving.
Placement score per host slot is ``(load + 1) * (1 + dcn_distance) /
health`` — equal-load ties break toward the coordinator's own process,
and a remote slot earns traffic exactly when the local one is loaded
enough to pay the DCN hop.

Eviction here is HOST-granularity (the fleet failure domain is the
process — its runtime, its NIC, its host memory): repeated device
blames on one process (``ElasticController.should_evict_host``) evict
the whole slot — it is removed from placement permanently and its
queued requests MIGRATE through the ordinary placer, the same
evicted-not-drained semantics the device-level plane pins, one level
up.

``HOST_TIERS`` / ``FLEET_PLACEMENTS`` are the runtime spellings of
``contracts.HOST_TIERS`` / ``contracts.FLEET_PLACEMENTS`` (the lint
axis-drift pass cross-checks them against ``events.AXIS_LABELS``).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

# Runtime spellings of contracts.HOST_TIERS / contracts.FLEET_PLACEMENTS
# (lint axis-drift cross-checks both against events.AXIS_LABELS).
HOST_TIERS = ("local", "dcn")
FLEET_PLACEMENTS = ("dcn_cost", "round_robin")

# Runtime spelling of contracts.FLEET_HOPS (lint axis-drift checks this
# copy and events.AXIS_LABELS["hop"] against it). Each hop is one
# ``fleet_hop_<hop>_seconds`` histogram family, ordered along the
# request's path: coordinator queue wait, DCN wire round trip (minus
# the remote's wall time), remote receive->execute gap, remote execute
# wall, and extra wall re-executing after a detection.
FLEET_HOPS = ("queue_wait", "rtt", "remote_queue", "remote_execute",
              "retry")


@dataclasses.dataclass
class HostSlot:
    """One per-process pool the dispatcher can place on.

    ``runner`` executes one request spec on that host's pool and
    returns the reply dict (for the coordinator's own process a direct
    call; for remote ranks a TCP round trip — fleet/worker.py wires
    both). ``dcn_distance`` is the placement cost term: 0.0 for the
    coordinator's own process, >= 1.0 per DCN hop.
    """

    host: int
    runner: Callable[[dict], dict]
    host_tier: str = "dcn"
    dcn_distance: float = 1.0
    workers: int = 2


class FleetDispatcher:
    """Place request specs on host slots; evict whole hosts under load.

    Thread model: ``submit`` (any thread) enqueues on the chosen slot's
    queue; each slot owns ``workers`` daemon threads draining it. A
    request found queued on an evicted slot is re-placed through the
    ordinary scorer instead of executed — queued work MIGRATES, exactly
    like the device-level pool's eviction. ``on_reply(host, spec,
    reply)`` runs on the slot worker thread after every completed
    request — the blame feed (fleet/worker.py inspects replies for
    detections and calls ``ElasticController.note_device_blame``).
    """

    def __init__(self, slots: Sequence[HostSlot], *,
                 placement: str = "dcn_cost", health=None, registry=None,
                 timeline=None, on_reply=None):
        if placement not in FLEET_PLACEMENTS:
            raise ValueError(f"unknown fleet placement {placement!r};"
                             f" expected one of {FLEET_PLACEMENTS}")
        self.slots = list(slots)
        self.placement = placement
        self.health = health
        self.registry = registry
        self.timeline = timeline
        self.on_reply = on_reply
        self._lock = threading.Lock()
        self._queues = {s.host: queue.Queue() for s in self.slots}
        self._inflight = {s.host: 0 for s in self.slots}
        self._batches = {s.host: 0 for s in self.slots}
        self._requests = {s.host: 0 for s in self.slots}
        self._skew = {}  # host -> last wire-handshake clock skew (s)
        self._hop_hist = {}  # (host, hop) -> registry Histogram
        self._evicted: set = set()
        self._rr = 0
        self._stop = threading.Event()
        self._threads = []
        for slot in self.slots:
            for i in range(max(1, slot.workers)):
                t = threading.Thread(
                    target=self._slot_worker, args=(slot,),
                    name=f"fleet-host{slot.host}-w{i}", daemon=True)
                t.start()
                self._threads.append(t)

    # -- placement ---------------------------------------------------------

    def _score(self, slot: HostSlot) -> float:
        with self._lock:
            load = self._queues[slot.host].qsize() \
                + self._inflight[slot.host]
        hscore = 1.0
        if self.health is not None:
            hscore = max(float(self.health.score(f"host{slot.host}")),
                         1e-6)
        return (load + 1.0) * (1.0 + float(slot.dcn_distance)) / hscore

    def eligible(self) -> list:
        with self._lock:
            evicted = set(self._evicted)
        return [s for s in self.slots if s.host not in evicted]

    def choose(self) -> HostSlot:
        cands = self.eligible()
        if not cands:
            raise RuntimeError("fleet dispatcher: every host is evicted")
        if self.placement == "round_robin":
            with self._lock:
                slot = cands[self._rr % len(cands)]
                self._rr += 1
            return slot
        return min(cands, key=self._score)

    def submit(self, spec: dict) -> Future:
        slot = self.choose()
        fut: Future = Future()
        # Trace context crosses the wire INSIDE the spec (the JSON-lines
        # hop carries whole specs, so no envelope change): reuse the
        # caller's / ambient ID, else mint one. ``t_submit`` is the
        # coordinator's wall clock — the queue_wait hop's start, and the
        # send-timestamp the merged trace anchors the flow on.
        if spec.get("trace_id") is None:
            from ft_sgemm_tpu.serve import tracing

            spec["trace_id"] = (tracing.current_trace_id()
                                or tracing.new_trace_id())
        spec.setdefault("t_submit", time.time())
        with self._lock:
            self._requests[slot.host] += 1
        if self.registry is not None:
            self.registry.counter("fleet_dispatch_requests",
                                  host_tier=slot.host_tier).inc()
        if self.timeline is not None:
            self.timeline.point("fleet", f"submit_host{slot.host}",
                                trace_id=spec["trace_id"],
                                host=slot.host,
                                host_tier=slot.host_tier)
        self._queues[slot.host].put((spec, fut))
        return fut

    # -- slot workers ------------------------------------------------------

    def _slot_worker(self, slot: HostSlot) -> None:
        q = self._queues[slot.host]
        while not self._stop.is_set():
            try:
                spec, fut = q.get(timeout=0.05)
            except queue.Empty:
                continue
            with self._lock:
                dead = slot.host in self._evicted
            if dead:
                # Migrate, never execute: the evicted-host analog of the
                # pool's queued-batch migration.
                try:
                    other = self.choose()
                    self._queues[other.host].put((spec, fut))
                except RuntimeError as e:
                    fut.set_exception(e)
                continue
            with self._lock:
                self._inflight[slot.host] += 1
            t_dequeue = time.time()
            try:
                reply = slot.runner(spec)
            except Exception as e:  # noqa: BLE001 — reply path owns errors
                reply = {"ok": False, "error": f"{type(e).__name__}: {e}",
                         "host": slot.host}
            finally:
                with self._lock:
                    self._inflight[slot.host] -= 1
                    self._batches[slot.host] += 1
            try:
                self._note_hops(slot, spec, reply, t_dequeue,
                                time.time() - t_dequeue)
            except Exception:  # noqa: BLE001 — observability only
                pass
            if self.on_reply is not None:
                try:
                    self.on_reply(slot.host, spec, reply)
                except Exception:  # noqa: BLE001 — observability only
                    pass
            fut.set_result(reply)

    # -- per-hop latency + clock skew --------------------------------------

    def _observe_hop(self, slot: HostSlot, hop: str, v) -> None:
        if not isinstance(v, (int, float)) or v < 0:
            return
        from ft_sgemm_tpu.telemetry.registry import LATENCY_BUCKETS

        h = self.registry.histogram(f"fleet_hop_{hop}_seconds",
                                    buckets=LATENCY_BUCKETS,
                                    host=str(slot.host),
                                    host_tier=slot.host_tier)
        h.observe(float(v))
        with self._lock:
            self._hop_hist[(slot.host, hop)] = h

    def _note_hops(self, slot: HostSlot, spec: dict, reply: dict,
                   t_dequeue: float, runner_seconds: float) -> None:
        """Decompose one completed request into the FLEET_HOPS latency
        taxonomy and record the remote rank's wire-handshake clock skew.
        Every field is read tolerantly — a reply from an older/foreign
        runner simply contributes fewer hops, never an error."""
        if self.registry is None or not isinstance(reply, dict):
            return
        t_submit = spec.get("t_submit")
        if isinstance(t_submit, (int, float)):
            self._observe_hop(slot, "queue_wait", t_dequeue - t_submit)
        self._observe_hop(slot, "remote_execute", reply.get("seconds"))
        self._observe_hop(slot, "retry", reply.get("retry_seconds"))
        wire = reply.get("wire")
        if isinstance(wire, dict):
            # The remote runner already solved the NTP-midpoint
            # handshake (fleet/worker.py::_remote_runner): rtt is the
            # wire round trip minus the remote's hold time, skew the
            # midpoint clock offset — refreshed on every connection.
            self._observe_hop(slot, "rtt", wire.get("rtt_seconds"))
            self._observe_hop(slot, "remote_queue",
                              wire.get("remote_queue_seconds"))
            skew = wire.get("skew_seconds")
            if isinstance(skew, (int, float)):
                with self._lock:
                    self._skew[slot.host] = float(skew)
                self.registry.gauge("fleet_clock_skew_seconds",
                                    host=str(slot.host)).set(float(skew))
        elif slot.host_tier == "local":
            # The coordinator's own pool: no wire, no skew — the whole
            # runner wall IS the execute+queue hop already recorded.
            with self._lock:
                self._skew.setdefault(slot.host, 0.0)

    # -- host eviction -----------------------------------------------------

    def evict_host(self, host: int, reason: str = "host_blame") -> dict:
        """Remove one host slot from placement permanently and migrate
        its queued requests — evicted, NOT drained: the slot never
        becomes a candidate again, unlike a pool drain (which re-admits
        on recovery). Returns the eviction facts."""
        with self._lock:
            self._evicted.add(int(host))
        q = self._queues[int(host)]
        migrated = 0
        while True:
            try:
                spec, fut = q.get_nowait()
            except queue.Empty:
                break
            try:
                other = self.choose()
                self._queues[other.host].put((spec, fut))
                migrated += 1
            except RuntimeError as e:
                fut.set_exception(e)
        survivors = len(self.eligible())
        facts = {"host": int(host), "reason": reason,
                 "action": "evicted", "migrated": migrated,
                 "surviving_hosts": survivors, "ts": time.monotonic()}
        if self.registry is not None:
            self.registry.counter("fleet_host_evictions").inc()
            self.registry.gauge("fleet_hosts_eligible").set(survivors)
        if self.timeline is not None:
            self.timeline.point("fleet", f"evict_host{host}",
                                reason=reason, migrated=migrated,
                                survivors=survivors)
        from ft_sgemm_tpu import telemetry

        telemetry.record_step_event(
            "evicted", op="fleet_dispatch",
            extra={"host": int(host), "host_tier": "dcn",
                   "reason": reason, "action": "evicted",
                   "migrated": migrated,
                   "surviving_hosts": survivors})
        return facts

    # -- introspection / shutdown -----------------------------------------

    def stats(self) -> dict:
        from ft_sgemm_tpu.telemetry.registry import histogram_percentiles

        with self._lock:
            hop_hist = {k: h.value for k, h in self._hop_hist.items()}
            skew = dict(self._skew)
            out = {
                "placement": self.placement,
                "evicted_hosts": sorted(self._evicted),
                "per_host": {
                    s.host: {"host_tier": s.host_tier,
                             "dcn_distance": s.dcn_distance,
                             "queued": self._queues[s.host].qsize(),
                             "inflight": self._inflight[s.host],
                             "batches": self._batches[s.host],
                             "requests": self._requests[s.host]}
                    for s in self.slots},
            }
        for s in self.slots:
            row = out["per_host"][s.host]
            if s.host in skew:
                row["clock_skew_seconds"] = skew[s.host]
            # Percentile ESTIMATES from the single stats path — the
            # same registry histogram buckets /metrics exports, never a
            # second latency accumulator (DESIGN.md §11 discipline).
            hops = {}
            for hop in FLEET_HOPS:
                value = hop_hist.get((s.host, hop))
                if value and value.get("count"):
                    hops[hop] = histogram_percentiles(value)
            if hops:
                row["hop_percentiles"] = hops
        return out

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)


__all__ = ["FLEET_PLACEMENTS", "FleetDispatcher", "HOST_TIERS",
           "HostSlot"]
