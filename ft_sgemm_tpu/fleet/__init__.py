"""Fleet runtime: real multi-process meshes, supervised and served.

- :mod:`ft_sgemm_tpu.fleet.launch` — the stdlib-only launcher/
  coordinator (spawn N CPU processes, wire ``jax.distributed``,
  supervise kill-safely, salvage). The jax-free bench supervisor
  path-loads the file directly; importing it here is equally safe.
- :mod:`ft_sgemm_tpu.fleet.worker` — the spawned rank program (never
  imported by the supervisor side).
- :mod:`ft_sgemm_tpu.fleet.dispatch` — the cross-host serve dispatcher
  (per-process pools, DCN distance as placement cost, host-granularity
  eviction).
"""

from ft_sgemm_tpu.fleet.dispatch import (FLEET_PLACEMENTS, FleetDispatcher,
                                         HOST_TIERS, HostSlot)
from ft_sgemm_tpu.fleet.launch import FleetSpec, launch_fleet, pick_port

__all__ = [
    "FLEET_PLACEMENTS",
    "FleetDispatcher",
    "FleetSpec",
    "HOST_TIERS",
    "HostSlot",
    "launch_fleet",
    "pick_port",
]
