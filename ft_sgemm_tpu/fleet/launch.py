"""Fleet launcher/coordinator: real multi-process CPU meshes, kill-safe.

Everything below ``parallel/multihost.py`` is honest about a DCN only
when there IS one: this module spawns N local CPU processes (each with
its own ``XLA_FLAGS --xla_force_host_platform_device_count`` vdev set),
wires ``jax.distributed.initialize`` coordination (address, process_id,
num_processes) through the ``FT_SGEMM_FLEET_*`` environment, and
supervises the ranks the way bench.py's monitor supervises its worker:
per-rank timelines, heartbeat watching, a named degradation — never a
hang — when a rank wedges, and salvage of whatever each rank completed
when it dies. ``2 procs x 4 vdevs`` is the CI shape; the same launcher
runs any local fleet (``cli fleet --procs --vdevs``).

HARD CONSTRAINT — stdlib only, no package-relative imports: the jax-free
bench supervisor (``bench.py --fleet``) loads this file directly via
``importlib.util.spec_from_file_location`` (the timeline.py discipline;
declared in ``contracts.STDLIB_ONLY_MODULES``, proven by
``scripts/stdlib_smoke.py``). The jax side lives entirely in the
spawned workers (``fleet/worker.py``); the package timeline module is
itself stdlib-only and is loaded here BY PATH so this module works both
imported normally and path-loaded under ``python -S``.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, Optional

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))
_WORKER_PATH = os.path.join(_PKG_DIR, "worker.py")


def _load_timeline():
    """Path-load telemetry/timeline.py (stdlib-only by contract) so the
    recorder works identically when this module itself was path-loaded
    by the jax-free supervisor (a package import would pull jax in)."""
    path = os.path.join(_PKG_DIR, os.pardir, "telemetry", "timeline.py")
    spec = importlib.util.spec_from_file_location("_fleet_timeline",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def pick_port() -> int:
    """A free TCP port on localhost for the jax.distributed coordinator."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class FleetSpec:
    """One fleet launch: N local processes x M virtual CPU devices.

    ``program`` names the worker program (fleet/worker.py dispatches on
    it): "noop" (init + report), "counters" (cross-process staged
    counters, localization, DCN tiers), "smoke" (counters + the serve/
    host-eviction acts), "wedge" (a deliberately hung rank — the
    kill-salvage self-test; never inits jax). ``wedge_after`` is the
    max heartbeat gap before a live rank is declared wedged and killed
    (named degradation); ``deadline_seconds`` bounds the whole launch.
    """

    procs: int = 2
    vdevs: int = 4
    program: str = "smoke"
    workdir: str = "fleet_run"
    coordinator_port: int = 0
    deadline_seconds: float = 600.0
    wedge_after: float = 30.0
    poll_seconds: float = 0.2
    python: Optional[str] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    program_args: dict = dataclasses.field(default_factory=dict)


class _HeartbeatTail:
    """Incremental heartbeat reader over one rank's timeline JSONL:
    byte offsets advance only past complete lines (the LiveAggregator
    discipline, stdlib-side), so a torn tail from a dying rank is
    re-read once completed, never half-parsed."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.last_beat: Optional[float] = None
        self.beats = 0

    def poll(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8",
                      errors="replace") as fh:
                fh.seek(self.offset)
                chunk = fh.read()
        except OSError:
            return
        cut = chunk.rfind("\n")
        if cut < 0:
            return
        complete = chunk[:cut + 1]
        self.offset += len(complete.encode("utf-8", errors="replace"))
        for line in complete.splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "heartbeat":
                t = rec.get("t")
                if isinstance(t, (int, float)):
                    self.last_beat = t
                    self.beats += 1


def _rank_env(spec: FleetSpec, rank: int, port: int,
              rankdir: str) -> dict:
    env = dict(os.environ)
    # REPLACE, never append: the parent may pin its own vdev count
    # (pytest runs with 8) and the rank must get exactly spec.vdevs.
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={spec.vdevs}")
    env["JAX_PLATFORMS"] = "cpu"
    # ``python fleet/worker.py`` puts fleet/ — not the repo root — on
    # sys.path; the rank imports the package via PYTHONPATH instead.
    pp = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (_REPO_ROOT if not pp
                         else _REPO_ROOT + os.pathsep + pp)
    env.setdefault("FT_SGEMM_COMPILE_CACHE", "0")
    env.setdefault("FT_SGEMM_TUNER_CACHE",
                   os.path.join(rankdir, "tuner_cache.json"))
    env["FT_SGEMM_FLEET_RANK"] = str(rank)
    env["FT_SGEMM_FLEET_NPROCS"] = str(spec.procs)
    env["FT_SGEMM_FLEET_COORD"] = f"127.0.0.1:{port}"
    env["FT_SGEMM_FLEET_VDEVS"] = str(spec.vdevs)
    env["FT_SGEMM_FLEET_PROGRAM"] = spec.program
    env["FT_SGEMM_FLEET_DIR"] = rankdir
    env["FT_SGEMM_FLEET_WORKDIR"] = os.path.dirname(rankdir)
    env["FT_SGEMM_FLEET_ARGS"] = json.dumps(spec.program_args)
    env.update(spec.env)
    return env


def _salvage(timeline_mod, timeline_path: str) -> dict:
    """What a dead rank completed: its timeline's stage values and
    heartbeat health (the bench supervisor's salvage contract, per
    rank)."""
    try:
        records = timeline_mod.read_timeline(timeline_path)
    except OSError:
        return {"heartbeats": 0, "stage_values": {}}
    summary = timeline_mod.summarize_timeline(records)
    return {"heartbeats": summary["heartbeats"],
            "max_heartbeat_gap": summary["max_heartbeat_gap"],
            "killed_at_stage": summary["killed_at_stage"],
            "stage_values": summary["stage_values"]}


def _terminate(proc, grace: float = 3.0) -> None:
    if proc.poll() is not None:
        return
    try:
        proc.send_signal(signal.SIGTERM)
    except OSError:
        return
    deadline = time.monotonic() + grace
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    if proc.poll() is None:
        try:
            proc.kill()
        except OSError:
            pass
        proc.wait(timeout=5.0)


def launch_fleet(spec: FleetSpec) -> dict:
    """Spawn, supervise, and collect one fleet. Returns the report::

        {"ok": bool, "procs", "vdevs", "program", "wall_seconds",
         "coordinator": "127.0.0.1:PORT",
         "ranks": {rank: {"status": "ok"|"failed"|"wedged"|"deadline",
                          "rc": int|None, "heartbeats": int,
                          "result": dict|None, "salvage": dict|None}},
         "result": <rank 0's result dict>|None}

    Kill-safe by construction: any exit path terminates every still-live
    rank; a wedged rank (heartbeat gap > ``wedge_after``) is killed by
    name with a ``kill`` marker in the fleet timeline — the run DEGRADES
    to a named partial report, it never hangs.
    """
    tl_mod = _load_timeline()
    workdir = os.path.abspath(spec.workdir)
    os.makedirs(workdir, exist_ok=True)
    fleet_tl = tl_mod.TimelineRecorder(
        os.path.join(workdir, "fleet.timeline.jsonl"))
    port = spec.coordinator_port or pick_port()
    python = spec.python or sys.executable
    t0 = time.monotonic()

    procs: Dict[int, subprocess.Popen] = {}
    tails: Dict[int, _HeartbeatTail] = {}
    logs = []
    status: Dict[int, str] = {}
    spawned_at: Dict[int, float] = {}
    try:
        for rank in range(spec.procs):
            rankdir = os.path.join(workdir, f"rank{rank}")
            os.makedirs(rankdir, exist_ok=True)
            log = open(os.path.join(rankdir, "log.txt"), "w",
                       encoding="utf-8")
            logs.append(log)
            procs[rank] = subprocess.Popen(
                [python, _WORKER_PATH],
                env=_rank_env(spec, rank, port, rankdir),
                cwd=_REPO_ROOT, stdout=log, stderr=subprocess.STDOUT)
            tails[rank] = _HeartbeatTail(
                os.path.join(rankdir, "timeline.jsonl"))
            spawned_at[rank] = time.monotonic()
            fleet_tl.point("fleet", f"spawn:rank{rank}",
                           pid=procs[rank].pid, program=spec.program)

        deadline = t0 + spec.deadline_seconds
        live = set(procs)
        while live:
            now = time.monotonic()
            for rank in sorted(live):
                proc = procs[rank]
                tails[rank].poll()
                if proc.poll() is not None:
                    live.discard(rank)
                    status[rank] = ("exited" if proc.returncode == 0
                                    else "failed")
                    fleet_tl.point("fleet", f"exit:rank{rank}",
                                   rc=proc.returncode)
                    continue
                last = tails[rank].last_beat
                # Wall-clock basis for the gap: beats carry time.time()
                # stamps, so compare against time.time(), with the spawn
                # moment (monotonic) covering the never-beat case.
                gap = (time.time() - last if last is not None
                       else now - spawned_at[rank])
                if gap > spec.wedge_after:
                    status[rank] = "wedged"
                    fleet_tl.point(
                        "kill", f"rank{rank}:wedged",
                        heartbeat_gap=round(gap, 3),
                        beats=tails[rank].beats)
                    _terminate(proc)
                    live.discard(rank)
            if live and now > deadline:
                for rank in sorted(live):
                    status[rank] = "deadline"
                    fleet_tl.point("kill", f"rank{rank}:deadline",
                                   deadline_seconds=spec.deadline_seconds)
                    _terminate(procs[rank])
                live.clear()
            if live:
                time.sleep(spec.poll_seconds)
    finally:
        for proc in procs.values():
            _terminate(proc)
        for log in logs:
            try:
                log.close()
            except OSError:
                pass

    ranks = {}
    for rank in range(spec.procs):
        rankdir = os.path.join(workdir, f"rank{rank}")
        tails[rank].poll()
        result = None
        rpath = os.path.join(rankdir, "result.json")
        try:
            with open(rpath, "r", encoding="utf-8") as fh:
                result = json.load(fh)
        except (OSError, json.JSONDecodeError):
            result = None
        st = status.get(rank, "failed")
        if st == "exited":
            st = "ok" if (result is not None
                          and result.get("ok", False)) else "failed"
        salvage = None
        if result is None:
            salvage = _salvage(tl_mod,
                               os.path.join(rankdir, "timeline.jsonl"))
        ranks[rank] = {"status": st,
                       "rc": procs[rank].returncode,
                       "heartbeats": tails[rank].beats,
                       "result": result, "salvage": salvage}
    report = {
        "ok": all(r["status"] == "ok" for r in ranks.values()),
        "procs": spec.procs, "vdevs": spec.vdevs,
        "program": spec.program,
        "coordinator": f"127.0.0.1:{port}",
        "wall_seconds": round(time.monotonic() - t0, 3),
        "ranks": ranks,
        "result": ranks.get(0, {}).get("result"),
        # Where traceview.merge_fleet stitches the per-rank timelines
        # (+ this supervisor's) into ONE skew-corrected Perfetto trace.
        "workdir": workdir,
        "merged_trace": os.path.join(workdir, "fleet.trace.json"),
    }
    fleet_tl.point("fleet", "collected",
                   ok=report["ok"],
                   statuses={r: ranks[r]["status"] for r in ranks})
    fleet_tl.close()
    return report


__all__ = ["FleetSpec", "launch_fleet", "pick_port"]
