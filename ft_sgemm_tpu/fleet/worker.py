"""Fleet rank program: one process of the multi-process mesh.

Spawned by ``fleet/launch.py`` (never imported by the supervisor side)
with its coordinates in the ``FT_SGEMM_FLEET_*`` environment. Module
scope imports ONLY the standard library on purpose: the "wedge"
program — the launcher's kill-salvage self-test — must hang without
ever touching jax, so jax and the package load lazily inside the
programs that need them.

Programs (``FT_SGEMM_FLEET_PROGRAM``):

- ``wedge``    — write a couple of heartbeats, then stop beating and
  sleep: a deliberately wedged rank the supervisor must detect by
  heartbeat gap, kill by name, and salvage.
- ``noop``     — bring up ``jax.distributed`` (gloo CPU collectives),
  report the global device view, exit: the spawn/collect path.
- ``counters`` — the DCN-honesty phases every rank runs SPMD on the
  real 2-proc mesh: staged-vs-flat counter equality across the process
  boundary, cross-process ``inject_coords`` localization into per-rank
  event shards, and the fleet checksum tiers with an in-flight DCN
  corruption detected at — only at — the global tier.
- ``trace``    — the cross-process trace-join drill: the TCP serve hop
  with a forced detect->retry on the remote rank, so one trace_id flows
  coordinator -> remote execute -> remote retry in the merged Perfetto
  trace (the tier-1 shape of the smoke program's serve tier).
- ``smoke``    — ``counters`` plus the serve acts: per-process pools
  behind the coordinator's :class:`~ft_sgemm_tpu.fleet.dispatch.
  FleetDispatcher` (DCN distance as placement cost), host-granularity
  blame on injected faults from the non-coordinator rank, whole-HOST
  eviction under load, reshard onto the survivor process, and goodput
  recovery — the ``bench.py --fleet --smoke`` acceptance run.

Every rank heartbeats its own timeline (the supervisor's liveness
feed), spans each phase (the salvage payload), streams telemetry events
to per-rank JSONL shards, and writes ``result.json`` atomically at the
end. Rank 0 is the coordinator: it additionally tails + merges every
rank's shards live (``telemetry.aggregate.LiveAggregator``) so the
merged fleet view — and the ``DeviceHealthTracker`` behind
``/metrics`` / ``cli top`` — covers devices it cannot address.
"""

from __future__ import annotations

import importlib.util
import json
import os
import socket
import socketserver
import sys
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))


def _env(name: str, default=None):
    return os.environ.get(f"FT_SGEMM_FLEET_{name}", default)


def _load_timeline():
    path = os.path.abspath(
        os.path.join(_HERE, os.pardir, "telemetry", "timeline.py"))
    spec = importlib.util.spec_from_file_location("_worker_timeline", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


class _Ctx:
    """One rank's coordinates + recorders (threaded: the heartbeat
    thread and the serve handler threads all write through here; the
    timeline recorder is internally locked, the rest is read-only after
    construction)."""

    def __init__(self):
        self.rank = int(_env("RANK", "0"))
        self.nprocs = int(_env("NPROCS", "1"))
        self.coord = _env("COORD", "127.0.0.1:12321")
        self.vdevs = int(_env("VDEVS", "4"))
        self.program = _env("PROGRAM", "noop")
        self.rankdir = _env("DIR", ".")
        self.workdir = _env("WORKDIR", os.path.dirname(self.rankdir) or ".")
        try:
            self.args = json.loads(_env("ARGS", "{}") or "{}")
        except json.JSONDecodeError:
            self.args = {}
        tl_mod = _load_timeline()
        self.tl = tl_mod.TimelineRecorder(
            os.path.join(self.rankdir, "timeline.jsonl"))
        self._beat_stop = threading.Event()
        self._beat_thread = None

    def start_heartbeat(self, period: float = 0.5) -> None:
        def beat():
            while not self._beat_stop.wait(period):
                self.tl.point("heartbeat", f"rank{self.rank}")

        self._beat_thread = threading.Thread(target=beat, daemon=True,
                                             name="fleet-heartbeat")
        self._beat_thread.start()

    def stop_heartbeat(self) -> None:
        self._beat_stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2.0)

    def write_result(self, result: dict) -> None:
        path = os.path.join(self.rankdir, "result.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(result, fh, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Programs
# ---------------------------------------------------------------------------


def run_wedge(ctx: _Ctx) -> int:
    """Beat twice, then wedge: alive but silent — the supervisor must
    kill this rank by heartbeat gap and salvage the finished span."""
    with ctx.tl.span("wedge_warmup", kind="stage") as info:
        ctx.tl.point("heartbeat", f"rank{ctx.rank}")
        time.sleep(0.1)
        ctx.tl.point("heartbeat", f"rank{ctx.rank}")
        info["value"] = {"beats": 2}
    time.sleep(float(ctx.args.get("wedge_sleep", 3600.0)))
    return 0


def _init_distributed(ctx: _Ctx):
    """Bring up jax with this rank's coordinates: gloo CPU collectives
    must be selected BEFORE ``jax.distributed.initialize``."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    if ctx.nprocs > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        from ft_sgemm_tpu.parallel.multihost import initialize

        initialize(coordinator_address=ctx.coord,
                   num_processes=ctx.nprocs, process_id=ctx.rank)
    return jax


def run_noop(ctx: _Ctx) -> int:
    with ctx.tl.span("distributed_init", kind="stage") as info:
        jax = _init_distributed(ctx)
        info["value"] = {"process_count": jax.process_count()}
    ctx.write_result({
        "ok": jax.process_count() == ctx.nprocs
        and len(jax.local_devices()) == ctx.vdevs,
        "rank": ctx.rank,
        "process_count": jax.process_count(),
        "device_count": len(jax.devices()),
        "local_devices": [str(d) for d in jax.local_devices()],
    })
    return 0


def _verify_local_shards(c_global, want_np) -> int:
    """Verify the LOCAL shards of a multi-process global array against
    the full numpy oracle (fetching the whole array would touch
    non-addressable devices); returns the bad-element count."""
    import numpy as np

    from ft_sgemm_tpu.utils import verify_matrix

    bad = 0
    for shard in c_global.addressable_shards:
        got = np.asarray(shard.data)
        ok, nbad, _ = verify_matrix(want_np[shard.index], got,
                                    verbose=False)
        bad += 0 if ok else nbad
    return bad


def _counters_phases(ctx: _Ctx, jax) -> dict:
    """The SPMD DCN-honesty phases (every rank runs these in lockstep).

    Returns the facts dict; raises AssertionError on any pinned
    property failing — the rank's result then reports ok=False.
    """
    import jax.numpy as jnp
    import numpy as np

    from ft_sgemm_tpu import sgemm_reference, telemetry
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.injection import InjectionSpec
    from ft_sgemm_tpu.parallel import (hierarchical_psum,
                                       make_multihost_mesh,
                                       multihost_ft_sgemm)
    from ft_sgemm_tpu.parallel.sharded import shard_map
    from ft_sgemm_tpu.resilience import fleet_tiered_ft_sgemm
    from ft_sgemm_tpu.resilience.tiers import checksum_tolerance
    from ft_sgemm_tpu.utils import generate_random_matrix
    from jax.sharding import PartitionSpec as P

    facts: dict = {}
    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)

    with ctx.tl.span("mesh", kind="stage") as info:
        mesh = make_multihost_mesh(hosts=ctx.nprocs)
        # The satellite-1 pin, on the REAL process boundary: a multiple
        # of process_count subdivides each process's devices into
        # contiguous slots.
        mesh_multi = make_multihost_mesh(hosts=2 * ctx.nprocs)
        facts["mesh"] = dict(mesh.shape)
        facts["mesh_multiple"] = dict(mesh_multi.shape)
        info["value"] = facts["mesh"]

    h, mx, my = (mesh.shape["host"], mesh.shape["x"], mesh.shape["y"])
    ndev = h * mx * my

    with ctx.tl.span("staged_vs_flat", kind="stage") as info:
        # Integer counters staged one axis at a time vs the flat psum,
        # across a REAL process boundary: must agree EXACTLY.
        def count_step(v):
            idx = (jax.lax.axis_index("host") * 100
                   + jax.lax.axis_index("x") * 10
                   + jax.lax.axis_index("y"))
            mine = v[0, 0] + idx.astype(jnp.int32)
            staged = hierarchical_psum(mine, ("y", "x", "host"))
            flat = jax.lax.psum(mine, ("host", "x", "y"))
            return (staged.reshape(1, 1), flat.reshape(1, 1))

        fn = shard_map(count_step, mesh=mesh,
                       in_specs=(P(("host", "x"), "y"),),
                       out_specs=(P(None, None), P(None, None)))
        seed = jnp.ones((h * mx, my), jnp.int32)
        staged, flat = jax.jit(fn)(seed)
        facts["staged"] = int(staged[0, 0])
        facts["flat"] = int(flat[0, 0])
        facts["staged_equals_flat"] = facts["staged"] == facts["flat"]
        assert facts["staged_equals_flat"], (facts["staged"],
                                             facts["flat"])
        info["value"] = {"staged": facts["staged"], "flat": facts["flat"]}

    m, n, k = 512, 128, 256
    rng = np.random.default_rng(int(ctx.args.get("seed", 7)))
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    want = None

    with telemetry.session(os.path.join(ctx.rankdir, "events.jsonl")):
        with ctx.tl.span("multihost_inject_all", kind="stage") as info:
            inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
            res = multihost_ft_sgemm(a, b, c, mesh, tile, alpha=1.0,
                                     beta=-1.5, inject=inj,
                                     threshold="adaptive")
            want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
            bad = _verify_local_shards(res.c, want)
            det = int(res.num_detected)
            facts["inject_all_detections"] = det
            facts["inject_all_bad_elements"] = bad
            assert bad == 0 and det == ndev, (bad, det, ndev)
            info["value"] = {"detections": det}

    # Localization gets its OWN shard so the cross-process attribution
    # assert reads an unambiguous stream.
    target = tuple(ctx.args.get("inject_coords", (h - 1, 0, 0)))
    with telemetry.session(
            os.path.join(ctx.rankdir, "events_localize.jsonl")):
        with ctx.tl.span("localize", kind="stage") as info:
            inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
            res = multihost_ft_sgemm(a, b, c, mesh, tile, alpha=1.0,
                                     beta=-1.5, inject=inj,
                                     inject_coords=target)
            bad = _verify_local_shards(res.c, want)
            det = int(res.num_detected)
            facts["localize_target"] = list(target)
            facts["localize_detections"] = det
            assert bad == 0 and det == 1, (bad, det)
            info["value"] = {"detections": det, "target": list(target)}

    with telemetry.session(
            os.path.join(ctx.rankdir, "events_tiers.jsonl")) as registry:
        with ctx.tl.span("dcn_tiers", kind="stage") as info:
            amax = float(np.abs(a).max())
            bmax = float(np.abs(b).max())
            tol0 = checksum_tolerance(m // (h * mx), k // my, amax, bmax)
            # In-flight corruption of the DCN hop, struck on the
            # non-coordinator host: every pre-DCN stage is clean, so
            # tier-of-detection MUST be "global".
            res, report = fleet_tiered_ft_sgemm(
                a, b, c, mesh, tile, alpha=1.0, beta=-1.5,
                dcn_corrupt=(((h - 1, 0, 0), 3, 50.0 * tol0),),
                registry=registry)
            facts["dcn_tier"] = report.tier
            facts["dcn_residuals"] = {
                t: float(v) for t, v in report.residuals.items()}
            assert report.detected and report.tier == "global", report
            info["value"] = {"tier": report.tier}

    if ctx.rank == 0:
        with ctx.tl.span("merged_view", kind="stage") as info:
            from ft_sgemm_tpu.telemetry.aggregate import LiveAggregator
            from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

            agg = LiveAggregator()
            for r in range(ctx.nprocs):
                agg.add_shard(os.path.join(ctx.workdir, f"rank{r}",
                                           "events.jsonl"), host=r)
            # Ranks finish their phases at different moments; wait for
            # every rank's inject-all attribution to land.
            deadline = time.monotonic() + 120.0
            view = None
            while time.monotonic() < deadline:
                agg.poll()
                view = agg.fleet_view()
                if len(view["hosts"]) >= ctx.nprocs and \
                        len(view["devices"]) >= ndev:
                    break
                time.sleep(0.2)
            hosts = sorted(kk for kk in view["hosts"] if kk is not None)
            facts["merged_hosts"] = hosts
            facts["merged_devices"] = len(view["devices"])
            assert hosts == list(range(ctx.nprocs)), view["hosts"]

            # The cross-process localization, read from the MERGED view
            # of the localize shards: exactly one faulty device, on the
            # host inject_coords named, with its mesh coordinates.
            loc = LiveAggregator()
            for r in range(ctx.nprocs):
                loc.add_shard(os.path.join(ctx.workdir, f"rank{r}",
                                           "events_localize.jsonl"),
                              host=r)
            deadline = time.monotonic() + 120.0
            faulty = []
            while time.monotonic() < deadline:
                loc.poll()
                # Only rows with mesh coordinates are per-DEVICE
                # attributions; a clean rank's call event still carries
                # the global psum'd count as a synthetic mesh-label row.
                faulty = [((hh, dd), row) for (hh, dd), row
                          in loc.device_table()["devices"].items()
                          if row["detected"] > 0
                          and row.get("coords") is not None]
                if faulty:
                    break
                time.sleep(0.2)
            assert len(faulty) == 1, faulty
            (fh, fdev), frow = faulty[0]
            facts["localized"] = {"host": fh, "device": fdev,
                                  "coords": frow["coords"],
                                  "detected": frow["detected"]}
            assert fh == target[0], (fh, target)
            assert frow["coords"] == list(target), (frow, target)

            # The live merge feeds device_health for non-addressable
            # ranks: every faulty fleet device gets a tracked label.
            tracker = DeviceHealthTracker()
            agg.feed_health(tracker)
            covered = sorted(tracker.rows())
            facts["health_labels"] = covered
            assert any(lbl.startswith(f"host{ctx.nprocs - 1}:")
                       for lbl in covered), covered
            info["value"] = {"hosts": hosts,
                             "devices": facts["merged_devices"],
                             "localized": facts["localized"]}
    return facts


def run_counters(ctx: _Ctx) -> int:
    jax = _init_distributed(ctx)
    facts = _counters_phases(ctx, jax)
    facts.update({"ok": True, "rank": ctx.rank,
                  "process_count": jax.process_count()})
    ctx.write_result(facts)
    return 0


# ---------------------------------------------------------------------------
# The serve tier (smoke program)
# ---------------------------------------------------------------------------


class _PoolExecutor:
    """One rank's per-process pool: the local vdevs behind the
    device-level placement machinery, executing deterministic request
    specs (seed -> matrices) through the fused-ABFT kernel, verified
    against the numpy oracle before the reply leaves the rank."""

    def __init__(self, ctx: _Ctx, *, devices=None, bucket: int = 128):
        import jax

        from ft_sgemm_tpu.configs import KernelShape
        from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
        from ft_sgemm_tpu.serve.pool import DevicePool
        from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

        self.ctx = ctx
        self.bucket = int(bucket)
        devs = list(devices if devices is not None
                    else jax.local_devices()[:2])
        self.health = DeviceHealthTracker()
        self.pool = DevicePool(devs, health=self.health)
        tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
        self._fn = make_ft_sgemm(tile, alpha=1.0, beta=0.0)
        self._lock = threading.Lock()
        self._compiled: dict = {}
        self._served = 0
        self._served_detections = 0

    def _get_compiled(self, index: int, injected: bool):
        import jax

        from ft_sgemm_tpu.injection import InjectionSpec

        key = (index, injected)
        with self._lock:
            fn = self._compiled.get(key)
        if fn is not None:
            return fn
        spec = (InjectionSpec(enabled=True, every=1, magnitude=10000.0)
                if injected else None)
        fn = jax.jit(lambda a, b, c: self._fn(a, b, c, inject=spec))
        with self._lock:
            self._compiled[key] = fn
        return fn

    def run(self, spec: dict) -> dict:
        import jax
        import numpy as np

        from ft_sgemm_tpu.ops.common import gemm_cost_breakdown
        from ft_sgemm_tpu.perf.economics import gemm_request_cost
        from ft_sgemm_tpu.utils import verify_matrix

        trace_id = spec.get("trace_id")
        t_exec_start = time.time()
        rng = np.random.default_rng(int(spec.get("seed", 0)))
        nn = self.bucket
        a = rng.standard_normal((nn, nn), dtype=np.float32)
        b = rng.standard_normal((nn, nn), dtype=np.float32)
        c = np.zeros((nn, nn), np.float32)
        injected = bool(spec.get("inject")) or (
            spec.get("inject_host") is not None
            and int(spec["inject_host"]) == self.ctx.rank)
        force_retry = (spec.get("force_retry_host") is not None
                       and int(spec["force_retry_host"]) == self.ctx.rank)
        index = self.pool.choose()
        device = self.pool.devices[index]
        fn = self._get_compiled(index, injected)
        aj = jax.device_put(a, device)
        bj = jax.device_put(b, device)
        cj = jax.device_put(c, device)
        t0 = time.monotonic()
        retries = 0
        retry_detections = 0
        retry_seconds = 0.0
        if force_retry:
            # Deterministic detect->retry hop for the trace-join drill:
            # run the injected variant once, DISCARD the (corrected)
            # attempt as a detection would, and re-execute clean below.
            # The discarded attempt's wall and flops are the request's
            # retry overhead; its detections ride a separate reply key
            # so the coordinator's blame feed sees only real faults.
            bad = self._get_compiled(index, True)(aj, bj, cj)
            np.asarray(bad.c)
            retry_detections = int(bad.num_detected)
            retries = 1
            self.ctx.tl.point("fleet", f"rank{self.ctx.rank}:retry",
                              trace_id=trace_id,
                              detections=retry_detections)
            retry_t0 = time.monotonic()
        res = fn(aj, bj, cj)
        got = np.asarray(res.c)
        if force_retry:
            retry_seconds = time.monotonic() - retry_t0
        det = int(res.num_detected)
        unc = int(res.num_uncorrectable)
        want = (a.astype(np.float64) @ b.astype(np.float64).T).astype(
            np.float32)
        ok_v, _, _ = verify_matrix(want, got, verbose=False)
        self.pool.note_batch(index, 1)
        self.health.observe(self.pool.labels[index], calls=1,
                            detected=det, uncorrectable=unc)
        with self._lock:
            self._served += 1
            self._served_detections += det
        seconds = round(time.monotonic() - t0, 6)
        ok = bool(ok_v and unc == 0)
        if trace_id is not None:
            # The remote half of the cross-process trace join: the same
            # trace_id the coordinator stamped at submit, on this
            # rank's OWN timeline (merge_fleet stitches the flow).
            self.ctx.tl.point("fleet", f"rank{self.ctx.rank}:execute",
                              trace_id=trace_id, detections=det,
                              seconds=seconds,
                              device=self.pool.labels[index])
        # Request cost economics: the executor prices its own work with
        # the shared component cost model (fp32 operands, this pool's
        # kernel strategy) and ships the accounting home in the reply —
        # the coordinator's CostLedger never re-prices remote work.
        parts = gemm_cost_breakdown(nn, nn, nn, 4,
                                    block=(128, 128, 128),
                                    strategy="weighted")
        productive, overhead = gemm_request_cost(parts, retries=retries)
        return {"ok": ok, "correct": bool(ok_v),
                "detections": det, "uncorrectable": unc,
                "host": self.ctx.rank,
                "device": self.pool.labels[index],
                "seconds": seconds,
                "trace_id": trace_id,
                "t_exec_start": t_exec_start,
                "retries": retries,
                "retry_detections": retry_detections,
                "retry_seconds": round(retry_seconds, 6),
                "economics": {
                    "flops_productive": productive,
                    "overhead": overhead,
                    "tokens": nn,
                    "tokens_correct": nn if ok else 0,
                    "seconds": seconds}}

    def stats(self) -> dict:
        with self._lock:
            return {"served": self._served,
                    "detections": self._served_detections}


def _serve_remote(ctx: _Ctx, executor: _PoolExecutor) -> dict:
    """Non-coordinator serve loop: a JSON-lines TCP server over the
    rank's pool; runs until the coordinator sends ``{"op": "stop"}``."""
    stop = threading.Event()

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            line = self.rfile.readline()
            t_wire_recv = time.time()
            if not line:
                return
            try:
                spec = json.loads(line.decode("utf-8"))
            except json.JSONDecodeError:
                return
            if spec.get("op") == "stop":
                reply = {"ok": True, "op": "stop"}
                stop.set()
            else:
                reply = executor.run(spec)
            # The remote half of the NTP-midpoint clock handshake: this
            # rank's wall clock at wire receive and wire send ride every
            # reply; the caller (_remote_runner) holds the other two
            # timestamps and solves for skew + rtt per connection.
            reply["wire"] = {"t_wire_recv": t_wire_recv,
                             "t_wire_send": time.time(),
                             "t_exec_start": reply.get("t_exec_start")}
            self.wfile.write((json.dumps(reply) + "\n").encode("utf-8"))

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Handler)
    port = srv.server_address[1]
    with open(os.path.join(ctx.rankdir, "serve.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"port": port, "rank": ctx.rank}, fh)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="fleet-serve")
    t.start()
    ctx.tl.point("serve", f"rank{ctx.rank}:listening", port=port)
    deadline = time.monotonic() + float(ctx.args.get(
        "serve_deadline", 420.0))
    while not stop.is_set() and time.monotonic() < deadline:
        time.sleep(0.1)
    srv.shutdown()
    srv.server_close()
    return {"port": port, "stopped": stop.is_set(), **executor.stats()}


def _remote_runner(port: int):
    def run(spec: dict) -> dict:
        t_send = time.time()
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=120.0) as conn:
            conn.sendall((json.dumps(spec) + "\n").encode("utf-8"))
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        t_recv = time.time()
        reply = json.loads(buf.decode("utf-8"))
        wire = reply.get("wire")
        if isinstance(wire, dict):
            tr, tw = wire.get("t_wire_recv"), wire.get("t_wire_send")
            if isinstance(tr, (int, float)) and isinstance(
                    tw, (int, float)):
                # NTP midpoint: the remote clock's offset assuming the
                # two wire legs are symmetric — the estimate's error is
                # bounded by half the leg asymmetry (DESIGN.md §21).
                # Refreshed on EVERY connection; the dispatcher records
                # the latest as fleet_clock_skew_seconds{host=}.
                wire["skew_seconds"] = ((tr - t_send) + (tw - t_recv)) / 2.0
                # rtt = wire round trip minus the remote's hold time
                # (both differences on one clock each, so skew cancels).
                wire["rtt_seconds"] = max(
                    (t_recv - t_send) - (tw - tr), 0.0)
                texec = wire.get("t_exec_start")
                if isinstance(texec, (int, float)):
                    wire["remote_queue_seconds"] = max(texec - tr, 0.0)
        return reply

    return run


def _drive(dispatcher, n_requests: int, seed0: int,
           inject_host=None, force_retry_host=None,
           timeout: float = 240.0) -> dict:
    """Burst-submit ``n_requests`` specs, wait for every reply, return
    the phase stats (the drill's _drive_phase shape, fleet-side)."""
    t0 = time.monotonic()
    futs = [dispatcher.submit({"seed": seed0 + i,
                               "inject_host": inject_host,
                               "force_retry_host": force_retry_host})
            for i in range(n_requests)]
    first_ok = None
    correct = incorrect = retried = 0
    trace_ids: list = []
    by_host: dict = {}
    for fut in futs:
        reply = fut.result(timeout=timeout)
        hh = reply.get("host")
        by_host[hh] = by_host.get(hh, 0) + 1
        if reply.get("retries"):
            retried += 1
            if reply.get("trace_id"):
                trace_ids.append(reply["trace_id"])
        if reply.get("ok") and reply.get("correct"):
            correct += 1
            if first_ok is None:
                first_ok = time.monotonic()
        else:
            incorrect += 1
    wall = time.monotonic() - t0
    return {"submitted": n_requests, "correct": correct,
            "incorrect": incorrect, "by_host": by_host,
            "retried": retried, "retried_trace_ids": trace_ids[:8],
            "wall_seconds": round(wall, 3),
            "first_correct_ts": first_ok,
            "goodput_rps": round(correct / wall, 3) if wall > 0 else None}


def _wire_slots(ctx: _Ctx, executor: "_PoolExecutor"):
    """Build the dispatcher's host slots: rank 0 runs in-process, every
    other rank is reached over its published TCP serve port (waits for
    the rank's ``serve.json``)."""
    from ft_sgemm_tpu.fleet.dispatch import HostSlot

    slots = [HostSlot(host=0, runner=executor.run,
                      host_tier="local", dcn_distance=0.0)]
    ports = {}
    deadline = time.monotonic() + 180.0
    for r in range(1, ctx.nprocs):
        path = os.path.join(ctx.workdir, f"rank{r}", "serve.json")
        while time.monotonic() < deadline:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    ports[r] = json.load(fh)["port"]
                break
            except (OSError, json.JSONDecodeError, KeyError):
                time.sleep(0.1)
        if r not in ports:
            raise TimeoutError(f"rank{r} never published its port")
        slots.append(HostSlot(host=r, runner=_remote_runner(ports[r]),
                              host_tier="dcn", dcn_distance=1.0))
    return slots, ports


def _serve_coordinator(ctx: _Ctx, executor: _PoolExecutor, jax) -> dict:
    """Rank 0's serve acts: dispatch across per-process pools, blame
    the faulty host, evict it under load, reshard onto the survivor
    process, and measure goodput recovery."""
    import numpy as np

    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.fleet.dispatch import FleetDispatcher
    from ft_sgemm_tpu.resilience import (ElasticController,
                                         EvictionPolicy, surviving_mesh)
    from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

    facts: dict = {}
    n_req = int(ctx.args.get("requests", 24))
    faulty_host = ctx.nprocs - 1

    with ctx.tl.span("serve_wire", kind="stage") as info:
        slots, ports = _wire_slots(ctx, executor)
        info["value"] = {"ports": ports}

    fleet_health = DeviceHealthTracker()
    policy = EvictionPolicy(host_blame_limit=int(
        ctx.args.get("host_blame_limit", 3)))
    controller = ElasticController(policy, timeline=ctx.tl)
    registry = telemetry.get_registry()
    blamed: dict = {}
    blame_lock = threading.Lock()

    from ft_sgemm_tpu.perf.economics import CostLedger

    econ = CostLedger()

    def on_reply(host, spec, reply):
        if reply.get("detections", 0) > 0 or not reply.get("ok", False):
            controller.note_device_blame(host,
                                         reply.get("device", "unknown"))
            registry.counter("fleet_device_blames").inc()
            with blame_lock:
                blamed[host] = blamed.get(host, 0) + 1
        # The cost plane rides the same reply feed as blame: every
        # rank prices its own work (executor.run's economics block) and
        # the coordinator only aggregates.
        econ.merge_reply(reply.get("economics"),
                         device=reply.get("device"),
                         host=host, ok=bool(reply.get("ok")),
                         trace_id=reply.get("trace_id"))

    dispatcher = FleetDispatcher(slots, health=fleet_health,
                                 registry=registry, timeline=ctx.tl,
                                 on_reply=on_reply)
    try:
        with ctx.tl.span("serve_baseline", kind="stage") as info:
            base = _drive(dispatcher, n_req, seed0=1000)
            facts["baseline"] = base
            assert base["incorrect"] == 0, base
            assert len(base["by_host"]) == ctx.nprocs, base["by_host"]
            info["value"] = {"goodput_rps": base["goodput_rps"],
                             "by_host": base["by_host"]}

        with ctx.tl.span("serve_trace", kind="stage") as info:
            # The cross-process trace drill: forced detect->retry on the
            # remote rank so ONE trace_id flows coordinator submit ->
            # remote execute -> remote retry in the merged Perfetto
            # trace (ISSUE-20's flow-join acceptance). Discarded-attempt
            # detections ride a separate reply key, so the blame feed
            # stays quiet until the real fault phase below.
            tr = _drive(dispatcher, max(6, n_req // 3), seed0=3000,
                        force_retry_host=faulty_host)
            facts["trace"] = tr
            assert tr["incorrect"] == 0, tr
            assert tr["retried"] > 0, tr
            info["value"] = {"retried": tr["retried"],
                             "trace_ids": tr["retried_trace_ids"][:3]}

        with ctx.tl.span("serve_fault", kind="stage") as info:
            controller.mark_fault()
            # Injected (ABFT-corrected: still zero incorrect results)
            # faults on the non-coordinator host; its replies carry
            # detections, the blame feed accumulates on that host.
            rounds = 0
            decision = None
            while decision is None and rounds < 6:
                fault = _drive(dispatcher, max(6, n_req // 3),
                               seed0=5000 + 100 * rounds,
                               inject_host=faulty_host)
                facts["fault"] = fault
                assert fault["incorrect"] == 0, fault
                rounds += 1
                decision = controller.should_evict_host(
                    total_hosts=ctx.nprocs,
                    evicted_hosts=dispatcher.stats()["evicted_hosts"])
            assert decision is not None, (
                "blame never crossed the host_blame_limit",
                controller.host_blames(faulty_host))
            facts["eviction_decision"] = {"host": decision[0],
                                          "reason": decision[1]}
            facts["host_blames"] = controller.host_blames(faulty_host)
            assert decision[0] == faulty_host, decision
            info["value"] = facts["eviction_decision"]

        with ctx.tl.span("host_evict", kind="stage") as info:
            ev = dispatcher.evict_host(decision[0], reason=decision[1])
            controller.record_host_eviction(ev)
            facts["eviction"] = {kk: vv for kk, vv in ev.items()
                                 if kk != "ts"}
            assert ev["action"] == "evicted", ev
            info["value"] = facts["eviction"]

        with ctx.tl.span("host_reshard", kind="stage") as info:
            # Reshard the mesh paths onto the SURVIVOR processes: every
            # remaining device is addressable to them, so the rebuilt
            # mesh is immediately usable without the dead rank.
            t0 = time.monotonic()
            mesh2 = surviving_mesh(devices=list(jax.devices()),
                                   exclude_hosts=(decision[0],))
            survivors = [d for d in mesh2.devices.flat]
            assert all(d.process_index != decision[0]
                       for d in survivors), mesh2
            from ft_sgemm_tpu.configs import KernelShape
            from ft_sgemm_tpu.parallel import sharded_ft_sgemm
            from ft_sgemm_tpu.utils import (generate_random_matrix,
                                            verify_matrix)

            tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
            rng = np.random.default_rng(11)
            msz = 128 * mesh2.shape["x"]
            ksz = 128 * mesh2.shape["y"]
            aa = generate_random_matrix(msz, ksz, rng=rng)
            bb = generate_random_matrix(128, ksz, rng=rng)
            cc = np.zeros((msz, 128), np.float32)
            res = sharded_ft_sgemm(aa, bb, cc, mesh2, tile, alpha=1.0,
                                   beta=0.0)
            want = (aa.astype(np.float64) @ bb.astype(np.float64).T
                    ).astype(np.float32)
            ok_v, _, _ = verify_matrix(want, np.asarray(res.c),
                                       verbose=False)
            facts["reshard"] = {
                "devices": len(survivors),
                "mesh": dict(mesh2.shape),
                "seconds": round(time.monotonic() - t0, 3),
                "ok": bool(ok_v)}
            assert ok_v
            info["value"] = facts["reshard"]

        with ctx.tl.span("serve_recovery", kind="stage") as info:
            rec = _drive(dispatcher, n_req, seed0=9000)
            facts["recovery"] = rec
            assert rec["incorrect"] == 0, rec
            assert list(rec["by_host"]) == [0], rec["by_host"]
            mttr = (controller.mttr_seconds(rec["first_correct_ts"])
                    if rec["first_correct_ts"] else None)
            ratio = (round(rec["goodput_rps"] / base["goodput_rps"], 3)
                     if base["goodput_rps"] else None)
            facts["goodput_recovery_ratio"] = ratio
            facts["mttr_seconds"] = (round(mttr, 3)
                                     if mttr is not None else None)
            assert ratio is not None and ratio >= 0.7, ratio
            info["value"] = {"goodput_rps": rec["goodput_rps"],
                             "ratio": ratio}
    finally:
        for slot in slots[1:]:
            try:
                slot.runner({"op": "stop"})
            except OSError:
                pass
        dispatcher.stop()
    facts["dispatcher"] = dispatcher.stats()
    # Publish the aggregated cost view as live economics_* gauges (the
    # monitor /metrics + cli top feed) and keep the snapshot as a fact
    # — bench.py forwards it as the artifact's economics context block.
    facts["economics"] = econ.publish(registry)
    return facts


def _trace_coordinator(ctx: _Ctx, executor: _PoolExecutor) -> dict:
    """Rank 0's trace-drill acts: wire the TCP slots, drive one
    baseline burst and one forced detect->retry burst on the remote
    rank — just enough wire traffic for ``traceview.merge_fleet`` to
    join one trace_id across the process boundary. The tier-1 shape of
    the smoke program's serve acts (no eviction/reshard)."""
    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.fleet.dispatch import FleetDispatcher
    from ft_sgemm_tpu.perf.economics import CostLedger

    facts: dict = {}
    n_req = int(ctx.args.get("requests", 8))
    remote = ctx.nprocs - 1

    with ctx.tl.span("serve_wire", kind="stage") as info:
        slots, ports = _wire_slots(ctx, executor)
        info["value"] = {"ports": ports}

    registry = telemetry.get_registry()
    econ = CostLedger()

    def on_reply(host, spec, reply):
        econ.merge_reply(reply.get("economics"),
                         device=reply.get("device"),
                         host=host, ok=bool(reply.get("ok")),
                         trace_id=reply.get("trace_id"))

    dispatcher = FleetDispatcher(slots, registry=registry,
                                 timeline=ctx.tl, on_reply=on_reply)
    try:
        with ctx.tl.span("serve_baseline", kind="stage") as info:
            base = _drive(dispatcher, n_req, seed0=1000)
            facts["baseline"] = base
            assert base["incorrect"] == 0, base
            info["value"] = {"by_host": base["by_host"]}

        with ctx.tl.span("serve_trace", kind="stage") as info:
            tr = _drive(dispatcher, max(4, n_req // 2), seed0=3000,
                        force_retry_host=remote)
            facts["trace"] = tr
            assert tr["incorrect"] == 0, tr
            assert tr["retried"] > 0, tr
            info["value"] = {"retried": tr["retried"],
                             "trace_ids": tr["retried_trace_ids"][:3]}
    finally:
        for slot in slots[1:]:
            try:
                slot.runner({"op": "stop"})
            except OSError:
                pass
        dispatcher.stop()
    facts["dispatcher"] = dispatcher.stats()
    facts["economics"] = econ.publish(registry)
    return facts


def run_trace(ctx: _Ctx) -> int:
    """The cross-process trace-join drill: real jax.distributed ranks,
    the real TCP serve hop, one forced retry on the remote rank — the
    minimal program whose merged trace must show one trace_id flowing
    coordinator -> remote execute -> remote retry (tests/test_fleet.py
    runs it tier-1; the smoke program carries the full acceptance)."""
    jax = _init_distributed(ctx)
    with ctx.tl.span("serve_pool", kind="stage") as info:
        executor = _PoolExecutor(ctx)
        info["value"] = {"devices": list(executor.pool.labels)}
    from ft_sgemm_tpu import telemetry

    with telemetry.session(os.path.join(ctx.rankdir,
                                        "events_serve.jsonl")):
        if ctx.rank == 0:
            serve = _trace_coordinator(ctx, executor)
        else:
            serve = _serve_remote(ctx, executor)
    result = {"ok": True, "rank": ctx.rank,
              "process_count": jax.process_count(), "serve": serve}
    if ctx.rank == 0:
        disp = serve.get("dispatcher", {})
        skew = {str(h): row["clock_skew_seconds"]
                for h, row in (disp.get("per_host") or {}).items()
                if isinstance(row, dict) and isinstance(
                    row.get("clock_skew_seconds"), (int, float))}
        result["fleet"] = {
            "economics": serve.get("economics"),
            "clock_skew_seconds": skew,
            "trace_retried": serve.get("trace", {}).get("retried"),
            "trace_ids": serve.get("trace", {}).get("retried_trace_ids"),
        }
    ctx.write_result(result)
    return 0


def run_smoke(ctx: _Ctx) -> int:
    jax = _init_distributed(ctx)
    facts = _counters_phases(ctx, jax)
    with ctx.tl.span("serve_pool", kind="stage") as info:
        executor = _PoolExecutor(ctx)
        info["value"] = {"devices": list(executor.pool.labels)}
    from ft_sgemm_tpu import telemetry

    with telemetry.session(os.path.join(ctx.rankdir,
                                        "events_serve.jsonl")):
        if ctx.rank == 0:
            serve = _serve_coordinator(ctx, executor, jax)
        else:
            serve = _serve_remote(ctx, executor)
    result = {"ok": True, "rank": ctx.rank,
              "process_count": jax.process_count(), **facts,
              "serve": serve}
    if ctx.rank == 0:
        result["fleet"] = _fleet_facts(ctx, facts, serve)
    ctx.write_result(result)
    return 0


def _fleet_facts(ctx: _Ctx, facts: dict, serve: dict) -> dict:
    """The artifact context block bench.py --fleet ingests as fleet.*
    ledger measurements."""
    base = serve.get("baseline", {})
    rec = serve.get("recovery", {})
    disp = serve.get("dispatcher", {})
    skew = {str(h): row["clock_skew_seconds"]
            for h, row in (disp.get("per_host") or {}).items()
            if isinstance(row, dict) and isinstance(
                row.get("clock_skew_seconds"), (int, float))}
    return {
        "economics": serve.get("economics"),
        "clock_skew_seconds": skew,
        "trace_retried": serve.get("trace", {}).get("retried"),
        "trace_ids": serve.get("trace", {}).get("retried_trace_ids"),
        "processes": ctx.nprocs,
        "vdevs_per_process": ctx.vdevs,
        "staged_equals_flat": facts.get("staged_equals_flat"),
        "global_tier": facts.get("dcn_tier"),
        "global_tier_detections": int(facts.get("dcn_tier") == "global"),
        "localized": facts.get("localized"),
        "merged_hosts": facts.get("merged_hosts"),
        "goodput_pre_rps": base.get("goodput_rps"),
        "goodput_post_rps": rec.get("goodput_rps"),
        "goodput_recovery_ratio": serve.get("goodput_recovery_ratio"),
        "mttr_seconds": serve.get("mttr_seconds"),
        "incorrect_responses": (base.get("incorrect", 0)
                                + serve.get("fault", {}).get(
                                    "incorrect", 0)
                                + rec.get("incorrect", 0)),
        "evicted_host": serve.get("eviction", {}).get("host"),
        "eviction_action": serve.get("eviction", {}).get("action"),
        "host_blames": serve.get("host_blames"),
        "reshard": serve.get("reshard"),
    }


PROGRAMS = {"wedge": run_wedge, "noop": run_noop,
            "counters": run_counters, "smoke": run_smoke,
            "trace": run_trace}


def main() -> int:
    ctx = _Ctx()
    program = PROGRAMS.get(ctx.program)
    if program is None:
        ctx.write_result({"ok": False, "rank": ctx.rank,
                          "error": f"unknown program {ctx.program!r}"})
        return 2
    if ctx.program != "wedge":
        ctx.start_heartbeat()
    try:
        with ctx.tl.span(f"program:{ctx.program}", kind="stage"):
            return program(ctx)
    except BaseException as e:  # noqa: BLE001 — the rank's last words
        ctx.write_result({"ok": False, "rank": ctx.rank,
                          "error": f"{type(e).__name__}: {e}"})
        return 1
    finally:
        ctx.stop_heartbeat()
        ctx.tl.close()


if __name__ == "__main__":
    sys.exit(main())
