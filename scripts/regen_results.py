"""Regenerate the RESULTS.md "Run ledger" section from LEDGER.jsonl.

RESULTS.md's hand-written measurement narrative stays authoritative;
this script owns ONLY the auto-generated block between the
``<!-- ledger:begin -->`` / ``<!-- ledger:end -->`` markers (appended at
the end of the file if absent), so the perf trajectory — every banked
run including the null/killed ones, with delta-vs-previous-run columns —
is a committed, reviewable artifact that regenerates deterministically
from the ledger instead of drifting as prose.

Usage: python scripts/regen_results.py [LEDGER.jsonl] [RESULTS.md]
       [--check]     (exit 1 if RESULTS.md is stale, write nothing)

Jax-free: loads perf/ledger.py by file path (stdlib-only by contract).
"""

import importlib.util
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN = "<!-- ledger:begin -->"
END = "<!-- ledger:end -->"


def _load_ledger():
    path = os.path.join(_ROOT, "ft_sgemm_tpu", "perf", "ledger.py")
    spec = importlib.util.spec_from_file_location("_ft_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def render_section(entries, ledger_mod) -> str:
    """The markdown block between the markers: one table row per run in
    append order, headline + delta vs the previous run of the SAME
    (metric, platform) series, and the partial/kill/degradation notes
    that make the null-run sequence legible."""
    entries = ledger_mod.dedup_entries(entries)
    lines = [BEGIN,
             "## Run ledger (auto-generated — scripts/regen_results.py)",
             "",
             f"{len(entries)} runs in `LEDGER.jsonl`. `Δ prev` compares "
             "each run's headline to the previous run of the same "
             "(metric, platform) series; nulls propagate as `—`.",
             "",
             "| run | kind | platform | git rev | metric | value | "
             "Δ prev | notes |",
             "|---|---|---|---|---|---|---|---|"]
    last_by_series = {}
    for e in entries:
        p = e.get("platform") or {}
        plat = p.get("device_kind") or p.get("used") or "?"
        metric = e.get("metric") or "-"
        val = e.get("value")
        series = (metric, plat)
        delta = "—"
        prev = last_by_series.get(series)
        if isinstance(val, (int, float)):
            if isinstance(prev, (int, float)) and prev:
                delta = f"{100 * (val - prev) / abs(prev):+.1f}%"
            last_by_series[series] = val
        shown = (f"{val:.1f} {e.get('unit') or ''}".rstrip()
                 if isinstance(val, (int, float)) else "null")
        notes = []
        if e.get("partial"):
            notes.append("PARTIAL@" + (e.get("killed_at_stage")
                                       or "?"))
        notes += [d for d in (e.get("degradations") or [])
                  if not d.startswith("partial:")][:2]
        lines.append(
            f"| {e.get('run_id') or '?'} | {e.get('kind') or '?'} "
            f"| {plat} | {(e.get('git_rev') or '?')[:12]} | {metric} "
            f"| {shown} | {delta} | {'; '.join(notes) or ' '} |")
    lines.append(END)
    return "\n".join(lines)


def splice(text: str, section: str) -> str:
    if BEGIN in text and END in text:
        head, rest = text.split(BEGIN, 1)
        _, tail = rest.split(END, 1)
        return head + section + tail
    if not text.endswith("\n"):
        text += "\n"
    return text + "\n" + section + "\n"


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    flags = [a for a in argv if a.startswith("--")]
    args = [a for a in argv if not a.startswith("--")]
    check = "--check" in flags
    ledger_path = args[0] if args else os.path.join(_ROOT, "LEDGER.jsonl")
    results_path = args[1] if len(args) > 1 else os.path.join(
        _ROOT, "RESULTS.md")
    ledger = _load_ledger()
    try:
        entries = ledger.read_ledger(ledger_path)
    except OSError as e:
        print(f"cannot read ledger: {e}", file=sys.stderr)
        return 2
    section = render_section(entries, ledger)
    try:
        with open(results_path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        text = ""
    new = splice(text, section)
    if check:
        if new != text:
            print(f"{results_path} is stale vs {ledger_path} "
                  "(run scripts/regen_results.py)", file=sys.stderr)
            return 1
        print(f"{results_path} is current")
        return 0
    if new != text:
        with open(results_path, "w", encoding="utf-8") as fh:
            fh.write(new)
        print(f"wrote ledger section ({len(entries)} runs) to"
              f" {results_path}")
    else:
        print(f"{results_path} already current")
    return 0


if __name__ == "__main__":
    sys.exit(main())
