"""Ingest bench/serve artifacts into the longitudinal run ledger.

The jax-free seeder/CI half of ``ft_sgemm_tpu/perf/ledger.py``: reads
each artifact (emitted bench line, driver wrapper with ``parsed``,
multichip probe, baseline doc — null and partial ones included) and
appends one distilled row per file to the ledger JSONL. Never fails on
hostile input: a run that measured nothing lands as a row whose
``degradations`` list names why — that sequence IS the observability
(BENCH_r01–r05 are the expected diet).

The committed ``LEDGER.jsonl`` at the repo root was seeded with::

    python scripts/ingest_ledger.py LEDGER.jsonl \
        BENCH_r0*.json MULTICHIP_r0*.json BASELINE*.json

and CI re-seeds a scratch copy from it, ingests the fresh smoke/serve
artifacts, and runs ``cli trend --gate`` over the result.

Usage: python scripts/ingest_ledger.py LEDGER.jsonl ARTIFACT.json...
       [--run-id=ID]   (single artifact only)

Loads the ledger module by file path (stdlib-only by contract), so this
script runs in any process — including ones that must never import jax.
"""

import importlib.util
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_ledger():
    path = os.path.join(_ROOT, "ft_sgemm_tpu", "perf", "ledger.py")
    spec = importlib.util.spec_from_file_location("_ft_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    args = [a for a in argv if not a.startswith("--")]
    flags = [a for a in argv if a.startswith("--")]
    if len(args) < 2:
        print(__doc__)
        return 2
    run_id = None
    for f in flags:
        if f.startswith("--run-id="):
            run_id = f.split("=", 1)[1]
        else:
            print(f"unknown flag {f!r}", file=sys.stderr)
            return 2
    ledger_path, artifacts = args[0], args[1:]
    if run_id is not None and len(artifacts) > 1:
        print("--run-id= only applies to a single artifact",
              file=sys.stderr)
        return 2
    ledger = _load_ledger()
    for path in artifacts:
        entry = ledger.ingest_file(path, run_id=run_id)
        ledger.append(ledger_path, entry)
        deg = entry.get("degradations") or []
        print(f"ingested {entry['run_id']} ({entry['kind']}) from"
              f" {os.path.basename(path)}"
              + (f"  [{'; '.join(deg[:2])}]" if deg else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
