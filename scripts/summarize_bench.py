"""Summarize banked bench records into a RESULTS-ready table.

Reads `.bench/records_*.jsonl` (the fsync'd stage records bench.py's
worker appends; see bench.py's module docstring) and prints, per records
file: the backend identity, every measured stage with GFLOPS and derived
ratios, and the errors — so a scarce tunnel window's yield can be read
(and pasted into RESULTS.md) at a glance.

Also accepts emitted bench ARTIFACTS (the one-line ``{"metric": ...}``
JSON object bench.py prints): the row shows metric/value/vs_baseline,
and a salvaged partial run (``context.partial: true`` — the supervisor
promoted the best completed stage after a deadline kill) is annotated
PARTIAL@<killed_at_stage> with its completed-stage list instead of
being mistaken for a full sweep.

When a run ledger is available (``--ledger=PATH``, default the repo's
committed ``LEDGER.jsonl``), each artifact row also gets a
delta-vs-previous-ledger-run column: the headline compared to the last
non-null value of the same (metric, platform) series — the one-glance
"did this window move the number" view. ``--ledger=`` with a missing
file (or no committed ledger) degrades to no delta column, never an
error.

Usage: python scripts/summarize_bench.py [records.jsonl|artifact.json ...]
       [--ledger=LEDGER.jsonl]
(defaults to every .bench/records_*.jsonl, newest first)
"""

import glob
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path):
    """Parse a records file with bench.py's own loader — the canonical
    semantics (later lines win, ok pops a stale error, torn writes and
    stray lines skipped, errors="replace" decoding) live there."""
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench._read_records(path)


# Stages whose value is a plain number but NOT a GFLOPS reading.
_SCALAR_STAGES = {"injected_faults_per_tile"}
# bf16 stages compare against bf16_xla, not the f32 xla_dot.
_BF16_STAGES = {"bf16_plain", "bf16_abft", "bf16_fused", "bf16_xla"}


def _fmt(v, name=""):
    if isinstance(v, dict):
        g = v.get("gflops")
        s = v.get("strategy")
        if g is not None:
            return f"{g:10.1f} GFLOPS" + (f"  [{s}]" if s else "")
        return json.dumps(v)
    if isinstance(v, (int, float)):
        if name in _SCALAR_STAGES:
            return f"{v:10g}"
        return f"{v:10.1f} GFLOPS"
    return str(v)


_LEDGER_MOD = None


def _load_ledger_mod():
    global _LEDGER_MOD
    if _LEDGER_MOD is None:
        spec = importlib.util.spec_from_file_location(
            "_ft_ledger",
            os.path.join(_ROOT, "ft_sgemm_tpu", "perf", "ledger.py"))
        _LEDGER_MOD = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_LEDGER_MOD)
    return _LEDGER_MOD


def _load_ledger_entries(path):
    """Deduplicated ledger entries, or None when no ledger is readable
    (the no-delta-column degradation, never an error)."""
    try:
        mod = _load_ledger_mod()
        return mod.dedup_entries(mod.read_ledger(path))
    except (OSError, ValueError):
        return None


def _ledger_delta(entries, obj):
    """(delta_fraction, prev_run_id) of this artifact's headline vs the
    last non-null ledger value of the same (metric, platform) series, or
    None when either side is null/absent."""
    if not entries:
        return None
    mod = _load_ledger_mod()
    probe = mod.ingest(obj, run_id="_probe")
    val = probe.get("value")
    if not isinstance(val, (int, float)):
        return None
    if probe.get("metric") == "bench_smoke":
        return None  # the smoke headline is a 0/1 ok flag, not a measure
    key = (probe.get("metric"), mod.platform_key(probe).split("/")[-1])
    for e in reversed(entries):
        prev = e.get("value")
        if ((e.get("metric"),
             mod.platform_key(e).split("/")[-1]) == key
                and isinstance(prev, (int, float)) and prev):
            return (val - prev) / abs(prev), e.get("run_id")
    return None


def _try_artifact(path):
    """Parse ``path`` as an emitted bench artifact; None when it is a
    records file (JSONL stage records have no top-level "metric")."""
    try:
        with open(path, errors="replace") as f:
            obj = json.loads(f.read())
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) and "metric" in obj else None


def summarize_artifact(path, obj, ledger_entries=None):
    ctx = obj.get("context") or {}
    print(f"== {os.path.basename(path)} (bench artifact)")
    v = obj.get("value")
    vs = obj.get("vs_baseline")
    line = (f"   {obj.get('metric', '?'):34s} "
            + (f"{v:10.1f} {obj.get('unit', '')}" if isinstance(
                v, (int, float)) else f"{'null':>10s}"))
    if isinstance(vs, (int, float)):
        line += f"  (x{vs:.3f} vs baseline)"
    delta = _ledger_delta(ledger_entries, obj)
    if delta is not None:
        line += f"  (Δ {100 * delta[0]:+.1f}% vs ledger run {delta[1]})"
    if ctx.get("partial"):
        # The kill stage rides the row itself: a PARTIAL row pasted in
        # isolation must still say where the run died.
        line += ("  PARTIAL@" + (ctx.get("killed_at_stage") or "?")
                 + " (salvaged from a killed run)")
    print(line)
    if ctx.get("partial"):
        if ctx.get("killed_at_stage"):
            print(f"   {'killed during':34s} {ctx['killed_at_stage']}")
        done = ctx.get("completed_stages")
        if done:
            print(f"   {'completed stages':34s} {', '.join(done)}")
    slo = ctx.get("slo")
    if isinstance(slo, dict):
        # Serving artifacts carry the final SLO/error-budget + health
        # snapshot (telemetry/monitor.py) — the fleet-facing numbers.
        status = slo.get("status", "?")
        reasons = slo.get("reasons") or []
        print(f"   {'slo status':34s} {status}"
              + ("  (" + "; ".join(str(r) for r in reasons) + ")"
                 if reasons else ""))
        budget = slo.get("budget_remaining")
        burn = slo.get("burn_rate")
        if budget is not None or burn is not None:
            print(f"   {'slo error budget':34s} "
                  f"remaining {budget if budget is not None else '?'}"
                  f"  burn {burn if burn is not None else '?'}x")
        hmin = slo.get("device_health_min")
        if hmin is not None:
            worst = ""
            dh = slo.get("device_health") or {}
            if dh:
                dev = min(dh, key=dh.get)
                worst = f"  (worst: {dev})"
            print(f"   {'device health min':34s} {hmin}{worst}")
    rec = ctx.get("recovery")
    if isinstance(rec, dict):
        # Elastic-recovery drill facts (resilience/elastic.py): the
        # eviction row — who was evicted, how fast service recovered,
        # and how cheap the recompute ladder ran.
        print(f"   {'eviction':34s} "
              f"{rec.get('evicted_device') or 'none'}"
              f"  (reason {rec.get('reason') or '?'}; migrated "
              f"{rec.get('migrated_batches', 0)} queued)")
        mttr = rec.get("mttr_seconds")
        ratio = rec.get("goodput_recovery_ratio")
        print(f"   {'recovery':34s} "
              f"mttr {mttr if mttr is not None else '?'}s  goodput "
              f"x{ratio if ratio is not None else '?'} of pre-fault  "
              f"incorrect {rec.get('incorrect_responses', '?')}")
        tiers = rec.get("tier_detections")
        if isinstance(tiers, dict):
            td = "  ".join(f"{t}={n}" for t, n in tiers.items())
            print(f"   {'checksum tiers':34s} {td}")
        flops = rec.get("panel_recompute_flops_ratio")
        if flops is not None:
            print(f"   {'panel recompute flops':34s} "
                  f"{flops} of full retry")
    chaos = ctx.get("chaos")
    if isinstance(chaos, dict) and isinstance(chaos.get("models"), dict):
        # Chaos campaign coverage (ft_sgemm_tpu/chaos): one row per
        # fault model — detection rate, p95 detection latency, MTTR,
        # and the MTBF-derived policy verdict.
        def _r(v, pat="{:.2f}"):
            return pat.format(v) if isinstance(v, (int, float)) else "-"

        for name, m in chaos["models"].items():
            if not isinstance(m, dict):
                continue
            roll = m.get("rollup") or {}
            pol = m.get("policy") or {}
            verdict = (f"every={pol.get('check_every', '?')}"
                       f"/{pol.get('threshold_mode', '?')}"
                       + ("/evict" if pol.get("evict") else ""))
            print(f"   {'chaos ' + name:34s} "
                  f"det {_r(roll.get('detection_rate'))}"
                  f"  p95 "
                  f"{_r(roll.get('p95_detection_latency_seconds'), '{:.4f}')}s"
                  f"  mttr {_r(roll.get('mttr_seconds'), '{:.3f}')}s"
                  f"  fp {_r(roll.get('false_positive_rate'))}"
                  f"  policy {verdict}")
    econ = ctx.get("economics")
    if not isinstance(econ, dict) and isinstance(ctx.get("fleet"), dict):
        econ = ctx["fleet"].get("economics")
    if isinstance(econ, dict):
        # Request cost economics (perf/economics.py): the useful-vs-
        # overhead flops split and the correct-token throughput.
        def _e(v, pat="{:.4f}"):
            return pat.format(v) if isinstance(v, (int, float)) else "-"

        print(f"   {'economics useful flops':34s} "
              f"{_e(econ.get('useful_flops_fraction'))}"
              f"  of {_e(econ.get('flops_total'), '{:.4g}')} total"
              f"  ({econ.get('requests', '?')} requests)")
        fracs = econ.get("overhead_fractions")
        if isinstance(fracs, dict):
            bits = "  ".join(
                f"{c}={_e(v)}" for c, v in sorted(fracs.items())
                if isinstance(v, (int, float)) and v)
            if bits:
                print(f"   {'economics overhead':34s} {bits}")
        tcs = econ.get("tokens_correct_per_second_per_device")
        if tcs is not None:
            print(f"   {'tokens-correct/s/device':34s} {_e(tcs, '{:.3f}')}"
                  f"  ({econ.get('tokens_correct', '?')} correct of "
                  f"{econ.get('tokens', '?')})")
    disp = (ctx.get("fleet") or {}).get("dispatcher") \
        if isinstance(ctx.get("fleet"), dict) else None
    if isinstance(disp, dict) and isinstance(disp.get("per_host"), dict):
        # Fleet hop decomposition + measured clock skew per host
        # (fleet/dispatch.py stats()).
        for h, row in sorted(disp["per_host"].items(),
                             key=lambda kv: str(kv[0])):
            if not isinstance(row, dict):
                continue
            skew = row.get("clock_skew_seconds")
            pcts = row.get("hop_percentiles") or {}
            bits = "  ".join(
                f"{name}[p95]={p.get('p95'):.4g}s"
                for name, p in sorted(pcts.items())
                if isinstance(p, dict)
                and isinstance(p.get("p95"), (int, float)))
            print(f"   {'fleet host ' + str(h):34s} "
                  f"reqs {row.get('requests', '?')}"
                  + (f"  skew {skew:+.4f}s"
                     if isinstance(skew, (int, float)) else "")
                  + (f"  {bits}" if bits else ""))
    for name, e in (ctx.get("errors") or {}).items():
        first = str(e).splitlines()[0] if e else ""
        print(f"   {name:34s} ERROR: {first[:90]}")
    print()


def summarize(path, ledger_entries=None):
    artifact = _try_artifact(path)
    if artifact is not None:
        summarize_artifact(path, artifact, ledger_entries=ledger_entries)
        return
    vals, errs = _load(path)
    print(f"== {os.path.basename(path)}")
    backend = vals.get("backend")
    if backend:
        print(f"   backend: {backend}")
    ratio_base = vals.get("xla_dot")
    for name, v in vals.items():
        # Tombstones (backend_guard/worker_crash "cleared: ..." markers)
        # are provenance, not measurements.
        if name in ("backend", "_reset_token", "backend_guard",
                    "worker_crash"):
            continue
        line = f"   {name:34s} {_fmt(v, name)}"
        g = v.get("gflops") if isinstance(v, dict) else (
            v if isinstance(v, (int, float)) else None)
        if (g and isinstance(ratio_base, (int, float)) and ratio_base
                and name != "xla_dot"
                and name not in _SCALAR_STAGES
                and name not in _BF16_STAGES):
            line += f"  ({g / ratio_base * 100:5.1f}% of xla_dot)"
        print(line)
    bf = vals.get("bf16_xla")
    for name in ("bf16_plain", "bf16_abft", "bf16_fused"):
        v = vals.get(name)
        if isinstance(v, (int, float)) and isinstance(bf, (int, float)) and bf:
            print(f"   {name + ' vs bf16 dot':34s} {v / bf * 100:9.1f}%")
    for name, e in errs.items():
        first = str(e).splitlines()[0] if e else ""
        print(f"   {name:34s} ERROR: {first[:90]}")
    print()


def main():
    argv = sys.argv[1:]
    ledger_path = os.path.join(_ROOT, "LEDGER.jsonl")
    paths = []
    for a in argv:
        if a.startswith("--ledger="):
            ledger_path = a.split("=", 1)[1]
        else:
            paths.append(a)
    paths = paths or sorted(
        glob.glob(os.path.join(_ROOT, ".bench", "records_*.jsonl")),
        key=os.path.getmtime, reverse=True)
    if not paths:
        print("no records files found under .bench/")
        return 1
    ledger_entries = _load_ledger_entries(ledger_path)
    for p in paths:
        summarize(p, ledger_entries=ledger_entries)
    return 0


if __name__ == "__main__":
    sys.exit(main())
