"""Tile-size sweep on real TPU: find the fastest (bm, bn, bk) per kernel.

Sweeps the plain and fused-ABFT (weighted + rowcol) kernels at M=N=K=4096
and prints GFLOPS per candidate block tile, sorted. Used to pick the
shipped SHAPES; not part of the package surface.

Usage: python scripts/tune_tiles.py [size] [--ft] [--rowcol] [--bf16]
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")

from ft_sgemm_tpu.configs import KernelShape, vmem_limit_bytes  # noqa: E402
from ft_sgemm_tpu.injection import InjectionSpec  # noqa: E402
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm  # noqa: E402
from ft_sgemm_tpu.ops.sgemm import make_sgemm  # noqa: E402
from ft_sgemm_tpu.ops.vmem import MIB, estimate_vmem_bytes  # noqa: E402
from ft_sgemm_tpu.utils.matrices import generate_random_matrix  # noqa: E402
from ft_sgemm_tpu.utils.timing import bench_seconds_per_call  # noqa: E402

SIZE = 4096
CANDIDATES = [
    (512, 512, 256),
    (512, 512, 512),
    (512, 1024, 256),
    (512, 1024, 512),
    (512, 768, 256),
    (768, 512, 256),
    (512, 1536, 256),
    (384, 1024, 256),
    (256, 1024, 512),
    (512, 768, 512),
    (768, 512, 512),
    (384, 512, 512),
    # Enabled by accumulate-in-out_ref (no bm*bn*4 acc scratch): bigger
    # square output tiles amortize the FT checksum VPU work (encode cost
    # per FLOP ~ 1/bm + 1/bn).
    (512, 512, 1024),
    (768, 768, 512),
    (1024, 512, 256),
    (1024, 1024, 256),
    # Enabled by vmem_limit_bytes=64MiB (the 16MiB default rejected
    # these): deeper K amortizes the FT check epilogues further.
    (512, 512, 2048),
    (1024, 1024, 512),
    (1024, 512, 512),
]


BF16_EXTRA = [
    # bf16 halves the A/B tile bytes; deeper/wider tiles fit VMEM.
    # ((512, 512, 1024) moved into the shared CANDIDATES list.)
    (512, 1024, 1024),
    (1024, 512, 512),
    (512, 2048, 256),
    (1024, 1024, 512),
    (512, 512, 2048),
    # Square-tile family freed up by dropping the acc scratch.
    (1024, 1024, 1024),
    (768, 768, 768),
    (1024, 768, 512),
    (768, 1024, 512),
    (1536, 512, 512),
    (512, 1536, 512),
]


def main():
    size = SIZE
    for tok in sys.argv[1:]:
        if tok.isdigit():
            size = int(tok)
    do_ft = "--ft" in sys.argv
    do_rowcol = "--rowcol" in sys.argv
    strategy_flag = next((t.split("=", 1)[1] for t in sys.argv
                          if t.startswith("--strategy=")), None)
    if strategy_flag is not None:
        from ft_sgemm_tpu.ops.ft_sgemm import STRATEGIES

        if strategy_flag not in STRATEGIES:
            sys.exit(f"--strategy must be one of {STRATEGIES}, got"
                     f" {strategy_flag!r}")
    in_dtype = "bfloat16" if "--bf16" in sys.argv else "float32"
    candidates = CANDIDATES + (BF16_EXTRA if in_dtype == "bfloat16" else [])

    rng = np.random.default_rng(10)
    a = jax.device_put(generate_random_matrix(size, size, rng=rng))
    b = jax.device_put(generate_random_matrix(size, size, rng=rng))
    c = jax.device_put(generate_random_matrix(size, size, rng=rng))
    flop = 2.0 * size**3

    # Pre-filter by the calibrated VMEM-footprint estimator: a candidate
    # predicted over the Mosaic budget would burn scarce tunnel-window
    # seconds dying inside the compiler (explicit KernelShapes are
    # deliberately never auto-shrunk — the row label must be the measured
    # tile). Logged, not silent: the sweep's output says exactly which
    # tiles were skipped and why. The variant mirrors make_ft_sgemm's
    # resolve_cadence decision at the swept settings: no explicit
    # check_every means the weighted strategy takes its single-final-
    # check default, i.e. the lighter precomp body (the injection clamp
    # cannot drop the cadence below nk here: bn*every >= 128 > nk at
    # every swept size).
    variant = (strategy_flag if strategy_flag
               else "rowcol" if do_rowcol
               else "weighted" if do_ft else "plain")
    if variant == "weighted":
        variant = "weighted_precomp"
    limit = vmem_limit_bytes()
    itemsize = 2 if in_dtype == "bfloat16" else 4

    results = []
    for bm, bn, bk in candidates:
        shape = KernelShape(f"t{bm}x{bn}x{bk}", bm, bn, bk, (0,) * 7)
        est = estimate_vmem_bytes(shape, variant, in_itemsize=itemsize)
        if est > limit:
            print(f"{shape.name:18s} SKIPPED: predicted ~{est / MIB:.1f}"
                  f" MiB scoped VMEM > {limit / MIB:.0f} MiB limit")
            continue
        try:
            if do_ft or do_rowcol or strategy_flag:
                strat = (strategy_flag if strategy_flag
                         else "rowcol" if do_rowcol else "weighted")
                inj = InjectionSpec.reference_like(size, bk)
                ft = make_ft_sgemm(shape, alpha=1.0, beta=-1.5, strategy=strat,
                                   in_dtype=in_dtype)
                fn = lambda a, b, x: ft(a, b, x, inj).c  # noqa: E731
            else:
                fn = make_sgemm(shape, alpha=1.0, beta=-1.5, in_dtype=in_dtype)
            sec = bench_seconds_per_call(fn, a, b, c, min_device_time=1.0)
            gf = flop / 1e9 / sec
        except Exception as e:  # noqa: BLE001 - sweep must survive bad tiles
            print(f"{shape.name:18s} FAILED: {type(e).__name__}: {str(e)[:120]}")
            continue
        results.append((gf, shape.name))
        print(f"{shape.name:18s} {gf:9.1f} GFLOPS", flush=True)

    print("\nbest first:")
    for gf, name in sorted(results, reverse=True):
        print(f"  {name:18s} {gf:9.1f}")


if __name__ == "__main__":
    main()
