"""Live-TPU validation: Mosaic compile + correctness + quick GFLOPS.

Run directly on a machine with a TPU attached (uses whatever platform the
environment provides). The pytest suite never requires a TPU; this script is
the hardware gate.

Usage: python scripts/validate_tpu.py [size] [--full]
"""

import sys
import time

import numpy as np
import jax

sys.path.insert(0, ".")

from ft_sgemm_tpu import (  # noqa: E402
    InjectionSpec,
    SHAPES,
    make_ft_sgemm,
    make_sgemm,
    sgemm_reference,
)
from ft_sgemm_tpu.configs import SHAPE_ORDER  # noqa: E402
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix  # noqa: E402
from ft_sgemm_tpu.utils.timing import gflops, time_fn  # noqa: E402

ALPHA, BETA = 1.0, -1.5


def main():
    size = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 2048
    full = "--full" in sys.argv
    print(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.default_rng(10)
    # Device-resident inputs: timing must not include host->device transfer
    # (the reference times kernels on device-resident buffers too,
    # sgemm.cu:69-96 H2D happens once before the perf loop).
    a = jax.device_put(generate_random_matrix(size, size, rng=rng))
    b = jax.device_put(generate_random_matrix(size, size, rng=rng))
    c = jax.device_put(generate_random_matrix(size, size, rng=rng))

    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    t = time_fn(lambda: sgemm_reference(a, b, c, ALPHA, BETA))
    xla_gf = gflops(size, size, size, t)
    print(f"{'xla_dot':28s} {xla_gf:9.1f} GFLOPS")

    shapes = SHAPE_ORDER if full else ("huge",)
    for name in shapes:
        fn = make_sgemm(name, alpha=ALPHA, beta=BETA)
        got = np.asarray(fn(a, b, c))
        ok, nbad, _ = verify_matrix(want, got, verbose=False)
        t = time_fn(lambda: fn(a, b, c))
        gf = gflops(size, size, size, t)
        print(f"{'sgemm_' + name:28s} {gf:9.1f} GFLOPS  "
              f"verify={'OK' if ok else f'FAIL({nbad})'}  "
              f"({gf / xla_gf * 100:5.1f}% of XLA)")

    for strategy in (("rowcol", "global", "weighted") if full else ("rowcol",)):
        for name in shapes:
            shape = SHAPES[name]
            inj = InjectionSpec.reference_like(size, shape.bk)
            fn = make_ft_sgemm(name, alpha=ALPHA, beta=BETA, strategy=strategy)
            res = fn(a, b, c, inject=inj)
            got = np.asarray(res.c)
            ok, nbad, _ = verify_matrix(want, got, verbose=False)
            if strategy == "global":
                ok_str = f"detect-only det={int(res.num_detected)}"
            else:
                ok_str = (f"verify={'OK' if ok else f'FAIL({nbad})'} "
                          f"det={int(res.num_detected)}")
            t = time_fn(lambda: fn(a, b, c, inject=inj))
            gf = gflops(size, size, size, t)
            print(f"{'ft_sgemm_' + name + ':' + strategy:28s} {gf:9.1f} GFLOPS  "
                  f"{ok_str}  ({gf / xla_gf * 100:5.1f}% of XLA)")


if __name__ == "__main__":
    main()
