"""Live-TPU validation: Mosaic compile + correctness + quick GFLOPS.

Run directly on a machine with a TPU attached (uses whatever platform the
environment provides). The pytest suite never requires a TPU; this script is
the hardware gate.

Usage: python scripts/validate_tpu.py [size] [--full] [--bf16]

``--bf16`` additionally validates the bf16 input mode against the XLA dot
over the same bf16-rounded inputs (full-rate MXU path).
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")

from ft_sgemm_tpu import (  # noqa: E402
    InjectionSpec,
    SHAPES,
    make_ft_sgemm,
    make_sgemm,
    sgemm_reference,
)
from ft_sgemm_tpu.configs import SHAPE_ORDER  # noqa: E402
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix  # noqa: E402
from ft_sgemm_tpu.utils.timing import bench_seconds_per_call, gflops  # noqa: E402


def _gf(fn, a, b, c, size):
    # Chained-rep timing (rep loop inside jit): through the axon tunnel a
    # single dispatch is dominated by ~50ms roundtrip latency and under-
    # reports GFLOPS by ~15x; bench_seconds_per_call cancels it. reps=1:
    # it returns seconds per single call (gflops' default reps=5 pairs with
    # time_fn's 5-rep loop, not with this timer).
    return gflops(size, size, size,
                  bench_seconds_per_call(fn, a, b, c, min_device_time=1.0),
                  reps=1)

ALPHA, BETA = 1.0, -1.5


def main():
    size = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 2048
    full = "--full" in sys.argv
    print(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.default_rng(10)
    # Device-resident inputs: timing must not include host->device transfer
    # (the reference times kernels on device-resident buffers too,
    # sgemm.cu:69-96 H2D happens once before the perf loop).
    a = jax.device_put(generate_random_matrix(size, size, rng=rng))
    b = jax.device_put(generate_random_matrix(size, size, rng=rng))
    c = jax.device_put(generate_random_matrix(size, size, rng=rng))

    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    xla_gf = _gf(lambda a, b, x: sgemm_reference(a, b, x, ALPHA, BETA),
                 a, b, c, size)
    print(f"{'xla_dot':28s} {xla_gf:9.1f} GFLOPS")

    shapes = SHAPE_ORDER if full else ("huge",)
    for name in shapes:
        fn = make_sgemm(name, alpha=ALPHA, beta=BETA)
        got = np.asarray(fn(a, b, c))
        ok, nbad, _ = verify_matrix(want, got, verbose=False)
        gf = _gf(fn, a, b, c, size)
        print(f"{'sgemm_' + name:28s} {gf:9.1f} GFLOPS  "
              f"verify={'OK' if ok else f'FAIL({nbad})'}  "
              f"({gf / xla_gf * 100:5.1f}% of XLA)")

    # "weighted" always runs: its default cadence routes to the
    # precomputed-checksum kernel, which must Mosaic-compile every round.
    for strategy in (("rowcol", "global", "weighted", "fused") if full
                     else ("rowcol", "weighted", "fused")):
        for name in shapes:
            shape = SHAPES[name]
            inj = InjectionSpec.reference_like(size, shape.bk)
            fn = make_ft_sgemm(name, alpha=ALPHA, beta=BETA, strategy=strategy)
            res = fn(a, b, c, inject=inj)
            got = np.asarray(res.c)
            ok, nbad, _ = verify_matrix(want, got, verbose=False)
            if strategy == "global":
                ok_str = f"detect-only det={int(res.num_detected)}"
            else:
                ok_str = (f"verify={'OK' if ok else f'FAIL({nbad})'} "
                          f"det={int(res.num_detected)}"
                          f" unc={int(res.num_uncorrectable)}")
            gf = _gf(lambda a, b, x: fn(a, b, x, inject=inj).c, a, b, c, size)
            print(f"{'ft_sgemm_' + name + ':' + strategy:28s} {gf:9.1f} GFLOPS  "
                  f"{ok_str}  ({gf / xla_gf * 100:5.1f}% of XLA)")

    # Adaptive thresholds: the traced noise-bound estimator + runtime SMEM
    # threshold scalars must compile and catch tiny (magnitude-5) faults
    # the fixed 9500 threshold is blind to.
    inj_tiny = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    fn_auto = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA,
                            strategy="weighted", threshold="auto")
    res = fn_auto(a, b, c, inject=inj_tiny)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    print(f"{'ft_huge:weighted:auto-thr':28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(res.num_detected)} unc={int(res.num_uncorrectable)} "
          f"(magnitude-5 faults)")

    # Multi-fault rowcol (forced): the weighted-column-checksum variant
    # whose kernel body differs from the auto-skipped path; must Mosaic-
    # compile and correct a coarse-cadence fault backlog on hardware.
    inj_mf = InjectionSpec.reference_like(size, SHAPES["huge"].bk)
    fn_mf = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="rowcol",
                          multifault=True)
    res = fn_mf(a, b, c, inject=inj_mf)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    gf = _gf(lambda a, b, x: fn_mf(a, b, x, inject=inj_mf).c, a, b, c, size)
    print(f"{'ft_sgemm_huge:rowcol-mf':28s} {gf:9.1f} GFLOPS  "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(res.num_detected)} unc={int(res.num_uncorrectable)}  "
          f"({gf / xla_gf * 100:5.1f}% of XLA)")

    # Differentiable paths (never hardware-compiled before round 3):
    # fwd+bwd FT matmul under jax.grad, and diff attention, tiny shapes.
    import jax.numpy as jnp  # noqa: E402

    from ft_sgemm_tpu import make_ft_attention_diff, make_ft_matmul  # noqa: E402

    inj1s = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    mm = make_ft_matmul("huge", inject=inj1s, with_counts=True)
    sa = min(size, 1024)
    xs = jax.device_put(generate_random_matrix(sa, sa, rng=rng))
    ws = jax.device_put(generate_random_matrix(sa, sa, rng=rng))

    def loss(w):
        r = mm(xs, w)
        return jnp.sum(jnp.tanh(r.out)), (r.detections, r.uncorrectable)

    (lv, (dct, unc)), gw = jax.jit(
        jax.value_and_grad(loss, has_aux=True))(ws)
    want_g = jax.grad(
        lambda w: jnp.sum(jnp.tanh(xs @ w.T)))(ws)
    ok, nbad, _ = verify_matrix(np.asarray(want_g), np.asarray(gw),
                                verbose=False)
    print(f"{'ft_matmul grad (with_counts)':28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(dct)} unc={int(unc)}")

    attd = make_ft_attention_diff(inject=inj1s, with_counts=True)
    qd = jax.device_put(generate_random_matrix(1024, 128, rng=rng))
    dq = jax.jit(jax.grad(lambda q: jnp.sum(jnp.tanh(attd(q, qd, qd).out))))(qd)
    print(f"{'ft_attention_diff grad':28s}            "
          f"finite={bool(np.isfinite(np.asarray(dq)).all())}")

    # Parallel paths on the live chip (1x1 mesh, d=1 ring): Pallas-under-
    # shard_map must Mosaic-compile at least once per round — the pytest
    # suite only ever runs these interpreted on CPU, which cannot catch
    # Mosaic-only lowering failures.
    from ft_sgemm_tpu.parallel import (  # noqa: E402
        make_mesh, make_ring_mesh, ring_ft_sgemm, sharded_ft_sgemm)

    inj = InjectionSpec.reference_like(size, SHAPES["huge"].bk)
    res = sharded_ft_sgemm(a, b, c, make_mesh(1), "huge",
                           alpha=ALPHA, beta=BETA, inject=inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    print(f"{'sharded_ft_sgemm (1x1 mesh)':28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(res.num_detected)}")
    res = ring_ft_sgemm(a, b, c, make_ring_mesh(1), "huge",
                        alpha=ALPHA, beta=BETA, inject=inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    print(f"{'ring_ft_sgemm (d=1 ring)':28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(res.num_detected)}")

    # FT attention (both GEMMs ABFT-protected + softmax invariant): Mosaic-
    # compile + verify the composed op and its ring form on the live chip.
    from ft_sgemm_tpu import attention_reference, ft_attention  # noqa: E402
    from ft_sgemm_tpu.parallel import ring_ft_attention  # noqa: E402

    la, dh = min(size, 2048), 128
    q = jax.device_put(generate_random_matrix(la, dh, rng=rng))
    kk = jax.device_put(generate_random_matrix(la, dh, rng=rng))
    vv = jax.device_put(generate_random_matrix(la, dh, rng=rng))
    inj1 = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    want_att = np.asarray(attention_reference(q, kk, vv))
    ares = ft_attention(q, kk, vv, inject=inj1)
    ok, nbad, _ = verify_matrix(want_att, np.asarray(ares.out), verbose=False)
    print(f"{'ft_attention (L=%d)' % la:28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(ares.detections)} softmax_flags={int(ares.softmax_flags)}")
    ares = ring_ft_attention(q, kk, vv, make_ring_mesh(1), inject=inj1)
    ok, nbad, _ = verify_matrix(want_att, np.asarray(ares.out), verbose=False)
    print(f"{'ring_ft_attention (d=1)':28s}            "
          f"verify={'OK' if ok else f'FAIL({nbad})'} "
          f"det={int(ares.detections)}")

    if "--bf16" in sys.argv:
        import jax.numpy as jnp

        # Pre-cast so per-rep casts trace to no-ops in the timing loop.
        a16 = jax.device_put(jnp.asarray(a, jnp.bfloat16))
        b16 = jax.device_put(jnp.asarray(b, jnp.bfloat16))
        want16 = np.asarray(
            sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))
        xla16_gf = _gf(
            lambda a, b, x: sgemm_reference(a, b, x, ALPHA, BETA,
                                            in_dtype="bfloat16"),
            a16, b16, c, size)
        print(f"{'xla_dot_bf16':28s} {xla16_gf:9.1f} GFLOPS")
        for name in shapes:
            fn = make_sgemm(name, alpha=ALPHA, beta=BETA, in_dtype="bfloat16")
            ok, nbad, _ = verify_matrix(want16, np.asarray(fn(a, b, c)),
                                        verbose=False)
            gf = _gf(fn, a16, b16, c, size)
            print(f"{'sgemm_' + name + ':bf16':28s} {gf:9.1f} GFLOPS  "
                  f"verify={'OK' if ok else f'FAIL({nbad})'}  "
                  f"({gf / xla16_gf * 100:5.1f}% of XLA bf16)")
        for strategy in (("rowcol", "weighted", "fused") if full
                         else ("weighted", "fused")):
            for name in shapes:
                fn = make_ft_sgemm(name, alpha=ALPHA, beta=BETA,
                                   strategy=strategy, in_dtype="bfloat16")
                # Cadence from the tile the kernel actually runs (bf16
                # overrides change bk), keeping rows comparable to f32.
                inj = InjectionSpec.reference_like(size, fn.shape_config.bk)
                res = fn(a, b, c, inject=inj)
                ok, nbad, _ = verify_matrix(want16, np.asarray(res.c),
                                            verbose=False)
                gf = _gf(lambda a, b, x: fn(a, b, x, inject=inj).c,
                         a16, b16, c, size)
                print(f"{'ft_' + name + ':' + strategy + ':bf16':28s} "
                      f"{gf:9.1f} GFLOPS  "
                      f"verify={'OK' if ok else f'FAIL({nbad})'} "
                      f"det={int(res.num_detected)}  "
                      f"({gf / xla16_gf * 100:5.1f}% of XLA bf16)")


if __name__ == "__main__":
    main()
