"""Live detection-rate + threshold-calibration study (VERDICT r1 item 4).

The evaluation the reference's paper performs but its repo never shipped
(SURVEY.md §4, arXiv:2305.01024): sweep fault magnitudes across the 9500
operating threshold per strategy on the real chip, record detection rate
and output correctness, and check the closed-form noise-floor estimator
against measurement. Output is a ready-to-paste markdown section for
RESULTS.md.

Usage: python scripts/detection_study.py [size] [--strategy=all|rowcol|...]
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")

from ft_sgemm_tpu.analysis import (  # noqa: E402
    calibrate_threshold,
    detection_rate_sweep,
    estimate_noise_floor,
)
from ft_sgemm_tpu.injection import REFERENCE_THRESHOLD  # noqa: E402
from ft_sgemm_tpu.utils.matrices import generate_random_matrix  # noqa: E402

# Magnitudes bracketing the 9500 threshold: deep below (designed misses),
# the transition zone, and safely above (must all be caught).
MAGNITUDES = (1e2, 1e3, 5e3, 9e3, 9.4e3, 9.6e3, 1e4, 2e4, 1e5, 1e6)


def _print_sweep(pts):
    print("| magnitude | injected | detected | rate | output correct |")
    print("|---|---|---|---|---|")
    for p in pts:
        print(f"| {p.magnitude:g} | {p.expected_faults} | {p.detected} |"
              f" {p.detection_rate:.2f} |"
              f" {'yes' if p.output_correct else 'no'} |")
    print()


def main():
    size = 4096
    strategies = ("rowcol", "weighted", "global")
    for tok in sys.argv[1:]:
        if tok.isdigit():
            size = int(tok)
        elif tok.startswith("--strategy=") and tok.split("=", 1)[1] != "all":
            strategies = (tok.split("=", 1)[1],)

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    rng = np.random.default_rng(10)
    a = generate_random_matrix(size, size, rng=rng)
    b = generate_random_matrix(size, size, rng=rng)
    c = generate_random_matrix(size, size, rng=rng)

    cal = calibrate_threshold(a, b, c)
    est = estimate_noise_floor(a, b, c)
    print(f"\n## Detection-rate study at {size} (live"
          f" {jax.default_backend()}, threshold={REFERENCE_THRESHOLD:g})\n")
    print(f"Noise floor: measured {cal.noise_floor:.3g} vs closed-form bound"
          f" {est:.3g} (bound/measured = {est / max(cal.noise_floor, 1e-30):.1f}x);"
          f" calibrated min threshold {cal.threshold:.3g}"
          f" (margin {cal.margin:g}), min reliably-detectable fault"
          f" {cal.min_detectable:.3g}. The reference operating point"
          f" (threshold 9500, faults 1e4) sits"
          f" {REFERENCE_THRESHOLD / max(cal.threshold, 1e-30):.0f}x above the"
          f" calibrated floor-derived threshold.\n")

    for strategy in strategies:
        print(f"### strategy={strategy}\n")
        _print_sweep(detection_rate_sweep(
            a, b, c, MAGNITUDES, "huge", strategy=strategy))

    # Adaptive thresholds (threshold="auto"): the same sweep at magnitudes
    # the fixed 9500 threshold is blind to — live proof of the V-ABFT-style
    # per-call calibration (detection floor ~= margin x data noise floor).
    from ft_sgemm_tpu.ops.common import DEFAULT_THRESHOLD_MARGIN

    tiny = [m for m in (0.01, 0.1, 1.0, 10.0, 100.0)
            if m > 2.0 * DEFAULT_THRESHOLD_MARGIN * est]  # detectable ones
    print('### strategy=weighted, threshold="auto" (fixed 9500 detects none'
          ' of these)\n')
    _print_sweep(detection_rate_sweep(
        a, b, c, tiny, "huge", strategy="weighted", threshold="auto"))


if __name__ == "__main__":
    main()
