"""AOT compile-probe of the full bench ladder (VERDICT r5 #1a).

Round 4's one hardware window burned its minutes discovering — one timed
stage at a time — that every FT kernel except rowcol failed Mosaic
compilation. This probe compiles, WITHOUT running, exactly the jitted
rep-loop computations ``bench.py`` will execute at the target size:
operands are ``jax.ShapeDtypeStruct``s (no data touches the chip; on the
axon tunnel, Mosaic compilation happens in the chipless remote compile
helper, so only the tunnel's compile service is needed), and the loop
constructor is shared with the timing path (``timing._make_rep_loop``)
so every successful probe compile is a persistent-cache hit for the
subsequent bench/validate stages — window minutes then go to timing, not
compiling, and a compile regression is identified in one shot with the
exact Mosaic error per variant.

Usage: python scripts/compile_probe.py [size]
Prints one status line per variant and a final JSON summary line;
exit 0 iff every variant compiled.
"""

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ft_sgemm_tpu import InjectionSpec, SHAPES, make_ft_sgemm, make_sgemm  # noqa: E402
from ft_sgemm_tpu.ops.reference import sgemm_reference  # noqa: E402
from ft_sgemm_tpu.perf import compile_cache  # noqa: E402
from ft_sgemm_tpu.utils.timing import compile_bench_loop  # noqa: E402

# Shared, observable persistent cache (FT_SGEMM_COMPILE_CACHE overrides
# or disables) — the probe's compiles are the warm-start deposit the
# later bench withdraws, and the final JSON line reports the traffic.
_CACHE_STATUS = compile_cache.enable()

SIZE = 4096


def _ft(size, **kwargs):
    """An FT callable exactly as bench.py's stages build it (factory args
    AND injection schedule must match for the traced HLO to match)."""
    ft = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, **kwargs)
    inj = InjectionSpec.reference_like(size, ft.shape_config.bk)
    return lambda a, b, x: ft(a, b, x, inj).c


def main():
    size = SIZE
    for tok in sys.argv[1:]:
        if tok.isdigit():
            size = int(tok)
    f32 = jax.ShapeDtypeStruct((size, size), jnp.float32)
    bf16 = jax.ShapeDtypeStruct((size, size), jnp.bfloat16)
    nk = size // SHAPES["huge"].bk

    variants = [
        ("xla_dot", f32,
         lambda: (lambda a, b, x: sgemm_reference(a, b, x, 1.0, -1.5))),
        ("plain_huge", f32,
         lambda: make_sgemm("huge", alpha=1.0, beta=-1.5)),
        # The headline ladder, every rung (bench.py worker_main).
        ("ft_weighted_precomp", f32,
         lambda: _ft(size, strategy="weighted")),
        ("ft_rowcol", f32, lambda: _ft(size, strategy="rowcol")),
        ("ft_fused", f32, lambda: _ft(size, strategy="fused")),
        ("bf16_plain", bf16,
         lambda: make_sgemm("huge", alpha=1.0, beta=-1.5,
                            in_dtype="bfloat16")),
        ("bf16_abft", bf16,
         lambda: _ft(size, strategy="weighted", in_dtype="bfloat16")),
        ("bf16_fused", bf16,
         lambda: _ft(size, strategy="fused", in_dtype="bfloat16")),
        ("bf16_xla", bf16,
         lambda: (lambda a, b, x: sgemm_reference(a, b, x, 1.0, -1.5,
                                                  in_dtype="bfloat16"))),
    ]
    if nk >= 2:
        variants.insert(3, ("ft_weighted_inkernel", f32,
                            lambda: _ft(size, strategy="weighted",
                                        check_every=nk // 2)))

    print(f"compile_probe: backend={jax.default_backend()} size={size}",
          flush=True)
    results = {}
    for name, ab, make_fn in variants:
        t0 = time.perf_counter()
        try:
            compile_bench_loop(make_fn(), ab, ab, f32)
            dt = time.perf_counter() - t0
            results[name] = {"ok": True, "seconds": round(dt, 1)}
            print(f"compile_probe: {name} OK ({dt:.1f}s)", flush=True)
        except Exception as e:  # noqa: BLE001 — per-variant report is the job
            results[name] = {"ok": False,
                             "error": f"{type(e).__name__}: {str(e)[:400]}"}
            print(f"compile_probe: {name} FAILED "
                  f"{type(e).__name__}: {str(e)[:200]}", flush=True)
    ok = all(r["ok"] for r in results.values())
    print(json.dumps({"metric": "compile_probe", "size": size,
                      "backend": jax.default_backend(), "ok": ok,
                      "compile_cache": compile_cache.stats(),
                      "variants": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
