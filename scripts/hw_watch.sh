#!/bin/bash
# Hardware watcher: probe the axon TPU tunnel; the moment a window opens,
# run the full hardware stage list, banking results as it goes. The axon
# tunnel comes and goes (rounds 2-4 each saw multi-hour outages bracketing
# ~20-minute windows), so every stage must land the instant one opens —
# bench.py's code-version-keyed records then hand the numbers to the
# driver's scoring run even if the tunnel is down again by round end.
#
# Usage: nohup bash scripts/hw_watch.sh >> .bench/watch.log 2>&1 &
# A stage that completes writes a .bench/done_<stage>_<key> marker and is
# not re-run while the measurement-relevant code (bench.py's
# _code_version_key) is unchanged. Delete markers to force a re-run.
#
# The script self-supervises: the top-level invocation only restarts the
# inner probe loop when it dies (round 4 lost a window to a watcher whose
# log just stopped at 05:06 with nothing recording that it was dead).
# Liveness is observable two ways: an epoch timestamp is written to
# .bench/watch.hb every probe cycle AND at every stage start (staleness
# while healthy is therefore bounded by the longest single stage budget,
# 2400 s — not the multi-hour stage-list total), and every inner-loop
# exit is logged with its rc before the 60 s re-arm. The supervisor runs the inner loop as a
# background child and waits on it, so INT/TERM to the supervisor pid is
# handled immediately (bash defers traps while a FOREGROUND child runs)
# and is forwarded to the child's whole process group.

# Resolve our own absolute path BEFORE cd: the supervisor re-execs
# "$self" after the cd, and a relative $0 (invoked as e.g.
# `cd /root && bash repo/scripts/hw_watch.sh`) would resolve against the
# new cwd, fail rc=127, and leave the supervisor re-arming forever
# without ever running a stage.
self=$(readlink -f "$0") || exit 1
cd "$(dirname "$self")/.." || exit 1
mkdir -p .bench .bench/jaxcache

if [ "${HW_WATCH_INNER:-}" != 1 ]; then
  child=
  on_sig() {
    echo "[watch-supervisor] $(date -u +%H:%M:%S) terminated by signal"
    if [ -n "$child" ]; then
      kill -- -"$child" 2>/dev/null || kill "$child" 2>/dev/null
    fi
    exit 130
  }
  trap on_sig INT TERM
  echo "[watch-supervisor] $(date -u +%H:%M:%S) armed (pid $$)"
  while true; do
    # setsid: the inner loop gets its own process group, so on_sig can
    # kill the stage subprocesses (python/timeout) along with it.
    HW_WATCH_INNER=1 setsid bash "$self" &
    child=$!
    wait "$child"
    rc=$?
    child=
    if [ "$rc" = 0 ]; then
      echo "[watch-supervisor] $(date -u +%H:%M:%S) inner loop finished: all stages banked"
      exit 0
    fi
    echo "[watch-supervisor] $(date -u +%H:%M:%S) inner loop DIED rc=$rc; re-arming in 60s"
    sleep 60 &
    wait $!
  done
fi
# Persistent executable cache for every stage (same dir bench.py's worker
# configures): re-runs across windows skip identical Mosaic compiles.
export JAX_COMPILATION_CACHE_DIR="$PWD/.bench/jaxcache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0.5

# THE stage list — the single source for the run sequence, the window-open
# plan, and the all-banked check. Per-stage command/timeout/script live in
# the stage_cmd/stage_timeout/stage_script tables below.
# probe runs FIRST: it AOT-compiles the whole bench ladder through
# the tunnel's chipless compile helper (no chip time), banking every
# executable in the shared persistent cache — the later timed stages
# then spend window minutes timing, not compiling, and any Mosaic
# compile regression is identified in one shot with per-variant errors
# (VERDICT r5 #1a).
STAGES="probe bench validate gen detect attn tune_bf16_ft sweep tune_f32_ft"

stage_cmd() {
  case $1 in
    # External timeout must exceed bench.py's own 900 s deadline, or a
    # slow-but-successful run gets SIGTERM'd from outside and the stage
    # is never marked done.
    probe) echo "python scripts/compile_probe.py 4096" ;;
    bench) echo "python bench.py" ;;
    validate) echo "python scripts/validate_tpu.py 4096 --full --bf16" ;;
    gen) echo "python -m ft_sgemm_tpu.codegen.gen all && python -m ft_sgemm_tpu.codegen.gen huge 0 --dtype=bfloat16 && python -m ft_sgemm_tpu.codegen.gen huge 1 --dtype=bfloat16" ;;
    detect) echo "python scripts/detection_study.py 2048" ;;
    attn) echo "python scripts/bench_attention.py" ;;
    tune_bf16_ft) echo "python scripts/tune_tiles.py 4096 --ft --bf16" ;;
    # Last: the full 14-row driver sweep (VERDICT r4 #6 — RESULTS.md's
    # table is round-1/2 kernels). Longest stage; every measured cell is
    # flushed to the log immediately, so a tunnel drop mid-sweep still
    # leaves citable partial rows in .bench/sweep.log. --no-verify: the
    # verify pass is covered by the validate stage; a ~20-min window
    # should spend itself on table cells.
    sweep) echo "python -m ft_sgemm_tpu.cli 1024 6144 512 0 16 --mintime=0.5 --no-verify" ;;
    # f32 FT tile retune under the 64 MiB budget (VERDICT r4 #5): the
    # deep-K candidates the raised limit admits have never been timed.
    tune_f32_ft) echo "python scripts/tune_tiles.py 4096 --ft" ;;
  esac
}

stage_timeout() {
  case $1 in
    bench) echo 980 ;;
    validate | tune_bf16_ft | tune_f32_ft) echo 1200 ;;
    sweep) echo 2400 ;;
    *) echo 900 ;;
  esac
}

stage_script() {  # the stage's own script ('' if none)
  case $1 in
    probe) echo scripts/compile_probe.py ;;
    validate) echo scripts/validate_tpu.py ;;
    detect) echo scripts/detection_study.py ;;
    attn) echo scripts/bench_attention.py ;;
    tune_bf16_ft | tune_f32_ft) echo scripts/tune_tiles.py ;;
    *) echo "" ;;  # bench/gen/sweep code is already in the bench key
  esac
}

probe() {
  # -k: a tunnel-dead backend init can hang in C code and ignore the
  # TERM timeout sends (the round-2 bench postmortem failure mode);
  # SIGKILL must follow or one probe wedges the whole cycle.
  timeout -k 10 120 python -c "
import jax, jax.numpy as jnp
y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256)))
jax.block_until_ready(y)
assert jax.default_backend() == 'tpu'
" >/dev/null 2>&1
}

compile_gate() {
  # AOT-compile-only liveness: needs the tunnel's (chipless) compile
  # helper but NOT chip execution. Lets the compile-probe stage bank the
  # ladder's executables while the chip is unreachable, so a later chip
  # window starts timing immediately instead of compiling.
  timeout -k 10 120 python -c "
import jax, jax.numpy as jnp
jax.jit(lambda a: a + 1).lower(
    jax.ShapeDtypeStruct((8, 128), jnp.float32)).compile()
assert jax.default_backend() == 'tpu'
" >/dev/null 2>&1
}

stage_gate() {  # the cheapest liveness check a stage needs before running
  case $1 in
    probe) compile_gate ;;  # chipless: compile service is enough
    *) probe ;;
  esac
}

key() {  # key [stage-script] — per-stage marker key
  # bench._code_version_key deliberately excludes scripts/ (editing a
  # stage script must not discard bench.py's banked records), but the
  # watcher's stage markers DO gate script-driven stages — so fold the
  # stage's OWN script (only: editing one stage script must not burn a
  # scarce tunnel window re-running every other stage) plus this watcher
  # into the marker key. On any failure emit a unique token: markers
  # then never match and the stage re-runs (the safe direction; a
  # constant fallback would let different code states share markers).
  STAGE_SCRIPT="${1:-}" python - <<'EOF'
import hashlib, importlib.util, os, uuid
try:
    spec = importlib.util.spec_from_file_location('bench', 'bench.py')
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    k = b._code_version_key()
    h = hashlib.sha1()
    paths = ['scripts/hw_watch.sh']
    if os.environ.get('STAGE_SCRIPT'):
        paths.append(os.environ['STAGE_SCRIPT'])
    for p in paths:
        with open(p, 'rb') as f:
            h.update(p.encode() + b'\0' + f.read() + b'\0')
    print((k or uuid.uuid4().hex[:12]) + '-' + h.hexdigest()[:8])
except Exception:
    print('fail-' + uuid.uuid4().hex[:12])
EOF
}

# Per-cycle key cache: key() spawns a python subprocess that hashes the
# repo — computing it once per stage per cycle (instead of up to 3x per
# stage) keeps window-open overhead to ~7 subprocess spawns. Keys are
# refreshed at every tunnel-UP probe, so a mid-window code edit is picked
# up one cycle later (the accepted tradeoff; edits during a live window
# are operator error anyway).
declare -A KEYS
refresh_keys() {
  local s
  for s in $STAGES; do
    KEYS[$s]=$(key "$(stage_script "$s")")
  done
}

# The process group of the stage currently running (its setsid leader's
# pid), so a TERM/INT to this inner loop can kill the whole stage tree —
# stages run in their OWN sessions now, out of reach of the supervisor's
# group kill.
CUR_STAGE_PG=
on_inner_sig() {
  [ -n "$CUR_STAGE_PG" ] && kill -TERM -- "-$CUR_STAGE_PG" 2>/dev/null
  exit 143
}
trap on_inner_sig INT TERM

run_staged_cmd() {  # run_staged_cmd <timeout> <log> <cmd...>
  # Each timed stage gets its OWN process group (setsid) and the timeout
  # escalation kills the GROUP: `timeout -k` signals only its direct
  # child, so a compound stage (e.g. gen's `a && b && c` wrapper bash)
  # that got TERM/KILLed would orphan the in-flight python child — which
  # keeps holding the tunnel/chip while every later stage's gate and
  # timeout runs against it (ADVICE r5).
  local tmo=$1 log=$2; shift 2
  setsid bash -c "$*" > "$log" 2>&1 &
  local pid=$!
  CUR_STAGE_PG=$pid
  (
    sleep "$tmo"
    kill -TERM -- "-$pid" 2>/dev/null
    sleep 15
    kill -KILL -- "-$pid" 2>/dev/null
  ) &
  local watchdog=$!
  local rc
  wait "$pid"; rc=$?
  CUR_STAGE_PG=
  # Stage finished first: stop the watchdog shell so its pending kills
  # can never fire at a (possibly reused) pgid. Its in-flight sleep may
  # linger as an orphan; with the shell dead, nothing runs after it.
  kill "$watchdog" 2>/dev/null
  wait "$watchdog" 2>/dev/null
  return "$rc"
}

run_stage() {  # run_stage <name> — cmd/timeout/key from the stage tables
  local name=$1
  local tmo; tmo=$(stage_timeout "$name")
  local marker=".bench/done_${name}_${KEYS[$name]}"
  # Refresh the heartbeat per stage, not just per probe cycle: the stage
  # list can run for hours (sweep alone has a 2400s budget) and a
  # heartbeat that goes stale mid-window would make the watcher read
  # "dead" exactly while it is doing its most important work.
  date -u +%s > .bench/watch.hb
  if [ -e "$marker" ]; then
    echo "[watch] $name already done for key ${KEYS[$name]}"
    return 0
  fi
  # Re-gate before every stage: windows are ~20 min and can close
  # mid-list; without this, one drop burns every remaining stage's full
  # timeout against a dead tunnel before the outer loop probes again.
  # (The compile-probe stage's gate is compile-service-only.)
  if ! stage_gate "$name"; then
    echo "[watch] $(date -u +%H:%M:%S) tunnel dropped before $name"
    return 1
  fi
  echo "[watch] $(date -u +%H:%M:%S) running $name (timeout ${tmo}s)"
  if run_staged_cmd "$tmo" ".bench/${name}.log" "$(stage_cmd "$name")"; then
    touch "$marker"
    echo "[watch] $(date -u +%H:%M:%S) $name OK"
  else
    local rc=$?  # BEFORE the $(date) substitution below resets $?
    echo "[watch] $(date -u +%H:%M:%S) $name FAILED rc=$rc (see .bench/${name}.log)"
    return 1
  fi
}

stage_plan() {  # log which stages are pending vs banked for current keys
  local pending="" done="" s
  for s in $STAGES; do
    if [ -e ".bench/done_${s}_${KEYS[$s]}" ]; then
      done="$done $s"
    else
      pending="$pending $s"
    fi
  done
  echo "[watch] stage plan: pending:${pending:- none}; banked:${done:- none}"
}

while true; do
  date -u +%s > .bench/watch.hb
  if probe; then
    echo "[watch] $(date -u +%H:%M:%S) tunnel UP"
    refresh_keys
    stage_plan
    all=1
    for s in $STAGES; do
      run_stage "$s"
      [ -e ".bench/done_${s}_${KEYS[$s]}" ] || all=0
    done
    if [ "$all" = 1 ]; then
      echo "[watch] all stages banked; exiting"
      exit 0
    fi
  else
    echo "[watch] $(date -u +%H:%M:%S) tunnel down"
    # The chip being down doesn't mean the compile service is: if the
    # probe stage is still pending, try to bank its ladder compiles now
    # so a later chip window starts timing immediately.
    KEYS[probe]=$(key "$(stage_script probe)")
    if [ ! -e ".bench/done_probe_${KEYS[probe]}" ] && compile_gate; then
      echo "[watch] $(date -u +%H:%M:%S) compile service UP (chip down)"
      run_stage probe
    fi
  fi
  sleep 240
done
