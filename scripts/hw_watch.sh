#!/bin/bash
# Hardware watcher: probe the axon TPU tunnel; the moment a window opens,
# run the full hardware stage list, banking results as it goes. The axon
# tunnel comes and goes (rounds 2-4 each saw multi-hour outages bracketing
# ~20-minute windows), so every stage must land the instant one opens —
# bench.py's code-version-keyed records then hand the numbers to the
# driver's scoring run even if the tunnel is down again by round end.
#
# Usage: nohup bash scripts/hw_watch.sh >> .bench/watch.log 2>&1 &
# A stage that completes writes a .bench/done_<stage>_<key> marker and is
# not re-run while the measurement-relevant code (bench.py's
# _code_version_key) is unchanged. Delete markers to force a re-run.

cd "$(dirname "$0")/.." || exit 1
mkdir -p .bench .bench/jaxcache
# Persistent executable cache for every stage (same dir bench.py's worker
# configures): re-runs across windows skip identical Mosaic compiles.
export JAX_COMPILATION_CACHE_DIR="$PWD/.bench/jaxcache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0.5

probe() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256)))
jax.block_until_ready(y)
assert jax.default_backend() == 'tpu'
" >/dev/null 2>&1
}

key() {  # key [stage-script] — per-stage marker key
  # bench._code_version_key deliberately excludes scripts/ (editing a
  # stage script must not discard bench.py's banked records), but the
  # watcher's stage markers DO gate script-driven stages — so fold the
  # stage's OWN script (only: editing one stage script must not burn a
  # scarce tunnel window re-running every other stage) plus this watcher
  # into the marker key. On any failure emit a unique token: markers
  # then never match and the stage re-runs (the safe direction; a
  # constant fallback would let different code states share markers).
  STAGE_SCRIPT="${1:-}" python - <<'EOF'
import hashlib, importlib.util, os, uuid
try:
    spec = importlib.util.spec_from_file_location('bench', 'bench.py')
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    k = b._code_version_key()
    h = hashlib.sha1()
    paths = ['scripts/hw_watch.sh']
    if os.environ.get('STAGE_SCRIPT'):
        paths.append(os.environ['STAGE_SCRIPT'])
    for p in paths:
        with open(p, 'rb') as f:
            h.update(p.encode() + b'\0' + f.read() + b'\0')
    print((k or uuid.uuid4().hex[:12]) + '-' + h.hexdigest()[:8])
except Exception:
    print('fail-' + uuid.uuid4().hex[:12])
EOF
}

stage_script() {  # stage_script <name> — the stage's own script ('' if none)
  case $1 in
    validate) echo scripts/validate_tpu.py ;;
    detect) echo scripts/detection_study.py ;;
    attn) echo scripts/bench_attention.py ;;
    tune_bf16_ft) echo scripts/tune_tiles.py ;;
    *) echo "" ;;  # bench/gen code is already in the bench key
  esac
}

run_stage() {  # run_stage <name> <timeout-s> <cmd...>
  local name=$1 tmo=$2; shift 2
  local k; k=$(key "$(stage_script "$name")")
  local marker=".bench/done_${name}_${k}"
  if [ -e "$marker" ]; then
    echo "[watch] $name already done for key $k"
    return 0
  fi
  # Re-probe before every stage: windows are ~20 min and can close
  # mid-list; without this, one drop burns every remaining stage's full
  # timeout against a dead tunnel before the outer loop probes again.
  if ! probe; then
    echo "[watch] $(date -u +%H:%M:%S) tunnel dropped before $name"
    return 1
  fi
  echo "[watch] $(date -u +%H:%M:%S) running $name (timeout ${tmo}s)"
  if timeout "$tmo" "$@" > ".bench/${name}.log" 2>&1; then
    touch "$marker"
    echo "[watch] $(date -u +%H:%M:%S) $name OK"
  else
    local rc=$?  # BEFORE the $(date) substitution below resets $?
    echo "[watch] $(date -u +%H:%M:%S) $name FAILED rc=$rc (see .bench/${name}.log)"
    return 1
  fi
}

while true; do
  if probe; then
    echo "[watch] $(date -u +%H:%M:%S) tunnel UP"
    # External timeout must exceed bench.py's own 900 s deadline, or a
    # slow-but-successful run gets SIGTERM'd from outside and the stage
    # is never marked done.
    run_stage bench 980 python bench.py
    run_stage validate 1200 python scripts/validate_tpu.py 4096 --full --bf16
    run_stage gen 900 bash -c "python -m ft_sgemm_tpu.codegen.gen all && python -m ft_sgemm_tpu.codegen.gen huge 0 --dtype=bfloat16 && python -m ft_sgemm_tpu.codegen.gen huge 1 --dtype=bfloat16"
    run_stage detect 900 python scripts/detection_study.py 2048
    run_stage attn 900 python scripts/bench_attention.py
    run_stage tune_bf16_ft 1200 python scripts/tune_tiles.py 4096 --ft --bf16
    all=1
    for s in bench validate gen detect attn tune_bf16_ft; do
      [ -e ".bench/done_${s}_$(key "$(stage_script "$s")")" ] || all=0
    done
    if [ "$all" = 1 ]; then
      echo "[watch] all stages banked; exiting"
      exit 0
    fi
  else
    echo "[watch] $(date -u +%H:%M:%S) tunnel down"
  fi
  sleep 240
done
