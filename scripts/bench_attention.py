"""Live FT-attention benchmark: overhead of ABFT-protected attention.

Measures plain XLA attention vs ft_attention (both GEMMs through the
fused-ABFT kernels, injection on) at long sequence lengths on the real
chip. GFLOPS counts the two GEMMs (2*L*Lk*d + 2*L*Lk*dv), the standard
attention accounting.

Usage: python scripts/bench_attention.py [L] [--bf16]
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")

from ft_sgemm_tpu import InjectionSpec, make_ft_attention  # noqa: E402
from ft_sgemm_tpu.ops.attention import attention_reference  # noqa: E402
from ft_sgemm_tpu.utils.matrices import generate_random_matrix  # noqa: E402
from ft_sgemm_tpu.utils.timing import bench_seconds_per_call  # noqa: E402

D_HEAD = 128


def main():
    size = 4096
    for tok in sys.argv[1:]:
        if tok.isdigit():
            size = int(tok)
    in_dtype = "bfloat16" if "--bf16" in sys.argv else "float32"

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    rng = np.random.default_rng(10)
    q = jax.device_put(generate_random_matrix(size, D_HEAD, rng=rng))
    k = jax.device_put(generate_random_matrix(size, D_HEAD, rng=rng))
    v = jax.device_put(generate_random_matrix(size, D_HEAD, rng=rng))
    flop = 2.0 * size * size * D_HEAD * 2  # QK^T + PV

    # bench_seconds_per_call has the (a, b, c) GEMM calling shape — attention
    # maps (q, k, v) onto it directly.
    xla = lambda q, k, v: attention_reference(q, k, v, in_dtype=in_dtype)  # noqa: E731
    sec = bench_seconds_per_call(xla, q, k, v, min_device_time=2.0)
    xla_gf = flop / 1e9 / sec
    print(f"{'xla_attention':24s} {xla_gf:10.1f} GFLOPS")

    fn = make_ft_attention(in_dtype=in_dtype)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = fn(q, k, v, inj)
    print(f"  det={int(res.detections)} softmax_flags="
          f"{int(res.softmax_flags)}")
    # Fold detections/softmax_flags into the timed output so XLA cannot
    # dead-code-eliminate the invariant checks being benchmarked (1e-30
    # scaling, not *0.0 — the algebraic simplifier folds the latter).
    def ft(q, k, v):
        r = fn(q, k, v, inj)
        return r.out + (r.detections + r.softmax_flags).astype(np.float32) * 1e-30
    sec = bench_seconds_per_call(ft, q, k, v, min_device_time=2.0)
    ft_gf = flop / 1e9 / sec
    print(f"{'ft_attention (inject on)':24s} {ft_gf:10.1f} GFLOPS  "
          f"({ft_gf / xla_gf * 100:5.1f}% of XLA attention, "
          f"overhead {100 * (1 - ft_gf / xla_gf):.1f}%)")

    # Ring attention at d=1: the sequence-parallel dataflow (K/V rotation +
    # online softmax) on one device — isolates the ring machinery's cost
    # from multi-chip communication (VERDICT r2 item 9).
    from ft_sgemm_tpu.parallel import make_ring_mesh, ring_ft_attention

    mesh = make_ring_mesh(1)

    def ring(q, k, v):
        r = ring_ft_attention(q, k, v, mesh, inject=inj, in_dtype=in_dtype)
        return r.out + (r.detections + r.softmax_flags).astype(
            np.float32) * 1e-30

    rres = ring_ft_attention(q, k, v, mesh, inject=inj, in_dtype=in_dtype)
    print(f"  ring det={int(rres.detections)} softmax_flags="
          f"{int(rres.softmax_flags)} unc={int(rres.uncorrectable)}")
    sec = bench_seconds_per_call(ring, q, k, v, min_device_time=2.0)
    ring_gf = flop / 1e9 / sec
    print(f"{'ring_ft_attention (d=1)':24s} {ring_gf:10.1f} GFLOPS  "
          f"({ring_gf / xla_gf * 100:5.1f}% of XLA attention)")


if __name__ == "__main__":
    main()
