#!/usr/bin/env python3
"""Path-loadability smoke for the declared stdlib-only modules.

The static half of the contract lives in ftlint's import-graph pass
(AST-verified: stdlib-only imports at module scope, no relative
imports). This script is the DYNAMIC half CI runs next to it: every
module in ``contracts.STDLIB_ONLY_MODULES`` is executed by FILE PATH in
a bare ``python -S`` subprocess (no site-packages, so jax/numpy are not
merely unimported — they are uninstallable) whose meta-path additionally
raises on any jax import attempt. A module that passes here is proven
loadable by the jax-free bench supervisor and the CI artifact tooling,
not just believed to be.

Exit 0 all loadable / 1 any failure / 2 internal error (the compare.py
contract). Stdlib-only itself, obviously.

Usage: python scripts/stdlib_smoke.py [REPO_ROOT]
"""

import importlib.util
import json
import os
import subprocess
import sys

_CHILD_PROG = r"""
import importlib.util, sys

class _Block:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax import attempted in stdlib-only module")

sys.meta_path.insert(0, _Block())
path = sys.argv[1]
spec = importlib.util.spec_from_file_location("_stdlib_smoke_target", path)
mod = importlib.util.module_from_spec(spec)
# Register before exec: stdlib machinery (dataclasses under
# `from __future__ import annotations`) resolves the defining module
# through sys.modules — the full canonical path-load recipe.
sys.modules[spec.name] = mod
spec.loader.exec_module(mod)
assert "jax" not in sys.modules
print("ok")
"""


def declared_modules(root: str):
    """STDLIB_ONLY_MODULES, read by path-loading contracts.py itself —
    the declaration module is its own first smoke target."""
    path = os.path.join(root, "ft_sgemm_tpu", "contracts.py")
    spec = importlib.util.spec_from_file_location("_contracts", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.STDLIB_ONLY_MODULES)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = os.path.abspath(argv[0]) if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    try:
        modules = declared_modules(root)
    except Exception as e:  # noqa: BLE001 — exit-2 contract
        print(f"stdlib_smoke: cannot read contracts: {e}",
              file=sys.stderr)
        return 2
    results = {}
    failed = []
    for rel in modules:
        target = os.path.join(root, rel)
        # -S: no site-packages — the interpreter literally cannot import
        # jax even if the blocker were bypassed. -E ignores PYTHONPATH
        # pollution from the calling environment.
        proc = subprocess.run(
            [sys.executable, "-S", "-E", "-c", _CHILD_PROG, target],
            capture_output=True, text=True, timeout=120)
        ok = proc.returncode == 0 and proc.stdout.strip() == "ok"
        results[rel] = "ok" if ok else (
            proc.stderr.strip().splitlines()[-1] if proc.stderr.strip()
            else f"rc={proc.returncode}")
        if not ok:
            failed.append(rel)
        print(f"{'PASS' if ok else 'FAIL'}  {rel}"
              + ("" if ok else f"  ({results[rel]})"))
    print(json.dumps({"checked": len(modules), "failed": failed},
                     sort_keys=True))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
