"""Long-context fault-tolerant training: ring attention over a mesh.

The full round-trip of the framework's distributed story in one script:
a model whose attention core is :class:`ft_sgemm_tpu.nn.FtRingSelfAttention`
— K/V shards rotate an ICI ring through the online-softmax recurrence, so
the sequence never has to fit on one device — trains under per-call fault
injection with every GEMM of forward AND backward (projections, per-hop
ring GEMMs, MLP) running through the fused-ABFT Pallas kernels. Fault
counts stream per step; checkpoints go through the ABFT clean-state gate
(:class:`ft_sgemm_tpu.checkpoint.FtCheckpointer`) and the run RESUMES
from the newest clean checkpoint on restart.

Runs anywhere: by default it builds the ring from N virtual CPU devices
(the same surface the test suite and the driver's multi-chip dryrun
use), so no multi-chip hardware is needed; on a real pod pass
``--real-devices`` to ring over the attached chips' ICI instead:

    python examples/train_long_context.py [--devices 8] [--steps N]
        [--seq-scale S] [--no-inject] [--real-devices]
        [--ckpt DIR [--ckpt-every N]]

Sequence length is ``128 * devices * seq-scale`` — each device holds a
``128 * seq-scale``-row shard of queries and streams everyone else's
key/value blocks through its FT kernels.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq-scale", type=int, default=1)
    ap.add_argument("--no-inject", action="store_true")
    ap.add_argument("--real-devices", action="store_true",
                    help="ring over the attached accelerators' ICI "
                         "instead of a virtual CPU ring")
    ap.add_argument("--ckpt", default=None, metavar="DIR")
    ap.add_argument("--ckpt-every", type=int, default=3)
    args = ap.parse_args()
    args.ckpt_every = max(1, args.ckpt_every)

    if not args.real_devices:
        # Virtual ring BEFORE importing jax (same contract as the dryrun).
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if not args.real_devices:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ft_sgemm_tpu import InjectionSpec
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtTransformerBlock
    from ft_sgemm_tpu.parallel import make_ring_mesh
    from ft_sgemm_tpu.checkpoint import total_count

    mesh = make_ring_mesh(args.devices)
    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    length, d_model = 128 * args.devices * args.seq_scale, 64
    inject = (None if args.no_inject
              else InjectionSpec(enabled=True, every=1, magnitude=10000.0))

    class LongModel(nn.Module):
        @nn.compact
        def __call__(self, x, bwd_sink):
            # ring_mesh swaps the block's mixer to the sequence-parallel
            # ring attention core — the long-context transformer is a
            # config flag (ft_sgemm_tpu.nn.FtTransformerBlock docstring).
            return FtTransformerBlock(
                num_heads=2, mlp_ratio=2, causal=True,
                ring_mesh=mesh, inject=inject, inject_bwd=inject,
                dense_shape=tile, qk_shape=tile,
                pv_shape=tile)(x, bwd_sink)

    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.normal(size=(length, d_model)) * 0.3,
                    jnp.float32)
    y = jnp.roll(x, 1, axis=0)  # predict the previous row (causal-friendly)

    model = LongModel()
    params = model.init(jax.random.key(0), x, jnp.zeros(2))["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    ckpt, start = None, 0
    if args.ckpt:
        from ft_sgemm_tpu.checkpoint import FtCheckpointer

        ckpt = FtCheckpointer(args.ckpt)
        # Restore REPLICATED over the ring mesh: a plain restore commits
        # arrays to one device, and the jitted step's inner shard_map
        # (all mesh devices) refuses committed single-device operands.
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep),
            {"params": params, "opt_state": opt_state})
        try:
            latest, restored = ckpt.restore_latest(target)
        except Exception as e:  # noqa: BLE001 — stale-tree checkpoints
            print(f"checkpoint in {args.ckpt} does not match this model "
                  f"({type(e).__name__}); starting fresh", file=sys.stderr)
            latest = None
        if latest is not None:
            params, opt_state = restored["params"], restored["opt_state"]
            start = latest + 1
            print(f"resumed from step {latest} in {args.ckpt}")

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p, sink):
            out, mut = model.apply({"params": p}, x, sink,
                                   mutable=[COUNTS_COLLECTION])
            return jnp.mean((out - y) ** 2), mut[COUNTS_COLLECTION]

        (loss, counts), (grads, bwd) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, jnp.zeros(2))
        upd, opt_state = tx.update(grads, opt_state)
        return (optax.apply_updates(params, upd), opt_state, loss,
                counts, bwd)

    print(f"ring={args.devices} devices  L={length}  d_model={d_model}  "
          f"inject={'off' if args.no_inject else 'magnitude 1e4 per call'}")
    print(f"{'step':>5} {'loss':>12} {'detected':>9} {'sm_flags':>9} "
          f"{'uncorrectable':>14} {'bwd_det':>8} {'bwd_unc':>8}")
    try:
        for i in range(start, args.steps):
            params, opt_state, loss, counts, bwd = step(params, opt_state)
            det = total_count(counts, "detections")
            flags = total_count(counts, "softmax_flags")
            unc = total_count(counts, "uncorrectable")
            bwd_det, bwd_unc = int(bwd[0]), int(bwd[1])
            print(f"{i:>5} {float(loss):>12.6f} {det:>9} {flags:>9} "
                  f"{unc:>14} {bwd_det:>8} {bwd_unc:>8}")
            if unc or bwd_unc:
                print("uncorrectable interval reported: re-run the step",
                      file=sys.stderr)
                return 1
            if ckpt and ((i + 1) % args.ckpt_every == 0
                         or i == args.steps - 1):
                saved = ckpt.save(i, {"params": params,
                                      "opt_state": opt_state},
                                  uncorrectable=unc + bwd_unc)
                if not saved:
                    # A silently missing periodic save would widen the
                    # crash-loss window past --ckpt-every (see train_ft).
                    print(f"warning: checkpoint at step {i} was NOT "
                          "written (save skipped or refused)",
                          file=sys.stderr)
    finally:
        if ckpt:
            ckpt.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
