"""End-to-end fault-tolerant training example.

Trains a two-layer MLP regression with EVERY GEMM (forward and backward)
running through the fused-ABFT Pallas kernels while silent-data-corruption
faults of magnitude 1e4 are injected into every kernel call — and logs the
per-layer fault activity each step. The loss curve is indistinguishable
from a fault-free run: that is the framework's end-to-end claim.

The logged ``detected``/``uncorrectable`` columns (and the re-run gate)
observe the FORWARD GEMMs through the ``ft_counts`` flax collection. The
BACKWARD GEMMs report through the gradient side-channel: one ``(2,)``
``bwd_sink`` array threads through every ``FtDense`` and the step takes
``jax.grad`` with respect to it — the sink's "gradient" is
``[detections, uncorrectable]`` summed over all backward GEMMs
(ops/autodiff.py module docstring), logged here as the ``bwd_det`` /
``bwd_unc`` columns and folded into the same re-run gate. Corruption in
any of the six GEMMs of this MLP's step is corrected or reported —
never silent.

Runs anywhere (real TPU, or CPU interpret mode for a demo):

    python examples/train_ft.py [--steps N] [--no-inject] [--cpu]
                                [--ckpt DIR [--ckpt-every N]]

With ``--no-inject`` the same model runs clean (detections must be 0);
diff the two loss columns to see that injected-and-corrected training
matches clean training to float noise.

With ``--ckpt DIR`` the run checkpoints through
:class:`ft_sgemm_tpu.checkpoint.FtCheckpointer` and RESUMES from the
newest checkpoint on restart — kill it mid-run and rerun the same command
to see the step counter continue. The checkpointer enforces the ABFT
clean-state gate: a step reporting a nonzero ``uncorrectable`` count is
never persisted (checkpointing unverified state would launder detected
corruption into every later resume).

The explicit re-run gate below is written out for clarity; production
loops can use :func:`ft_sgemm_tpu.train.resilient_step`, which packages
the same policy (bounded retry from the pre-step state, restore from the
newest clean checkpoint on persistent reports).
"""

import argparse
import os
import sys

# Runnable from any cwd: anchor the import path on the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--no-inject", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (interpret-mode kernels)")
    ap.add_argument("--ckpt", default=None, metavar="DIR",
                    help="checkpoint/resume through FtCheckpointer")
    ap.add_argument("--ckpt-every", type=int, default=5)
    args = ap.parse_args()
    args.ckpt_every = max(1, args.ckpt_every)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ft_sgemm_tpu import InjectionSpec
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtDense
    from ft_sgemm_tpu.checkpoint import total_count
    from ft_sgemm_tpu.utils import generate_random_matrix

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    inject = (None if args.no_inject
              else InjectionSpec(enabled=True, every=1, magnitude=10000.0))

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, bwd_sink):
            h = jnp.tanh(FtDense(128, shape=tile, inject=inject)(x,
                                                                 bwd_sink))
            return FtDense(128, shape=tile, inject=inject)(h, bwd_sink)

    rng = np.random.default_rng(10)
    x = jnp.asarray(generate_random_matrix(256, 128, rng=rng))
    w_true = jnp.asarray(generate_random_matrix(128, 128, rng=rng))
    y = jnp.tanh(x @ w_true.T)

    model = MLP()
    params = model.init(jax.random.key(0), x, jnp.zeros(2))["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    ckpt, start = None, 0
    if args.ckpt:
        from ft_sgemm_tpu.checkpoint import FtCheckpointer

        ckpt = FtCheckpointer(args.ckpt)
        # The target pytree keeps its structure (incl. optax NamedTuple
        # states) — restore fills the leaves.
        latest, restored = ckpt.restore_latest(
            {"params": params, "opt_state": opt_state})
        if latest is not None:
            params, opt_state = restored["params"], restored["opt_state"]
            start = latest + 1
            print(f"resumed from step {latest} in {args.ckpt}")

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p, sink):
            out, mut = model.apply({"params": p}, x, sink,
                                   mutable=[COUNTS_COLLECTION])
            counts = mut[COUNTS_COLLECTION]
            return jnp.mean((out - y) ** 2), counts

        (loss, counts), (grads, bwd_counts) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, jnp.zeros(2))
        updates, opt_state = tx.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state, loss,
                counts, bwd_counts)

    print(f"backend={jax.default_backend()}  "
          f"inject={'off' if args.no_inject else 'magnitude 1e4, every call'}")
    print(f"{'step':>5} {'loss':>12} {'detected':>9} {'uncorrectable':>14} "
          f"{'bwd_det':>8} {'bwd_unc':>8}")
    try:
        for i in range(start, args.steps):
            params, opt_state, loss, counts, bwd = step(params, opt_state)
            det = total_count(counts, "detections")
            unc = total_count(counts, "uncorrectable")
            bwd_det, bwd_unc = int(bwd[0]), int(bwd[1])
            print(f"{i:>5} {float(loss):>12.6f} {det:>9} {unc:>14} "
                  f"{bwd_det:>8} {bwd_unc:>8}")
            if unc or bwd_unc:
                # Any GEMM of the step (forward or backward) with a
                # violated correction assumption: the step must not be
                # trusted.
                print("uncorrectable interval reported: re-run the step",
                      file=sys.stderr)
                return 1
            if ckpt and ((i + 1) % args.ckpt_every == 0
                         or i == args.steps - 1):
                # The clean gate holds by construction here (unc would
                # have returned above), but pass the report anyway: the
                # gate, not the call site, owns the policy.
                saved = ckpt.save(i, {"params": params,
                                      "opt_state": opt_state},
                                  uncorrectable=unc + bwd_unc)
                if not saved:
                    # False covers orbax should_save skips as well as
                    # gate refusals: a silently missing periodic save
                    # would widen the crash-loss window past --ckpt-every.
                    print(f"warning: checkpoint at step {i} was NOT "
                          "written (save skipped or refused)",
                          file=sys.stderr)
    finally:
        if ckpt:
            ckpt.close()  # waits for in-flight async saves; surfaces
            # their failures even on the error-exit path
    return 0


if __name__ == "__main__":
    sys.exit(main())
