"""End-to-end fault-tolerant training example.

Trains a two-layer MLP regression with EVERY GEMM (forward and backward)
running through the fused-ABFT Pallas kernels while silent-data-corruption
faults of magnitude 1e4 are injected into every kernel call — and logs the
per-layer fault activity each step. The loss curve is indistinguishable
from a fault-free run: that is the framework's end-to-end claim.

The logged ``detected``/``uncorrectable`` columns (and the re-run gate)
observe the FORWARD GEMMs: a ``jax.custom_vjp`` backward has no primal
output to carry counts, so the backward GEMMs are corrected in-kernel by
the same strategy but their counts are not per-step observable
(ops/autodiff.py module docstring). The loss-curve comparison against
``--no-inject`` is what demonstrates the backward path end to end.

Runs anywhere (real TPU, or CPU interpret mode for a demo):

    python examples/train_ft.py [--steps N] [--no-inject] [--cpu]

With ``--no-inject`` the same model runs clean (detections must be 0);
diff the two loss columns to see that injected-and-corrected training
matches clean training to float noise.
"""

import argparse
import os
import sys

# Runnable from any cwd: anchor the import path on the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--no-inject", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (interpret-mode kernels)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ft_sgemm_tpu import InjectionSpec
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtDense
    from ft_sgemm_tpu.utils import generate_random_matrix

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    inject = (None if args.no_inject
              else InjectionSpec(enabled=True, every=1, magnitude=10000.0))

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = jnp.tanh(FtDense(128, shape=tile, inject=inject)(x))
            return FtDense(128, shape=tile, inject=inject)(h)

    rng = np.random.default_rng(10)
    x = jnp.asarray(generate_random_matrix(256, 128, rng=rng))
    w_true = jnp.asarray(generate_random_matrix(128, 128, rng=rng))
    y = jnp.tanh(x @ w_true.T)

    model = MLP()
    params = model.init(jax.random.key(0), x)["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out, mut = model.apply({"params": p}, x,
                                   mutable=[COUNTS_COLLECTION])
            counts = mut[COUNTS_COLLECTION]
            return jnp.mean((out - y) ** 2), counts

        (loss, counts), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, counts

    print(f"backend={jax.default_backend()}  "
          f"inject={'off' if args.no_inject else 'magnitude 1e4, every call'}")
    print(f"{'step':>5} {'loss':>12} {'detected':>9} {'uncorrectable':>14}")
    for i in range(args.steps):
        params, opt_state, loss, counts = step(params, opt_state)
        leaves = jax.tree_util.tree_leaves_with_path(counts)
        det = sum(int(v) for p, v in leaves if "detections" in str(p))
        unc = sum(int(v) for p, v in leaves if "uncorrectable" in str(p))
        print(f"{i:>5} {float(loss):>12.6f} {det:>9} {unc:>14}")
        if unc:
            # Forward-GEMM gate (see module docstring for scope).
            print("uncorrectable interval reported: re-run the step",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
