"""Request cost economics tests (ISSUE 20): the flops-accounted
useful-vs-overhead ledger. Pins the component pricing against the
repo's own cost models (``gemm_cost_breakdown``, ``recover_local``'s
recomputed_flops), the sums-to-one-by-construction snapshot invariant,
useful-fraction degradation on a REAL BlockEngine under injected
faults, the wire-shape tolerance of ``merge_reply``, the live gauge
publish, and the ledger ingest + trend-gate ride of ``economics.*``."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ft_sgemm_tpu.cli import main as cli_main
from ft_sgemm_tpu.ops.common import gemm_cost_breakdown
from ft_sgemm_tpu.perf import ledger
from ft_sgemm_tpu.perf.economics import (
    OVERHEAD_CAUSES,
    CostLedger,
    CostRecord,
    attention_cost,
    gemm_request_cost,
    kv_reverify_flops,
    recovery_overhead,
)
from ft_sgemm_tpu.resilience.recompute import recover_local
from ft_sgemm_tpu.telemetry import MetricsRegistry
from ft_sgemm_tpu.telemetry.registry import to_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Component pricing: one cost model, no second opinion
# ---------------------------------------------------------------------------


def test_gemm_request_cost_matches_cost_breakdown_exactly():
    """The request price IS the roofline's component decomposition:
    base is productive, encode+check are the premium, each retry
    re-executes the whole pass."""
    parts = gemm_cost_breakdown(512, 512, 512, 4,
                                block=(128, 128, 128),
                                strategy="rowcol")
    productive, overhead = gemm_request_cost(parts, retries=2,
                                             recompute_flops=123.0)
    assert productive == parts["flops_base"]
    assert overhead["encode"] == parts["flops_encode"]
    assert overhead["check"] == parts["flops_check"]
    assert overhead["retry"] == 2 * (parts["flops_base"]
                                     + parts["flops_encode"]
                                     + parts["flops_check"])
    assert overhead["recompute"] == 123.0
    # Clean request: no retry/recompute keys at all.
    _, clean = gemm_request_cost(parts)
    assert set(clean) == {"encode", "check"}


def test_attention_cost_formula_pinned():
    lq, lk, d, dv = 128, 256, 16, 16
    parts = attention_cost(lq, lk, d, dv)
    assert parts["flops_base"] == 2 * lq * lk * (d + dv)
    assert parts["flops_encode"] == 2 * (lk * (d + dv) + lq * d)
    assert parts["flops_check"] == 2 * lq * (lk + dv)


def test_kv_reverify_flops_pinned():
    got = kv_reverify_flops(restores=2, reread_rows=40, page_size=8,
                            d=16, dv=16)
    assert got == 2 * 2 * 8 * 32 + 2 * 40 * 32


def test_recovery_overhead_is_recover_local_accounting(rng):
    """The ladder's own flops accounting is the recompute price —
    economics never reprices a recovery."""
    m, n, k = 64, 256, 64
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    bad = (a @ b.T)
    bad[3, 7] += 1000.0
    bad[9, 9] -= 750.0  # multi-element, one panel -> panel_recompute
    _, outcome = recover_local(a, b, bad, num_panels=8)
    assert outcome.rung == "panel_recompute"
    assert recovery_overhead(outcome) == outcome.recomputed_flops
    assert recovery_overhead(outcome) > 0
    # Dict shape (the wire form) prices identically.
    assert recovery_overhead(
        {"recomputed_flops": outcome.recomputed_flops}) \
        == outcome.recomputed_flops


def test_cost_record_rejects_unknown_cause():
    with pytest.raises(ValueError, match="unknown overhead cause"):
        CostRecord(flops_productive=1.0, overhead={"cosmic_rays": 1.0})
    assert "cosmic_rays" not in OVERHEAD_CAUSES


# ---------------------------------------------------------------------------
# Snapshot invariants: fractions sum to 1 by construction
# ---------------------------------------------------------------------------


def test_snapshot_fractions_sum_to_one_exactly():
    led = CostLedger()
    led.add(flops_productive=700.0,
            overhead={"encode": 100.0, "check": 50.0, "retry": 150.0},
            tokens=128, tokens_correct=128, device="cpu:0", bucket="b0")
    led.add(flops_productive=300.0, overhead={"kv_reverify": 200.0},
            tokens=64, tokens_correct=32, device="cpu:1", bucket="b0",
            host=1, ok=False)
    snap = led.snapshot(wall_seconds=2.0)
    total = 700 + 100 + 50 + 150 + 300 + 200
    assert snap["flops_total"] == total
    assert snap["useful_flops_fraction"] == round(1000 / total, 6)
    fracs = snap["overhead_fractions"]
    assert set(fracs) == set(OVERHEAD_CAUSES)
    # The construction pin: useful + every overhead share == 1 exactly
    # (same denominator everywhere), so the breakdown can't sum past 1.
    assert snap["useful_flops_fraction"] + sum(fracs.values()) \
        == pytest.approx(1.0, abs=1e-9)
    assert snap["overhead_flops_fraction"] \
        == pytest.approx(1.0 - snap["useful_flops_fraction"], abs=1e-5)
    assert snap["requests"] == 2 and snap["requests_ok"] == 1
    assert snap["tokens_correct"] == 160
    # 160 correct tokens / 2 s wall / 2 distinct devices.
    assert snap["devices"] == 2
    assert snap["tokens_correct_per_second_per_device"] \
        == pytest.approx(40.0)
    assert snap["per_device"]["cpu:0"]["requests"] == 1
    assert snap["per_bucket"]["b0"]["requests"] == 2
    assert snap["per_host"][1]["tokens_correct"] == 32


def test_empty_ledger_snapshot_is_none_not_garbage():
    snap = CostLedger().snapshot()
    assert snap["useful_flops_fraction"] is None
    assert snap["tokens_correct_per_second_per_device"] is None
    assert snap["flops_total"] == 0


def test_merge_reply_tolerates_hostile_shapes():
    led = CostLedger()
    assert led.merge_reply(None) is None
    assert led.merge_reply("nope") is None
    assert led.merge_reply({"overhead": "broken",
                            "flops_productive": "x"}) is not None
    rec = led.merge_reply({"flops_productive": 10.0,
                           "overhead": {"retry": 5.0, "bogus": 99.0},
                           "tokens": 4, "tokens_correct": 4,
                           "seconds": 0.1}, host=1)
    assert rec is not None
    assert rec.overhead == {"retry": 5.0}  # unknown causes dropped
    snap = led.snapshot()
    assert snap["flops_productive"] == 10.0
    assert snap["flops_overhead"]["retry"] == 5.0


def test_publish_sets_live_gauges():
    led = CostLedger()
    led.add(flops_productive=900.0, overhead={"retry": 100.0},
            tokens=10, tokens_correct=10, device="cpu:0")
    reg = MetricsRegistry()
    snap = led.publish(reg, wall_seconds=1.0, devices=2)
    text = to_prometheus(reg.collect())
    assert "economics_useful_flops_fraction 0.9" in text
    assert 'economics_overhead_flops_fraction{overhead_cause="retry"}' \
        in text
    assert "economics_tokens_correct_per_second_per_device 5" in text
    assert snap["useful_flops_fraction"] == 0.9


# ---------------------------------------------------------------------------
# Real engine: faults make the useful fraction fall
# ---------------------------------------------------------------------------


def test_useful_fraction_falls_under_faults_on_real_engine(rng):
    """End-to-end accounting on a REAL BlockEngine: a clean prefill
    prices only the always-on premium; adversarial in-flight faults add
    retry flops and stored-KV corruption adds kv_reverify flops — the
    useful-flops fraction strictly falls and the causes are named."""
    from ft_sgemm_tpu.serve import (BlockEngine, BlockRequest,
                                    default_block_bucket_set)
    d = 16
    eng = BlockEngine(default_block_bucket_set((128, 256), d=d),
                      max_batch=2, max_wait=0.02, retry_backoff=0.001,
                      kv_page_size=16)
    eng.start()
    try:
        def qkv(n):
            return (rng.standard_normal((n, d)).astype(np.float32),
                    rng.standard_normal((n, d)).astype(np.float32),
                    rng.standard_normal((n, d)).astype(np.float32))

        q, k, v = qkv(40)
        pre = BlockRequest("prefill", q, k, v)
        assert eng.submit(pre).result(timeout=300).ok
        clean = eng.economics.snapshot()
        assert clean["requests"] == 1
        assert 0 < clean["useful_flops_fraction"] < 1
        assert clean["overhead_fractions"]["retry"] == 0
        # Adversarial inject: uncorrectable in flight -> bounded retry.
        q2, k2, v2 = qkv(200)
        res = eng.submit(BlockRequest("prefill", q2, k2, v2,
                                      variant="adversarial")).result(300)
        assert res.ok and res.retries >= 1
        # Stored-state fault: multi-element page corruption -> restore.
        eng.corrupt_kv(pre.seq_id, page=0, row=2, cols=(1, 5, 9),
                       magnitude=400.0)
        q1, k1, v1 = qkv(1)
        res = eng.submit(BlockRequest("decode", q1, k1, v1,
                                      seq_id=pre.seq_id)).result(300)
        assert res.ok and res.kv_restores >= 1
        snap = eng.economics.snapshot()
        assert snap["requests"] == 3
        assert snap["useful_flops_fraction"] \
            < clean["useful_flops_fraction"]
        assert snap["flops_overhead"]["retry"] > 0
        assert snap["flops_overhead"]["kv_reverify"] > 0
        assert snap["useful_flops_fraction"] \
            + sum(snap["overhead_fractions"].values()) \
            == pytest.approx(1.0, abs=1e-4)
        # The engine's stats() carries the same view for bench context.
        st = eng.stats()
        assert st["economics"]["requests"] == 3
        # And the live gauges made it onto the engine registry.
        text = to_prometheus(eng.registry.collect())
        assert "economics_useful_flops_fraction" in text
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Ledger ride: economics.* measurements + trend gate
# ---------------------------------------------------------------------------


def _econ_artifact(useful, tcpspd=50.0):
    return {"metric": "fleet_smoke", "value": 1.0, "unit": "ok",
            "context": {"platform_used": "cpu", "device_kind": "cpu",
                        "economics": {
                            "useful_flops_fraction": useful,
                            "overhead_flops_fraction":
                                round(1.0 - useful, 6),
                            "tokens_correct_per_second_per_device":
                                tcpspd,
                            "requests": 8, "requests_ok": 8,
                            "flops_total": 1e9,
                            "overhead_fractions": {"retry": 0.1},
                            "tokens_correct": 1024}}}


def test_ledger_ingests_economics_measurements():
    entry = ledger.ingest(_econ_artifact(0.85), run_id="r0")
    m = entry["measurements"]
    assert m["economics.useful_flops_fraction"]["value"] == 0.85
    assert m["economics.useful_flops_fraction"]["higher_is_better"]
    assert m["economics.overhead_flops_fraction"]["value"] == 0.15
    assert not m["economics.overhead_flops_fraction"]["higher_is_better"]
    assert m["economics.tokens_correct_per_second_per_device"][
        "value"] == 50.0
    assert entry["economics"]["overhead_fractions"] == {"retry": 0.1}
    # The fleet-nested spelling ingests identically.
    econ = _econ_artifact(0.85)["context"]["economics"]
    art = {"metric": "fleet_smoke", "value": 1.0, "unit": "ok",
           "context": {"platform_used": "cpu", "device_kind": "cpu",
                       "fleet": {"economics": econ}}}
    nested = ledger.ingest(art, run_id="r1")
    assert nested["measurements"]["economics.useful_flops_fraction"][
        "value"] == 0.85


def test_trend_gate_fails_on_useful_fraction_regression(tmp_path,
                                                        capsys):
    """ISSUE 20 acceptance: a seeded useful-flops-fraction collapse
    trips `cli trend --gate` exit 1 on the economics series."""
    path = str(tmp_path / "led.jsonl")
    for i in range(4):
        ledger.append(path, ledger.ingest(_econ_artifact(0.9),
                                          run_id=f"r{i}"))
    ledger.append(path, ledger.ingest(_econ_artifact(0.45),
                                      run_id="regressed"))
    rc = cli_main(["cli", "trend", path, "--gate"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "economics.useful_flops_fraction" in out
    assert "regression" in out


def test_trend_gate_passes_on_stable_economics(tmp_path, capsys):
    path = str(tmp_path / "led.jsonl")
    for i in range(5):
        ledger.append(path, ledger.ingest(_econ_artifact(0.9),
                                          run_id=f"r{i}"))
    assert cli_main(["cli", "trend", path, "--gate"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# CLI report + stdlib discipline
# ---------------------------------------------------------------------------


def test_cli_economics_report(tmp_path, capsys):
    art = tmp_path / "artifact.json"
    art.write_text(json.dumps(_econ_artifact(0.85)), encoding="utf-8")
    assert cli_main(["cli", "economics", str(art)]) == 0
    out = capsys.readouterr().out
    assert "useful flops" in out and "85" in out
    assert "retry" in out
    # JSON mode round-trips the block.
    assert cli_main(["cli", "economics", str(art),
                     "--format=json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["useful_flops_fraction"] == 0.85
    # Missing block -> rc 1; unreadable -> rc 2.
    bare = tmp_path / "bare.json"
    bare.write_text("{}", encoding="utf-8")
    assert cli_main(["cli", "economics", str(bare)]) == 1
    capsys.readouterr()
    assert cli_main(["cli", "economics",
                     str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()


def test_economics_module_is_loadable_without_the_package(tmp_path):
    """timeline.py discipline: the jax-free supervisor loads the cost
    plane directly from its file path — no package import, no jax."""
    script = tmp_path / "load_economics.py"
    script.write_text(
        "import importlib.util, os, sys\n"
        f"path = os.path.join({REPO!r}, 'ft_sgemm_tpu', 'perf',"
        " 'economics.py')\n"
        "for mod in list(sys.modules):\n"
        "    assert not mod.startswith('ft_sgemm_tpu'), mod\n"
        "spec = importlib.util.spec_from_file_location('_econ', path)\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "sys.modules['_econ'] = m\n"
        "spec.loader.exec_module(m)\n"
        "led = m.CostLedger()\n"
        "led.add(flops_productive=9.0, overhead={'retry': 1.0})\n"
        "snap = led.snapshot(wall_seconds=1.0)\n"
        "assert snap['useful_flops_fraction'] == 0.9, snap\n"
        "assert 'jax' not in sys.modules\n"
        "assert 'numpy' not in sys.modules\n"
        "print('OK')\n", encoding="utf-8")
    out = subprocess.run([sys.executable, str(script)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def test_cli_top_renders_economics_and_fleet_rows():
    """`cli top` surfaces the live cost plane (economics_* gauges) and
    the fleet rows (per-host clock skew, merged hop percentiles) from a
    real /metrics scrape."""
    import io

    from ft_sgemm_tpu.cli import run_top
    from ft_sgemm_tpu.telemetry.monitor import start_monitor
    from ft_sgemm_tpu.telemetry.registry import LATENCY_BUCKETS

    reg = MetricsRegistry()
    led = CostLedger()
    led.add(flops_productive=900.0, overhead={"retry": 100.0},
            tokens=64, tokens_correct=64, device="cpu:0")
    led.publish(reg, wall_seconds=1.0)
    reg.gauge("fleet_clock_skew_seconds", host="1").set(0.012)
    reg.histogram("fleet_hop_rtt_seconds", buckets=LATENCY_BUCKETS,
                  host="1", host_tier="dcn").observe(0.004)
    mon, server = start_monitor(0, registry=reg, attach=False)
    try:
        buf = io.StringIO()
        assert run_top(server.url, out=buf, interval=0.01,
                       iterations=1) == 0
        txt = buf.getvalue()
        assert "economics: useful flops 0.9" in txt
        assert "overhead:" in txt and "retry=0.1" in txt
        assert "fleet: clock skew host1=+0.0120s" in txt
        assert "hop rtt" in txt and "n 1" in txt
    finally:
        server.close()
