"""Serving-layer tests: bucketing edge cases, continuous-batching
dispatch, the SLO-aware retry contract (corrected SDC = zero retries;
uncorrectable = bucket-scoped retry only), warm-path purity (zero compile
spans in steady state, pinned through perf/wallclock attribution), the
telemetry-histogram latency percentiles, and the concurrency-safety of
the tuner/compile caches under threaded dispatch (ISSUE 8)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ft_sgemm_tpu.serve import (
    Bucket,
    BucketOverflowError,
    ServeEngine,
    ServeRequest,
    default_bucket_set,
    select_bucket,
)
from ft_sgemm_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    MetricsRegistry,
    histogram_percentiles,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Bucketing edge cases (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_bucket_boundary_exact_routes_to_own_bucket():
    buckets = default_bucket_set((256, 512))
    b = select_bucket(buckets, 256, 256, 256)
    assert (b.m, b.n, b.k) == (256, 256, 256)


def test_bucket_smallest_fit_wins():
    buckets = default_bucket_set((256, 512, 1024))
    assert select_bucket(buckets, 200, 180, 257).k == 512
    assert select_bucket(buckets, 100, 100, 100).m == 256


def test_bucket_overflow_is_named_error():
    buckets = default_bucket_set((256,))
    with pytest.raises(BucketOverflowError) as ei:
        select_bucket(buckets, 257, 100, 100)
    msg = str(ei.value)
    assert "257x100x100" in msg and "256x256x256" in msg
    # It is also a ValueError, so generic callers degrade sanely.
    assert isinstance(ei.value, ValueError)


def test_bucket_dims_must_be_mxu_granules():
    with pytest.raises(ValueError, match="multiple of 128"):
        Bucket(100, 128, 128)
    with pytest.raises(ValueError, match="powers of two"):
        default_bucket_set((384,))


def test_int8_buckets_route_to_rowcol():
    """PR-7 legality: int8 ships only the exact strategies, so the
    default int8 bucket set is rowcol and a ratio-localizing int8 bucket
    is rejected with the kernel factory's own error."""
    buckets = default_bucket_set((256,), in_dtype="int8")
    assert all(b.strategy == "rowcol" for b in buckets)
    with pytest.raises(ValueError, match="int8"):
        Bucket(256, 256, 256, in_dtype="int8", strategy="weighted")
    b = select_bucket(buckets, 100, 100, 100, in_dtype="int8")
    assert b.in_dtype == "int8" and b.strategy == "rowcol"


def test_dtype_mismatch_has_no_bucket():
    buckets = default_bucket_set((256,), in_dtype="float32")
    with pytest.raises(BucketOverflowError, match="none configured"):
        select_bucket(buckets, 128, 128, 128, in_dtype="int8")


# ---------------------------------------------------------------------------
# Engine: continuous batching + retry contract
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    """One prewarmed two-bucket engine shared by the dispatch tests —
    prewarm compiles 3 variants x 2 buckets once for the module, and its
    streamed timeline is what the warm-path test reads afterwards."""
    tl_path = str(tmp_path_factory.mktemp("serve") / "serve.timeline.jsonl")
    eng = ServeEngine(default_bucket_set((128, 256)),
                      max_batch=3, max_wait=0.05, retry_backoff=0.001,
                      timeline=tl_path)
    eng.start()
    eng.prewarm()
    yield eng
    eng.close()


def _request(rng, m, n, k, variant="clean"):
    return ServeRequest(
        a=rng.standard_normal((m, k)).astype(np.float32),
        b=rng.standard_normal((n, k)).astype(np.float32),
        variant=variant)


def test_empty_queue_drain_returns_immediately(engine):
    t0 = time.monotonic()
    engine.drain(timeout=5.0)
    assert time.monotonic() - t0 < 1.0


def test_max_wait_flush_fires_before_batch_full(engine, rng):
    """A single request (batch of 1 of max 3) must flush on the max-wait
    deadline, not wait for batchmates that never come."""
    fut = engine.submit(_request(rng, 100, 110, 90))
    res = fut.result(timeout=60.0)
    assert res.ok and res.retries == 0
    assert res.c.shape == (100, 110)


def test_batch_full_flushes_before_max_wait(rng):
    """max_batch requests flush immediately even under an enormous
    max-wait — continuous batching, not fixed-window batching."""
    eng = ServeEngine(default_bucket_set((128,)), max_batch=2,
                      max_wait=60.0)
    eng.start()
    try:
        t0 = time.monotonic()
        futs = [eng.submit(_request(rng, 64, 64, 64)) for _ in range(2)]
        for f in futs:
            assert f.result(timeout=120.0).ok
        assert time.monotonic() - t0 < 50.0  # nowhere near max_wait
    finally:
        eng.close()


def test_result_is_correct_and_sliced(engine, rng):
    req = _request(rng, 120, 70, 130)
    res = engine.submit(req).result(timeout=60.0)
    want = req.a @ req.b.T
    assert res.c.shape == want.shape
    np.testing.assert_allclose(res.c, want, rtol=1e-4, atol=1e-3)


def test_corrected_sdc_is_free(engine, rng):
    """THE acceptance pin: a detected-and-corrected SDC completes with
    ZERO retries and a numerically correct result."""
    before = engine.stats()
    req = _request(rng, 200, 180, 160, variant="inject")
    res = engine.submit(req).result(timeout=60.0)
    assert res.detections > 0
    assert res.uncorrectable == 0
    assert res.corrected and res.ok
    assert res.retries == 0
    want = req.a @ req.b.T
    np.testing.assert_allclose(res.c, want, rtol=1e-4, atol=1e-3)
    after = engine.stats()
    assert after["corrected_free"] == before["corrected_free"] + 1
    assert after["retries"] == before["retries"]
    assert after["whole_queue_retries"] == 0


def test_uncorrectable_retries_only_affected_bucket(engine, rng):
    """THE other acceptance pin: an uncorrectable fault retries only the
    affected bucket's request — the other bucket's traffic (and the
    queue as a whole) never re-executes."""
    before = engine.stats()
    bad = engine.submit(_request(rng, 200, 200, 200,
                                 variant="adversarial"))
    clean = [engine.submit(_request(rng, 64, 64, 64)) for _ in range(3)]
    res = bad.result(timeout=120.0)
    assert res.retries >= 1          # the fault cost a bucket retry
    assert res.ok                    # ...and the retry (clean) succeeded
    for f in clean:
        r = f.result(timeout=60.0)
        assert r.ok and r.retries == 0
    after = engine.stats()
    big, small = "256x256x256|float32|weighted", "128x128x128|float32|weighted"
    assert (after["per_bucket"][big]["retries"]
            > before["per_bucket"][big]["retries"])
    assert (after["per_bucket"][small]["retries"]
            == before["per_bucket"][small]["retries"])
    assert after["whole_queue_retries"] == 0


def test_per_request_attribution_and_prom_export(engine, rng, tmp_path):
    """Each request's own counter grids feed its fault event (request id,
    bucket, tile blame), and the event log exports the latency histogram
    through `cli telemetry --format=prom` — the registry machinery is
    the only percentile implementation."""
    import io

    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.cli import run_telemetry_summary
    from ft_sgemm_tpu.telemetry import read_events, registry_from_events
    from ft_sgemm_tpu.telemetry.registry import to_prometheus

    log = tmp_path / "serve_events.jsonl"
    telemetry.configure(log, log_clean=True)
    try:
        reqs = [_request(rng, 150, 150, 150, variant="inject"),
                _request(rng, 64, 64, 64, variant="clean")]
        for res in [engine.submit(r).result(timeout=60.0) for r in reqs]:
            assert res.ok
    finally:
        telemetry.disable()
    events = [e for e in read_events(log) if e.op == "serve_gemm"]
    assert len(events) == 2
    by_id = {e.extra["request_id"]: e for e in events}
    inj_ev = by_id[reqs[0].request_id]
    assert inj_ev.outcome == "corrected"
    assert inj_ev.tiles, "per-request tile blame missing"
    assert inj_ev.extra["bucket"] == "256x256x256|float32|weighted"
    assert inj_ev.layer == inj_ev.extra["bucket"]
    assert inj_ev.extra["latency_seconds"] > 0
    assert by_id[reqs[1].request_id].outcome == "clean"
    # Rebuilt registry carries the serve latency histogram...
    reg = registry_from_events(read_events(log))
    prom = to_prometheus(reg.collect())
    assert "serve_latency_seconds_bucket" in prom
    assert 'op="serve_gemm"' in prom
    # ...and the CLI's prom exporter is the same path.
    buf = io.StringIO()
    assert run_telemetry_summary(str(log), out=buf, fmt="prom") == 0
    assert "serve_latency_seconds_bucket" in buf.getvalue()


def test_int8_requests_run_exact(rng):
    """int8 requests route to the rowcol bucket and come back EXACT
    (int32 accumulation): the serving path for production quant dtypes."""
    eng = ServeEngine(default_bucket_set((128,), in_dtype="int8"),
                      max_batch=2, max_wait=0.02)
    eng.start()
    try:
        a = np.round(rng.standard_normal((100, 90)) * 3).astype(np.float32)
        b = np.round(rng.standard_normal((80, 90)) * 3).astype(np.float32)
        res = eng.submit(ServeRequest(a=a, b=b, in_dtype="int8")
                         ).result(timeout=120.0)
        assert res.ok
        np.testing.assert_array_equal(res.c, a @ b.T)
    finally:
        eng.close()


def test_overflow_submit_counts_rejection(engine, rng):
    before = engine.stats()["rejected"]
    with pytest.raises(BucketOverflowError):
        engine.submit(_request(rng, 300, 100, 100))
    assert engine.stats()["rejected"] == before + 1


# ---------------------------------------------------------------------------
# Warm-path purity: zero compile spans in steady state
# ---------------------------------------------------------------------------


def test_prewarmed_steady_state_records_zero_compile_spans(engine):
    """Acceptance pin: every compile span in the engine's timeline
    precedes the prewarm_done point; the steady-state window attributes
    ZERO wall to the compile phase (perf/wallclock)."""
    from ft_sgemm_tpu.perf import wallclock
    from ft_sgemm_tpu.telemetry import timeline as tl_mod

    engine.drain(timeout=30.0)
    records = tl_mod.read_timeline(engine._tl.path)
    done = [r for r in records if r.get("name") == "prewarm_done"]
    assert done, "prewarm_done point missing from timeline"
    t_done = done[0]["t"]
    pre = [r for r in records if r["t"] <= t_done]
    post = [r for r in records if r["t"] > t_done]
    assert any(r.get("kind") == "compile" for r in pre), \
        "prewarm compiles must be recorded"
    assert not any(r.get("kind") == "compile" for r in post), \
        "steady-state serve dispatched a compile"
    # Served batches exist after prewarm, and the phase attribution of
    # the steady-state window books zero compile wall.
    summary = tl_mod.summarize_timeline(post)
    assert any(s["kind"] == "stage" and s["name"].startswith("serve[")
               for s in summary["spans"])
    wall = wallclock.attribute_wall(summary)
    assert wall["seconds"]["compile"] == 0.0
    assert wall["fractions"]["compile"] == 0.0


def test_unprewarmed_compile_is_recorded_honestly(rng, tmp_path):
    """Without prewarm, the first dispatch's compile lands as a compile
    span — the timeline never claims a warm path it didn't have."""
    from ft_sgemm_tpu.telemetry import timeline as tl_mod

    tl_path = str(tmp_path / "cold.timeline.jsonl")
    eng = ServeEngine(default_bucket_set((128,)), max_batch=1,
                      max_wait=0.01, timeline=tl_path)
    eng.start()
    try:
        assert eng.submit(_request(rng, 64, 64, 64)).result(120.0).ok
    finally:
        eng.close()
    records = tl_mod.read_timeline(tl_path)
    assert any(r.get("kind") == "compile"
               and r["name"].startswith("compile[") for r in records)


# ---------------------------------------------------------------------------
# Latency percentiles: the telemetry histogram machinery IS the stats
# ---------------------------------------------------------------------------


def test_latency_percentiles_pinned_on_synthetic_distribution():
    """p50/p99 against a known distribution: 10 obs in the ~2ms
    half-decade, 10 in the ~20ms one, 1 at 50s. Estimates resolve to
    bucket upper bounds (the documented Prometheus-style contract)."""
    reg = MetricsRegistry()
    hist = reg.histogram("serve_latency_seconds", buckets=LATENCY_BUCKETS)
    for _ in range(10):
        hist.observe(0.002)
    for _ in range(10):
        hist.observe(0.02)
    hist.observe(50.0)
    pct = histogram_percentiles(hist.value, quantiles=(0.5, 0.99))
    # 21 obs: p50 needs 10.5 -> second populated bucket (ub 10^-1.5);
    # p99 needs 20.79 -> the 50s outlier's bucket (ub 100).
    assert pct["p50"] == pytest.approx(10.0 ** -1.5)
    assert pct["p99"] == pytest.approx(100.0)
    assert pct["max"] == pytest.approx(100.0)


def test_engine_latency_percentiles_live(engine):
    pct = engine.latency_percentiles()
    assert pct["p50"] is not None and pct["p99"] is not None
    assert pct["p50"] <= pct["p99"]


# ---------------------------------------------------------------------------
# Cache thread-safety under concurrent dispatch (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_tuner_cache_threaded_lookups_and_stores(tmp_path, monkeypatch):
    """8 reader threads hammer lookup_tile while a writer stores fresh
    winners: no exceptions, every read is either a miss or a valid
    cached tile, and the final state serves the last store."""
    from ft_sgemm_tpu import tuner
    from ft_sgemm_tpu.tuner import cache

    path = str(tmp_path / "tuner_cache.json")
    monkeypatch.setenv("FT_SGEMM_TUNER_CACHE", path)
    cache.clear_memo()
    key = tuner.make_key(512, 512, 512, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    errors = []

    def reader():
        try:
            for _ in range(300):
                tile = tuner.lookup_tile(512, 512, 512,
                                         strategy="weighted",
                                         in_dtype="float32",
                                         injection_enabled=False)
                assert tile is None or tile.block[0] % 128 == 0
        except Exception as e:  # noqa: BLE001 — the test's whole point
            errors.append(e)

    def writer():
        try:
            for i in range(10):
                cache.store(key, {"block": [128 * (1 + i % 4), 128, 128]},
                            path)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = ([threading.Thread(target=reader) for _ in range(8)]
               + [threading.Thread(target=writer)])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not errors, errors
    tile = tuner.lookup_tile(512, 512, 512, strategy="weighted",
                             in_dtype="float32", injection_enabled=False)
    assert tile is not None and tile.block == (128 * (1 + 9 % 4), 128, 128)
    cache.clear_memo()


def test_compile_cache_enable_threaded(tmp_path, monkeypatch):
    """Concurrent enable() calls (the serving layer's dispatch vs a
    prewarm) serialize on the enable lock: every caller sees a
    consistent enabled status pointing at the same directory."""
    from ft_sgemm_tpu.perf import compile_cache

    cache_dir = str(tmp_path / "jaxcache")
    monkeypatch.setenv("FT_SGEMM_COMPILE_CACHE", cache_dir)
    results = []
    errors = []

    def worker():
        try:
            results.append(compile_cache.enable())
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors, errors
        assert len(results) == 8
        assert all(r["enabled"] for r in results), results
        assert all(r["path"] == cache_dir for r in results)
    finally:
        compile_cache.disable()
        compile_cache._reset_for_tests()


# ---------------------------------------------------------------------------
# bench.py --serve --smoke + CLI surfaces
# ---------------------------------------------------------------------------


def test_cli_serve_dry_run(capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "serve", "--dry-run", "--buckets=256,512"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "256x256x256|float32|weighted" in out
    assert "512x512x512|float32|weighted" in out
    assert "tuner-key" in out
    assert "dry run: nothing compiled" in out


def test_headline_prewarm_plan_matches_ladder():
    """ISSUE 8 satellite: the worker's automatic prewarm compiles the
    headline ladder's exact recipe set, in ladder order."""
    import bench

    plan = bench._headline_prewarm_plan(4096, 512)
    labels = [label for label, _ in plan]
    assert labels == ["weighted", "weighted_inkernel", "rowcol"]
    assert plan[1][1] == {"strategy": "weighted", "check_every": 4}
    # Shallow K: the in-kernel rung drops, ladder order survives.
    assert [l for l, _ in bench._headline_prewarm_plan(512, 512)] == [
        "weighted", "rowcol"]


def test_bench_serve_smoke_emits_goodput_artifact(tmp_path):
    """Acceptance: `bench.py --serve --smoke` on CPU emits ONE non-null
    JSON line with p50/p99 latency, throughput, and goodput-under-
    injection; zero whole-queue retries; every completed request correct
    (corrected SDCs free, uncorrectable ones recovered by bucket-scoped
    retry); zero steady-state compile spans."""
    tl_path = str(tmp_path / "serve.timeline.jsonl")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               FT_SGEMM_BENCH_TIMELINE=tl_path,
               FT_SGEMM_TUNER_CACHE=str(tmp_path / "tuner_cache.json"),
               FT_SGEMM_COMPILE_CACHE="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serve",
         "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    art = json.loads(line)
    assert art["metric"] == "serve_goodput_rps"
    assert art["value"] is not None and art["value"] > 0
    ctx = art["context"]
    assert ctx["p50_latency_seconds"] is not None
    assert ctx["p99_latency_seconds"] is not None
    assert ctx["throughput_rps"] > 0
    assert ctx["goodput_rps"] > 0
    assert ctx["whole_queue_retries"] == 0
    assert ctx["uncorrectable_final"] == 0
    assert ctx["correct"] == ctx["completed"] > 0
    assert ctx["verified"] is True
    assert ctx["steady_state_compile_spans"] == 0
    assert ctx["smoke"] is True and ctx["serve"] is True
    # The injection actually happened (goodput-UNDER-INJECTION).
    assert ctx["variants"].get("inject", 0) + ctx["variants"].get(
        "adversarial", 0) > 0
    assert os.path.exists(tl_path)
