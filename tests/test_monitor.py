"""Live observability plane tests (ISSUE 9): request-scoped trace IDs
joined across serve/retry events via the /events endpoint, the stdlib
HTTP exporter (/metrics, /healthz, /events), SLO error budgets with
edge-triggered alerts, continuous device-health scoring (mesh
``inject_coords`` localization goes LIVE), the scrape-clean Prometheus
exposition (# HELP/# TYPE + label escaping, pinned by a parser
round-trip), concurrent scrape-during-serve safety, `cli top`,
`cli telemetry --watch`, and the zero-overhead-off pin."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.serve import (
    ServeEngine,
    ServeRequest,
    default_bucket_set,
)
from ft_sgemm_tpu.serve.tracing import (
    current_trace_id,
    new_trace_id,
    stamp,
    trace_scope,
)
from ft_sgemm_tpu.telemetry.monitor import (
    DeviceHealthTracker,
    EventRing,
    HealthConfig,
    Monitor,
    MonitorServer,
    SloConfig,
    SloTracker,
)
from ft_sgemm_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    MetricsRegistry,
    parse_prometheus,
    to_prometheus,
)


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


# NOTE on ordering: the module-scoped ``served`` fixture shares the
# process-wide telemetry registry, so tests that RESET global telemetry
# (the mesh-localization acceptance test) are placed after every
# served-dependent test — file order is execution order under the
# suite's no-randomization config.


# ---------------------------------------------------------------------------
# Tracing primitives
# ---------------------------------------------------------------------------


def test_trace_ids_are_unique_and_scoped():
    ids = {new_trace_id() for _ in range(256)}
    assert len(ids) == 256
    assert all(len(t) == 16 for t in ids)
    assert current_trace_id() is None
    with trace_scope("outer123"):
        assert current_trace_id() == "outer123"
        with trace_scope("inner456"):
            assert current_trace_id() == "inner456"
        assert current_trace_id() == "outer123"
    assert current_trace_id() is None


def test_stamp_merges_without_overwriting():
    assert stamp(None) is None  # no ambient id: untouched
    with trace_scope("t1"):
        assert stamp(None) == {"trace_id": "t1"}
        assert stamp({"k": 1}) == {"k": 1, "trace_id": "t1"}
        # An explicit id on the event wins over the ambient scope.
        assert stamp({"trace_id": "explicit"}) == {"trace_id": "explicit"}
    assert stamp({"k": 1}, trace_id="t2") == {"k": 1, "trace_id": "t2"}


# ---------------------------------------------------------------------------
# Event ring
# ---------------------------------------------------------------------------


def test_event_ring_since_semantics():
    ring = EventRing(capacity=4)
    for i in range(6):
        ring.append({"i": i})
    events, cursor = ring.since(0)
    assert cursor == 6
    assert [e["i"] for e in events] == [2, 3, 4, 5]  # capacity-bounded
    newer, cursor2 = ring.since(cursor)
    assert newer == [] and cursor2 == 6
    ring.append({"i": 6})
    newer, _ = ring.since(cursor)
    assert [e["i"] for e in newer] == [6]
    limited, _ = ring.since(0, limit=2)
    assert [e["i"] for e in limited] == [5, 6]


# ---------------------------------------------------------------------------
# Prometheus exposition: scrape-clean + parser round trip (satellite)
# ---------------------------------------------------------------------------


def test_prometheus_has_help_and_type_per_family():
    reg = MetricsRegistry()
    reg.counter("ft_detections", op="x").inc(3)
    reg.gauge("device_health", device="d0").set(0.5)
    reg.histogram("serve_latency_seconds",
                  buckets=LATENCY_BUCKETS).observe(0.01)
    text = to_prometheus(reg.collect())
    for family in ("ft_detections", "device_health",
                   "serve_latency_seconds"):
        assert f"# HELP {family} " in text
        assert f"# TYPE {family} " in text
        # HELP precedes TYPE precedes samples (exposition convention).
        assert text.index(f"# HELP {family}") < text.index(
            f"# TYPE {family}")
    # Known families carry real help strings, not the generic fallback.
    assert "# HELP device_health Continuous per-device health" in text


def test_prometheus_label_escaping_and_round_trip():
    """The exposition is scrape-clean: hostile label values (newlines,
    quotes, backslashes) escape correctly and the whole document parses
    back into the exact collect() snapshot."""
    reg = MetricsRegistry()
    reg.counter("ft_calls", op='quo"te', layer="back\\slash").inc(2)
    reg.counter("ft_calls", op="multi\nline").inc(5)
    reg.gauge("device_health", device="TFRT_CPU_0").set(0.875)
    h = reg.histogram("ft_residual", buckets=(1.0, 10.0, float("inf")),
                      op="a b")
    h.observe(0.5)
    h.observe(5.0)
    h.observe(1e9)
    text = to_prometheus(reg.collect())
    # The hostile values came out escaped, not raw (a raw newline in a
    # label value would tear every later series off the scrape).
    assert 'op="multi\\nline"' in text
    assert 'op="quo\\"te"' in text
    assert 'layer="back\\\\slash"' in text
    parsed = parse_prometheus(text)

    def norm(series):
        return sorted(
            (json.dumps({"kind": s["kind"], "name": s["name"],
                         "labels": s["labels"], "value": s["value"]},
                        sort_keys=True))
            for s in series)

    # Names sanitize identically on both sides (no dots in these), so
    # the round trip is exact: kinds, labels, values, histogram buckets.
    assert norm(parsed) == norm(
        [{"kind": s["kind"], "name": s["name"], "labels": s["labels"],
          "value": (dict(s["value"],
                         buckets=[float(b) for b in s["value"]["buckets"]])
                    if s["kind"] == "histogram" else s["value"])}
         for s in reg.collect()])


def test_parse_prometheus_rejects_torn_lines():
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus("ft_calls{op=\"x\"} ")


def test_monitor_and_tracing_load_without_the_package(tmp_path):
    """The timeline discipline extended: monitor.py and tracing.py are
    stdlib-only at module scope and work loaded by FILE PATH (the
    jax-free exporter constraint — in-package collaborators are lazy
    and injectable)."""
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent / "ft_sgemm_tpu"

    def load(rel, name):
        spec = importlib.util.spec_from_file_location(name, root / rel)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    tr = load("serve/tracing.py", "_standalone_tracing")
    with tr.trace_scope(tr.new_trace_id()) as tid:
        assert tr.current_trace_id() == tid

    mon_mod = load("telemetry/monitor.py", "_standalone_monitor")
    alerts = []
    mon = mon_mod.Monitor(
        registry=MetricsRegistry(), render=to_prometheus,
        emit_alert=alerts.append,
        slo=mon_mod.SloConfig(p99_latency_seconds=0.001, budget=0.01))
    mon.observe_request({"outcome": "clean", "op": "serve_gemm",
                         "device": "d0",
                         "extra": {"latency_seconds": 1.0, "ok": True}})
    assert alerts and alerts[0]["extra"]["kind"] == "slo_burn"
    srv = mon_mod.MonitorServer(mon, port=0).start()
    try:
        _, text = _get(srv.url + "/metrics")
        assert "slo_burn_rate" in text
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------


def test_slo_budget_and_burn_math():
    slo = SloTracker(SloConfig(p99_latency_seconds=1.0, budget=0.1,
                               window_seconds=3600.0))
    for _ in range(18):
        slo.record(0.1, True)
    s = slo.snapshot()
    assert s["burn_rate"] == 0.0 and s["budget_remaining"] == 1.0
    slo.record(5.0, True)   # latency violation
    slo.record(0.1, False)  # failure violation
    s = slo.snapshot()
    assert s["requests"] == 20 and s["violations"] == 2
    # 2/20 violating at a 10% budget -> burn exactly 1.0.
    assert s["burn_rate"] == pytest.approx(1.0)
    assert s["budget_remaining"] == pytest.approx(0.0)
    assert s["goodput_ratio"] == pytest.approx(18 / 20)
    assert s["observed_p99_seconds"] == pytest.approx(5.0)


def test_slo_alert_fires_once_on_crossing_and_rearms():
    fired = []
    slo = SloTracker(SloConfig(p99_latency_seconds=1.0, budget=0.5,
                               window_seconds=0.5),
                     on_alert=fired.append)
    t = 1000.0
    slo.record(9.0, False, now=t)  # 1/1 violating, burn 2.0 -> alert
    assert len(fired) == 1 and fired[0]["burn_rate"] >= 1.0
    slo.record(9.0, False, now=t + 0.01)  # still burning: NO new edge
    assert len(fired) == 1
    # Window rolls past the violations -> burn drops to 0 -> re-armed.
    for i in range(10):
        slo.record(0.1, True, now=t + 1.0 + i * 0.01)
    assert slo.snapshot(now=t + 1.2)["burn_rate"] == 0.0
    slo.record(9.0, False, now=t + 2.0)
    slo.record(9.0, False, now=t + 2.01)
    assert len(fired) == 2


def test_slo_alert_lands_in_jsonl_stream(tmp_path):
    """The threshold-crossing alert is a normal JSONL event: outcome
    "alert", op "monitor", crossing facts in extra."""
    log = tmp_path / "ev.jsonl"
    telemetry.reset()
    telemetry.configure(log)
    mon = Monitor(slo=SloConfig(p99_latency_seconds=0.001, budget=0.01,
                                window_seconds=60.0))
    mon.observe_request({"outcome": "clean", "op": "serve_gemm",
                         "device": "d0",
                         "extra": {"latency_seconds": 5.0, "ok": True}})
    telemetry.disable()
    alerts = [e for e in telemetry.read_events(log)
              if e.outcome == "alert"]
    assert len(alerts) == 1
    assert alerts[0].op == "monitor"
    assert alerts[0].extra["kind"] == "slo_burn"
    assert alerts[0].extra["burn_rate"] >= 1.0


# ---------------------------------------------------------------------------
# Device health
# ---------------------------------------------------------------------------


def test_device_health_clean_is_one_faulty_ranks_below():
    t = DeviceHealthTracker()
    t.observe("clean", calls=10)
    t.observe("noisy", calls=10, detected=10)
    t.observe("broken", calls=10, detected=10, uncorrectable=5)
    s = t.scores()
    assert s["clean"] == 1.0
    assert s["broken"] < s["noisy"] < s["clean"]


def test_device_health_drift_flags_before_uncorrectables():
    """Residual creep toward the threshold lowers the score with ZERO
    fault counts on the books — the early-warning the ISSUE names."""
    cfg = HealthConfig(drift_min_n=20)
    t = DeviceHealthTracker(cfg)
    rng = np.random.default_rng(0)
    for _ in range(50):  # baseline: residuals ~1e-3
        t.observe("d0", calls=1,
                  residual=1e-3 * (1 + 0.05 * rng.standard_normal()))
    healthy = t.score("d0")
    # Stationary jitter stays inside the drift grace: score holds at 1.
    assert healthy > 0.95
    for _ in range(8):  # creep: two decades toward the threshold
        t.observe("d0", calls=1, residual=1e-1)
    assert t.drift_z("d0") > 1.5
    assert t.score("d0") < 0.8 < healthy
    # Still zero faults: this is drift detection, not fault counting.
    assert t.rows()["d0"]["detected"] == 0
    assert t.rows()["d0"]["uncorrectable"] == 0


def test_sync_counts_is_idempotent():
    t = DeviceHealthTracker()
    t.sync_counts("d0", calls=8, detected=4, uncorrectable=0)
    first = t.score("d0")
    t.sync_counts("d0", calls=8, detected=4, uncorrectable=0)
    assert t.score("d0") == first  # re-scrape never double-counts


# ---------------------------------------------------------------------------
# HTTP exporter
# ---------------------------------------------------------------------------


def test_monitor_server_endpoints():
    mon = Monitor(registry=MetricsRegistry())
    mon.observe_request({"outcome": "clean", "op": "serve_gemm",
                         "device": "d0",
                         "extra": {"latency_seconds": 0.01, "ok": True,
                                   "trace_id": "abc"}})
    srv = MonitorServer(mon, port=0).start()
    try:
        assert srv.port > 0
        code, metrics = _get(srv.url + "/metrics")
        assert code == 200
        assert "slo_budget_remaining 1.0" in metrics
        assert 'device_health{device="d0"} 1.0' in metrics
        parse_prometheus(metrics)  # valid exposition
        code, health = _get(srv.url + "/healthz")
        assert code == 200
        h = json.loads(health)
        assert h["status"] == "OK" and h["reasons"] == []
        code, ev = _get(srv.url + "/events?since=0")
        body = json.loads(ev)
        assert body["next"] == 1
        assert body["events"][0]["extra"]["trace_id"] == "abc"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()


def test_healthz_failing_returns_503():
    mon = Monitor(registry=MetricsRegistry())
    mon.health.observe("dead", calls=10, detected=10, uncorrectable=10)
    assert mon.health_status()["status"] == "FAILING"
    srv = MonitorServer(mon, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/healthz")
        assert ei.value.code == 503
        body = json.loads(ei.value.read().decode())
        assert body["status"] == "FAILING"
        assert any("dead" in r for r in body["reasons"])
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# End-to-end: serve engine + monitor
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    """One monitored, prewarmed engine that served a known load: a
    correctable-injected request, a clean one, and an adversarial one
    (whose uncorrectable fault costs a bucket-scoped retry). Shared by
    the trace-join / exporter / concurrency tests below."""
    telemetry.reset()
    registry = telemetry.configure(None, log_clean=True)
    mon = Monitor(registry=registry,
                  slo=SloConfig(p99_latency_seconds=600.0)).attach()
    srv = MonitorServer(mon, port=0).start()
    rng = np.random.default_rng(10)
    eng = ServeEngine(default_bucket_set((128, 256)), max_batch=2,
                      max_wait=0.02, retry_backoff=0.001, monitor=mon)
    eng.start()
    eng.prewarm()

    def req(m, n, k, variant):
        return ServeRequest(
            a=rng.standard_normal((m, k)).astype(np.float32),
            b=rng.standard_normal((n, k)).astype(np.float32),
            variant=variant)

    requests = {"inject": req(200, 180, 160, "inject"),
                "clean": req(64, 64, 64, "clean"),
                "adversarial": req(200, 200, 200, "adversarial")}
    results = {name: eng.submit(r).result(timeout=300.0)
               for name, r in requests.items()}
    eng.drain(timeout=60.0)
    yield {"engine": eng, "monitor": mon, "server": srv,
           "requests": requests, "results": results, "rng": rng}
    eng.close()
    srv.close()
    mon.detach()
    telemetry.reset()


def test_trace_join_via_events_endpoint(served):
    """THE acceptance pin: one injected request's trace_id links its
    serve_gemm event (with tile blame), and the adversarial request's
    trace_id links its serve_gemm event AND its retry event — all read
    from the live /events endpoint, not the JSONL file."""
    _, body = _get(served["server"].url + "/events?since=0")
    events = json.loads(body)["events"]
    serve_evs = {e["extra"]["trace_id"]: e for e in events
                 if e.get("op") == "serve_gemm"}
    retry_evs = [e for e in events if e.get("outcome") == "retry"]

    inj = served["requests"]["inject"]
    res = served["results"]["inject"]
    assert res.trace_id == inj.trace_id  # response carries the trace
    ev = serve_evs[inj.trace_id]
    assert ev["outcome"] == "corrected"
    assert ev["tiles"], "tile blame missing from the traced event"
    assert ev["tiles"] == res.blame_tiles
    assert ev["extra"]["request_id"] == inj.request_id
    assert ev["device"], "device attribution missing"

    adv = served["requests"]["adversarial"]
    adv_res = served["results"]["adversarial"]
    assert adv_res.retries >= 1 and adv_res.ok
    adv_ev = serve_evs[adv.trace_id]
    assert adv_ev["extra"]["retries"] >= 1
    joined = [e for e in retry_evs
              if e["extra"]["trace_id"] == adv.trace_id]
    assert joined, "retry event does not join the adversarial trace"
    assert joined[0]["extra"]["request_id"] == adv.request_id

    clean_ev = serve_evs[served["requests"]["clean"].trace_id]
    assert clean_ev["outcome"] == "clean" and not clean_ev.get("tiles")


def test_trace_id_spans_jsonl_and_timeline(tmp_path):
    """The same trace_id lands in the JSONL fault event, the retry
    ladder event, AND the timeline's enqueue/batch records — the
    one-grep join across every stream."""
    from ft_sgemm_tpu.telemetry import timeline as tl_mod

    log = tmp_path / "ev.jsonl"
    tl_path = str(tmp_path / "serve.tl.jsonl")
    telemetry.configure(log, log_clean=True)
    rng = np.random.default_rng(3)
    eng = ServeEngine(default_bucket_set((256,)), max_batch=1,
                      max_wait=0.01, retry_backoff=0.001,
                      timeline=tl_path)
    eng.start()
    try:
        r = ServeRequest(
            a=rng.standard_normal((200, 200)).astype(np.float32),
            b=rng.standard_normal((200, 200)).astype(np.float32),
            variant="adversarial")
        assert eng.submit(r).result(timeout=300.0).ok
    finally:
        eng.close()
        telemetry.disable()
    evs = list(telemetry.read_events(log))
    assert any(e.op == "serve_gemm"
               and e.extra.get("trace_id") == r.trace_id for e in evs)
    assert any(e.outcome == "retry"
               and e.extra.get("trace_id") == r.trace_id for e in evs)
    records = tl_mod.read_timeline(tl_path)
    assert any(rec.get("name") == "enqueue"
               and rec.get("trace_id") == r.trace_id for rec in records)
    assert any(rec.get("kind") == "stage"
               and r.trace_id in (rec.get("trace_ids") or [])
               for rec in records)


def test_metrics_exposition_covers_serve_and_health(served):
    _, text = _get(served["server"].url + "/metrics")
    assert "serve_latency_seconds_bucket" in text
    assert "slo_budget_remaining" in text and "slo_burn_rate" in text
    gauges = re.findall(r'device_health\{device="([^"]+)"\} ([0-9.eE+-]+)',
                        text)
    assert gauges and all(0.0 < float(v) <= 1.0 for _, v in gauges)
    series = parse_prometheus(text)  # the exposition stays parseable
    hist = [s for s in series if s["name"] == "serve_latency_seconds"
            and not s["labels"]]
    assert hist and hist[0]["value"]["count"] >= 3


def test_slo_snapshot_and_artifact_shape(served):
    snap = served["monitor"].snapshot()
    assert snap["status"] in ("OK", "DEGRADED", "FAILING")
    assert snap["window_requests"] >= 3
    assert 0.0 <= snap["budget_remaining"] <= 1.0
    assert snap["device_health"] and snap["device_health_min"] is not None
    assert snap["device_health_min"] == min(snap["device_health"].values())


def test_concurrent_scrape_during_serve(served):
    """Satellite: hammer /metrics from threads while the engine drains an
    injected load — no exceptions, monotone counters between scrapes,
    and a valid final exposition."""
    url = served["server"].url
    errors = []
    totals = []
    stop = threading.Event()

    def scraper():
        last = -1.0
        try:
            while not stop.is_set():
                _, text = _get(url + "/metrics")
                series = parse_prometheus(text)
                total = sum(s["value"] for s in series
                            if s["name"] == "serve_requests")
                assert total >= last, (total, last)  # counters monotone
                last = total
                totals.append(total)
        except Exception as e:  # noqa: BLE001 — the test's whole point
            errors.append(e)

    threads = [threading.Thread(target=scraper) for _ in range(4)]
    for t in threads:
        t.start()
    eng, rng = served["engine"], served["rng"]
    futs = []
    for i in range(12):
        variant = "inject" if i % 3 == 0 else "clean"
        futs.append(eng.submit(ServeRequest(
            a=rng.standard_normal((100, 90)).astype(np.float32),
            b=rng.standard_normal((80, 90)).astype(np.float32),
            variant=variant)))
    for f in futs:
        assert f.result(timeout=300.0).ok
    eng.drain(timeout=60.0)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    assert not errors, errors
    assert totals, "scrapers never completed a scrape"
    _, final = _get(url + "/metrics")
    series = parse_prometheus(final)  # final exposition valid
    assert sum(s["value"] for s in series
               if s["name"] == "serve_requests") >= 15


def test_monitor_off_is_byte_identical(served):
    """Zero overhead when off: monitor= changes NOTHING about the
    compiled serve executables — the lowered HLO of a bucket's kernel is
    byte-identical with and without a monitor (the --telemetry
    discipline from PR 1)."""
    import jax
    import jax.numpy as jnp

    bucket = default_bucket_set((128,))[0]

    def lowered(monitor):
        eng = ServeEngine([bucket], monitor=monitor)
        kern = eng._kernel(bucket, "clean")
        spec = eng._variant_spec(bucket, "clean")
        avals = [jax.ShapeDtypeStruct((128, 128), jnp.float32)] * 3
        return jax.jit(lambda a, b, c: kern(a, b, c, spec)).lower(
            *avals).as_text()

    assert lowered(None) == lowered(served["monitor"])


# ---------------------------------------------------------------------------
# CLI surfaces: top + telemetry --watch
# ---------------------------------------------------------------------------


def test_cli_top_renders_live_view(served, capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "top", served["server"].url, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "health:" in out
    assert "slo: budget remaining" in out
    assert "device health:" in out
    assert "bucket" in out and "p99" in out
    assert re.search(r"trace=[0-9a-f]{16}", out), "event tail lost traces"


def test_cli_top_unreachable_exits_2(capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "top", "http://127.0.0.1:9/", "--once",
                   "--interval=0.01"])
    assert rc == 2


def test_cli_telemetry_watch_follows_growing_log(tmp_path, capsys):
    """Satellite: --watch tails a shard that grows WHILE the watcher
    runs — the late-appended events appear in a re-rendered summary."""
    from ft_sgemm_tpu import cli
    from ft_sgemm_tpu.telemetry.events import FaultEvent

    log = tmp_path / "grow.jsonl"
    log.write_text(FaultEvent(outcome="corrected", op="early",
                              detected=1, corrected=1).to_json() + "\n")

    def appender():
        time.sleep(0.4)
        with open(log, "a") as fh:
            for _ in range(3):
                fh.write(FaultEvent(outcome="uncorrectable", op="late",
                                    detected=2,
                                    uncorrectable=1).to_json() + "\n")
                fh.flush()

    t = threading.Thread(target=appender)
    t.start()
    rc = cli.main(["cli", "telemetry", str(log), "--watch",
                   "--watch-seconds=1.5", "--interval=0.1"])
    t.join(timeout=10.0)
    out = capsys.readouterr().out
    assert rc == 0
    assert "early" in out
    assert "late" in out, "appended events never surfaced"
    # Re-summarized: a later frame counts all four events.
    assert "(4 events)" in out
    assert out.index("(1 events)") < out.index("(4 events)")


def test_cli_telemetry_watch_waits_for_missing_file(tmp_path, capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "telemetry", str(tmp_path / "nope.jsonl"),
                   "--watch", "--watch-seconds=0.2", "--interval=0.05"])
    assert rc == 0  # absent file = empty stream, not an error
    assert "(0 events)" in capsys.readouterr().out


def test_watch_skips_torn_tail_until_complete(tmp_path, capsys):
    from ft_sgemm_tpu import cli

    log = tmp_path / "t.jsonl"
    log.write_text('{"outcome": "corrected", "op": "ok", "detected": 1}\n'
                   '{"outcome": "corrected", "op": "tornop", "det')
    rc = cli.main(["cli", "telemetry", str(log), "--watch",
                   "--watch-seconds=0.2", "--interval=0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "(1 events)" in out and "tornop" not in out


# ---------------------------------------------------------------------------
# Mesh localization goes live (acceptance). Runs AFTER every
# served-dependent test: its cleanup resets process-wide telemetry.
# ---------------------------------------------------------------------------


def test_mesh_injected_device_ranks_worst_and_healthz_degrades(rng):
    """Acceptance: under a single-device inject_coords load on the
    8-vdev CPU mesh, /metrics ranks the injected device worst with every
    other device at 1.0, and /healthz reports DEGRADED naming it; a
    clean load reports OK with all-healthy scores."""
    from ft_sgemm_tpu import InjectionSpec
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.parallel import make_mesh, sharded_ft_sgemm

    a = rng.standard_normal((256, 512)).astype(np.float32)
    b = rng.standard_normal((128, 512)).astype(np.float32)
    c = rng.standard_normal((256, 128)).astype(np.float32)
    mesh = make_mesh(8)
    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    target = (1, 2)
    target_dev = str(mesh.devices[target[0]][target[1]])

    def run(inject):
        telemetry.reset()
        registry = telemetry.configure(None, log_clean=True)
        mon = Monitor(registry=registry).attach()
        srv = MonitorServer(mon, port=0).start()
        try:
            for _ in range(3):
                kwargs = ({"inject": InjectionSpec(enabled=True, every=1),
                           "inject_coords": target} if inject else {})
                sharded_ft_sgemm(a, b, c, mesh, tile, **kwargs)
            _, text = _get(srv.url + "/metrics")
            gauges = {d: float(v) for d, v in re.findall(
                r'device_health\{device="([^"]+)"\} ([0-9.eE+-]+)', text)}
            try:
                _, body = _get(srv.url + "/healthz")
                health = json.loads(body)
            except urllib.error.HTTPError as e:
                health = json.loads(e.read().decode())
            return gauges, health
        finally:
            srv.close()
            mon.detach()
            telemetry.reset()

    gauges, health = run(inject=True)
    assert len(gauges) == 8, gauges
    assert min(gauges, key=gauges.get) == target_dev
    assert gauges[target_dev] < 0.9
    assert all(v == 1.0 for d, v in gauges.items() if d != target_dev)
    assert health["status"] == "DEGRADED"
    assert any(target_dev in r for r in health["reasons"])

    clean_gauges, clean_health = run(inject=False)
    assert len(clean_gauges) == 8
    assert all(v == 1.0 for v in clean_gauges.values())
    assert clean_health["status"] == "OK" and not clean_health["reasons"]


# ---------------------------------------------------------------------------
# Serve-bench artifact carries the SLO section
# ---------------------------------------------------------------------------


def test_run_serve_bench_embeds_slo_and_health(tmp_path):
    from ft_sgemm_tpu.serve import run_serve_bench

    stats = run_serve_bench(smoke=True, bucket_sizes=(128, 256),
                            num_requests=6, inject_rate=0.5,
                            adversarial_rate=0.0)
    slo = stats["slo"]
    assert slo["status"] in ("OK", "DEGRADED", "FAILING")
    assert slo["window_requests"] == stats["completed"] > 0
    assert 0.0 <= slo["budget_remaining"] <= 1.0
    assert stats["device_health"]
    assert slo["device_health_min"] is not None
    # And the RunReport SLO section renders it.
    from ft_sgemm_tpu.perf.report import RunReport

    rr = RunReport(manifest={}, slo=slo)
    md = rr.to_markdown()
    assert "## SLO" in md and "error budget remaining" in md
    assert RunReport.from_dict(rr.to_dict()).slo == slo


def test_run_serve_bench_monitor_port_serves_http():
    from ft_sgemm_tpu.serve import run_serve_bench

    seen = {}

    class _Probe:
        """Timeline stand-in: grab the live URL mid-run and scrape it."""

        path = None

        def point(self, kind, name, **fields):
            if "monitor_url" in fields:
                seen["url"] = fields["monitor_url"]
                _, text = _get(fields["monitor_url"] + "/metrics")
                seen["scrape"] = text

        def span(self, *a, **k):
            import contextlib

            return contextlib.nullcontext({})

    stats = run_serve_bench(smoke=True, bucket_sizes=(128,),
                            num_requests=3, inject_rate=0.0,
                            adversarial_rate=0.0, monitor_port=0,
                            timeline=_Probe())
    assert stats["monitor_url"] == seen["url"]
    assert "slo_budget_remaining" in seen["scrape"]
