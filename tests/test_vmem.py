"""VMEM-footprint estimator and trace-time tile fitting (ops/vmem.py).

The ground truth is round 4's one completed hardware window
(``.bench/records_b855854_4096.jsonl``): Mosaic's own scoped-VMEM
accounting for four kernel variants that FAILED at the 16 MiB default
limit, plus the variants known to have compiled at it. The estimator must
(a) predict every recorded OOM, (b) not flag anything that really
compiled, and (c) pass every shipped configuration at the 64 MiB budget —
so the bench ladder can never again lose rungs to a compile error.
"""

import dataclasses

import numpy as np
import pytest

import ft_sgemm_tpu as ft
from ft_sgemm_tpu.configs import (
    BF16_TILE_OVERRIDES,
    SHAPE_ORDER,
    SHAPES,
    VMEM_LIMIT_BYTES,
    shape_for_dtype,
    vmem_limit_bytes,
)
from ft_sgemm_tpu.ops.vmem import (
    MIB,
    TEMP_TILE_FACTORS,
    estimate_vmem_bytes,
    fit_block_to_vmem,
)

HUGE = SHAPES["huge"]
BF16_FT_TILE = dataclasses.replace(
    HUGE, bm=BF16_TILE_OVERRIDES[("huge", True)][0],
    bn=BF16_TILE_OVERRIDES[("huge", True)][1],
    bk=BF16_TILE_OVERRIDES[("huge", True)][2])
LIMIT_16 = 16 * MIB

# The four Mosaic-recorded OOMs: (variant, shape, in_itemsize,
# observed MiB). bf16_abft ran the weighted strategy at its single-final-
# check default, i.e. the precomp body, at the bf16-FT override tile.
RECORDED_OOMS = [
    ("weighted_precomp", HUGE, 4, 16.27),
    ("weighted", HUGE, 4, 17.93),
    ("fused", HUGE, 4, 16.38),
    ("weighted_precomp", BF16_FT_TILE, 2, 17.75),
]

# Variants that really compiled under the 16 MiB default in the same
# window (plain f32/bf16, rowcol f32) — the estimator must not flag them.
RECORDED_FITS = [
    ("plain", HUGE, 4),
    ("plain", dataclasses.replace(HUGE, bk=2048), 2),  # bf16 plain tile
    ("rowcol", HUGE, 4),
]


@pytest.mark.parametrize("variant,shape,itemsize,observed", RECORDED_OOMS)
def test_estimator_predicts_recorded_ooms(variant, shape, itemsize,
                                          observed):
    est = estimate_vmem_bytes(shape, variant, in_itemsize=itemsize)
    assert est > LIMIT_16, (variant, est / MIB)
    # Conservative: the estimate must be at least Mosaic's own number
    # (else some real OOM would be predicted to fit at a tighter limit)...
    assert est >= observed * MIB, (variant, est / MIB, observed)
    # ...but still clear the shipped 64 MiB budget with real headroom.
    assert est < 0.75 * VMEM_LIMIT_BYTES, (variant, est / MIB)


@pytest.mark.parametrize("variant,shape,itemsize", RECORDED_FITS)
def test_estimator_passes_recorded_fits(variant, shape, itemsize):
    est = estimate_vmem_bytes(shape, variant, in_itemsize=itemsize)
    assert est <= LIMIT_16, (variant, est / MIB)


def test_every_shipped_config_fits_the_default_budget():
    """No shipped named shape x strategy x dtype may trigger a shrink."""
    for name in SHAPE_ORDER:
        for is_ft in (False, True):
            for itemsize, dtype in ((4, "float32"), (2, "bfloat16")):
                shape = shape_for_dtype(SHAPES[name], is_ft, dtype)
                variants = (
                    ("rowcol", "global", "weighted", "weighted_precomp",
                     "fused") if is_ft else ("plain",))
                for variant in variants:
                    est = estimate_vmem_bytes(
                        shape, variant, in_itemsize=itemsize)
                    assert est <= VMEM_LIMIT_BYTES, (
                        name, variant, dtype, est / MIB)


def test_unknown_variant_rejected():
    with pytest.raises(ValueError, match="unknown kernel variant"):
        estimate_vmem_bytes(HUGE, "warp")


def test_fit_noop_within_budget():
    assert fit_block_to_vmem(
        HUGE, "rowcol", limit=VMEM_LIMIT_BYTES, allow_shrink=True) is HUGE


def test_fit_shrinks_oversized_named_tile_with_warning():
    big = dataclasses.replace(HUGE, bm=1024, bn=1024, bk=2048)
    with pytest.warns(UserWarning, match="auto-shrunk"):
        fitted = fit_block_to_vmem(
            big, "weighted", limit=VMEM_LIMIT_BYTES, allow_shrink=True)
    assert fitted.block != big.block
    assert estimate_vmem_bytes(fitted, "weighted") <= VMEM_LIMIT_BYTES
    for v in fitted.block:
        assert v >= 128 and v % 128 == 0


def test_fit_warns_but_keeps_explicit_tile():
    big = dataclasses.replace(HUGE, bm=1024, bn=1024, bk=2048)
    with pytest.warns(UserWarning, match="not auto-shrunk"):
        kept = fit_block_to_vmem(
            big, "weighted", limit=VMEM_LIMIT_BYTES, allow_shrink=False)
    assert kept is big


def test_fit_shrinks_non_power_of_two_dims_legally():
    """Halving 384 would give the illegal 192; the shrink must step to a
    multiple of 128 (or raise the documented error), never crash in the
    KernelShape validator."""
    odd = dataclasses.replace(HUGE, bm=384, bn=384, bk=384)
    with pytest.warns(UserWarning, match="auto-shrunk"):
        fitted = fit_block_to_vmem(
            odd, "weighted", limit=8 * MIB, allow_shrink=True)
    assert estimate_vmem_bytes(fitted, "weighted") <= 8 * MIB
    for v in fitted.block:
        assert v >= 128 and v % 128 == 0


def test_fit_raises_when_unfittable():
    with pytest.raises(ValueError, match="cannot fit"):
        fit_block_to_vmem(
            HUGE, "weighted", limit=1 * MIB, allow_shrink=True)


def test_vmem_limit_env_override(monkeypatch):
    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", str(32 * MIB))
    assert vmem_limit_bytes() == 32 * MIB
    monkeypatch.delenv("FT_SGEMM_VMEM_LIMIT_BYTES")
    assert vmem_limit_bytes() == VMEM_LIMIT_BYTES  # cpu backend: default


def test_vmem_limit_malformed_env_names_the_variable(monkeypatch):
    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", "64MiB")
    with pytest.raises(ValueError, match="FT_SGEMM_VMEM_LIMIT_BYTES"):
        vmem_limit_bytes()
    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", "-1")
    with pytest.raises(ValueError, match="FT_SGEMM_VMEM_LIMIT_BYTES"):
        vmem_limit_bytes()


def test_vmem_limit_matches_generation_as_standalone_token():
    """v2/v3 detection tokenizes the device kind: 'TPU v3' drops to the
    16 MiB physical budget, while kinds that merely CONTAIN the characters
    (v23, v35lite) keep the default. Exercised through the cached
    resolver's device branch by faking the device query."""
    import unittest.mock as mock

    from ft_sgemm_tpu.configs import _resolve_vmem_limit

    def limit_for(kind):
        _resolve_vmem_limit.cache_clear()
        dev = mock.Mock()
        dev.device_kind = kind
        with mock.patch("jax.local_devices", return_value=[dev]):
            try:
                return _resolve_vmem_limit(None)
            finally:
                _resolve_vmem_limit.cache_clear()

    assert limit_for("TPU v2") == 16 * MIB
    assert limit_for("TPU v3") == 16 * MIB
    assert limit_for("TPU v4") == VMEM_LIMIT_BYTES
    assert limit_for("TPU v5 lite") == VMEM_LIMIT_BYTES
    assert limit_for("TPU v23") == VMEM_LIMIT_BYTES   # not a v2/v3 token
    assert limit_for("tpuv35x") == VMEM_LIMIT_BYTES


def test_vmem_limit_resolution_is_cached(monkeypatch):
    """The env-keyed resolver must not re-pay the device query per kernel
    trace: same env value -> same cached resolution object path."""
    from ft_sgemm_tpu.configs import _resolve_vmem_limit

    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", str(48 * MIB))
    before = _resolve_vmem_limit.cache_info().hits
    assert vmem_limit_bytes() == 48 * MIB
    assert vmem_limit_bytes() == 48 * MIB
    assert _resolve_vmem_limit.cache_info().hits > before


def test_fit_keeps_k_depth_when_temps_dominate():
    """ADVICE r5: the weighted temps term (factor * a_rows * bn * 4) is
    bk-independent; when draining bk to 128 cannot absorb the overage the
    fitter must shrink the dimension with the largest predicted reduction
    (bn here) instead of futilely spending all K-depth first."""
    wide = dataclasses.replace(HUGE, bm=512, bn=1024, bk=512)
    # bk floor can't fix it: ~31.5 MiB at bk=128 vs the 24 MiB limit.
    assert estimate_vmem_bytes(
        dataclasses.replace(wide, bk=128), "weighted") > 24 * MIB
    with pytest.warns(UserWarning, match="auto-shrunk"):
        fitted = fit_block_to_vmem(
            wide, "weighted", limit=24 * MIB, allow_shrink=True)
    assert estimate_vmem_bytes(fitted, "weighted") <= 24 * MIB
    assert fitted.bk == 512, (
        f"K-depth drained to {fitted.bk} though bk cannot fix the overage")
    assert fitted.bn < 1024


def test_fit_still_prefers_bk_when_it_suffices():
    """When bk alone CAN absorb the overage, it stays the first (cheapest)
    dimension shrunk — bm/bn untouched."""
    deep = dataclasses.replace(HUGE, bm=512, bn=512, bk=2048)
    limit = estimate_vmem_bytes(
        dataclasses.replace(deep, bk=1024), "plain", in_itemsize=4)
    with pytest.warns(UserWarning, match="auto-shrunk"):
        fitted = fit_block_to_vmem(
            deep, None, limit=limit, allow_shrink=True)
    assert (fitted.bm, fitted.bn) == (512, 512)
    assert fitted.bk < 2048


def test_oversized_named_shape_shrinks_end_to_end(monkeypatch, rng):
    """The wire-level guarantee: a named-shape call over budget produces a
    shrunk compile + warning and a CORRECT result — never an exception.
    Forced by dropping the env limit under the huge tile's footprint."""
    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", str(12 * MIB))
    n = 512
    a = ft.utils.generate_random_matrix(n, n, rng=rng)
    b = ft.utils.generate_random_matrix(n, n, rng=rng)
    c = ft.utils.generate_random_matrix(n, n, rng=rng)
    want = np.asarray(ft.sgemm_reference(a, b, c, 1.0, -1.5))
    with pytest.warns(UserWarning, match="auto-shrunk"):
        res = ft.ft_sgemm(a, b, c, "huge", strategy="weighted")
    ok, _, _ = ft.utils.verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok
    assert int(res.num_uncorrectable) == 0


def test_explicit_shape_is_never_shrunk_end_to_end(monkeypatch, rng):
    """Tile sweeps measure the tile their row label claims: an explicit
    KernelShape over budget warns but runs at the requested tile (on CPU
    interpret mode there is no Mosaic to fail the compile)."""
    monkeypatch.setenv("FT_SGEMM_VMEM_LIMIT_BYTES", str(12 * MIB))
    n = 512
    a = ft.utils.generate_random_matrix(n, n, rng=rng)
    b = ft.utils.generate_random_matrix(n, n, rng=rng)
    c = ft.utils.generate_random_matrix(n, n, rng=rng)
    want = np.asarray(ft.sgemm_reference(a, b, c, 1.0, -1.5))
    with pytest.warns(UserWarning, match="not auto-shrunk"):
        res = ft.ft_sgemm(a, b, c, SHAPES["huge"], strategy="weighted")
    ok, _, _ = ft.utils.verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok


def test_factors_cover_every_strategy():
    """Every wrapper-level strategy (plus the plain kernel and the precomp
    body) has a calibrated factor — a new strategy must add one. Since
    the encode axis, every (strategy, encode) kernel-level resolution
    must be covered too."""
    import ft_sgemm_tpu.ops.ft_sgemm as mod

    for strategy in mod.STRATEGIES:
        assert strategy in TEMP_TILE_FACTORS
        for encode in ("vpu", "mxu"):
            assert mod.resolve_kernel_strategy(
                strategy, encode) in TEMP_TILE_FACTORS, (strategy, encode)
    assert "plain" in TEMP_TILE_FACTORS
    assert "weighted_precomp" in TEMP_TILE_FACTORS


def test_every_shipped_config_fits_default_budget_mxu_variants():
    """The MXU-encode bodies (augmented A AND B tiles) must also clear
    the 64 MiB budget at every shipped named shape x dtype."""
    for name in SHAPE_ORDER:
        for itemsize, dtype in ((4, "float32"), (2, "bfloat16")):
            shape = shape_for_dtype(SHAPES[name], True, dtype)
            for variant in ("rowcol_mxu", "global_mxu"):
                est = estimate_vmem_bytes(shape, variant,
                                          in_itemsize=itemsize)
                assert est <= VMEM_LIMIT_BYTES, (
                    name, variant, dtype, est / MIB)
