"""MXU-fused checksum encode (``encode="mxu"``) across the kernel family.

Pins the encode axis's four contract points:

1. **Default is untouched** — ``encode="vpu"`` (and not passing ``encode``
   at all) lowers to BYTE-IDENTICAL HLO per strategy: the new axis changes
   nothing unless selected (the tests/test_telemetry.py pinning
   technique).
2. **One dot per K step** — under ``encode="mxu"`` the whole lowered
   module contains exactly ONE ``dot_general``: the expected checksums
   ride the kernel's augmented dot, with no second encode dot anywhere
   (the VPU weighted path, by contrast, shows its separate precompute
   dot).
3. **Correction parity** — injected single/multi faults are detected and
   corrected at ``check_every in {1, 2, nk}`` for all four strategies, on
   f32 and bf16 inputs, exactly as under the VPU encode; adversarial
   same-column schedules are REPORTED, never silent.
4. **C-operand aliasing** — the plain and FT pallas_calls alias the C
   input to the f32 output (the ``beta != 0`` epilogue must not allocate
   and copy a second HBM output buffer), pinned at the jaxpr-params level
   since interpret-mode lowering rewrites the alias functionally.
"""

import dataclasses

import jax
import numpy as np
import pytest

from ft_sgemm_tpu import (
    InjectionSpec,
    make_ft_sgemm,
    make_sgemm,
    sgemm_reference,
)
from ft_sgemm_tpu.configs import ENCODE_MODES, KernelShape, aug_rows
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)
STRATEGIES = ("rowcol", "global", "weighted", "fused")


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def _lower(fn, a, b, c):
    return jax.jit(lambda a, b, c: fn(a, b, c).c).lower(a, b, c).as_text()


def _oracle(a, b, c, in_dtype):
    if in_dtype == "float32":
        return np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    return np.asarray(
        sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))


# -- 1. default-path pin: encode="vpu" is byte-for-byte the default ----------


@pytest.mark.parametrize("strategy", ["rowcol", "global", "weighted"])
def test_default_encode_hlo_byte_identical(strategy, rng):
    a, b, c = _inputs(256, 128, 512)
    default = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy)
    explicit = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                             encode="vpu")
    assert _lower(default, a, b, c) == _lower(explicit, a, b, c), (
        f"{strategy}: explicit encode='vpu' changed the default HLO")
    mxu = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                        encode="mxu")
    assert _lower(mxu, a, b, c) != _lower(default, a, b, c), (
        f"{strategy}: encode='mxu' lowered to the VPU program — the axis"
        " did nothing")


def test_fused_strategy_is_weighted_mxu():
    """``strategy="fused"`` and ``("weighted", encode="mxu")`` are one
    program — the historical spelling and the axis spelling must never
    drift apart."""
    a, b, c = _inputs(256, 128, 512)
    fused = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="fused")
    wmxu = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="weighted",
                         encode="mxu")
    assert _lower(fused, a, b, c) == _lower(wmxu, a, b, c)
    assert fused.encode == "mxu"


def test_unknown_encode_rejected():
    with pytest.raises(ValueError, match="encode"):
        make_ft_sgemm(TILE, encode="warp")
    assert "vpu" in ENCODE_MODES and "mxu" in ENCODE_MODES


# -- 2. one dot_general per K step under encode="mxu" ------------------------


@pytest.mark.parametrize("in_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_mxu_encode_emits_exactly_one_dot(strategy, in_dtype):
    """The whole lowered module holds ONE dot_general: the kernel's
    augmented per-K-step dot. No VPU-encode elementwise streams, no
    out-of-kernel precompute dot (the weighted VPU default shows 2)."""
    a, b, c = _inputs(256, 128, 512)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                       encode="mxu", in_dtype=in_dtype)
    txt = _lower(ft, a, b, c)
    assert txt.count("stablehlo.dot_general") == 1, (
        f"{strategy}/{in_dtype}: expected exactly one dot_general")
    # The dot really is augmented: its lhs carries the checksum tail rows.
    aug = aug_rows(4 if in_dtype == "float32" else 2)
    assert f"tensor<{TILE.bm + aug}x{TILE.bk}x" in txt, (
        f"{strategy}/{in_dtype}: no augmented ({TILE.bm + aug}, {TILE.bk})"
        " A block in the lowered module")


def test_weighted_vpu_precomp_has_separate_encode_dot():
    """Contrast pin for the one-dot assertion: the VPU weighted default
    precomputes expectations with a SECOND dot outside the kernel."""
    a, b, c = _inputs(256, 128, 512)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="weighted")
    assert _lower(ft, a, b, c).count("stablehlo.dot_general") == 2


# -- 3. correction parity: cadence sweep x strategy x dtype ------------------


@pytest.mark.parametrize("in_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("check_every", [1, 2, 4])  # 4 == nk at k=512
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_mxu_cadence_sweep_multi_fault(strategy, check_every, in_dtype):
    """Dense injection (every=1: nk faults, multiple per interval at
    coarse cadences) under encode="mxu": correcting strategies restore
    the oracle exactly and report zero uncorrectable; the detect-only
    global strategy counts every fault and reports all uncorrected."""
    m = n = 128
    k = 512  # nk = 4 at bk=128
    a, b, c = _inputs(m, n, k, seed=7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                       encode="mxu", check_every=check_every,
                       in_dtype=in_dtype)
    res = ft(a, b, c, inject=inj)
    want = _oracle(a, b, c, in_dtype)
    if strategy == "global":
        # Event semantics (FtSgemmResult): same-interval faults collapse
        # into one event, so every=1 yields one event per CHECK.
        assert int(res.num_detected) == -(-4 // check_every)
        assert int(res.num_uncorrectable) == int(res.num_detected)
        return
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, (f"{strategy}/mxu/ce={check_every}/{in_dtype}: {nbad}"
                " corrupted elements survived")
    assert int(res.num_detected) == 4
    assert int(res.num_uncorrectable) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_mxu_single_fault_corrected(strategy):
    """One fault per run (every = nk): the single-fault baseline cell."""
    m = n = 128
    k = 512
    a, b, c = _inputs(m, n, k, seed=9)
    inj = InjectionSpec(enabled=True, every=4, magnitude=10000.0)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                       encode="mxu")
    res = ft(a, b, c, inject=inj)
    want = _oracle(a, b, c, "float32")
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{strategy}/mxu single fault: {nbad} corrupted"
    assert int(res.num_detected) == 1
    assert int(res.num_uncorrectable) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_mxu_same_column_faults_reported_not_silent(strategy):
    """The adversarial col_stride=0 schedule (multiple faults in ONE
    column per interval) defeats per-column localization under either
    encode — the MXU re-check must report it exactly like the VPU one."""
    a, b, c = _inputs(128, 128, 512, seed=8)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                        col_stride=0)
    kw = dict(check_every=4) if strategy == "weighted" else {}
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                       encode="mxu", **kw)
    res = ft(a, b, c, inject=inj)
    want = _oracle(a, b, c, "float32")
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    if not ok:
        assert int(res.num_uncorrectable) > 0, (
            f"{strategy}/mxu: {nbad} corrupted elements with NO report —"
            " silent corruption")


def test_mxu_rectangular_with_padding_and_injection():
    a, b, c = _inputs(300, 200, 520, seed=13)
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="rowcol",
                       encode="mxu")
    res = ft(a, b, c, inject=inj)
    want = _oracle(a, b, c, "float32")
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"mxu/rect: {nbad} corrupted elements survived"
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "global"])
def test_mxu_auto_threshold_catches_tiny_faults(strategy):
    """Adaptive thresholds compose with the MXU encode: magnitude-5
    faults (5 orders under the reference 9500) are caught."""
    a, b, c = _inputs(128, 128, 512, seed=17)
    inj = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    res = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                        encode="mxu", threshold="auto")(a, b, c, inject=inj)
    if strategy == "global":
        assert int(res.num_detected) == 4
        return
    want = _oracle(a, b, c, "float32")
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} tiny faults survived auto threshold under mxu"
    assert int(res.num_detected) == 4
    assert int(res.num_uncorrectable) == 0


def test_mxu_clean_runs_report_zero(rng):
    for strategy in ("rowcol", "global", "weighted"):
        res = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                            encode="mxu")(*_inputs(256, 128, 512, seed=2))
        assert int(res.num_detected) == 0, strategy
        assert int(res.num_uncorrectable) == 0, strategy


def test_attention_mxu_encode_matches_reference(rng):
    """The protected QK/PV paths accept the encode axis; clean outputs
    match the XLA oracle and injected faults are corrected in-kernel."""
    from ft_sgemm_tpu.ops.attention import (
        attention_reference, make_ft_attention)

    q = rng.standard_normal((128, 64)).astype(np.float32)
    k = rng.standard_normal((128, 64)).astype(np.float32)
    v = rng.standard_normal((128, 64)).astype(np.float32)
    attn = make_ft_attention(encode="mxu")
    assert attn.encode == "mxu"
    res = attn(q, k, v)
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(res.out), want, atol=2e-4)
    assert int(res.detections) == 0
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res_inj = attn(q, k, v, inject=inj)
    np.testing.assert_allclose(np.asarray(res_inj.out), want, atol=2e-2)
    assert int(res_inj.detections) > 0
    assert int(res_inj.uncorrectable) == 0


# -- 4. C-operand aliasing (beta != 0 epilogue reuses the buffer) ------------


def _pallas_call_params(jaxpr):
    """Every pallas_call eqn's params in a (possibly nested) jaxpr."""
    found = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            found.append(eqn.params)
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                found.extend(_pallas_call_params(inner))
    return found


def _alias_pairs(params):
    alias = params.get("input_output_aliases")
    return tuple(tuple(p) for p in alias) if alias else ()


def test_ft_c_operand_aliases_output(rng):
    a, b, c = _inputs(256, 128, 512)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA)
    jaxpr = jax.make_jaxpr(lambda a, b, c: ft(a, b, c).c)(a, b, c)
    (params,) = _pallas_call_params(jaxpr.jaxpr)
    # Operand order (inj, a, b, c): the C input aliases f32 output 0, so
    # the beta*C epilogue never allocates a second (M, N) HBM buffer.
    assert _alias_pairs(params) == ((3, 0),), params.get(
        "input_output_aliases")


def test_plain_c_operand_aliases_output(rng):
    a, b, c = _inputs(256, 128, 512)
    plain = make_sgemm(TILE, alpha=ALPHA, beta=BETA)
    jaxpr = jax.make_jaxpr(plain)(a, b, c)
    (params,) = _pallas_call_params(jaxpr.jaxpr)
    assert _alias_pairs(params) == ((2, 0),), params.get(
        "input_output_aliases")


def test_aliased_epilogue_still_reads_original_c(rng):
    """Semantics pin for the alias: the epilogue's beta*C must see the
    ORIGINAL C values (the kernel reads each C tile before its output
    tile retires), including under an outer jit where XLA may truly
    reuse the buffer."""
    a, b, c = _inputs(256, 256, 512, seed=3)
    plain = make_sgemm(TILE, alpha=ALPHA, beta=BETA)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    got = np.asarray(jax.jit(plain)(a, b, c))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


# -- cost model: FT kernels report honest flops/bytes ------------------------


def test_gemm_cost_estimate_ft_terms():
    from ft_sgemm_tpu.ops.common import gemm_cost_estimate

    m = n = k = 1024
    block = (128, 128, 128)
    plain = gemm_cost_estimate(m, n, k, 4)
    assert plain.flops == 2 * m * n * k  # the original 4-arg form
    vpu = gemm_cost_estimate(m, n, k, 4, block=block, strategy="rowcol",
                             multifault=True, check_every=1)
    mxu = gemm_cost_estimate(m, n, k, 4, block=block, strategy="rowcol_mxu",
                             multifault=True, check_every=1)
    for est in (vpu, mxu):
        assert est.flops > plain.flops, "encode/check flops missing"
        assert est.bytes_accessed >= plain.bytes_accessed
    # MXU-encode augments the operands: its extra HBM bytes must show.
    assert mxu.bytes_accessed > plain.bytes_accessed
    # Coarser cadence -> fewer detect/correct epilogues -> fewer flops.
    sparse = gemm_cost_estimate(m, n, k, 4, block=block, strategy="rowcol",
                                multifault=True, check_every=8)
    assert sparse.flops < vpu.flops
    # The precomp body has no in-kernel encode streams.
    precomp = gemm_cost_estimate(m, n, k, 4, block=block,
                                 strategy="weighted", check_every=None)
    inkernel = gemm_cost_estimate(m, n, k, 4, block=block,
                                  strategy="weighted", check_every=2)
    assert precomp.flops < inkernel.flops


# -- vmem model + configs: the new variants are first-class ------------------


def test_vmem_model_covers_mxu_variants():
    from ft_sgemm_tpu.ops.vmem import estimate_vmem_bytes

    base = estimate_vmem_bytes(TILE, "rowcol")
    mxu = estimate_vmem_bytes(TILE, "rowcol_mxu")
    assert mxu > base, "augmented tiles must cost VMEM in the model"
    gbase = estimate_vmem_bytes(TILE, "global")
    gmxu = estimate_vmem_bytes(TILE, "global_mxu")
    assert gmxu > gbase
    # bf16 halves the input itemsize but doubles the augmented rows.
    assert estimate_vmem_bytes(TILE, "rowcol_mxu", in_itemsize=2) > 0


def test_aug_block_legality():
    assert TILE.aug_block(8, 8) == (136, 136, 128)
    assert TILE.aug_block() == (128, 128, 128)
    with pytest.raises(ValueError, match="aug_a"):
        TILE.aug_block(3, 0)
    with pytest.raises(ValueError, match="aug_b"):
        TILE.aug_block(0, -8)
    assert aug_rows(4) == 8 and aug_rows(2) == 16


def test_fit_block_to_vmem_handles_mxu_variant():
    from ft_sgemm_tpu.ops.vmem import MIB, fit_block_to_vmem

    big = dataclasses.replace(TILE, bm=1024, bn=1024, bk=2048)
    with pytest.warns(UserWarning, match="auto-shrunk"):
        fitted = fit_block_to_vmem(big, "rowcol_mxu", limit=64 * MIB,
                                   allow_shrink=True)
    assert fitted.block != big.block


# -- tuner: encode is a searched, cached, schema-bumped dimension ------------


def test_tuner_key_separates_encode_modes(tmp_path, monkeypatch):
    from ft_sgemm_tpu import tuner

    kws = dict(strategy="rowcol", in_dtype="float32",
               injection_enabled=False)
    assert (tuner.make_key(256, 256, 256, encode="vpu", **kws)
            != tuner.make_key(256, 256, 256, encode="mxu", **kws))
    # The plain kernel has no encode axis: both spellings share a key.
    assert (tuner.make_key(256, 256, 256, strategy=None, encode="mxu",
                           in_dtype="float32", injection_enabled=False)
            == tuner.make_key(256, 256, 256, strategy=None, encode="vpu",
                              in_dtype="float32", injection_enabled=False))


def test_tuner_variant_maps_encode_to_kernel_bodies():
    from ft_sgemm_tpu.tuner.space import variant_for

    assert variant_for("rowcol", encode="mxu") == "rowcol_mxu"
    assert variant_for("global", encode="mxu") == "global_mxu"
    assert variant_for("weighted", encode="mxu") == "fused"
    assert variant_for("weighted", encode="vpu") == "weighted_precomp"
    assert variant_for("rowcol", encode="vpu") == "rowcol"
    assert variant_for(None) == "plain"


def test_schema1_cache_ignored_after_bump(tmp_path, monkeypatch):
    """Pre-encode-axis cache files (schema 1) would collide the two
    encode modes' winners under one key: the bumped loader must ignore
    them (with the standard warning), falling back to heuristics."""
    import json
    import warnings

    from ft_sgemm_tpu.tuner import cache as tcache

    path = tmp_path / "old_schema.json"
    path.write_text(json.dumps(
        {"schema": 1, "entries": {
            "cpu|256x256x256|float32|weighted|inj=0": {
                "block": [128, 128, 128]}}}))
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    try:
        with pytest.warns(UserWarning, match="schema"):
            assert tcache.load_entries() == {}
    finally:
        tcache.clear_memo()


def test_tune_mxu_persists_and_dispatch_uses_it(tmp_path, monkeypatch):
    from ft_sgemm_tpu import tuner
    from ft_sgemm_tpu.tuner import cache as tcache

    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "tuner_cache.json"))
    tcache.clear_memo()
    try:
        report = tuner.tune(128, strategy="rowcol", encode="mxu", budget=1,
                            reps=1, samples=1, method="interpret")
        assert report["best"] is not None
        assert report["encode"] == "mxu"
        assert "enc=mxu" in report["key"]
        tile = tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 encode="mxu", in_dtype="float32",
                                 injection_enabled=False)
        assert tile is not None
        assert tile.block == tuple(report["best"]["block"])
        # The other encode's key stays a miss: no cross-mode bleed.
        assert tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 encode="vpu", in_dtype="float32",
                                 injection_enabled=False) is None
    finally:
        tcache.clear_memo()


# -- telemetry: per-encode-mode counters -------------------------------------


def test_telemetry_counters_keyed_by_encode(rng, tmp_path):
    from ft_sgemm_tpu import telemetry

    telemetry.reset()
    telemetry.configure(tmp_path / "enc.jsonl")
    try:
        a, b, c = _inputs(128, 128, 256, seed=4)
        inj = InjectionSpec(enabled=True, every=1)
        for enc in ("vpu", "mxu"):
            ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA,
                               strategy="rowcol", encode=enc)
            ft(a, b, c, inject=inj)
        reg = telemetry.get_registry()
        assert reg.total("ft_calls", encode="vpu") == 1
        assert reg.total("ft_calls", encode="mxu") == 1
        assert reg.total("ft_detections", encode="mxu") > 0
        telemetry.disable()
        events = list(telemetry.read_events(tmp_path / "enc.jsonl"))
        assert {e.extra["encode"] for e in events} == {"vpu", "mxu"}
    finally:
        telemetry.reset()
