"""Backward-GEMM fault counts are observable (VERDICT r3 item 4).

A ``jax.custom_vjp`` backward has no primal output, so the backward
GEMMs' detection/uncorrectable counts ride the one output a backward
pass does have — a gradient: ``with_bwd_counts=True`` adds a ``bwd_sink``
argument whose custom "gradient" is ``[detections, uncorrectable]``
summed over the backward GEMMs (ops/autodiff.py module docstring).

These tests pin the contract end to end: clean runs report exactly zero;
corrected backward injection reports detections with zero uncorrectable
and oracle-exact gradients; an adversarial same-column schedule
(``col_stride=0`` — defeats weighted per-column localization) confined
to the BACKWARD pass surfaces a nonzero uncorrectable count to the
caller, including through a jitted ``FtDense`` training step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, make_ft_matmul
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)

# Geometry: the forward GEMM contracts over K=128 (one check interval —
# every schedule is correctable there), while BOTH backward GEMMs
# contract over 512 (dA over N, dB over M: four check intervals), so the
# same-column schedule is defeated exactly where this channel must see it.
M, N, K = 512, 512, 128


def _adversarial():
    """col_stride=0 pins every fault to one column: 2+ faults per check
    interval in one column defeat weighted localization (the known
    miscorrectable schedule of tests/test_ft_sgemm.py)."""
    return InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                         col_stride=0)


def _ab(seed=10):
    rng = np.random.default_rng(seed)
    return (generate_random_matrix(M, K, rng=rng),
            generate_random_matrix(N, K, rng=rng))


def _sink_grads(mm, a, b):
    def loss(a, b, sink):
        return jnp.sum(jnp.tanh(mm(a, b, sink)))

    return jax.grad(loss, argnums=(0, 1, 2))(a, b, jnp.zeros(2))


def test_clean_bwd_sink_is_zero_and_grads_match():
    a, b = _ab()
    mm = make_ft_matmul(TILE, with_bwd_counts=True)
    ga, gb, sink = _sink_grads(mm, a, b)
    assert sink.shape == (2,)
    assert float(sink[0]) == 0.0 and float(sink[1]) == 0.0
    ra, rb = jax.grad(lambda a, b: jnp.sum(jnp.tanh(a @ b.T)),
                      argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               rtol=1e-4, atol=1e-5)


def test_corrected_bwd_injection_reports_detections_only():
    """Rotating-schedule faults in the backward GEMMs alone: corrected
    in-kernel (oracle-exact grads), reported via the sink gradient as
    detections with zero uncorrectable; forward stays clean."""
    a, b = _ab(seed=3)
    inj_b = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    mm = make_ft_matmul(TILE, inject_bwd=inj_b, with_counts=True,
                        with_bwd_counts=True)
    fwd = mm(a, b, jnp.zeros(2))
    assert int(fwd.detections) == 0, "inject_bwd must not touch forward"

    def loss(a, b, sink):
        return jnp.sum(jnp.tanh(mm(a, b, sink).out))

    ga, gb, sink = jax.grad(loss, argnums=(0, 1, 2))(a, b, jnp.zeros(2))
    assert float(sink[0]) > 0, "backward detections must be reported"
    assert float(sink[1]) == 0.0
    ra, rb = jax.grad(lambda a, b: jnp.sum(jnp.tanh(a @ b.T)),
                      argnums=(0, 1))(a, b)
    for got, want, name in ((ga, ra, "dA"), (gb, rb, "dB")):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"{name}: {nbad} corrupted elements after correction"


def test_adversarial_bwd_schedule_surfaces_uncorrectable():
    """The round-gate case: a same-column schedule confined to the
    backward pass must surface a nonzero uncorrectable count — under jit,
    with the forward completely clean."""
    a, b = _ab(seed=5)
    mm = make_ft_matmul(TILE, strategy="weighted",
                        inject_bwd=_adversarial(), with_counts=True,
                        with_bwd_counts=True)

    @jax.jit
    def step(a, b, sink):
        def loss(a, b, sink):
            return jnp.sum(jnp.tanh(mm(a, b, sink).out))

        return jax.value_and_grad(loss, argnums=(0, 1, 2))(a, b, sink)

    _, (ga, gb, sink) = step(a, b, jnp.zeros(2))
    assert float(sink[1]) > 0, (
        "backward uncorrectable count must reach the caller")
    fwd = mm(a, b, jnp.zeros(2))
    assert int(fwd.uncorrectable) == 0, "forward must be clean"


def test_one_shot_wrapper_passes_sink_through():
    """ft_matmul(a, b, sink, with_bwd_counts=True) must reach the
    3-argument variant (the wrapper forwards positionals)."""
    from ft_sgemm_tpu import ft_matmul

    a, b = _ab(seed=9)
    out = ft_matmul(a, b, jnp.zeros(2), shape=TILE, with_bwd_counts=True)
    np.testing.assert_allclose(np.asarray(out), a @ b.T,
                               rtol=1e-4, atol=1e-5)


def test_ftdense_backward_adversarial_uncorrectable_surfaces():
    """VERDICT r3 item 4's done criterion: a col_stride=0 adversarial
    schedule in the BACKWARD pass of FtDense surfaces a nonzero
    uncorrectable count to the caller of a jitted training step."""
    flax = pytest.importorskip("flax")  # noqa: F841
    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtDense

    rng = np.random.default_rng(6)
    x = jnp.asarray(generate_random_matrix(M, K, rng=rng))
    y = jnp.asarray(generate_random_matrix(M, N, rng=rng))
    layer = FtDense(N, shape=TILE, inject_bwd=_adversarial())
    vars_ = layer.init(jax.random.key(0), x)

    @jax.jit
    def step(params, sink):
        def loss(p, sink):
            out, mut = layer.apply({"params": p}, x, sink,
                                   mutable=[COUNTS_COLLECTION])
            return jnp.mean((out - y) ** 2), mut[COUNTS_COLLECTION]

        (l, counts), grads = jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True)(params, sink)
        return l, counts, grads

    _, counts, (grads, sink_grad) = step(vars_["params"], jnp.zeros(2))
    [fwd_unc] = jax.tree_util.tree_leaves(counts["uncorrectable"])
    assert int(fwd_unc) == 0, "forward pass must be clean"
    assert float(sink_grad[1]) > 0, (
        "FtDense backward uncorrectable must surface to the caller")
    # Gradients still flow for every parameter.
    assert set(grads) == {"kernel", "bias"}


def test_ftdense_without_sink_unchanged():
    """bwd_sink is opt-in: the plain call path (no sink) keeps its exact
    previous behavior."""
    flax = pytest.importorskip("flax")  # noqa: F841
    from ft_sgemm_tpu.nn import FtDense

    rng = np.random.default_rng(7)
    x = jnp.asarray(generate_random_matrix(128, 128, rng=rng))
    layer = FtDense(64, shape=TILE)
    vars_ = layer.init(jax.random.key(1), x)
    out = layer.apply(vars_, x)
    want = np.asarray(x @ vars_["params"]["kernel"]
                      + vars_["params"]["bias"])
    ok, nbad, _ = verify_matrix(want, np.asarray(out), verbose=False)
    assert ok, f"{nbad} elements off vs plain dense"


def test_attention_bwd_sink_reports():
    """Differentiable attention's four backward GEMMs report through the
    same sink channel: rotating injection -> detections, adversarial
    same-column -> nonzero uncorrectable; clean -> exactly zero."""
    from ft_sgemm_tpu import make_ft_attention_diff

    rng = np.random.default_rng(8)
    l, d = 256, 128
    q, k, v = (generate_random_matrix(l, d, rng=rng) for _ in range(3))
    # bk=128 backward tiles: the dV/dQ/dK contractions (over L=256) then
    # span TWO check intervals, so col_stride=0 lands 2 same-column faults
    # per deferred check — the schedule weighted localization cannot fix.
    qk_t = KernelShape("attn_qk_t", 128, 128, 128, (0,) * 7)
    pv_t = KernelShape("attn_pv_t", 128, 128, 128, (0,) * 7)

    def sink_grad(att):
        def loss(q, k, v, sink):
            return jnp.sum(jnp.tanh(att(q, k, v, sink)))

        return jax.grad(loss, argnums=3)(q, k, v, jnp.zeros(2))

    mk = lambda **kw: make_ft_attention_diff(  # noqa: E731
        qk_shape=qk_t, pv_shape=pv_t, with_bwd_counts=True, **kw)

    clean = sink_grad(mk())
    assert float(clean[0]) == 0.0 and float(clean[1]) == 0.0

    rot = sink_grad(mk(
        inject_bwd=InjectionSpec(enabled=True, every=1, magnitude=10000.0)))
    assert float(rot[0]) > 0

    adv = sink_grad(mk(strategy="weighted", inject_bwd=_adversarial()))
    assert float(adv[1]) > 0, (
        "adversarial backward attention faults must be reported")
