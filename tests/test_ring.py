"""Ring collective-matmul FT-SGEMM over 8 virtual CPU devices.

Validates the ppermute dataflow: every device sees every B shard exactly
once, partial C column blocks land at the right offsets, and local ABFT
correction per hop keeps the output clean under injection.
"""

import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, sgemm_reference
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.parallel import make_ring_mesh, ring_ft_sgemm, ring_sgemm
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def test_ring_mesh_is_1d():
    mesh = make_ring_mesh(8)
    assert mesh.shape == {"x": 8}


@pytest.mark.parametrize("n_devices", [2, 8])
def test_ring_sgemm_matches_reference(n_devices):
    mesh = make_ring_mesh(n_devices)
    m, n, k = 128 * n_devices, 128 * n_devices, 256
    a, b, c = _inputs(m, n, k)
    got = np.asarray(ring_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ring_ft_clean_matches_reference():
    mesh = make_ring_mesh(4)
    m, n, k = 512, 512, 256
    a, b, c = _inputs(m, n, k, seed=3)
    res = ring_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    np.testing.assert_allclose(np.asarray(res.c), want, rtol=1e-4, atol=1e-4)
    assert int(res.num_detected) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_ring_ft_corrects_under_injection(strategy):
    mesh = make_ring_mesh(4)
    m, n, k = 512, 512, 256
    a, b, c = _inputs(m, n, k, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ring_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                        inject=inj, strategy=strategy)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{strategy}: {nbad} corrupted elements survived the ring"
    # Each of the 4 devices runs 4 hops; each hop is a (128x128) x K=256
    # FT call injecting expected_faults per its 1-tile grid.
    per_call = inj.expected_faults(k, TILE.bk)
    assert int(res.num_detected) == 4 * 4 * per_call


def test_ring_rejects_indivisible_shapes():
    mesh = make_ring_mesh(8)
    a, b, c = _inputs(100, 100, 128)
    with pytest.raises(ValueError, match="divide evenly"):
        ring_sgemm(a, b, c, mesh, TILE)


def test_ring_bf16_corrects_and_matches_rounded_oracle():
    from conftest import bf16_rounded_oracle

    mesh = make_ring_mesh(8)
    m, n, k = 256, 512, 256
    a, b, c = _inputs(m, n, k, seed=9)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ring_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                        inject=inj, in_dtype="bfloat16")
    want = bf16_rounded_oracle(a, b, c, ALPHA, BETA)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the bf16 ring"
    assert int(res.num_detected) > 0
