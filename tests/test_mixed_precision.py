"""bf16 mixed-precision kernel family tests.

The reference is f32-only (SGEMM); the TPU build adds an ``in_dtype`` axis:
A/B feed the MXU in its native bf16 input format while the accumulator,
checksums, and detect/correct math stay f32. The correctness oracle for the
bf16 path is the f32 XLA dot over the *bf16-rounded* inputs — a bf16xbf16
product is exact in f32, so rounding the inputs once captures the entire
precision difference and the remaining error is accumulation-order noise.
"""

import numpy as np
import pytest

from conftest import bf16_rounded_oracle

from ft_sgemm_tpu import (
    InjectionSpec,
    SHAPES,
    make_ft_sgemm,
    make_sgemm,
    sgemm_reference,
)
from ft_sgemm_tpu.ops.ft_sgemm import STRATEGIES
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def _rounded_oracle(a, b, c):
    return bf16_rounded_oracle(a, b, c, ALPHA, BETA)


def test_bf16_plain_matches_rounded_oracle():
    a, b, c = _inputs(256, 256, 512)
    fn = make_sgemm("test", alpha=ALPHA, beta=BETA, in_dtype="bfloat16")
    got = np.asarray(fn(a, b, c))
    np.testing.assert_allclose(got, _rounded_oracle(a, b, c),
                               rtol=1e-5, atol=1e-4)


def test_bf16_plain_close_to_f32_reference():
    # Input rounding dominates the bf16-vs-f32 gap; with the quantized
    # +-{0,...,0.9} inputs it grows ~sqrt(K) and measures ~0.06 max-abs at
    # K=512 — this pins the scale so regressions (e.g. accidental bf16
    # accumulation, which would be ~100x worse) are caught.
    a, b, c = _inputs(256, 256, 512, seed=3)
    fn = make_sgemm("test", alpha=ALPHA, beta=BETA, in_dtype="bfloat16")
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(fn(a, b, c)), verbose=False,
                                abs_tol=0.1, rel_tol=0.02)
    assert ok, f"{nbad} elements outside the bf16 tolerance"


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_bf16_ft_clean_matches_bf16_plain(strategy):
    a, b, c = _inputs(256, 256, 512, seed=4)
    ft = make_ft_sgemm("test", alpha=ALPHA, beta=BETA, strategy=strategy,
                       in_dtype="bfloat16")
    plain = make_sgemm("test", alpha=ALPHA, beta=BETA, in_dtype="bfloat16")
    res = ft(a, b, c)
    np.testing.assert_allclose(np.asarray(res.c), np.asarray(plain(a, b, c)),
                               rtol=1e-5, atol=1e-4)
    assert int(res.num_detected) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_bf16_ft_corrects_injected_faults(strategy):
    m = n = 256
    k = 1024
    a, b, c = _inputs(m, n, k, seed=5)
    shape = SHAPES["test"]
    inj = InjectionSpec.reference_like(k, shape.bk, num_faults=4)
    ft = make_ft_sgemm("test", alpha=ALPHA, beta=BETA, strategy=strategy,
                       in_dtype="bfloat16")
    res = ft(a, b, c, inject=inj)
    # Same threshold as f32: checksums see the rounded inputs, so the
    # noise floor is unchanged and reference-magnitude faults are caught.
    want = _rounded_oracle(a, b, c)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{strategy}/bf16: {nbad} corrupted elements survived"
    tiles = (m // shape.bm) * (n // shape.bn)
    assert int(res.num_detected) == tiles * inj.expected_faults(k, shape.bk)


def test_bf16_ft_global_detects():
    m = n = 256
    k = 512
    a, b, c = _inputs(m, n, k, seed=6)
    inj = InjectionSpec(enabled=True, every=k // SHAPES["test"].bk,
                        magnitude=10000.0)
    ft = make_ft_sgemm("test", alpha=ALPHA, beta=BETA, strategy="global",
                       in_dtype="bfloat16")
    res = ft(a, b, c, inject=inj)
    assert int(res.num_detected) >= 1


def test_in_dtype_validation():
    with pytest.raises(ValueError, match="in_dtype"):
        make_sgemm("test", in_dtype="float16")
    # int8 joined the family (PR 7) but only with the exact strategies;
    # the default weighted spelling is rejected naming the constraint.
    with pytest.raises(ValueError, match="rowcol"):
        make_ft_sgemm("test", in_dtype="int8")
    make_ft_sgemm("test", strategy="rowcol", in_dtype="int8")


def test_kernel_names_carry_dtype():
    assert make_sgemm("test", in_dtype="bfloat16").__name__.endswith("bfloat16")
    assert make_ft_sgemm("test").__name__ == "ft_sgemm_test_weighted"


def test_bf16_named_shape_picks_tuned_tile():
    from ft_sgemm_tpu.configs import SHAPES, shape_for_dtype

    assert make_sgemm("huge", in_dtype="bfloat16").shape_config.block == \
        (512, 512, 2048)
    assert make_ft_sgemm("huge", in_dtype="bfloat16").shape_config.block == \
        (512, 1024, 256)
    # f32 named shapes and explicit KernelShape objects are untouched.
    assert make_sgemm("huge").shape_config.block == (512, 512, 512)
    explicit = SHAPES["huge"]
    assert shape_for_dtype(explicit, False, "float32") is explicit
    assert make_sgemm(explicit, in_dtype="bfloat16").shape_config is explicit


def test_shrink_block_limits_padding_waste():
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.ops.common import shrink_block

    big = KernelShape("b", 512, 512, 2048, (0,) * 7)
    # Small K: bk halves down until padding waste is below one granule.
    assert shrink_block(big, 4096, 4096, 1024).block == (512, 512, 1024)
    assert shrink_block(big, 256, 1536, 512).block == (256, 512, 512)
    # Exact fits stay put.
    assert shrink_block(big, 4096, 4096, 4096) is big
    # Never shrinks to an illegal (non-128-multiple) value.
    odd = KernelShape("o", 384, 384, 384, (0,) * 7)
    assert shrink_block(odd, 128, 128, 128).block == (384, 384, 384)


def test_bf16_tuned_tiles_stay_correct_with_injection():
    # End-to-end over the real override tiles (shrunk to the test size):
    # wide-bn FT tile and deep-bk plain tile both verify.
    m = n = 256
    k = 1024
    a, b, c = _inputs(m, n, k, seed=31)
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    ft = make_ft_sgemm("huge", in_dtype="bfloat16", alpha=ALPHA, beta=BETA)
    res = ft(a, b, c, inject=inj)
    want = _rounded_oracle(a, b, c)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived on the bf16 FT tile"
    assert int(res.num_detected) > 0
    plain = make_sgemm("huge", in_dtype="bfloat16", alpha=ALPHA, beta=BETA)
    ok, nbad, _ = verify_matrix(want, np.asarray(plain(a, b, c)),
                                verbose=False)
    assert ok, f"{nbad} bad on the bf16 plain tile"


def test_auto_threshold_bf16_catches_small_faults():
    """Adaptive thresholds compose with the bf16 input mode: the noise
    bound is computed on the bf16-rounded values the MXU consumes, and
    small faults (magnitude 5, invisible at the fixed 9500) are detected
    and corrected within the bf16 verify tolerance."""
    from ft_sgemm_tpu.configs import KernelShape

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    a, b, c = _inputs(128, 128, 512, seed=23)
    inj = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    want = np.asarray(
        sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))
    for strategy in ("weighted", "fused"):
        res = make_ft_sgemm(tile, alpha=ALPHA, beta=BETA, strategy=strategy,
                            in_dtype="bfloat16",
                            threshold="auto")(a, b, c, inject=inj)
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"bf16/{strategy}: {nbad} small faults survived"
        assert int(res.num_detected) == 4
        assert int(res.num_uncorrectable) == 0
