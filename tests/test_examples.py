"""The examples are product surface: smoke them as real subprocesses.

Each runs a tiny configuration on the CPU backend and must exit 0 with
the fault columns showing detections > 0 and uncorrectable == 0 — the
same end-to-end claim the examples document.
"""

import os
import pathlib
import subprocess
import sys

import pytest

flax = pytest.importorskip("flax")
optax = pytest.importorskip("optax")

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(args, timeout=600):
    env = dict(os.environ)
    # The conftest's virtual-device settings must not leak in; each
    # example owns its backend setup.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, *args], cwd=ROOT, env=env,
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{args} rc={proc.returncode}\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}")
    return proc.stdout


def test_train_ft_example():
    out = _run(["examples/train_ft.py", "--cpu", "--steps", "2"])
    rows = [ln.split() for ln in out.splitlines()
            if ln.strip().startswith(("0 ", "1 "))]
    assert len(rows) == 2
    for row in rows:  # step loss det unc bwd_det bwd_unc
        assert int(row[2]) > 0 and int(row[3]) == 0
        assert int(row[4]) > 0 and int(row[5]) == 0


def test_train_long_context_example():
    out = _run(["examples/train_long_context.py", "--devices", "2",
                "--steps", "1"])
    rows = [ln.split() for ln in out.splitlines()
            if ln.strip().startswith("0 ")]
    assert len(rows) == 1
    row = rows[0]  # step loss det sm_flags unc bwd_det bwd_unc
    assert int(row[2]) > 0 and int(row[4]) == 0
    assert int(row[5]) > 0 and int(row[6]) == 0
