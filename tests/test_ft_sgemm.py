"""Fused-ABFT kernel tests: the zero-undetected-corruption acceptance gate.

The reference proves detect+correct implicitly: its FT kernels always inject
20 faults and must still pass the cuBLAS diff (sgemm.cu:222-227,
ft_sgemm_huge.cuh:324-327). Here injection is a parameter, so both the clean
path and the injected path are tested explicitly, per strategy.
"""

import numpy as np
import pytest

from ft_sgemm_tpu import (
    InjectionSpec,
    SHAPES,
    make_ft_sgemm,
    make_sgemm,
    sgemm_reference,
)
from ft_sgemm_tpu.configs import KernelShape, SHAPE_ORDER
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


@pytest.mark.parametrize("shape_name", SHAPE_ORDER)
def test_clean_matches_plain_kernel(shape_name):
    a, b, c = _inputs(256, 256, 512)
    ft = make_ft_sgemm(shape_name, alpha=ALPHA, beta=BETA)
    plain = make_sgemm(shape_name, alpha=ALPHA, beta=BETA)
    res = ft(a, b, c)
    np.testing.assert_allclose(
        np.asarray(res.c), np.asarray(plain(a, b, c)), rtol=1e-5, atol=1e-5
    )
    assert int(res.num_detected) == 0


@pytest.mark.parametrize("shape_name", SHAPE_ORDER)
def test_injected_faults_corrected(shape_name):
    m = n = 512
    k = 1024
    a, b, c = _inputs(m, n, k, seed=5)
    shape = SHAPES[shape_name]
    inj = InjectionSpec.reference_like(k, shape.bk, num_faults=4)
    ft = make_ft_sgemm(shape_name, alpha=ALPHA, beta=BETA)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{shape_name}: {nbad} corrupted elements survived correction"
    # Every injected fault was detected: faults per tile x number of tiles.
    mp = -(-m // shape.bm) * shape.bm
    np_ = -(-n // shape.bn) * shape.bn
    tiles = (mp // shape.bm) * (np_ // shape.bn)
    expected = tiles * inj.expected_faults(k, shape.bk)
    assert int(res.num_detected) == expected


def test_injection_count_scales_with_cadence():
    m = n = 512
    k = 2048
    a, b, c = _inputs(m, n, k, seed=6)
    shape = SHAPES["huge"]
    ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA)
    nk = k // shape.bk
    for every in (nk, nk // 2, nk // 4):
        inj = InjectionSpec(enabled=True, every=every, magnitude=10000.0)
        res = ft(a, b, c, inject=inj)
        assert int(res.num_detected) == inj.expected_faults(k, shape.bk)
        want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"every={every}: {nbad} corrupted elements survived"


def test_weighted_strategy_corrects():
    m = n = 512
    k = 1024
    a, b, c = _inputs(m, n, k, seed=8)
    shape = SHAPES["huge"]
    inj = InjectionSpec.reference_like(k, shape.bk, num_faults=4)
    ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="weighted")
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"weighted: {nbad} corrupted elements survived localization"
    assert int(res.num_detected) == inj.expected_faults(k, shape.bk)


def test_weighted_precomp_and_inkernel_cadences_agree():
    """Default weighted cadence routes to the precomputed-checksum kernel
    (no in-kernel encode); an intermediate cadence routes to the running
    in-kernel encode. Both must correct the same injected schedule."""
    m = n = 512
    k = 2048
    a, b, c = _inputs(m, n, k, seed=12)
    shape = SHAPES["huge"]
    nk = k // shape.bk
    inj = InjectionSpec.reference_like(k, shape.bk, num_faults=4)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    for ce in (None, max(1, nk // 2)):  # None -> nk -> precomp path
        ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA,
                           strategy="weighted", check_every=ce)
        res = ft(a, b, c, inject=inj)
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"check_every={ce}: {nbad} corrupted elements survived"
        assert int(res.num_detected) == inj.expected_faults(k, shape.bk)


def test_weighted_precomp_bf16_corrects():
    """bf16 input mode through the precomputed-checksum path: expectations
    are computed on the same bf16-rounded values the MXU consumes, so the
    residual noise floor stays far below the 9500 threshold."""
    m = n = 512
    k = 1024
    a, b, c = _inputs(m, n, k, seed=13)
    ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="weighted",
                       in_dtype="bfloat16")
    # The bf16 flagship resolves to its own tuned tile (BF16_TILE_OVERRIDES)
    # whose bk differs from the f32 tile — fault counts follow its K grid.
    bk = ft.shape_config.bk
    inj = InjectionSpec.reference_like(k, bk, num_faults=4)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(
        sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"bf16 precomp: {nbad} corrupted elements survived"
    assert int(res.num_detected) == inj.expected_faults(k, bk)


def test_weighted_bf16_inkernel_cadence_corrects():
    """bf16 weighted at an INTERMEDIATE cadence (in-kernel running encode,
    not the precomp path) — the remaining strategy x dtype x cadence cell."""
    m = n = 512
    k = 1024
    a, b, c = _inputs(m, n, k, seed=14)
    ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="weighted",
                       in_dtype="bfloat16", check_every=2)
    bk = ft.shape_config.bk
    inj = InjectionSpec.reference_like(k, bk, num_faults=4)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(
        sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"bf16 in-kernel weighted: {nbad} corrupted elements survived"
    assert int(res.num_detected) == inj.expected_faults(k, bk)


def test_precomp_expectation_noise_floor_bf16():
    """The bf16 hi+lo checksum-row split keeps precomputed-expectation
    error in the f32 accumulation-noise class. A single bf16 cast of
    ``w^T A`` (magnitudes ~1e4) costs ~0.3-1.4 of noise — deposited into
    every corrected element, which fails the 0.01/0.01 verify tolerance.
    Regression-guards the split in ``_expected_col_checksums``."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu.ops.ft_sgemm import _expected_col_checksums

    m = n = 512
    k = 1024
    a, b, _ = _inputs(m, n, k, seed=13)
    a16 = jnp.asarray(a, jnp.bfloat16)
    b16 = jnp.asarray(b, jnp.bfloat16)
    exp = _expected_col_checksums(a16, b16, m, jax.lax.Precision("default"))
    acc = jax.lax.dot_general(
        a16, b16, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    w = (jnp.arange(m, dtype=jnp.float32) + 1.0)[:, None]
    res_c = np.asarray(exp[0] - jnp.sum(acc, axis=0))
    res_cw = np.asarray(exp[1] - jnp.sum(acc * w, axis=0))
    # Bounds ~20x above observed f32 accumulation noise (0.004 / 1.2) and
    # ~15x below the single-cast regression (0.3-1.4 / 100+).
    assert np.abs(res_c).max() < 0.02, np.abs(res_c).max()
    assert np.abs(res_cw).max() < 20.0, np.abs(res_cw).max()


def test_weighted_localization_property_random_faults():
    """Property: for ANY set of single-fault-per-column corruptions above
    threshold, weighted localization corrects every one exactly (module
    docstring claim; the rotating injector is just one such pattern).
    Checked via the shared _weighted_localize helper on synthetic
    residuals over many random fault patterns."""
    import jax.numpy as jnp

    from ft_sgemm_tpu.ops.ft_sgemm import _weighted_localize

    rng = np.random.default_rng(21)
    bm, bn = 64, 48
    for trial in range(25):
        ncols = int(rng.integers(0, bn + 1))
        cols = rng.choice(bn, size=ncols, replace=False)
        rows = rng.integers(0, bm, size=ncols)
        mags = rng.uniform(1e4, 1e6, size=ncols) * rng.choice([-1.0, 1.0],
                                                              size=ncols)
        res_c = np.zeros((1, bn), np.float32)
        res_cw = np.zeros((1, bn), np.float32)
        res_c[0, cols] = mags
        res_cw[0, cols] = mags * (rows + 1)
        # Sub-threshold noise on unfaulted columns must not trigger.
        noise_cols = np.setdiff1d(np.arange(bn), cols)
        res_c[0, noise_cols] = rng.uniform(-1, 1, size=noise_cols.size)
        det_c = jnp.abs(jnp.asarray(res_c)) > 9500.0
        hit = np.asarray(_weighted_localize(
            jnp.asarray(res_c), jnp.asarray(res_cw), det_c, bm, bn))
        want = np.zeros((bm, bn), bool)
        want[rows, cols] = True
        np.testing.assert_array_equal(hit, want, err_msg=f"trial {trial}")


def test_global_strategy_detects_but_does_not_correct():
    m = n = 512
    k = 1024
    a, b, c = _inputs(m, n, k, seed=9)
    shape = SHAPES["huge"]
    inj = InjectionSpec(enabled=True, every=k // shape.bk, magnitude=10000.0)
    ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="global")
    res = ft(a, b, c, inject=inj)
    assert int(res.num_detected) >= 1
    # Detect-only: the corruption remains in the output.
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, _, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert not ok


def test_global_strategy_clean_is_correct():
    a, b, c = _inputs(384, 384, 512, seed=11)
    ft = make_ft_sgemm("large", alpha=ALPHA, beta=BETA, strategy="global")
    res = ft(a, b, c)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok and int(res.num_detected) == 0


def test_below_threshold_fault_not_detected():
    # A fault below err_bound passes silently — documents the threshold
    # semantics the reference relies on (err_bound1=9500 vs inject=10000).
    a, b, c = _inputs(256, 256, 512, seed=12)
    inj = InjectionSpec(enabled=True, every=100, magnitude=100.0)
    ft = make_ft_sgemm("small", alpha=ALPHA, beta=BETA)
    res = ft(a, b, c, inject=inj)
    assert int(res.num_detected) == 0


def test_dense_injection_with_sparse_check_cadence_still_corrects():
    # check_every coarser than the injection cadence puts >1 fault per check
    # interval; bare intersection correction would be ambiguous, so the
    # multi-fault rowcol variant localizes each flagged column's fault row
    # via the weighted checksum (no cadence clamp).
    m = n = 128
    k = 1024
    a, b, c = _inputs(m, n, k, seed=21)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm("small", alpha=ALPHA, beta=BETA, check_every=2)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived"
    assert int(res.num_detected) == inj.expected_faults(k, SHAPES["small"].bk)


def test_rowcol_single_final_check_corrects_fault_backlog():
    # The hardest multi-fault case: ONE deferred check sees every injected
    # fault at once (>1 row and >1 col flagged — bare row/col intersection
    # is provably ambiguous for equal magnitudes). The weighted column
    # checksum localizes each fault.
    m = n = 128
    k = 1024  # nk = 8 with bk=128 -> 8 faults in one check interval
    a, b, c = _inputs(m, n, k, seed=31)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm("small", alpha=ALPHA, beta=BETA, check_every=8)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the deferred check"
    assert int(res.num_detected) == 8


def test_rowcol_coarse_cadence_corrects_multifault_backlog():
    # Coarse (but not single) cadence with injection denser than the checks
    # must still fully correct — the exact scenario the removed
    # ce=min(ce, inject.every) clamp used to forbid.
    m = n = 256
    k = 256 * 30  # nk = 30 for the "medium" shape (bk=256)
    a, b, c = _inputs(m, n, k, seed=32)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm("medium", alpha=ALPHA, beta=BETA, check_every=5)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived"
    tiles = (m // 128) * (n // 128)  # injection is per output tile
    assert int(res.num_detected) == tiles * inj.expected_faults(
        k, SHAPES["medium"].bk)


def test_rowcol_deep_k_wraps_column_cycle():
    # nk > bn with a dense injector would wrap two faults into the same
    # column of one interval; the wrapper clamps the cadence to bn*every
    # (column-distinctness window), mirroring the weighted strategy.
    m = n = 128
    k = 128 * 130  # nk = 130 > bn = 128 for the "small" shape (bk=128)
    rng = np.random.default_rng(33)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm("small", alpha=ALPHA, beta=BETA, check_every=130)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the wrapped column cycle"
    assert int(res.num_detected) == 130


def test_global_counts_distinct_fault_events():
    # Unified num_detected semantics: a persistent uncorrected fault is ONE
    # event, not one per later check (the residual only moves when new
    # corruption lands).
    m = n = 256
    k = 2048
    a, b, c = _inputs(m, n, k, seed=34)
    shape = SHAPES["huge"]
    nk = -(-k // shape.bk)
    for faults in (1, 2):
        inj = InjectionSpec(enabled=True, every=nk // faults,
                            magnitude=10000.0)
        ft = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="global",
                           check_every=1)
        res = ft(a, b, c, inject=inj)
        assert int(res.num_detected) == inj.expected_faults(k, shape.bk), (
            f"faults={faults}")


def test_expected_faults_counts_padded_k_grid():
    # K=520 pads to 768 with bk=256 -> 3 k-steps -> every=2 injects at k=0,2.
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    assert inj.expected_faults(520, 256) == 2
    a, b, c = _inputs(128, 128, 520, seed=22)
    ft = make_ft_sgemm("medium", alpha=ALPHA, beta=BETA)
    res = ft(a, b, c, inject=inj)
    assert int(res.num_detected) == 2
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived"


def test_weighted_deep_k_wraps_column_cycle():
    # Regression: with nk > bn, a single deferred check would see two
    # faults in the SAME column (the rotating target wraps mod bn) and
    # the weighted ratio would localize a wrong row. The wrapper clamps
    # the cadence to bn*every so each check's faults stay in distinct
    # columns.
    m = n = 128
    k = 128 * 130  # nk = 130 > bn = 128 for the "small" shape (bk=128)
    rng = np.random.default_rng(23)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm("small", alpha=ALPHA, beta=BETA, strategy="weighted")
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the wrapped column cycle"
    assert int(res.num_detected) == 130


def test_rectangular_with_padding_and_injection():
    a, b, c = _inputs(300, 200, 520, seed=13)
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    ft = make_ft_sgemm("medium", alpha=ALPHA, beta=BETA)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived"
    assert int(res.num_detected) > 0


# ---------------------------------------------------------------------------
# Residual-after-correct re-check: two+ faults in ONE column of one check
# interval defeat per-column localization; the kernels must report the
# interval via FtSgemmResult.uncorrectable instead of silently miscorrecting
# (the round-2 documented limit, now closed).
# ---------------------------------------------------------------------------

# Small explicit tile for the adversarial-schedule tests: nk = K/128 check
# steps, fast in interpret mode (explicit KernelShape objects never shrink).
ADV_TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _same_column_spec():
    """col_stride=0 pins every fault to one column: the adversarial
    schedule the rotating default (coprime stride 61) can never produce."""
    return InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                         col_stride=0)


def _assert_reported_or_corrected(res, a, b, c, label):
    """The contract: either the output verifies clean, or uncorrectable is
    nonzero — corruption is never silent."""
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    if not ok:
        assert int(res.num_uncorrectable) > 0, (
            f"{label}: {nbad} corrupted elements survived with NO "
            f"uncorrectable report — silent corruption")


@pytest.mark.parametrize("check_every", [None, 2])
def test_weighted_same_column_faults_reported(check_every):
    """Weighted localization (precomp at default cadence, in-kernel encode
    at cadence 2) sees 4 same-column faults: per-column localization is
    defeated and the weighted residual re-check must flag it."""
    a, b, c = _inputs(128, 128, 512, seed=8)
    ft = make_ft_sgemm(
        ADV_TILE, alpha=ALPHA, beta=BETA, strategy="weighted",
        check_every=check_every)
    res = ft(a, b, c, inject=_same_column_spec())
    _assert_reported_or_corrected(res, a, b, c, f"weighted/{check_every}")
    # This schedule (2+ faults per interval in one column) is known
    # miscorrectable: the report must actually fire.
    assert int(res.num_uncorrectable) > 0


def test_rowcol_same_column_faults_corrected_exactly():
    """Plain row/col intersection handles same-column faults on DISTINCT
    rows exactly (each flagged row carries its own residual) — corrected,
    zero uncorrectable."""
    a, b, c = _inputs(128, 128, 512, seed=8)
    ft = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                       strategy="rowcol")
    res = ft(a, b, c, inject=_same_column_spec())
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"rowcol: {nbad} corrupted elements survived"
    assert int(res.num_uncorrectable) == 0
    assert int(res.num_detected) == 4  # nk=4 at bk=128, every=1


def test_rowcol_ambiguous_with_doubled_column_reported():
    """>=2 rows AND >=2 cols flagged routes rowcol-multifault to weighted
    localization; a column holding TWO of the faults breaks its 1-fault
    assumption. The row-residual re-check must flag the interval."""
    a, b, c = _inputs(128, 128, 512, seed=8)
    # Stride 64 over bn=128: faults alternate between two columns, so one
    # check interval covering all 4 faults sees 2 faults in EACH column.
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                        col_stride=64)
    ft = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                       strategy="rowcol", check_every=4, multifault=True)
    res = ft(a, b, c, inject=inj)
    _assert_reported_or_corrected(res, a, b, c, "rowcol/ambiguous")
    assert int(res.num_uncorrectable) > 0


def test_clean_runs_report_zero_uncorrectable():
    """No injection -> both counters exactly zero, every strategy."""
    a, b, c = _inputs(256, 128, 512, seed=2)
    for strategy in ("rowcol", "weighted"):
        res = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA,
                            strategy=strategy)(a, b, c)
        assert int(res.num_detected) == 0, strategy
        assert int(res.num_uncorrectable) == 0, strategy


def test_reference_like_injection_zero_uncorrectable():
    """The rotating (coprime-stride) injector keeps every interval
    correctable: corrections verified, uncorrectable == 0."""
    a, b, c = _inputs(256, 256, 1024, seed=6)
    inj = InjectionSpec.reference_like(1024, SHAPES["huge"].bk, num_faults=8)
    for strategy in ("rowcol", "weighted"):
        res = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA,
                            strategy=strategy)(a, b, c, inject=inj)
        want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"{strategy}: {nbad} corrupted"
        assert int(res.num_uncorrectable) == 0, strategy


def test_global_uncorrectable_equals_detections():
    """Detect-only strategy: every detection is by definition uncorrected."""
    a, b, c = _inputs(128, 128, 512, seed=3)
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    res = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, strategy="global",
                        check_every=2)(a, b, c, inject=inj)
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == int(res.num_detected)


@pytest.mark.parametrize("check_every", [None, 3])
def test_weighted_arithmetic_progression_faults_reported(check_every):
    """Equal-magnitude faults at rows in arithmetic progression (the
    rotating row stride makes col_stride=0 produce exactly this) zero BOTH
    the plain and first-moment residuals after the point-mass correction
    lands on the mean row — only the second-moment (w^2) re-check can see
    it. Round-3 review repro: K=384, rows 7/10/13 of one column."""
    a, b, c = _inputs(128, 128, 384, seed=8)  # nk=3 at bk=128
    ft = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                       strategy="weighted", check_every=check_every)
    res = ft(a, b, c, inject=_same_column_spec())
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, _, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert not ok, "3 same-column faults should defeat localization here"
    assert int(res.num_uncorrectable) > 0, "silent corruption"


# ---------------------------------------------------------------------------
# "fused" strategy (warp-level analog): checksum moments ride extra A rows
# through the SAME MXU dot — weighted-class correction at any cadence with
# zero per-panel encode work (reference include/ft_sgemm_huge_warp.cuh).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("check_every", [None, 2])
def test_fused_strategy_corrects(check_every):
    a, b, c = _inputs(256, 128, 512, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA, strategy="fused",
                       check_every=check_every)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"fused/{check_every}: {nbad} corrupted elements survived"
    assert int(res.num_detected) == 4 * 2  # nk faults x (gm*gn)=2 tiles
    assert int(res.num_uncorrectable) == 0


def test_fused_clean_matches_plain():
    a, b, c = _inputs(256, 256, 384, seed=1)
    res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                        strategy="fused")(a, b, c)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok and int(res.num_detected) == 0
    assert int(res.num_uncorrectable) == 0


def test_fused_same_column_faults_reported():
    """Same-column multi-fault intervals defeat per-column localization in
    the fused design too — the three-moment re-check must report."""
    a, b, c = _inputs(128, 128, 512, seed=8)
    res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                        strategy="fused")(a, b, c,
                                          inject=_same_column_spec())
    _assert_reported_or_corrected(res, a, b, c, "fused/same-col")
    assert int(res.num_uncorrectable) > 0


def test_fused_bf16_corrects():
    """bf16 fused: moment rows ride as hi/lo/lo2 bf16 triples in a 16-row
    augmented tail; corrections must stay within the bf16 verify
    tolerance and the re-check must stay quiet."""
    a, b, c = _inputs(256, 128, 512, seed=9)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA, strategy="fused",
                        in_dtype="bfloat16")(a, b, c, inject=inj)
    want = np.asarray(
        sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="bfloat16"))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"bf16 fused: {nbad} corrupted elements survived"
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == 0


def test_fused_rectangular_with_padding():
    a, b, c = _inputs(200, 130, 300, seed=12)  # every dim pads
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                        strategy="fused")(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"fused/rect: {nbad} corrupted elements survived"
    assert int(res.num_uncorrectable) == 0


def test_moment_correction_never_silent_property():
    """Property test of the shared correction core: for ANY same-sign
    above-threshold fault set, _moment_detect_correct either restores the
    exact accumulator or reports a nonzero uncorrectable count. This is
    the 'corruption is REPORTED, never silent' contract, checked as pure
    math across 200 random fault patterns (counts 1-5, random rows and
    columns including collisions, random magnitudes 1-100x threshold)."""
    import jax.numpy as jnp

    from ft_sgemm_tpu.injection import REFERENCE_THRESHOLD
    from ft_sgemm_tpu.ops.ft_sgemm import _moment_detect_correct

    bm = bn = 128
    rng = np.random.default_rng(42)
    base = rng.standard_normal((bm, bn)).astype(np.float32) * 10.0
    w = (np.arange(bm, dtype=np.float64) + 1.0)[:, None]
    exp_c = jnp.asarray((base.astype(np.float64)).sum(0)[None, :]
                        .astype(np.float32))
    exp_cw = jnp.asarray((base * w).sum(0)[None, :].astype(np.float32))
    exp_cw2 = jnp.asarray((base * w * w).sum(0)[None, :].astype(np.float32))

    silent, reported, corrected_n = 0, 0, 0
    for trial in range(300):
        nf = int(rng.integers(1, 6))
        rows = rng.integers(0, bm, nf)
        cols = rng.integers(0, bn, nf)
        if trial < 200:  # same-sign: the guaranteed-reported class
            signs = 1.0 if rng.random() < 0.5 else -1.0
        else:  # mixed signs: silent evasion needs an exact 3-moment
            # match of a point mass — measure-zero for random draws
            signs = rng.choice([-1.0, 1.0], nf)
        mags = signs * REFERENCE_THRESHOLD * rng.uniform(1.05, 100.0, nf)
        acc = base.copy()
        for r, c_, m_ in zip(rows, cols, mags):
            acc[r, c_] += np.float32(m_)
        thr = REFERENCE_THRESHOLD
        got, n_hit, n_unc = _moment_detect_correct(
            jnp.asarray(acc), exp_c, exp_cw, exp_cw2,
            (thr, thr, thr), bm, bn)
        ok = bool(np.allclose(np.asarray(got), base, atol=1.0))
        if ok and int(n_unc) == 0:
            corrected_n += 1
        elif int(n_unc) > 0:
            reported += 1
        else:
            silent += 1
    assert silent == 0, (
        f"{silent}/300 corrupted outputs passed with no report "
        f"(corrected={corrected_n}, reported={reported})")
    # Sanity: both branches of the contract must actually occur.
    assert corrected_n > 50 and reported > 5, (corrected_n, reported)


# ---------------------------------------------------------------------------
# Adaptive ("auto") thresholds: V-ABFT-style per-call data-dependent
# detection thresholds, computed from input moments at zero recompile cost.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("strategy", ["rowcol", "weighted", "fused",
                                      "global"])
def test_auto_threshold_catches_tiny_faults(strategy):
    """Faults of magnitude 5 sit five orders of magnitude under the
    reference's 9500 threshold (designed misses there) but far above the
    data's actual noise floor — auto mode must detect AND correct them."""
    a, b, c = _inputs(128, 128, 512, seed=17)
    inj = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))

    # Reference threshold: the faults pass silently (the documented blind
    # spot) and corrupt the output.
    res_ref = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                            strategy=strategy)(a, b, c, inject=inj)
    ok_ref, _, _ = verify_matrix(want, np.asarray(res_ref.c), verbose=False)
    assert not ok_ref and int(res_ref.num_detected) == 0

    res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                        threshold="auto")(a, b, c, inject=inj)
    if strategy == "global":
        # Detect-only (its auto threshold carries the sqrt(bn) whole-tile
        # aggregation scale): every fault must be counted, none corrected.
        assert int(res.num_detected) == 4
        assert int(res.num_uncorrectable) == int(res.num_detected)
        return
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{strategy}: {nbad} tiny faults survived auto threshold"
    assert int(res.num_detected) == 4  # nk=4, every=1
    assert int(res.num_uncorrectable) == 0


def test_auto_threshold_no_false_positives_clean():
    """Clean runs under auto thresholds must report zero detections (the
    margin over the calibrated bound absorbs reduction-order variance)."""
    for seed in (1, 2, 3):
        a, b, c = _inputs(256, 128, 512, seed=seed)
        for strategy in ("rowcol", "weighted", "fused", "global"):
            res = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                                strategy=strategy,
                                threshold="auto")(a, b, c)
            assert int(res.num_detected) == 0, (strategy, seed)
            assert int(res.num_uncorrectable) == 0, (strategy, seed)


def test_auto_threshold_composes_with_jit():
    import jax
    import jax.numpy as jnp

    a, b, c = _inputs(128, 128, 256, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    ft = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA,
                       strategy="weighted", threshold="auto")
    out = jax.jit(lambda a, b, c: ft(a, b, c, inj).c)(
        jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(out), verbose=False)
    assert ok, f"{nbad} faults survived under jit"


def test_runtime_threshold_reuses_compilation():
    """Thresholds are runtime scalars: changing the value must not mint a
    new kernel compilation (the detection study sweeps magnitudes, users
    sweep thresholds — recompiles would dominate)."""
    import jax

    a, b, c = _inputs(128, 128, 256, seed=5)
    ft1 = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA, threshold=9500.0)
    ft2 = make_ft_sgemm(ADV_TILE, alpha=ALPHA, beta=BETA, threshold=100.0)
    with jax.log_compiles():
        import io
        import logging

        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        logging.getLogger("jax._src.interpreters.pxla").addHandler(handler)
        try:
            ft1(a, b, c)
            n1 = buf.getvalue().count("Compiling")
            ft2(a, b, c)
            n2 = buf.getvalue().count("Compiling")
        finally:
            logging.getLogger("jax._src.interpreters.pxla").removeHandler(
                handler)
    assert n1 > 0, "log capture broke (JAX logger/message changed?)"
    assert n2 == n1, "threshold change must not recompile"
