"""Multi-host (DCN + ICI) mesh path over 8 virtual CPU devices.

Single-process stand-in for a pod: the ("host", "x", "y") mesh factors the
8 virtual devices as 2 "hosts" x 2 x 2, exercising the same program that
runs on real multi-host deployments (host axis = DCN there).
"""

import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, sgemm_reference
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.parallel import make_multihost_mesh, multihost_ft_sgemm
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def _mesh():
    # 8 virtual devices as 2 hosts x (2 x 2) ICI.
    return make_multihost_mesh(hosts=2, ici_axes=(2, 2))


def test_mesh_axes():
    mesh = _mesh()
    assert dict(mesh.shape) == {"host": 2, "x": 2, "y": 2}


def test_multihost_ft_corrects_before_collectives():
    mesh = _mesh()
    m, n, k = 512, 128, 256  # M/(2*2) = 128 rows, K/2 = 128 per device
    a, b, c = _inputs(m, n, k, seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = multihost_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                             inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements crossed the DCN/ICI collectives"
    # 8 devices x 1 local k-step x 1 local tile = 8 faults, all caught.
    assert int(res.num_detected) == 8


def test_multihost_scatter_output_matches():
    mesh = _mesh()
    m, n, k = 512, 256, 256  # N/2 = 128 per y shard
    a, b, c = _inputs(m, n, k, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    scat = multihost_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                              inject=inj, scatter_output=True)
    full = multihost_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                              inject=inj)
    np.testing.assert_allclose(np.asarray(scat.c), np.asarray(full.c),
                               rtol=1e-5, atol=1e-5)
    assert int(scat.num_detected) == int(full.num_detected) > 0


def test_multihost_bf16():
    from conftest import bf16_rounded_oracle

    mesh = _mesh()
    m, n, k = 512, 128, 256
    a, b, c = _inputs(m, n, k, seed=5)
    res = multihost_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                             in_dtype="bfloat16")
    want = bf16_rounded_oracle(a, b, c, ALPHA, BETA)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} bad"


def test_multihost_rejects_indivisible():
    mesh = _mesh()
    a, b, c = _inputs(302, 128, 256)  # 302 % (host*x = 4) != 0
    with pytest.raises(ValueError, match="divide evenly"):
        multihost_ft_sgemm(a, b, c, mesh, TILE)


def test_initialize_checks_state_not_message(monkeypatch):
    # Double-init detection queries the runtime state directly
    # (jax.distributed.is_initialized) instead of parsing exception text —
    # a real failure whose message merely contains "once"/"already" must
    # propagate, and an already-up runtime must short-circuit.
    import ft_sgemm_tpu.parallel.multihost as mh

    def must_not_call(**kw):
        raise AssertionError("initialize() called despite live runtime")

    # raising=False: older jax has no public is_initialized — the module
    # falls back to the client singleton, but the patched attribute (when
    # injectable) is still what it must consult first.
    monkeypatch.setattr(mh.jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    monkeypatch.setattr(mh.jax.distributed, "initialize", must_not_call)
    mh.initialize()  # already initialized: no call, no raise

    monkeypatch.setattr(mh.jax.distributed, "is_initialized", lambda: False,
                        raising=False)

    def fails(**kw):
        raise RuntimeError("coordinator said: connect at most once, already dead")

    monkeypatch.setattr(mh.jax.distributed, "initialize", fails)
    with pytest.raises(RuntimeError, match="coordinator said"):
        mh.initialize()


def test_multihost_ring_mesh_long_context():
    """The pod-scale long-context mesh: host-major 1-D ring over every
    device; the unchanged ring-attention family (and the flax ring
    module) runs over it, oracle-gated with injection on."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu import attention_reference
    from ft_sgemm_tpu.parallel import (
        make_multihost_ring_mesh, ring_ft_attention)

    mesh = make_multihost_ring_mesh()
    dnum = mesh.shape["x"]
    assert dnum == len(jax.devices())
    # Host-major: ring order is sorted by (process_index, id).
    ids = [d.id for d in mesh.devices.flat]
    assert ids == sorted(ids)

    rng = np.random.default_rng(11)
    lq, dh = 64 * dnum, 32
    q = generate_random_matrix(lq, dh, rng=rng)
    k = generate_random_matrix(lq, dh, rng=rng)
    v = generate_random_matrix(lq, dh, rng=rng)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ring_ft_attention(q, k, v, mesh, causal=True, inject=inj,
                            qk_shape=TILE, pv_shape=TILE)
    want = np.asarray(attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the multihost ring"
    assert int(res.detections) > 0
    flax = pytest.importorskip("flax")  # noqa: F841

    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtRingSelfAttention

    mod = FtRingSelfAttention(mesh=mesh, num_heads=2, causal=True,
                              inject=inj, dense_shape=TILE, qk_shape=TILE,
                              pv_shape=TILE)
    x = jnp.asarray(generate_random_matrix(lq, 64, rng=rng))
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    assert out.shape == x.shape
    assert int(mut[COUNTS_COLLECTION]["uncorrectable"]) == 0
    assert int(mut[COUNTS_COLLECTION]["detections"]) > 0
