"""Wall-clock phase attribution (perf/wallclock.py) + its surfaces.

The contract the tentpole pins: every attributed second lands in exactly
one phase bucket, unexplained wall is the explicit ``other`` bucket, the
fractions can never sum past 1, stage spans' compile/execute splits flow
timeline -> summary -> attribution -> RunReport "Wall attribution"
section -> ``wall.*`` registry series.
"""

import json

import pytest

from ft_sgemm_tpu.perf import wallclock
from ft_sgemm_tpu.perf.report import RunReport
from ft_sgemm_tpu.telemetry.registry import MetricsRegistry
from ft_sgemm_tpu.telemetry.timeline import read_timeline, summarize_timeline


def _summary(spans, wall=None):
    return {"spans": spans, "wall_seconds": wall}


def test_phase_mapping_covers_the_bench_span_vocabulary():
    spans = [
        {"kind": "compile", "name": "import_jax", "seconds": 8.0},
        {"kind": "compile", "name": "backend_init", "seconds": 120.0},
        {"kind": "compile", "name": "compile_cache_setup", "seconds": 0.2},
        {"kind": "compile", "name": "hlo_introspect", "seconds": 3.0},
        {"kind": "stage", "name": "device_put_inputs", "seconds": 2.0},
        {"kind": "stage", "name": "ft_rowcol", "seconds": 100.0,
         "compile_seconds": 70.0, "execute_seconds": 30.0},
        {"kind": "stage", "name": "xla_dot", "seconds": 5.0},  # no split
        {"kind": "tune", "name": "tune_search", "seconds": 10.0},
        {"kind": "attempt", "name": "worker", "seconds": 500.0},  # envelope
    ]
    attr = wallclock.attribute_wall(_summary(spans, wall=300.0))
    sec = attr["seconds"]
    assert sec["import"] == 8.0
    assert sec["backend_init"] == 120.0
    assert sec["compile"] == pytest.approx(73.0)  # hlo probe + stage split
    assert sec["transfer"] == 2.0
    assert sec["execute"] == pytest.approx(35.0)  # split + unsplit stage
    assert sec["tune"] == 10.0
    # other = cache setup + (300 - attributed) gap; the attempt envelope
    # contributes nothing.
    assert sec["other"] == pytest.approx(0.2 + (300.0 - 248.2))
    assert sum(attr["fractions"].values()) <= 1.0 + 1e-9
    assert attr["wall_seconds"] == 300.0


def test_fractions_never_exceed_one_even_with_overlapping_spans():
    # Double-booked spans beyond the wall: denominator grows instead of
    # reporting >100%.
    spans = [
        {"kind": "stage", "name": "a", "seconds": 80.0},
        {"kind": "stage", "name": "b", "seconds": 80.0},
    ]
    attr = wallclock.attribute_wall(_summary(spans, wall=100.0))
    assert sum(attr["fractions"].values()) <= 1.0 + 1e-9
    assert attr["fractions"]["execute"] == pytest.approx(1.0, abs=1e-3)


def test_headline_rung_spans_exclude_the_envelope():
    """The worker nests ladder-rung spans inside the outer ft_headline
    span; counting both would double-book the rung wall."""
    spans = [
        {"kind": "stage", "name": "ft_headline", "seconds": 100.0},
        {"kind": "stage", "name": "ft_headline[rowcol]", "seconds": 95.0,
         "compile_seconds": 60.0, "execute_seconds": 35.0},
    ]
    attr = wallclock.attribute_wall(_summary(spans, wall=100.0))
    assert attr["seconds"]["compile"] == pytest.approx(60.0)
    assert attr["seconds"]["execute"] == pytest.approx(35.0)
    assert sum(attr["fractions"].values()) <= 1.0 + 1e-9


def test_stage_split_clamps_to_span_wall():
    # A torn/buggy split larger than the span must not mint time.
    spans = [{"kind": "stage", "name": "s", "seconds": 10.0,
              "compile_seconds": 25.0, "execute_seconds": 25.0}]
    attr = wallclock.attribute_wall(_summary(spans, wall=10.0))
    assert attr["seconds"]["compile"] == 10.0
    assert attr["seconds"]["execute"] == 0.0
    assert sum(attr["fractions"].values()) <= 1.0 + 1e-9


def test_no_wall_falls_back_to_attributed_total():
    spans = [{"kind": "stage", "name": "s", "seconds": 4.0}]
    attr = wallclock.attribute_wall(_summary(spans))
    assert attr["wall_seconds"] == 4.0
    assert attr["fractions"]["execute"] == pytest.approx(1.0, abs=1e-3)


def test_split_flows_from_recorder_through_summary(tmp_path):
    """End-to-end: a recorder span that attaches the split lands it in
    the summary's span dict (the timeline passthrough) and the text
    rendering shows it."""
    from ft_sgemm_tpu.telemetry.timeline import format_timeline

    path = tmp_path / "tl.jsonl"
    # Raw records (the recorder's schema): the span wall must be
    # consistent with the split for the clamp not to bite, and a live
    # recorder closing in microseconds can't fabricate a 2 s span.
    path.write_text(
        json.dumps({"kind": "stage", "name": "ft_rowcol",
                    "phase": "start", "t": 100.0}) + "\n"
        + json.dumps({"kind": "stage", "name": "ft_rowcol",
                      "phase": "end", "t": 102.0, "seconds": 2.0,
                      "status": "ok", "value": 25600.0,
                      "compile_seconds": 1.5,
                      "execute_seconds": 0.5}) + "\n")
    summary = summarize_timeline(read_timeline(path))
    (span,) = summary["spans"]
    assert span["compile_seconds"] == 1.5
    assert span["execute_seconds"] == 0.5
    assert "compile 1.50s" in format_timeline(summary)
    attr = wallclock.attribute_wall(summary)
    assert attr["seconds"]["compile"] == pytest.approx(1.5)
    assert attr["seconds"]["execute"] == pytest.approx(0.5)


def test_record_wall_mirrors_registry_series():
    reg = MetricsRegistry()
    attr = wallclock.attribute_wall(_summary(
        [{"kind": "stage", "name": "s", "seconds": 4.0,
          "compile_seconds": 3.0, "execute_seconds": 1.0}], wall=5.0))
    wallclock.record_wall(attr, registry=reg)
    collected = {m["name"] for m in reg.collect()}
    assert "wall.compile_seconds" in collected
    assert "wall.compile_fraction" in collected
    assert "wall.total_seconds" in collected


def test_run_report_wall_roundtrip_and_markdown():
    attr = wallclock.attribute_wall(_summary(
        [{"kind": "stage", "name": "s", "seconds": 8.0,
          "compile_seconds": 6.0, "execute_seconds": 2.0}], wall=10.0))
    rr = RunReport(manifest={"device_kind": "cpu"}, stages=[], wall=attr)
    back = RunReport.from_json(rr.to_json())
    assert back.wall == attr
    md = back.to_markdown()
    assert "## Wall attribution" in md
    assert "| compile |" in md
    # Old reports (no wall) still round-trip and render without it.
    old = RunReport.from_dict({"manifest": {}})
    assert old.wall is None
    assert "Wall attribution" not in old.to_markdown()


def test_format_wall_renders_shares():
    attr = wallclock.attribute_wall(_summary(
        [{"kind": "compile", "name": "k", "seconds": 7.0},
         {"kind": "stage", "name": "s", "seconds": 3.0}], wall=10.0))
    text = wallclock.format_wall(attr)
    assert "compile" in text and "70.0%" in text


def test_cli_timeline_phases_flag(tmp_path, capsys):
    from ft_sgemm_tpu import cli

    path = tmp_path / "tl.jsonl"
    path.write_text(
        json.dumps({"kind": "stage", "name": "ft_rowcol",
                    "phase": "start", "t": 100.0}) + "\n"
        + json.dumps({"kind": "stage", "name": "ft_rowcol",
                      "phase": "end", "t": 101.0, "seconds": 1.0,
                      "status": "ok", "value": 321.0,
                      "compile_seconds": 0.9,
                      "execute_seconds": 0.1}) + "\n")
    assert cli.main(["cli", "timeline", str(path), "--phases"]) == 0
    out = capsys.readouterr().out
    assert "wall attribution" in out and "compile" in out
    assert cli.main(["cli", "timeline", str(path), "--phases",
                     "--format=json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["wall"]["seconds"]["compile"] == pytest.approx(0.9)
    assert sum(payload["wall"]["fractions"].values()) <= 1.0 + 1e-9
