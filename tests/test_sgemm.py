"""Differential tests: every plain Pallas kernel shape vs the XLA oracle.

Mirrors the reference's only correctness check — each kernel vs
cublasSgemm(OP_N, OP_T) under the utils.cu:61 tolerance (sgemm.cu:222) —
plus the non-square/odd-size coverage the reference lacks.
"""

import numpy as np
import pytest

from ft_sgemm_tpu import SHAPES, make_sgemm, sgemm_reference
from ft_sgemm_tpu.configs import SHAPE_ORDER
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


@pytest.mark.parametrize("shape_name", SHAPE_ORDER)
def test_square_matches_oracle(shape_name):
    a, b, c = _inputs(256, 256, 256)
    fn = make_sgemm(shape_name, alpha=ALPHA, beta=BETA)
    got = np.asarray(fn(a, b, c))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    ok, nbad, _ = verify_matrix(want, got, verbose=False)
    assert ok, f"{shape_name}: {nbad} elements out of tolerance"


@pytest.mark.parametrize(
    "m,n,k",
    [
        (384, 256, 512),   # multiple tiles
        (200, 136, 72),    # odd sizes -> padding on every axis
        (512, 128, 640),   # tall
        (128, 512, 640),   # wide
    ],
)
def test_rectangular_and_padded(m, n, k):
    a, b, c = _inputs(m, n, k, seed=7)
    fn = make_sgemm("huge", alpha=ALPHA, beta=BETA)
    got = np.asarray(fn(a, b, c))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_alpha_beta_variants():
    a, b, c = _inputs(128, 128, 128)
    for alpha, beta in [(1.0, 0.0), (2.0, -1.5), (0.5, 3.0)]:
        fn = make_sgemm("small", alpha=alpha, beta=beta)
        got = np.asarray(fn(a, b, c))
        want = np.asarray(sgemm_reference(a, b, c, alpha, beta))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_shape_table_is_mxu_legal():
    for name, shape in SHAPES.items():
        assert shape.bm % 128 == 0 and shape.bn % 128 == 0 and shape.bk % 128 == 0
        assert len(shape.ref_params) == 7
