"""CLI driver tests: argv contract, verification pass, perf table format."""

import io


from ft_sgemm_tpu import cli


def test_verification_pass_small():
    buf = io.StringIO()
    ok = cli.run_verification(end_size=256, st_kernel=0, end_kernel=16, out=buf)
    text = buf.getvalue()
    assert ok, text
    # All 14 table ids in range verify (0..6, 10..16 — 7..9 unused as in the
    # reference, sgemm.cu:197-199).
    assert text.count(": pass") == 14
    assert "abft_kernel_huge" in text


def test_perf_table_format():
    buf = io.StringIO()
    results = cli.run_perf_table(
        start_size=128, end_size=256, gap_size=128,
        st_kernel=0, end_kernel=1, min_device_time=0.02, out=buf,
    )
    text = buf.getvalue().splitlines()
    assert text[0].startswith("#####")
    assert text[1].startswith("Matrix Size         |")
    assert "     128|     256|" in text[1]
    assert text[2].startswith("xla_dot             |")
    assert text[3].startswith("kernel_sgemm_small  |")
    assert set(results) == {"xla_dot", "kernel_sgemm_small"}
    assert all(v > 0 for row in results.values() for v in row.values())


def test_main_argv_contract():
    # Too few args -> usage, exit 2 (reference reads argv[1..5], sgemm.cu:13-19).
    assert cli.main(["ft_sgemm", "1", "2"]) == 2
    assert cli.main(["ft_sgemm", "128", "128", "128", "11", "11",
                     "--no-perf"]) == 0


def test_verification_pass_bf16_mode():
    # --dtype=bfloat16: every row (vendor dot, plain, baseline, fused FT with
    # injection on) verifies against the bf16-rounded oracle.
    buf = io.StringIO()
    ok = cli.run_verification(end_size=256, st_kernel=0, end_kernel=16,
                              out=buf, in_dtype="bfloat16")
    assert ok, buf.getvalue()
    assert buf.getvalue().count(": pass") == 14


def test_main_rejects_bad_dtype():
    assert cli.main(["ft_sgemm", "128", "128", "128", "0", "0",
                     "--dtype=float16"]) == 2


def test_trace_flag_writes_profile(tmp_path):
    trace_dir = tmp_path / "trace"
    rc = cli.main(["ft_sgemm", "128", "128", "128", "0", "0", "--no-verify",
                   f"--trace={trace_dir}", "--mintime=0.01"])
    assert rc == 0
    # jax.profiler.trace writes a plugins/profile/<ts>/ tree under the dir.
    files = list(trace_dir.rglob("*"))
    assert any(f.is_file() for f in files), files


def test_cli_bf16_uses_override_tile():
    # --dtype=bfloat16 must pick up the tuned tile for named shapes
    # (regression: passing KernelShape objects bypassed the override).
    fn = cli._build_callable(6, 4096, inject_ft=False, in_dtype="bfloat16")
    assert fn.shape_config.block == (512, 512, 2048)


def test_cli_strategy_flag():
    buf = io.StringIO()
    ok = cli.run_verification(end_size=256, st_kernel=11, end_kernel=11,
                              out=buf, strategy="weighted")
    assert ok and ": pass" in buf.getvalue()
    # global is detect-only: its FT rows are gated on exact fault-event
    # counting (injection on) plus a clean-run diff, not on the corrupted
    # injected output.
    buf = io.StringIO()
    ok = cli.run_verification(end_size=256, st_kernel=11, end_kernel=11,
                              out=buf, strategy="global")
    assert ok, buf.getvalue()
    assert "detected" in buf.getvalue() and "clean diff ok" in buf.getvalue()
    assert cli.main(["ft_sgemm", "128", "128", "128", "0", "0",
                     "--strategy=bogus"]) == 2


def test_device_info_header():
    buf = io.StringIO()
    cli.print_device_info(out=buf)
    assert buf.getvalue().startswith("Device: ")


def test_perf_sweep_generates_host_inputs_once_per_size():
    """The sweep is size-major: N sizes x M kernel rows must cost exactly
    N host-input generations (round-2 finding: the row-major loop with
    lru_cache(2) regenerated every size for every row)."""
    cli._host_inputs.cache_clear()
    buf = io.StringIO()
    cli.run_perf_table(
        start_size=128, end_size=256, gap_size=128,
        st_kernel=0, end_kernel=2, min_device_time=0.02, out=buf,
    )
    info = cli._host_inputs.cache_info()
    assert info.misses == 2  # exactly one generation per size, ever
