"""ftlint (ft_sgemm_tpu/lint/core.py) — the static contract checker.

Three claims pinned here:

1. **The shipped tree is clean**: ``run_lint`` on the real repo exits 0
   with zero findings, the JSON output round-trips, and the axis-drift
   pass provably reads ALL SIX declaration sources ROADMAP item 5 names
   (configs, vmem, tuner key, telemetry labels, serve buckets, CLI).
2. **Each pass actually bites**: for every one of the five checks, a
   synthetic violation planted in a COPY of the real tree is caught with
   the right check name, file, and a plausible line — a checker that
   stays green on a seeded violation is worse than no checker.
3. **The linter is jax-free and path-loadable**: ``core.py`` runs by
   file path in a subprocess whose meta-path raises on any jax import
   (it is one of its own stdlib-only targets), and exits 0/1 per the
   compare.py contract.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from ft_sgemm_tpu.lint.core import (
    CHECK_ORDER,
    Finding,
    format_text,
    lint_facts,
    run_lint,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORE_PY = os.path.join(REPO_ROOT, "ft_sgemm_tpu", "lint", "core.py")

ALL_CHECKS = ("import-graph", "axis-drift", "lock-discipline",
              "smem-slots", "telemetry-schema")


def _copy_tree(tmp_path):
    """A mutable copy of the real package (plus the allowlist) the
    violation fixtures edit. bench.py/scripts are deliberately omitted:
    the package alone must carry every declaration source."""
    root = tmp_path / "repo"
    shutil.copytree(os.path.join(REPO_ROOT, "ft_sgemm_tpu"),
                    root / "ft_sgemm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copy(os.path.join(REPO_ROOT, "lint-allowlist.json"),
                root / "lint-allowlist.json")
    return str(root)


def _append(root, rel, text):
    with open(os.path.join(root, rel), "a", encoding="utf-8") as fh:
        fh.write(text)


# ---------------------------------------------------------------- clean


def test_shipped_tree_is_clean():
    result = run_lint(REPO_ROOT)
    assert result.internal_error is None
    assert result.findings == [], format_text(result)
    assert result.stale_entries == []
    assert result.exit_code == 0
    assert result.checks_run == list(ALL_CHECKS)


def test_runs_fast_enough():
    # The <10 s CI-blocking budget, with huge margin on any laptop.
    result = run_lint(REPO_ROOT)
    assert result.seconds < 10.0


def test_axis_pass_reads_all_six_declaration_sources():
    """ROADMAP item 5 names six hand-threading sites; the acceptance
    criterion is that the checker provably READS each declaration."""
    result = run_lint(REPO_ROOT, only=["axis-drift"])
    assert sorted(result.sources["axis-drift"]) == sorted([
        "ft_sgemm_tpu/configs.py",
        "ft_sgemm_tpu/ops/vmem.py",
        "ft_sgemm_tpu/tuner/cache.py",
        "ft_sgemm_tpu/telemetry/events.py",
        "ft_sgemm_tpu/serve/buckets.py",
        "ft_sgemm_tpu/cli.py",
    ])


def test_json_round_trip():
    result = run_lint(REPO_ROOT)
    doc = json.loads(json.dumps(result.to_dict()))
    assert doc["exit_code"] == 0
    assert doc["findings"] == []
    assert doc["checks_run"] == list(ALL_CHECKS)
    assert set(doc["sources"]) == set(ALL_CHECKS)
    # Findings themselves round-trip through their dict form.
    f = Finding("axis-drift", "a.py", 3, "s", "m")
    assert Finding(**json.loads(json.dumps(f.to_dict()))) == f


def test_only_selects_and_unknown_check_is_internal_error():
    result = run_lint(REPO_ROOT, only=["smem-slots"])
    assert result.checks_run == ["smem-slots"]
    assert result.exit_code == 0
    bad = run_lint(REPO_ROOT, only=["bogus"])
    assert bad.exit_code == 2
    assert "bogus" in bad.internal_error


# ------------------------------------------------- the five violations


def _single_finding(root, check, path_frag):
    result = run_lint(root)
    hits = [f for f in result.findings if f.check == check]
    assert hits, (f"seeded {check} violation not caught; all findings:\n"
                  + format_text(result))
    f = hits[0]
    assert path_frag in f.path
    assert f.line > 0
    assert result.exit_code == 1
    # The seeded violation must be the ONLY noise: no collateral
    # findings from other checks on an otherwise-clean copy.
    assert {x.check for x in result.findings} == {check}, format_text(result)
    return f


def test_catches_jax_smuggled_into_stdlib_only_module(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/telemetry/timeline.py", "\nimport jax\n")
    f = _single_finding(root, "import-graph",
                        "ft_sgemm_tpu/telemetry/timeline.py")
    assert "jax" in f.message


def test_catches_relative_import_escape(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/perf/ledger.py",
            "\n\ndef _sneaky():\n    from . import trend\n    return trend\n")
    f = _single_finding(root, "import-graph", "ft_sgemm_tpu/perf/ledger.py")
    assert "relative import" in f.message


def test_catches_rogue_axis_value(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/serve/buckets.py",
            '\n\ndef _rogue():\n    strategy = "colsum"\n'
            "    return strategy\n")
    f = _single_finding(root, "axis-drift", "ft_sgemm_tpu/serve/buckets.py")
    assert "colsum" in f.message


def test_catches_axis_drift_between_declarations(tmp_path):
    """A new axis value added in ONE place (telemetry's label mirror)
    but not the others is exactly the drift class the pass exists for."""
    root = _copy_tree(tmp_path)
    path = os.path.join(root, "ft_sgemm_tpu/telemetry/events.py")
    src = open(path, encoding="utf-8").read()
    assert '"encode": ("vpu", "mxu"),' in src
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(src.replace('"encode": ("vpu", "mxu"),',
                             '"encode": ("vpu", "mxu", "dma"),'))
    f = _single_finding(root, "axis-drift",
                        "ft_sgemm_tpu/telemetry/events.py")
    assert "AXIS_LABELS" in f.symbol


def test_catches_unguarded_threaded_write(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/serve/engine.py",
            '\n\n_EVIL = {}\n\n\ndef _flush_evil():\n'
            '    _EVIL["x"] = 1\n')
    f = _single_finding(root, "lock-discipline",
                        "ft_sgemm_tpu/serve/engine.py")
    assert "_EVIL" in f.symbol


def test_guarded_write_is_not_flagged(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/serve/engine.py",
            "\n\nimport threading as _t\n_EVIL = {}\n_EVIL_LOCK = "
            "_t.Lock()\n\n\ndef _flush_evil():\n"
            '    with _EVIL_LOCK:\n        _EVIL["x"] = 1\n')
    result = run_lint(root)
    assert not [f for f in result.findings
                if f.check == "lock-discipline"], format_text(result)


def test_catches_colliding_smem_slot(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/ops/ft_sgemm.py",
            "\n\ndef _ft_kernel_evil(inj_ref):\n"
            "    rogue = inj_ref[4]\n    return rogue\n")
    f = _single_finding(root, "smem-slots", "ft_sgemm_tpu/ops/ft_sgemm.py")
    assert "slot4" in f.symbol and "detect_threshold" in f.message


def test_catches_undeclared_smem_slot(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/ops/ft_sgemm.py",
            "\n\ndef _ft_kernel_evil(inj_ref):\n"
            "    threshold = inj_ref[11]\n    return threshold\n")
    f = _single_finding(root, "smem-slots", "ft_sgemm_tpu/ops/ft_sgemm.py")
    assert "slot11" in f.symbol


def test_catches_undeclared_event_kind_outcome_and_metric(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/serve/loadgen.py",
            "\n\ndef _emit_evil(reg, tl, FaultEvent):\n"
            '    tl.point("explosion", "boom")\n'
            '    reg.counter("mystery_metric").inc()\n'
            '    return FaultEvent(outcome="vaporized", op="x")\n')
    result = run_lint(root)
    syms = {f.symbol for f in result.findings
            if f.check == "telemetry-schema"}
    assert "kind='explosion'" in syms, format_text(result)
    assert "metric='mystery_metric'" in syms
    assert "outcome='vaporized'" in syms
    assert result.exit_code == 1


# ------------------------------------------------------- the allowlist


def test_allowlist_suppresses_and_stale_entries_fail(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/serve/engine.py",
            '\n\n_EVIL = {}\n\n\ndef _flush_evil():\n'
            '    _EVIL["x"] = 1\n')
    caught = run_lint(root)
    key = [f for f in caught.findings if f.check == "lock-discipline"][0]
    allow = {"version": 1, "entries": [
        {"check": key.check, "path": key.path, "symbol": key.symbol,
         "reason": "test: audited-safe"}]}
    with open(os.path.join(root, "lint-allowlist.json"), "w") as fh:
        json.dump(allow, fh)
    suppressed = run_lint(root)
    assert suppressed.exit_code == 0
    assert len(suppressed.suppressed) == 1
    # Entries WITHOUT a reason are ignored, not honored.
    allow["entries"][0].pop("reason")
    with open(os.path.join(root, "lint-allowlist.json"), "w") as fh:
        json.dump(allow, fh)
    assert run_lint(root).exit_code == 1
    # A stale entry (nothing matches) is itself a finding.
    allow = {"version": 1, "entries": [
        {"check": "lock-discipline", "path": "ft_sgemm_tpu/gone.py",
         "symbol": "ghost:_X", "reason": "stale"}]}
    with open(os.path.join(root, "lint-allowlist.json"), "w") as fh:
        json.dump(allow, fh)
    stale = run_lint(root)
    assert stale.stale_entries and stale.exit_code == 1


# ------------------------------------------- jax-free, path-loadable


@pytest.mark.parametrize("fmt,expect_rc", [("text", 0), ("json", 0)])
def test_core_runs_by_path_with_jax_blocked(fmt, expect_rc):
    """The CI invocation: core.py by file path, meta-path raising on any
    jax import — the linter is one of its own stdlib-only targets."""
    prog = f"""
import runpy, sys
class _Block:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax import blocked in lint subprocess")
sys.meta_path.insert(0, _Block())
sys.argv = ["core.py", "--format={fmt}"]
try:
    runpy.run_path({CORE_PY!r}, run_name="__main__")
except SystemExit as e:
    assert "jax" not in sys.modules
    sys.exit(e.code)
"""
    proc = subprocess.run([sys.executable, "-c", prog],
                          capture_output=True, text=True, timeout=120,
                          cwd=REPO_ROOT)
    assert proc.returncode == expect_rc, proc.stdout + proc.stderr
    if fmt == "json":
        doc = json.loads(proc.stdout)
        assert doc["exit_code"] == 0 and doc["findings"] == []


def test_exit_1_by_path_on_seeded_violation(tmp_path):
    root = _copy_tree(tmp_path)
    _append(root, "ft_sgemm_tpu/telemetry/traceview.py",
            "\nimport numpy\n")
    proc = subprocess.run(
        [sys.executable, CORE_PY, f"--root={root}", "--format=json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert any(f["check"] == "import-graph" and "numpy" in f["message"]
               for f in doc["findings"])


def test_lint_facts_shape():
    facts = lint_facts(REPO_ROOT)
    assert facts["findings"] == 0
    assert facts["internal_error"] is None
    assert 0 < facts["seconds"] < 10


def test_cli_lint_dispatch():
    """`python -m ft_sgemm_tpu.cli lint` reaches the same machinery
    (in-process: the cli module is already imported by the suite)."""
    from ft_sgemm_tpu import cli

    assert cli.main(["cli", "lint"]) == 0
    assert cli.main(["cli", "lint", "--only=bogus"]) == 2


def test_check_order_is_the_documented_five():
    assert CHECK_ORDER == list(ALL_CHECKS)
