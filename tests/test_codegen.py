"""Generator CLI tests (reference code_gen/main.py + gen.sh workflow)."""

import io

import pytest

from ft_sgemm_tpu.codegen import gen


def test_list_table():
    buf = io.StringIO()
    gen.print_table(out=buf)
    text = buf.getvalue()
    for name in ("small", "medium", "large", "tall", "wide", "huge", "test"):
        assert name in text
    # Reference provenance params present (main.py:8-16).
    assert "[16, 16, 16, 8, 16, 2, 2]" in text


def test_dump_single_variant(tmp_path):
    path = gen.dump_variant("small", True, 256, 256, 256, tmp_path)
    assert path.name == "ft_sgemm_small.txt"
    text = path.read_text()
    assert "jaxpr" in text and "lowered" in text
    assert "block tile (bm,bn,bk)=(128, 128, 128)" in text


def test_main_argv(tmp_path):
    assert gen.main(["gen", "list"]) == 0
    assert gen.main(["gen", "huge", "0", "256", "256", "256",
                     f"--out={tmp_path}"]) == 0
    assert (tmp_path / "sgemm_huge.txt").exists()
    assert gen.main(["gen", "bogus"]) == 2
    assert gen.main(["gen"]) == 2


def test_dump_bf16_variant(tmp_path):
    assert gen.main(["gen", "medium", "1", "256", "256", "256",
                     "--dtype=bfloat16", f"--out={tmp_path}"]) == 0
    path = tmp_path / "ft_sgemm_medium_bfloat16.txt"
    assert path.exists()
    text = path.read_text()
    assert "in_dtype=bfloat16" in text
    assert "bf16" in text  # the lowered StableHLO carries bf16 operand types
    assert gen.main(["gen", "medium", "1", "--dtype=float16"]) == 2


def test_cli_rejects_partial_mnk_and_bad_flags():
    # Lives here (not test_runtime.py) so it runs even without a native
    # toolchain: it only exercises argv parsing. Bad numeric input follows
    # the same message-and-exit-2 contract as every other argv error.
    assert gen.main(["gen", "huge", "1", "512"]) == 2
    assert gen.main(["gen", "huge", "yes"]) == 2
    assert gen.main(["gen", "huge", "1", "512", "512", "big"]) == 2
    assert gen.main(["gen", "--help"]) == 0
    assert gen.main(["gen", "--bogus-flag"]) == 2
