"""Generator CLI tests (reference code_gen/main.py + gen.sh workflow)."""

import io


from ft_sgemm_tpu.codegen import gen


def test_list_table():
    buf = io.StringIO()
    gen.print_table(out=buf)
    text = buf.getvalue()
    for name in ("small", "medium", "large", "tall", "wide", "huge", "test"):
        assert name in text
    # Reference provenance params present (main.py:8-16).
    assert "[16, 16, 16, 8, 16, 2, 2]" in text


def test_dump_single_variant(tmp_path):
    path = gen.dump_variant("small", True, 256, 256, 256, tmp_path)
    assert path.name == "ft_sgemm_small.txt"
    text = path.read_text()
    assert "jaxpr" in text and "lowered" in text
    assert "block tile (bm,bn,bk)=(128, 128, 128)" in text


def test_main_argv(tmp_path):
    assert gen.main(["gen", "list"]) == 0
    assert gen.main(["gen", "huge", "0", "256", "256", "256",
                     f"--out={tmp_path}"]) == 0
    assert (tmp_path / "sgemm_huge.txt").exists()
    assert gen.main(["gen", "bogus"]) == 2
    assert gen.main(["gen"]) == 2


def test_dump_bf16_variant(tmp_path):
    assert gen.main(["gen", "medium", "1", "256", "256", "256",
                     "--dtype=bfloat16", f"--out={tmp_path}"]) == 0
    path = tmp_path / "ft_sgemm_medium_bfloat16.txt"
    assert path.exists()
    text = path.read_text()
    assert "in_dtype=bfloat16" in text
    assert "bf16" in text  # the lowered StableHLO carries bf16 operand types
    assert gen.main(["gen", "medium", "1", "--dtype=float16"]) == 2


def test_committed_artifacts_cover_all_variants():
    """`generated/` is committed like the reference's include_code_gen/
    (main.py:17-19): 6 shapes x {plain, ft} at f32, plus the bf16
    flagship pair that has tuned tile overrides."""
    import pathlib

    from ft_sgemm_tpu.configs import BF16_TILE_OVERRIDES, SHAPE_ORDER

    gen_dir = pathlib.Path(__file__).resolve().parent.parent / "generated"
    expected = {
        gen.variant_name(name, if_abft)
        for name in SHAPE_ORDER for if_abft in (False, True)
    } | {
        gen.variant_name(name, if_abft, "bfloat16")
        for (name, if_abft) in BF16_TILE_OVERRIDES
    }
    have = {p.stem for p in gen_dir.glob("*.txt")}
    assert have == expected, (
        f"generated/ out of sync: missing {sorted(expected - have)}, "
        f"stray {sorted(have - expected)} — regenerate with "
        "`python -m ft_sgemm_tpu.codegen.gen all` (+ the bf16 flagship pair)")
    for p in gen_dir.glob("*.txt"):
        text = p.read_text()
        assert "jaxpr" in text and "lowered" in text, p.name


def test_cli_rejects_partial_mnk_and_bad_flags():
    # Lives here (not test_runtime.py) so it runs even without a native
    # toolchain: it only exercises argv parsing. Bad numeric input follows
    # the same message-and-exit-2 contract as every other argv error.
    assert gen.main(["gen", "huge", "1", "512"]) == 2
    assert gen.main(["gen", "huge", "yes"]) == 2
    assert gen.main(["gen", "huge", "1", "512", "512", "big"]) == 2
    assert gen.main(["gen", "--help"]) == 0
    assert gen.main(["gen", "--bogus-flag"]) == 2
