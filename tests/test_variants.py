"""ISSUE 13: the full kernel-variant descriptor and its joint search.

Pins, per the acceptance criteria:

1. **Default spellings are byte-identical HLO** — dispatching with
   ``variant=None`` / ``variant=DEFAULT_VARIANT`` / ``epilogue="none"``
   lowers to exactly the historical program, for the plain kernel and
   every (strategy, encode) FT body.
2. **Epilogue fusion is ABFT-correct under injection** — detect/correct
   operates on the pre-epilogue accumulator: injected faults are
   corrected and the output equals the HOST oracle (GEMM oracle composed
   with ``ops.reference.epilogue_reference``) for bias/relu/gelu/
   quantize across strategies and encodes, including int8-exact.
3. **Schema 3 -> 4 migration** — a schema-3 cache file misses cleanly
   with the standard warning (like the 2->3 pin), and the schema-4 key
   carries ``pipe=``/``grid=``/``cad=``/``epi=`` without collisions.
4. **VMEM model terms** — pipeline depth prices the real
   ``2*(depth-1)``-panel window; the cadence axis prices through the
   weighted in-kernel body (``variant_for(single_check=False)``).
5. **Joint search** — candidates carry non-default variants, everything
   not tried has a NAMED prune reason, the winner records its variant,
   and dispatch round-trips it.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ft_sgemm_tpu import tuner
from ft_sgemm_tpu.configs import (
    DEFAULT_VARIANT,
    DIM_SEMANTICS,
    EPILOGUE_ACTIVATIONS,
    EPILOGUE_QUANTIZE,
    GRID_ORDERS,
    PIPELINE_DEPTHS,
    EpilogueSpec,
    KernelShape,
    KernelVariant,
    canonical_variant,
)
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.ops.reference import epilogue_reference, sgemm_reference
from ft_sgemm_tpu.ops.sgemm import make_sgemm
from ft_sgemm_tpu.ops.vmem import estimate_vmem_bytes
from ft_sgemm_tpu.tuner import cache as tcache
from ft_sgemm_tpu.tuner import space as tspace

N = 256


def _operands(rng, m=N, n=N, k=N, int_lattice=False):
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    c = rng.standard_normal((m, n)).astype(np.float32)
    if int_lattice:
        a, b = np.round(a * 4.0), np.round(b * 4.0)
        c = np.round(c * 4.0)
    return a, b, c


def _lower_text(fn):
    args = tuple(jax.ShapeDtypeStruct((N, N), jnp.float32)
                 for _ in range(3))
    return jax.jit(fn).lower(*args).as_text()


# -- descriptor basics ------------------------------------------------------


def test_epilogue_spelling_roundtrip():
    for spelling in ("none", "bias", "relu", "bias+relu", "bias+gelu",
                     "qint8", "bias+gelu+qint8x0.5", "qfp8x2"):
        spec = EpilogueSpec.parse(spelling)
        assert EpilogueSpec.parse(spec.spelling) == spec
    assert EpilogueSpec.parse(None).is_identity
    assert EpilogueSpec.parse("none").spelling == "none"
    assert EpilogueSpec.parse("Bias+ReLU").spelling == "bias+relu"


def test_epilogue_rejects_bad_tokens():
    with pytest.raises(ValueError, match="legal tokens"):
        EpilogueSpec.parse("bias+frobnicate")
    with pytest.raises(ValueError, match="not a number"):
        EpilogueSpec.parse("qint8xlots")
    with pytest.raises(ValueError, match="scale"):
        EpilogueSpec(scale=2.0)  # scale without quantize
    with pytest.raises(ValueError, match="activation"):
        EpilogueSpec(activation="swish")


def test_kernel_variant_validation_and_axes_closed():
    assert DEFAULT_VARIANT.is_default
    v = KernelVariant(pipeline_depth=3, grid_order="nm",
                      dim_semantics="arbitrary", check_every=4,
                      epilogue="bias+relu")
    assert not v.is_default
    assert v.grid_spelling == "nm.arbitrary"
    assert v.cadence_spelling == "4"
    assert canonical_variant(None) == DEFAULT_VARIANT
    assert canonical_variant(dataclasses.asdict(v)) == v
    with pytest.raises(ValueError, match="pipeline_depth"):
        KernelVariant(pipeline_depth=7)
    with pytest.raises(ValueError, match="grid_order"):
        KernelVariant(grid_order="km")
    with pytest.raises(ValueError, match="check_every"):
        KernelVariant(check_every=0)
    with pytest.raises(ValueError, match="unknown KernelVariant"):
        canonical_variant({"warp_size": 32})
    # The declared axis tuples are what the descriptor validates against.
    assert 2 in PIPELINE_DEPTHS and "mn" in GRID_ORDERS
    assert "parallel" in DIM_SEMANTICS
    assert "none" in EPILOGUE_ACTIVATIONS and "none" in EPILOGUE_QUANTIZE


# -- (1) default spellings: byte-identical HLO ------------------------------


def test_default_variant_hlo_identical_plain():
    base = _lower_text(make_sgemm("small", tunable=False))
    with_variant = _lower_text(
        make_sgemm("small", tunable=False, variant=DEFAULT_VARIANT))
    assert base == with_variant


@pytest.mark.parametrize("strategy,encode", [
    ("weighted", "vpu"), ("weighted", "mxu"), ("rowcol", "vpu"),
    ("rowcol", "mxu"), ("global", "vpu"), ("global", "mxu"),
    ("fused", "mxu"),
])
def test_default_variant_hlo_identical_ft(strategy, encode):
    def build(**kw):
        kern = make_ft_sgemm("small", strategy=strategy, encode=encode,
                             tunable=False, **kw)
        return lambda a, b, c: kern(a, b, c, InjectionSpec.none()).c

    base = _lower_text(build())
    pinned = _lower_text(build(variant=DEFAULT_VARIANT, epilogue="none"))
    assert base == pinned


# -- (2) epilogue after correction: oracle under injection -----------------


@pytest.mark.parametrize("strategy,encode", [
    ("weighted", "vpu"), ("weighted", "mxu"), ("rowcol", "vpu"),
    ("rowcol", "mxu"), ("fused", "mxu"),
])
@pytest.mark.parametrize("epilogue", ["bias", "bias+relu", "bias+gelu"])
def test_epilogue_after_correction_under_injection(rng, strategy, encode,
                                                   epilogue):
    a, b, c = _operands(rng)
    bias = rng.standard_normal((N,)).astype(np.float32)
    inj = InjectionSpec.reference_like(N, 128)
    kern = make_ft_sgemm("small", strategy=strategy, encode=encode,
                         tunable=False, epilogue=epilogue)
    res = kern(a, b, c, inj, bias=bias)
    # Correction happened (pre-epilogue accumulator was verified)...
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == 0
    # ...and the output equals the host oracle THROUGH the epilogue: a
    # fault the epilogue's nonlinearity could launder would diverge here.
    want = epilogue_reference(
        np.asarray(sgemm_reference(a, b, c, 1.0, -1.5)), epilogue, bias)
    np.testing.assert_allclose(np.asarray(res.c), want, atol=3e-2)


def test_epilogue_detect_only_global_clean_path(rng):
    # global never corrects, so the oracle check runs CLEAN; the injected
    # run still detects (epilogue does not mask detection).
    a, b, c = _operands(rng)
    bias = rng.standard_normal((N,)).astype(np.float32)
    kern = make_ft_sgemm("small", strategy="global", tunable=False,
                         epilogue="bias+relu")
    res = kern(a, b, c, None, bias=bias)
    want = epilogue_reference(
        np.asarray(sgemm_reference(a, b, c, 1.0, -1.5)), "bias+relu", bias)
    np.testing.assert_allclose(np.asarray(res.c), want, atol=3e-2)
    res_inj = kern(a, b, c, InjectionSpec.reference_like(N, 128),
                   bias=bias)
    assert int(res_inj.num_detected) > 0


def test_epilogue_int8_exact_quantize(rng):
    a, b, c = _operands(rng, int_lattice=True)
    bias = np.round(
        rng.standard_normal((N,)) * 4.0).astype(np.float32)
    inj = InjectionSpec.reference_like(N, 128)
    kern = make_ft_sgemm("small", strategy="rowcol", in_dtype="int8",
                         tunable=False, epilogue="bias+qint8x0.25")
    res = kern(a, b, c, inj, bias=bias)
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == 0
    want = epilogue_reference(
        np.asarray(sgemm_reference(a, b, c, 1.0, -1.5, in_dtype="int8")),
        "bias+qint8x0.25", bias)
    # int8-exact: correction and quantize grid are both exact — equality,
    # not tolerance.
    np.testing.assert_array_equal(np.asarray(res.c), want)


def test_epilogue_fp8_quantize_roundtrip(rng):
    a, b, c = _operands(rng)
    kern = make_ft_sgemm("small", strategy="weighted", tunable=False,
                         epilogue="qfp8")
    res = kern(a, b, c, None)
    want = epilogue_reference(
        np.asarray(sgemm_reference(a, b, c, 1.0, -1.5)), "qfp8")
    out = np.asarray(res.c)
    # A half-ulp f32 accumulation-order difference between the kernel
    # and the XLA oracle can legitimately land on the NEIGHBORING fp8
    # step (e4m3's ~2^-3 relative grid amplifies it), so the pin is:
    # almost all values identical, every outlier within one grid step.
    exact = np.mean(out == want)
    assert exact > 0.98, f"only {exact:.3%} exact-grid matches"
    np.testing.assert_allclose(out, want, rtol=0.15, atol=0.02)
    # Every output value sits exactly on the fp8_e4m3 grid.
    import ml_dtypes

    np.testing.assert_array_equal(
        out, out.astype(ml_dtypes.float8_e4m3fn).astype(np.float32))


def test_epilogue_bias_required_and_rejected():
    kern = make_ft_sgemm("small", strategy="weighted", tunable=False,
                         epilogue="bias+relu")
    a = b = c = np.zeros((N, N), np.float32)
    with pytest.raises(ValueError, match="fuses a"):
        kern(a, b, c)
    plain = make_ft_sgemm("small", strategy="weighted", tunable=False)
    with pytest.raises(ValueError, match="does not fuse"):
        plain(a, b, c, None, bias=np.zeros((N,), np.float32))
    with pytest.raises(ValueError, match="length N"):
        kern(a, b, c, None, bias=np.zeros((N + 1,), np.float32))


# -- pipeline / grid axes: numeric equivalence ------------------------------


@pytest.mark.parametrize("variant", [
    KernelVariant(pipeline_depth=3),
    KernelVariant(grid_order="nm"),
    KernelVariant(dim_semantics="arbitrary"),
    KernelVariant(pipeline_depth=3, grid_order="nm",
                  dim_semantics="arbitrary"),
])
def test_variant_axes_numeric_equivalence_ft(rng, variant):
    a, b, c = _operands(rng)
    inj = InjectionSpec.reference_like(N, 128)
    kern = make_ft_sgemm("small", strategy="rowcol", tunable=False,
                         variant=variant)
    res = kern(a, b, c, inj)
    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    np.testing.assert_allclose(np.asarray(res.c), want, atol=3e-2)
    assert int(res.num_uncorrectable) == 0
    # Counter grids keep (grid_m, grid_n) orientation under either
    # traversal order.
    assert res.detections.shape == (N // 128, N // 128)


def test_variant_axes_numeric_equivalence_plain(rng):
    a, b, c = _operands(rng)
    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    for variant in (KernelVariant(pipeline_depth=3),
                    KernelVariant(grid_order="nm")):
        fn = make_sgemm("small", tunable=False, variant=variant)
        np.testing.assert_allclose(np.asarray(fn(a, b, c)), want,
                                   atol=2e-2)


# -- (4) VMEM model terms ---------------------------------------------------


def test_vmem_prices_pipeline_depth():
    shape = KernelShape("t", 256, 256, 256, (0,) * 7)
    d2 = estimate_vmem_bytes(shape, "weighted_precomp", pipeline_depth=2)
    d3 = estimate_vmem_bytes(shape, "weighted_precomp", pipeline_depth=3)
    # Depth 3 = one extra resident panel pair per stream:
    # 2 * (a_rows + b_rows) * bk * itemsize more bytes.
    assert d3 - d2 == 2 * (256 + 256) * 256 * 4
    with pytest.raises(ValueError, match="pipeline_depth"):
        estimate_vmem_bytes(shape, "weighted", pipeline_depth=5)


def test_vmem_prices_cadence_through_body_choice():
    # An intermediate cadence on weighted needs the running-partial-sum
    # in-kernel body — two calibrated VMEM units heavier than precomp.
    assert tspace.variant_for("weighted", single_check=True) == \
        "weighted_precomp"
    assert tspace.variant_for("weighted", single_check=False) == "weighted"
    shape = KernelShape("t", 512, 512, 512, (0,) * 7)
    precomp = estimate_vmem_bytes(shape, "weighted_precomp")
    inkernel = estimate_vmem_bytes(shape, "weighted")
    assert inkernel > precomp


# -- (3) cache schema 4 -----------------------------------------------------


def test_schema_is_5_and_schema3_misses_with_warning(tmp_path, monkeypatch):
    assert tcache.SCHEMA_VERSION == 5
    path = tmp_path / "cache.json"
    # A well-formed SCHEMA-3 file (two releases back): its keys lack the
    # variant components, so serving them would collide every variant's
    # winner — the load must MISS with the standard warning, exactly
    # like the 2->3 migration pin. (The 4->5 ring-axis migration is
    # pinned the same way in tests/test_overlap_pool.py.)
    path.write_text(json.dumps({"schema": 3, "entries": {
        "cpu|256x256x256|float32|weighted|enc=vpu|thr=static|inj=0":
            {"block": [256, 256, 256]},
    }}))
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    with pytest.warns(UserWarning, match="schema"):
        entries = tcache.load_entries()
    assert entries == {}
    assert tuner.lookup_winner(
        256, 256, 256, strategy="weighted", in_dtype="float32",
        injection_enabled=False) == (None, None)


def test_make_key_carries_variant_components_without_collisions():
    base = dict(strategy="weighted", in_dtype="float32",
                injection_enabled=False, device="cpu")
    k0 = tcache.make_key(256, 256, 256, **base)
    for frag in ("pipe=auto", "grid=auto", "cad=auto", "epi=none"):
        assert frag in k0
    keys = {
        k0,
        tcache.make_key(256, 256, 256, pipe="3", **base),
        tcache.make_key(256, 256, 256, grid="nm.parallel", **base),
        tcache.make_key(256, 256, 256, cad="4", **base),
        tcache.make_key(256, 256, 256, epi="bias+relu", **base),
    }
    assert len(keys) == 5  # every axis separates


def test_variant_key_components_resolver():
    comp = tuner.variant_key_components(None, None, "none")
    assert comp == {"pipe": "auto", "grid": "auto", "cad": "auto",
                    "epi": "none", "ring": "serial"}
    v = KernelVariant(pipeline_depth=3, grid_order="nm",
                      dim_semantics="arbitrary")
    comp = tuner.variant_key_components(v, 8, "bias+relu")
    assert comp == {"pipe": "3", "grid": "nm.arbitrary", "cad": "8",
                    "epi": "bias+relu", "ring": "serial"}


# -- (5) joint search -------------------------------------------------------


def test_joint_space_has_variants_and_named_prune_reasons():
    candidates, pruned = tspace.enumerate_joint_space(
        256, 256, 4096, strategy="weighted")
    variants = {c.variant for c in candidates}
    assert any(v.pipeline_depth == 3 for v in variants)
    assert any(v.dim_semantics == "arbitrary" for v in variants)
    assert any(v.check_every is not None for v in variants)
    # Everything not tried carries a reason; axis prunes name the axis.
    assert all(p.reason for p in pruned)
    reasons = " | ".join(p.reason for p in pruned)
    assert "joint-axis exploration capped" in reasons
    # 256x256 problem at big tiles: single-output-tile grids degenerate.
    assert "degenerate" in reasons


def test_joint_space_pins_axis():
    candidates, _ = tspace.enumerate_joint_space(
        256, 256, 4096, strategy="weighted", pin_pipeline=3)
    assert all(c.variant.pipeline_depth == 3 for c in candidates)


def test_joint_space_epilogue_rides_every_candidate():
    candidates, _ = tspace.enumerate_joint_space(
        256, 256, 512, strategy="weighted", epilogue="bias+relu")
    assert candidates
    assert all(c.variant.epilogue == "bias+relu" for c in candidates)


def test_tune_compile_method_finds_deep_pipeline_winner(tmp_path,
                                                        monkeypatch):
    # Deterministic joint-space proof (the CI assert): at K=4096 the
    # deepest tile covers 2048, so the depth-3 window (2 panels) halves
    # the K-grid — the compile method's grid-step score picks pipe=3.
    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "cache.json"))
    tcache.clear_memo()
    report = tuner.tune(256, 256, 4096, strategy="weighted",
                        method="compile", budget=10)
    best = report["best"]
    assert best["ok"]
    assert best["variant"]["pipeline_depth"] == 3
    # ...and the search beat (or tied) the measured heuristic baseline.
    assert best["score"] <= report["heuristic"]["score"]
    # Dispatch round-trips the winner.
    tile, var = tuner.lookup_winner(
        256, 256, 4096, strategy="weighted", in_dtype="float32",
        injection_enabled=False)
    assert tile is not None and var is not None
    assert var.pipeline_depth == 3
    # lookup_tile (the attention factories' view) still serves the tile.
    assert tuner.lookup_tile(
        256, 256, 4096, strategy="weighted", in_dtype="float32",
        injection_enabled=False).block == tuple(best["block"])


def test_dispatch_applies_tuned_variant(rng, tmp_path, monkeypatch):
    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "cache.json"))
    tcache.clear_memo()
    key = tcache.make_key(N, N, N, strategy="rowcol",
                          in_dtype="float32", injection_enabled=False)
    tcache.store(key, {
        "block": [128, 128, 128],
        "variant": {"pipeline_depth": 2, "grid_order": "nm",
                    "dim_semantics": "parallel", "check_every": 1,
                    "epilogue": "none"}})
    a, b, c = _operands(rng)
    kern = make_ft_sgemm("huge", strategy="rowcol")  # named => tunable
    res = kern(a, b, c, None)
    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    np.testing.assert_allclose(np.asarray(res.c), want, atol=2e-2)
    # The tuned 128-tile produced a 2x2 counter grid (the heuristic huge
    # tile would give 1x1) — proof the winner's tile AND variant applied.
    assert res.detections.shape == (2, 2)


def test_explicit_variant_pins_against_winner(rng, tmp_path, monkeypatch):
    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "cache.json"))
    tcache.clear_memo()
    # Winner exists under the AUTO key only; a pinned-variant caller keys
    # differently and must NOT pick it up.
    key = tcache.make_key(N, N, N, strategy="rowcol",
                          in_dtype="float32", injection_enabled=False)
    tcache.store(key, {"block": [128, 128, 128],
                       "variant": {"pipeline_depth": 3}})
    kern = make_ft_sgemm("huge", strategy="rowcol",
                         variant=KernelVariant(grid_order="nm"))
    a, b, c = _operands(rng)
    res = kern(a, b, c, None)
    # Heuristic huge tile (shrunk to 256) => single-tile counter grid.
    assert res.detections.shape == (1, 1)


# -- serve path -------------------------------------------------------------


def test_serve_bucket_epilogue_key_and_legality():
    from ft_sgemm_tpu.serve.buckets import Bucket, default_bucket_set

    b = Bucket(128, 128, 128, epilogue="Bias+ReLU")
    assert b.epilogue == "bias+relu"
    assert b.key.endswith("|epi=bias+relu")
    assert Bucket(128, 128, 128).key == "128x128x128|float32|weighted"
    buckets = default_bucket_set((128,), epilogue="bias+relu")
    assert buckets[0].epilogue == "bias+relu"
    with pytest.raises(ValueError, match="epilogue token"):
        Bucket(128, 128, 128, epilogue="nope")


def test_serve_engine_runs_epilogue_fused_bucket(rng):
    from ft_sgemm_tpu.serve.buckets import default_bucket_set
    from ft_sgemm_tpu.serve.engine import ServeEngine, ServeRequest

    buckets = default_bucket_set((128,), epilogue="bias+relu")
    a = rng.standard_normal((100, 96)).astype(np.float32)
    b = rng.standard_normal((120, 96)).astype(np.float32)
    bias = rng.standard_normal((120,)).astype(np.float32)
    with ServeEngine(buckets, beta=0.0) as eng:
        fut = eng.submit(ServeRequest(a=a, b=b, bias=bias,
                                      variant="inject"))
        res = fut.result(timeout=300)
    assert res.ok and res.corrected  # injected SDC corrected for free
    want = epilogue_reference(
        np.asarray(sgemm_reference(
            a, b, np.zeros((100, 120), np.float32), 1.0, 0.0)),
        "bias+relu", bias)
    np.testing.assert_allclose(res.c, want, atol=2e-2)


def test_serve_request_bias_validation():
    from ft_sgemm_tpu.serve.engine import ServeRequest

    a = np.zeros((4, 8), np.float32)
    b = np.zeros((6, 8), np.float32)
    with pytest.raises(ValueError, match="bias must have length"):
        ServeRequest(a=a, b=b, bias=np.zeros((5,), np.float32))


def test_loadgen_epilogue_verified_goodput(rng):
    from ft_sgemm_tpu.serve.loadgen import run_serve_bench

    stats = run_serve_bench(
        smoke=True, bucket_sizes=(128,), num_requests=6,
        inject_rate=0.4, adversarial_rate=0.0, verify=True,
        epilogue="bias+relu", monitor=None)
    assert stats["epilogue"] == "bias+relu"
    assert stats["completed"] > 0
    assert stats["verify_failures"] == 0
    assert stats["correct"] == stats["completed"]
    assert stats["goodput_rps"] > 0


# -- bench satellite: rung budgets + ladder order ---------------------------


def test_trend_stage_wall_budget():
    from ft_sgemm_tpu.perf import trend

    entries = [
        {"run_id": f"r{i}", "platform": {"device_kind": "cpu"},
         "measurements": {"stage[ft_headline[rowcol]].seconds":
                          {"value": 40.0 + i,
                           "higher_is_better": False}}}
        for i in range(4)]
    hist = trend.stage_seconds_history(entries, "ft_headline[rowcol]",
                                       "cpu")
    assert hist == [40.0, 41.0, 42.0, 43.0]
    budget = trend.stage_wall_budget(entries, "ft_headline[rowcol]",
                                     "cpu")
    assert budget is not None and budget > 41.5  # mean + 2 sigma
    assert trend.stage_wall_budget(entries, "missing", "cpu") is None
    assert trend.stage_wall_budget(entries, "missing", "cpu",
                                   default=30.0) == 30.0


def test_bench_ladder_orders_missing_rungs_first(monkeypatch, tmp_path):
    import importlib.util
    import sys as _sys

    _sys.path.insert(0, "/root/repo")
    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", "/root/repo/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Rec:
        def __init__(self, done):
            self._done = set(done)

        def done(self, name):
            return name in self._done

    ladder = [("flagship", {}), ("fallback", {}), ("rowcol", {})]
    ordered = bench._order_headline_ladder(
        ladder, Rec({"ft_headline[flagship]"}))
    assert [label for label, _ in ordered] == \
        ["fallback", "rowcol", "flagship"]
    # Budgets: ledger history drives the per-rung prediction; no ledger
    # falls back to the flat floor.
    monkeypatch.delenv("FT_SGEMM_LEDGER", raising=False)
    budgets = bench._headline_rung_budgets(
        {"device_kind": "cpu"}, ["flagship"])
    assert budgets == {"flagship": bench._RUNG_BUDGET_FLOOR}
    # With history: the ledger's stage series raises the budget.
    ledger = tmp_path / "ledger.jsonl"
    rows = [
        {"schema": 1, "run_id": f"r{i}", "kind": "bench",
         "platform": {"device_kind": "cpu", "used": "cpu"},
         "measurements": {"stage[ft_headline[flagship]].seconds":
                          {"value": 200.0, "higher_is_better": False}}}
        for i in range(3)]
    ledger.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    monkeypatch.setenv("FT_SGEMM_LEDGER", str(ledger))
    budgets = bench._headline_rung_budgets(
        {"device_kind": "cpu"}, ["flagship"])
    assert budgets["flagship"] >= 200.0


def test_ledger_banks_serve_path_p99(tmp_path):
    # ISSUE 13 acceptance: serve-path p99/goodput reach the ledger so
    # `cli trend --gate` judges a tuner win longitudinally.
    from ft_sgemm_tpu.perf import ledger

    artifact = {
        "metric": "serve_goodput_rps", "value": 120.0,
        "unit": "requests/s",
        "context": {"serve": True, "workload": "gemm", "smoke": True,
                    "epilogue": "bias+relu",
                    "goodput_rps": 120.0, "throughput_rps": 130.0,
                    "p50_latency_seconds": 0.01,
                    "p99_latency_seconds": 0.05,
                    "platform_used": "cpu"}}
    entry = ledger.ingest(artifact, run_id="r-epi")
    meas = entry["measurements"]
    assert meas["serve.p99_latency_seconds"]["value"] == 0.05
    assert meas["serve.p99_latency_seconds"]["higher_is_better"] is False
    assert meas["serve.throughput_rps"]["higher_is_better"] is True
    # The block workload keeps its own serve_block.* family untouched.
    assert not any(k.startswith("serve_block.") for k in meas)


# -- telemetry + lint extensions --------------------------------------------


def test_record_gemm_carries_epilogue_label(rng, tmp_path):
    from ft_sgemm_tpu import telemetry

    log = tmp_path / "ev.jsonl"
    a, b, c = _operands(rng, m=128, n=128, k=128)
    bias = np.zeros((128,), np.float32)
    telemetry.configure(str(log), log_clean=True)
    try:
        kern = make_ft_sgemm("small", strategy="weighted", tunable=False,
                             epilogue="bias+relu")
        kern(a, b, c, None, bias=bias)
        plain = make_ft_sgemm("small", strategy="weighted", tunable=False)
        plain(a, b, c, None)
    finally:
        telemetry.disable()
    events = [json.loads(line) for line in log.read_text().splitlines()]
    epis = [e.get("extra", {}).get("epilogue") for e in events]
    assert "bias+relu" in epis          # fused call labeled
    assert None in epis                 # default call unchanged


def test_lint_axis_drift_covers_variant_axes(tmp_path):
    import shutil
    import subprocess
    import sys as _sys

    root = tmp_path / "tree"
    root.mkdir()
    shutil.copytree("/root/repo/ft_sgemm_tpu", root / "ft_sgemm_tpu")
    contracts = root / "ft_sgemm_tpu" / "contracts.py"
    text = contracts.read_text()
    assert '"grid_order": ("mn", "nm")' in text
    contracts.write_text(text.replace(
        '"grid_order": ("mn", "nm")', '"grid_order": ("mn",)'))
    proc = subprocess.run(
        [_sys.executable, str(root / "ft_sgemm_tpu" / "lint" / "core.py"),
         "--only=axis-drift", "--format=json", f"--root={root}"],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert any("VARIANT_AXES[grid_order]" in fnd["symbol"]
               for fnd in doc["findings"])


def test_lint_axis_drift_catches_missing_key_marker(tmp_path):
    import shutil
    import subprocess
    import sys as _sys

    root = tmp_path / "tree"
    root.mkdir()
    shutil.copytree("/root/repo/ft_sgemm_tpu", root / "ft_sgemm_tpu")
    cache_py = root / "ft_sgemm_tpu" / "tuner" / "cache.py"
    text = cache_py.read_text()
    assert "pipe={pipe}" in text
    cache_py.write_text(text.replace("|pipe={pipe}", ""))
    proc = subprocess.run(
        [_sys.executable, str(root / "ft_sgemm_tpu" / "lint" / "core.py"),
         "--only=axis-drift", "--format=json", f"--root={root}"],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert any("pipe=" in fnd["message"] for fnd in doc["findings"])


# -- codegen ----------------------------------------------------------------


def test_codegen_accepts_full_dtype_family(tmp_path, capsys):
    from ft_sgemm_tpu.codegen import gen

    rc = gen.main(["gen", "small", "1", "128", "128", "128",
                   f"--out={tmp_path}", "--dtype=int8"])
    assert rc == 0
    assert (tmp_path / "ft_sgemm_small_int8.txt").exists()
    rc = gen.main(["gen", "small", "1", "128", "128", "128",
                   f"--out={tmp_path}", "--dtype=fp8"])
    assert rc == 0
    assert (tmp_path / "ft_sgemm_small_float8_e4m3fn.txt").exists()
    rc = gen.main(["gen", "small", "0", "--dtype=float64"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "--dtype must be one of" in err


def test_codegen_named_skip_for_illegal_pair(tmp_path):
    from ft_sgemm_tpu.codegen import gen

    # fused is illegal for int8 (1-byte dtypes carry no checksum rows):
    # the generator surfaces the kernel family's own constraint.
    with pytest.raises(ValueError, match="illegal for int8"):
        gen.lower_variant("small", True, 128, 128, 128, in_dtype="int8",
                          strategy="fused")


def test_codegen_dumps_tuned_variants(tmp_path, monkeypatch, capsys):
    from ft_sgemm_tpu.codegen import gen

    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "cache.json"))
    tcache.clear_memo()
    key = tcache.make_key(128, 128, 256, strategy="rowcol",
                          in_dtype="float32", injection_enabled=False,
                          device="cpu")
    tcache.store(key, {
        "block": [128, 128, 128], "problem": [128, 128, 256],
        "variant": {"pipeline_depth": 3, "epilogue": "bias+relu"}})
    out_dir = tmp_path / "generated"
    written = gen.dump_tuned(out_dir)
    assert len(written) == 1
    text = written[0].read_text()
    assert "pipe=3" in text and "epi=bias+relu" in text
    assert "===== lowered (StableHLO) =====" in text
    assert "pipe3" in written[0].name and "epi_bias_relu" in written[0].name
