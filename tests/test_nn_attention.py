"""FtSelfAttention / FtTransformerBlock: the model-family layer.

Oracle-differential tests in the reference's style (SURVEY.md §4 — every
kernel verified against the vendor dot): the flax attention module under
full injection must match a pure-XLA transformer oracle built from the
same parameters, with faults corrected, counts observable, and gradients
flowing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax = pytest.importorskip("flax")
optax = pytest.importorskip("optax")

from ft_sgemm_tpu import InjectionSpec  # noqa: E402
from ft_sgemm_tpu.nn import (  # noqa: E402
    COUNTS_COLLECTION,
    FtSelfAttention,
    FtTransformerBlock,
)
from ft_sgemm_tpu.ops.attention import attention_reference  # noqa: E402
from ft_sgemm_tpu.utils import verify_matrix  # noqa: E402

INJ = InjectionSpec(enabled=True, every=1, magnitude=10000.0)


def _x(batch=2, length=32, d=32, seed=0):
    k = jax.random.key(seed)
    return jax.random.normal(k, (batch, length, d)) * 0.3


def _oracle_attention(variables, x, num_heads, causal):
    """Same math via plain XLA ops from the module's own parameters."""
    p = variables["params"]

    def proj(name, t):
        return t @ p[name]["kernel"] + p[name]["bias"]

    q, k, v = (proj(n, x) for n in ("query", "key", "value"))
    b, length, qkv = q.shape
    dh = qkv // num_heads
    split = lambda t: t.reshape(  # noqa: E731
        b, length, num_heads, dh).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)
    out = jax.vmap(jax.vmap(
        lambda qq, kk, vv: attention_reference(qq, kk, vv, causal=causal)
    ))(q, k, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, length, qkv)
    return proj("out", out)


@pytest.mark.parametrize("causal", [False, True])
def test_attention_matches_oracle_under_injection(causal):
    x = _x()
    mod = FtSelfAttention(num_heads=2, causal=causal, inject=INJ)
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    want = _oracle_attention(variables, x, 2, causal)
    ok, nbad, _ = verify_matrix(np.asarray(want).reshape(-1, x.shape[-1]),
                                np.asarray(out).reshape(-1, x.shape[-1]),
                                verbose=False)
    assert ok, f"{nbad} mismatches vs the XLA oracle"
    counts = mut[COUNTS_COLLECTION]
    assert int(counts["detections"]) > 0, "injection must be detected"
    assert int(counts["uncorrectable"]) == 0
    # Projection sub-layers report under their own scopes.
    assert "query" in counts and "detections" in counts["query"]


def test_gradients_flow_and_bwd_counts_report():
    x = _x()
    mod = FtSelfAttention(num_heads=2, inject=INJ, inject_bwd=INJ)
    variables = mod.init(jax.random.key(1), x)

    def loss(params, sink):
        out = mod.apply({"params": params["params"]}, x, sink)
        return jnp.sum(out ** 2)

    (g, bwd) = jax.grad(loss, argnums=(0, 1))(
        {"params": variables["params"]}, jnp.zeros(2))
    flat = jax.tree.leaves(g)
    assert all(bool(jnp.all(jnp.isfinite(leaf))) for leaf in flat)
    assert any(float(jnp.max(jnp.abs(leaf))) > 0 for leaf in flat)
    # The gradient side-channel reports backward-GEMM fault activity.
    assert float(bwd[0]) > 0, "bwd detections must be reported"
    assert float(bwd[1]) == 0


def test_adversarial_bwd_schedule_surfaces_uncorrectable():
    """col_stride=0 (all faults in one column) in the BACKWARD pass only:
    the report channel must carry a nonzero uncorrectable count to the
    caller — never silent (VERDICT r3 item 4's done criterion, extended
    to the attention layer)."""
    x = _x(batch=1)
    adv = InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                        col_stride=0)
    # qkv_features=512 => d_head=256 => the dP gradient GEMM (contracts
    # over d_head, qk profile bk=128) runs nk=2 K-steps: two same-column
    # faults land in one deferred-check interval, where localization must
    # misfire and the re-check must REPORT (a single fault per call is
    # simply corrected — no uncorrectable to surface).
    mod = FtSelfAttention(num_heads=2, qkv_features=512, inject_bwd=adv)
    variables = mod.init(jax.random.key(1), x)

    def loss(params, sink):
        out = mod.apply({"params": params}, x, sink)
        return jnp.sum(out ** 2)

    _, bwd = jax.grad(loss, argnums=(0, 1))(variables["params"],
                                            jnp.zeros(2))
    assert float(bwd[1]) > 0, (
        "adversarial backward corruption must surface as uncorrectable")


def test_transformer_block_trains_under_injection():
    x = _x(batch=1, length=32, d=32)
    y = jnp.roll(x, 1, axis=-1)
    mod = FtTransformerBlock(num_heads=2, causal=True, inject=INJ)
    variables = mod.init(jax.random.key(1), x)
    params = variables["params"]
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            out, mut = mod.apply({"params": p}, x,
                                 mutable=[COUNTS_COLLECTION])
            counts = mut[COUNTS_COLLECTION]
            return jnp.mean((out - y) ** 2), counts

        (loss, counts), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        upd, opt = tx.update(grads, opt)
        return optax.apply_updates(params, upd), opt, loss, counts

    losses = []
    for _ in range(4):
        params, opt, loss, counts = step(params, opt)
        losses.append(float(loss))
        unc = sum(int(np.sum(v)) for pth, v
                  in jax.tree_util.tree_leaves_with_path(counts)
                  if "uncorrectable" in str(pth))
        assert unc == 0
    assert losses[-1] < losses[0], (
        f"loss must fall under per-call injection: {losses}")


def test_transformer_stack_scans_with_per_layer_counts():
    """FtTransformer: nn.scan-stacked blocks — one traced body regardless
    of depth, params and ft_counts carrying a leading layer axis, every
    layer's fault report visible (and summable into the re-run gate)."""
    from ft_sgemm_tpu.nn import FtTransformer

    x = _x(batch=1)
    mod = FtTransformer(num_layers=3, num_heads=2, causal=True, inject=INJ)
    variables = mod.init(jax.random.key(1), x)
    # Parameters are stacked over layers by scan.
    kern = variables["params"]["layers"]["block"]["attn"]["query"]["kernel"]
    assert kern.shape[0] == 3
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    assert out.shape == x.shape
    leaves = jax.tree_util.tree_leaves_with_path(mut[COUNTS_COLLECTION])
    det_leaves = [v for p, v in leaves if "detections" in str(p)]
    assert det_leaves and all(v.shape[0] == 3 for v in det_leaves)
    # Every layer detected its injected faults; none went uncorrectable.
    assert all(int(np.sum(v[layer])) > 0
               for v in det_leaves for layer in range(3))
    assert sum(int(np.sum(v)) for p, v in leaves
               if "uncorrectable" in str(p)) == 0

    # Gradients flow through the scanned stack.
    def loss(params):
        return jnp.sum(mod.apply({"params": params}, x) ** 2)

    g = jax.grad(loss)(variables["params"])
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(g))


def test_transformer_remat_matches_plain():
    """remat=True (jax.checkpoint per block — the HBM-for-FLOPs trade)
    must change memory behavior only: loss, gradients, and fault counts
    all match the plain stack, and the replayed forward's counts are not
    double-reported."""
    from ft_sgemm_tpu.nn import FtTransformer

    x = _x(batch=1)

    def run(remat):
        mod = FtTransformer(num_layers=2, num_heads=2, causal=True,
                            inject=INJ, remat=remat)
        variables = mod.init(jax.random.key(1), x)

        def loss(p):
            out, mut = mod.apply({"params": p}, x,
                                 mutable=[COUNTS_COLLECTION])
            return jnp.sum(out ** 2), mut[COUNTS_COLLECTION]

        (lv, counts), g = jax.value_and_grad(loss, has_aux=True)(
            variables["params"])
        det = sum(int(np.sum(v)) for p, v
                  in jax.tree_util.tree_leaves_with_path(counts)
                  if "detections" in str(p))
        return float(lv), det, jax.tree.leaves(g)

    l0, d0, g0 = run(False)
    l1, d1, g1 = run(True)
    # Counts are integers and must match exactly; the loss is f32 and the
    # remat wrapper may compile the primal forward under different
    # fusions, so allow last-ulp drift.
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    assert d0 == d1 > 0
    for a, b in zip(g0, g1):
        # The replayed forward compiles in a different fusion context, so
        # f32 reassociation noise is expected; a protection regression
        # (an uncorrected 1e4-scale fault reaching gradients) is nine
        # orders of magnitude above this tolerance.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_unbatched_input_shape():
    x = _x()[0]  # (L, D)
    mod = FtSelfAttention(num_heads=2)
    variables = mod.init(jax.random.key(1), x)
    out = mod.apply(variables, x)
    assert out.shape == x.shape


def test_bf16_in_dtype_smoke():
    """bf16 input mode flows through projections and the attention core:
    output keeps the caller's dtype, faults are detected and corrected."""
    x = _x(batch=1, seed=9)
    mod = FtSelfAttention(num_heads=2, in_dtype="bfloat16", inject=INJ)
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    assert out.dtype == x.dtype
    counts = mut[COUNTS_COLLECTION]
    assert int(counts["detections"]) > 0
    assert int(counts["uncorrectable"]) == 0
    assert bool(jnp.all(jnp.isfinite(out)))


def _ring_mesh(n):
    from ft_sgemm_tpu.parallel import make_ring_mesh

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    return make_ring_mesh(n)


def _oracle_ring(variables, x, num_heads, causal):
    """Single-device oracle for the ring module: same params, plain XLA."""
    p = variables["params"]

    def proj(name, t):
        return t @ p[name]["kernel"] + p[name]["bias"]

    q, k, v = (proj(n, x) for n in ("query", "key", "value"))
    length, qkv = q.shape
    dh = qkv // num_heads
    heads = lambda t: t.reshape(  # noqa: E731
        length, num_heads, dh).transpose(1, 0, 2)
    out = jax.vmap(
        lambda qq, kk, vv: attention_reference(qq, kk, vv, causal=causal)
    )(heads(q), heads(k), heads(v))
    return proj("out", out.transpose(1, 0, 2).reshape(length, qkv))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_module_matches_oracle(causal):
    """The long-context flax layer: ring-distributed attention core over a
    4-device mesh, injection on everywhere, vs the single-device XLA
    oracle built from the module's own parameters."""
    from ft_sgemm_tpu.nn import FtRingSelfAttention

    mesh = _ring_mesh(4)
    x = _x(batch=1, length=128, d=32, seed=5)[0]
    mod = FtRingSelfAttention(mesh=mesh, num_heads=2, causal=causal,
                              inject=INJ)
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    want = _oracle_ring(variables, x, 2, causal)
    ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(out),
                                verbose=False)
    assert ok, f"{nbad} mismatches vs the XLA oracle"
    counts = mut[COUNTS_COLLECTION]
    assert int(counts["detections"]) > 0
    assert int(counts["uncorrectable"]) == 0


def test_ring_attention_module_grads_and_bwd_report():
    from ft_sgemm_tpu.nn import FtRingSelfAttention

    mesh = _ring_mesh(4)
    x = _x(batch=1, length=128, d=32, seed=6)[0]
    mod = FtRingSelfAttention(mesh=mesh, num_heads=2, causal=True,
                              inject=INJ, inject_bwd=INJ)
    variables = mod.init(jax.random.key(1), x)

    def loss(params, sink):
        return jnp.sum(mod.apply({"params": params}, x, sink) ** 2)

    g, bwd = jax.grad(loss, argnums=(0, 1))(variables["params"],
                                            jnp.zeros(2))
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(g))
    assert float(bwd[0]) > 0, "ring backward detections must be reported"
    assert float(bwd[1]) == 0


def test_transformer_block_with_ring_mixer():
    """ring_mesh on FtTransformerBlock swaps the mixer to the
    sequence-parallel ring core: the long-context block is a config
    flag. Grads flow; counts stay clean under injection."""
    from ft_sgemm_tpu.nn import FtTransformerBlock

    mesh = _ring_mesh(4)
    x = _x(batch=1, length=128, d=32, seed=7)[0]
    mod = FtTransformerBlock(num_heads=2, causal=True, inject=INJ,
                             ring_mesh=mesh)
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    assert out.shape == x.shape
    counts = mut[COUNTS_COLLECTION]["attn"]
    assert int(counts["detections"]) > 0
    assert int(counts["uncorrectable"]) == 0

    def loss(p):
        return jnp.sum(mod.apply({"params": p}, x) ** 2)

    g = jax.grad(loss)(variables["params"])
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(g))


def test_transformer_stack_plumbs_ring_mesh():
    """FtTransformer(ring_mesh=...) reaches every scanned block: the
    stacked long-context model is the same config flag."""
    from ft_sgemm_tpu.nn import FtTransformer

    mesh = _ring_mesh(4)
    x = _x(batch=1, length=128, d=32, seed=8)[0]
    mod = FtTransformer(num_layers=2, num_heads=2, causal=True,
                        inject=INJ, ring_mesh=mesh)
    variables = mod.init(jax.random.key(1), x)
    out, mut = mod.apply(variables, x, mutable=[COUNTS_COLLECTION])
    assert out.shape == x.shape
    leaves = jax.tree_util.tree_leaves_with_path(mut[COUNTS_COLLECTION])
    assert sum(int(np.sum(v)) for p, v in leaves
               if "detections" in str(p)) > 0
    assert sum(int(np.sum(v)) for p, v in leaves
               if "uncorrectable" in str(p)) == 0


def test_ring_attention_module_rejects_batched_input():
    from ft_sgemm_tpu.nn import FtRingSelfAttention

    mesh = _ring_mesh(4)
    mod = FtRingSelfAttention(mesh=mesh, num_heads=2)
    with pytest.raises(ValueError, match="unbatched"):
        mod.init(jax.random.key(1), _x(batch=2, length=128, d=32))
