"""C->output buffer reuse across every hot entry point.

PR 3 made the plain and FT pallas_calls alias their C operand onto the
f32 output (``input_output_aliases`` — the beta*C epilogue reads each C
tile in the grid step that retires its output tile, so XLA reuses the
HBM buffer instead of allocating a second (M, N) array). This file
extends the pin to the REMAINING hot entry points: every ``parallel/``
path and both attention factories must reach a pallas_call that carries
the alias (the wrapper layers — shard_map, fori_loop ring hops, vjp
plumbing — must not launder it away), and the parallel wrappers'
``donate_c=True`` must additionally donate the OUTER C buffer at their
jit boundary (``donated_invars`` pinned in the traced pjit params) with
unchanged numerics.
"""

import jax
import numpy as np
import pytest

from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.attention import make_ft_attention
from ft_sgemm_tpu.ops.reference import sgemm_reference
from ft_sgemm_tpu.parallel import (
    make_mesh,
    make_multihost_mesh,
    make_ring_mesh,
    multihost_ft_sgemm,
    ring_ft_attention,
    ring_ft_sgemm,
    ring_sgemm,
    sharded_ft_sgemm,
    sharded_sgemm,
)

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _scan_pallas_params(jaxpr, out=None):
    """Every pallas_call eqn's params in a jaxpr, recursing through BOTH
    ClosedJaxpr params (pjit, while/fori bodies) and raw Jaxpr params
    (shard_map) — the wrapper layers the parallel paths stack up."""
    out = [] if out is None else out
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append(eqn.params)
        for v in eqn.params.values():
            if hasattr(v, "eqns"):  # raw Jaxpr (shard_map)
                _scan_pallas_params(v, out)
            elif hasattr(v, "jaxpr"):  # ClosedJaxpr (pjit, loops)
                _scan_pallas_params(v.jaxpr, out)
    return out


def _scan_donations(jaxpr, out=None):
    """Every pjit eqn's ``donated_invars`` tuple in a jaxpr."""
    out = [] if out is None else out
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("pjit", "jit"):
            di = eqn.params.get("donated_invars")
            if di is not None:
                out.append(tuple(di))
        for v in eqn.params.values():
            if hasattr(v, "eqns"):
                _scan_donations(v, out)
            elif hasattr(v, "jaxpr"):
                _scan_donations(v.jaxpr, out)
    return out


def _alias_pairs(params):
    alias = params.get("input_output_aliases")
    return tuple(tuple(p) for p in alias) if alias else ()


def _assert_all_ft_aliased(jaxpr, expect_calls):
    """Every pallas_call reached must alias its C operand (slot 3 for the
    FT kernels' (inj, a, b, c) operand order) onto f32 output 0."""
    params = _scan_pallas_params(jaxpr)
    assert len(params) == expect_calls, (
        f"expected {expect_calls} pallas_call(s), found {len(params)}")
    for p in params:
        assert _alias_pairs(p) == ((3, 0),), p.get("input_output_aliases")


def _inputs(rng, m=256, n=128, k=512):
    return (rng.standard_normal((m, k)).astype(np.float32),
            rng.standard_normal((n, k)).astype(np.float32),
            rng.standard_normal((m, n)).astype(np.float32))


# -- parallel/ family: pallas alias survives the wrapper layers --------------


def test_sharded_ft_alias_pinned(rng):
    a, b, c = _inputs(rng)
    mesh = make_mesh(8)
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: sharded_ft_sgemm(a, b, c, mesh, TILE).c)(a, b, c)
    _assert_all_ft_aliased(jaxpr.jaxpr, expect_calls=1)


def test_sharded_plain_alias_pinned(rng):
    a, b, c = _inputs(rng)
    mesh = make_mesh(8)
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: sharded_sgemm(a, b, c, mesh, TILE))(a, b, c)
    (params,) = _scan_pallas_params(jaxpr.jaxpr)
    # Plain kernel operand order (a, b, c): C is slot 2.
    assert _alias_pairs(params) == ((2, 0),), params.get(
        "input_output_aliases")


def test_ring_ft_alias_pinned(rng):
    a, b, c = _inputs(rng, 256, 256, 512)
    mesh = make_ring_mesh(8)
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: ring_ft_sgemm(a, b, c, mesh, TILE).c)(a, b, c)
    _assert_all_ft_aliased(jaxpr.jaxpr, expect_calls=1)


def test_ring_plain_alias_pinned(rng):
    a, b, c = _inputs(rng, 256, 256, 512)
    mesh = make_ring_mesh(8)
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: ring_sgemm(a, b, c, mesh, TILE))(a, b, c)
    (params,) = _scan_pallas_params(jaxpr.jaxpr)
    assert _alias_pairs(params) == ((2, 0),), params.get(
        "input_output_aliases")


def test_multihost_ft_alias_pinned(rng):
    a, b, c = _inputs(rng)
    mesh = make_multihost_mesh(hosts=2, ici_axes=(2, 2))
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: multihost_ft_sgemm(a, b, c, mesh, TILE).c)(a, b, c)
    _assert_all_ft_aliased(jaxpr.jaxpr, expect_calls=1)


# -- attention factories: both protected GEMMs alias -------------------------


def test_attention_qk_pv_alias_pinned(rng):
    q = rng.standard_normal((256, 128)).astype(np.float32)
    k = rng.standard_normal((256, 128)).astype(np.float32)
    v = rng.standard_normal((256, 128)).astype(np.float32)
    attn = make_ft_attention(qk_shape=TILE, pv_shape=TILE)
    jaxpr = jax.make_jaxpr(lambda q, k, v: attn(q, k, v).out)(q, k, v)
    # QK and PV kernels: two pallas_calls, both with the C->output alias.
    _assert_all_ft_aliased(jaxpr.jaxpr, expect_calls=2)


def test_ring_attention_alias_pinned(rng):
    q = rng.standard_normal((256, 128)).astype(np.float32)
    k = rng.standard_normal((256, 128)).astype(np.float32)
    v = rng.standard_normal((256, 128)).astype(np.float32)
    mesh = make_ring_mesh(8)
    jaxpr = jax.make_jaxpr(
        lambda q, k, v: ring_ft_attention(
            q, k, v, mesh, qk_shape=TILE, pv_shape=TILE).out)(q, k, v)
    params = _scan_pallas_params(jaxpr.jaxpr)
    assert params, "ring attention reached no pallas_call"
    for p in params:
        assert _alias_pairs(p) == ((3, 0),), p.get("input_output_aliases")


# -- donate_c: the OUTER jit boundary donates C too --------------------------


@pytest.mark.parametrize("path", ["sharded_ft", "sharded_plain", "ring_ft",
                                  "ring_plain", "multihost_ft"])
def test_donate_c_pins_donation_and_preserves_numerics(rng, path):
    if path in ("ring_ft", "ring_plain"):
        a, b, c = _inputs(rng, 256, 256, 512)
        mesh = make_ring_mesh(8)
        call = ring_ft_sgemm if path == "ring_ft" else ring_sgemm
    elif path == "multihost_ft":
        a, b, c = _inputs(rng)
        mesh = make_multihost_mesh(hosts=2, ici_axes=(2, 2))
        call = multihost_ft_sgemm
    else:
        a, b, c = _inputs(rng)
        mesh = make_mesh(8)
        call = sharded_ft_sgemm if path == "sharded_ft" else sharded_sgemm

    def run(a, b, c, donate):
        out = call(a, b, c, mesh, TILE, donate_c=donate)
        return out if path.endswith("plain") else out.c

    # Donation pinned in the traced pjit params: exactly the C argument
    # (invar 2) is donated, nothing else.
    jaxpr = jax.make_jaxpr(lambda a, b, c: run(a, b, c, True))(a, b, c)
    donations = _scan_donations(jaxpr.jaxpr)
    assert (False, False, True) in donations, donations
    # And with donation OFF nothing is donated anywhere.
    jaxpr0 = jax.make_jaxpr(lambda a, b, c: run(a, b, c, False))(a, b, c)
    assert all(not any(d) for d in _scan_donations(jaxpr0.jaxpr))

    # Numerics identical (numpy inputs: each call gets a fresh buffer,
    # so the donated path is observable only as the saved allocation).
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    got = np.asarray(run(a, b, c, True))
    np.testing.assert_allclose(got, want, atol=5e-4)


def test_donated_ft_still_corrects_injected_faults(rng):
    """Donation must not change the detect/correct story: an injected
    fault on the donated path is corrected and counted exactly as on
    the undonated one."""
    a, b, c = _inputs(rng)
    mesh = make_mesh(8)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = sharded_ft_sgemm(a, b, c, mesh, TILE, inject=inj, donate_c=True)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    from ft_sgemm_tpu.utils import verify_matrix

    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived on the donated path"
    assert int(res.num_detected) > 0
    assert int(np.sum(np.asarray(res.uncorrectable))) == 0
